"""Collective-communication wrappers.

TPU-native equivalents of the reference's MPI support layer
(dccrg_mpi_support.hpp): where dccrg wraps MPI_Allgatherv /
MPI_Allreduce / point-to-point neighbor reduces, this module wraps the
XLA collectives that ride the ICI mesh. The functions are meant to be
called *inside* ``shard_map``-mapped code (they need an axis name in
scope); each also has a ``host_*`` twin that runs the same collective
as a tiny jitted program over a mesh — the form application code uses
for occasional global reductions (e.g. the Poisson dot products,
tests/poisson/poisson_solve.hpp:278-360, use psum the same way).

- ``all_gather``  — All_Gather (dccrg_mpi_support.hpp:101-234)
- ``all_reduce``  — All_Reduce, sum (dccrg_mpi_support.hpp:240-269)
- ``all_finite``  — the resilience watchdog's probe: fused per-device
  ``all(isfinite)`` + min all-reduce, one scalar to the host
- ``some_reduce`` — Some_Reduce: reduce contributions only from a
  device's peer set (dccrg_mpi_support.hpp:285-380, which reduces
  values from neighbor processes via point-to-point messages; on TPU
  the peer sets are static masks and the exchange is one all_gather)
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .compat import shard_map as _shard_map


def all_gather(x, axis_name: str):
    """Every device's ``x`` stacked along a new leading axis."""
    return lax.all_gather(x, axis_name)


def all_reduce(x, axis_name: str, op: str = "sum"):
    """Elementwise reduction across the mesh axis."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduction {op!r}")


def all_finite(xs, axis_name: str):
    """Watchdog reduction: 1 iff every element of every array in
    ``xs`` on every device is finite. Each device fuses its local
    ``all(isfinite)`` over the list, then one min all-reduce crosses
    the mesh — so the resilience watchdog (resilience.check_finite)
    pulls a single scalar to the host no matter how many fields it
    guards."""
    ok = jnp.ones((), jnp.int32)
    for x in xs:
        ok = ok * jnp.all(jnp.isfinite(x)).astype(jnp.int32)
    return all_reduce(ok, axis_name, "min")


def field_sums(xs, axis_name: str):
    """Integrity reduction: the global sum of every array in ``xs``,
    fused like :func:`all_finite` — each device reduces its local
    arrays to a ``[len(xs)]`` vector, then ONE psum crosses the mesh.
    The SDC defense (:mod:`dccrg_tpu.integrity`) uses it for
    conservation-sum invariants: the result is replicated, so every
    rank reads the identical value and the drift verdict needs no
    further consensus round."""
    parts = jnp.stack([jnp.sum(x).astype(jnp.float32) for x in xs])
    return all_reduce(parts, axis_name, "sum")


def some_reduce(x, peer_mask, axis_name: str):
    """Sum of ``x`` over each device's peer set only.

    ``peer_mask``: [n_dev, n_dev] bool, ``peer_mask[q, p]`` true when
    device q reduces device p's contribution (the reference reduces
    over processes it shares a boundary with). The device's own row is
    applied on the device, so the result differs per device.
    """
    gathered = lax.all_gather(x, axis_name)  # [n_dev, ...]
    me = lax.axis_index(axis_name)
    w = peer_mask[me].astype(x.dtype)  # [n_dev]
    return jnp.tensordot(w, gathered, axes=1)


# Compiled host-collective programs, cached per (collective key, mesh,
# arg count). The host_* wrappers run EVERY step on hot resilience
# paths (the watchdog probe, the per-step trip consensus of
# ResilientRunner, the checkpoint CRC gather) — rebuilding
# jit(shard_map(...)) per call re-traced the program each time; with a
# stable jitted callable, jax's own cache makes repeat calls
# dispatch-only. FIFO-bounded: unlike grid._program_cache (which dies
# with its grid), this dict outlives every grid, so a long-lived
# driver cycling through many distinct meshes must not accumulate
# executables forever (far above the handful any one process uses).
_MESH_PROGRAMS: dict = {}
_MESH_PROGRAMS_CAP = 64


def _mesh_map(mesh: Mesh, key, build, *args):
    """Run ``build(axis)``'s body as ``jit(shard_map(...))`` over
    ``mesh`` with every arg row-sharded along the mesh axis. ``key``
    names the collective for the program cache (closures have no
    stable identity)."""
    axis = mesh.axis_names[0]
    spec = NamedSharding(mesh, P(axis))
    ck = (key, mesh, len(args))
    fn = _MESH_PROGRAMS.get(ck)
    if fn is None:
        mapped = _shard_map(
            build(axis), mesh=mesh,
            in_specs=(P(axis),) * len(args),
            out_specs=P(axis),
            check_vma=False,
        )
        fn = jax.jit(mapped)
        while len(_MESH_PROGRAMS) >= _MESH_PROGRAMS_CAP:
            _MESH_PROGRAMS.pop(next(iter(_MESH_PROGRAMS)))
        _MESH_PROGRAMS[ck] = fn
    args = [jnp.asarray(a, device=spec) for a in args]
    return fn(*args)


def pull_replicated(arr) -> np.ndarray:
    """Host copy of a device array whose value is replicated — or whose
    per-device rows are identical (any all-gathered / all-reduced
    result). Fully-addressable arrays pull directly; on a multi-process
    mesh only this process's first addressable shard is read — the
    foreign shards hold the same bytes by construction, which is
    exactly what a plain ``np.asarray`` cannot know (it refuses
    non-addressable arrays)."""
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    block = np.asarray(arr.addressable_shards[0].data)
    if block.shape == tuple(arr.shape):  # replicated output (P())
        return block
    # row-sharded output with identical rows: replicate the local row
    return np.broadcast_to(block[0], tuple(arr.shape)).copy()


def host_all_gather(mesh: Mesh, x) -> np.ndarray:
    """Run all_gather over ``mesh``; ``x`` is [n_dev, ...] sharded rows.
    Returns [n_dev, n_dev, ...] (each device's view, replicated)."""
    out = _mesh_map(mesh, "all_gather",
                    lambda axis: lambda v: all_gather(v[0], axis)[None],
                    jnp.asarray(x))
    return pull_replicated(out)


def host_all_reduce(mesh: Mesh, x, op: str = "sum") -> np.ndarray:
    """Reduce [n_dev, ...] rows across the mesh axis; returns one row."""
    out = _mesh_map(mesh, ("all_reduce", op),
                    lambda axis: lambda v: all_reduce(v[0], axis, op)[None],
                    jnp.asarray(x))
    return pull_replicated(out)[0]


def host_some_reduce(mesh: Mesh, x, peer_mask) -> np.ndarray:
    """Per-device neighbor-set sum of [n_dev, ...] rows."""
    mask = np.asarray(peer_mask, dtype=bool)

    def build(axis):
        def body(v, mask_row):
            # the mask rides in row-sharded: this device's block IS its
            # peer row (peer_mask[me]), so the program stays cacheable
            # across different masks instead of baking one in
            gathered = all_gather(v[0], axis)  # [n_dev, ...]
            w = mask_row[0].astype(v.dtype)  # [n_dev]
            return jnp.tensordot(w, gathered, axes=1)[None]

        return body

    # per-device results differ — no replicated pull possible (host
    # introspection of some_reduce stays a single-controller API)
    return np.asarray(_mesh_map(mesh, "some_reduce", build,
                                jnp.asarray(x), mask))
