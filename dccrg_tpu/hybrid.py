"""Hybrid structure plan for refined (AMR-carrying) grids.

The generic plan builder is O(total cells) with a large constant: it
streams ~26 neighbor entries per cell through the engine, dedups,
inverts and argsorts them even when 99% of the grid sits in uniform
same-level blocks. The reference's own rebuild is incremental per-cell
work (dccrg.hpp:10642-10690); this module is the vectorized
counterpart, built on one observation: **a cell whose whole (symmetric)
neighborhood consists of same-level leaves resolves closed-form** — at
any level, not just level 0 — because level-l ids are linear in the
level-l lattice coordinates (dccrg_mapping.hpp:154-209). Cells are
classified per level:

- level-0 cells away from any refined slot (box-dilated refined-root
  lattice) are *far*: tables come from the uniform lattice builder
  (native dn_far_tables writing the final layout in place / np.roll
  maps);
- level-l (l >= 1) cells whose neighbors at every symmetrized offset
  exist as level-l leaves are *easy*: neighbor positions come from
  level-l index arithmetic + one binary search per offset;
- everything else — the shell of cells near a level transition — is
  *hard* and runs through the generic engine
  (neighbors.find_neighbors_of), so engine cost scales with the
  refinement *surface*, not the refined volume, and not the grid.

All three classes merge into the same row layout, ghost sets and
send/receive lists the generic builder produces. Stencil tables are
split: far/easy rows share a dense [n_dev, L, k] table whose offsets
are per-slot constants scaled by a per-row cell size (synthesized on
device), hard rows get their own compact [n_dev, H, S_hard] tables
with explicit offsets — a hard cell can hold ~8x more entries (up to 8
children per refined window) than a uniform-bulk cell, so padding
every row to the hard width would waste ~8x HBM and gather bandwidth.
Stencils run the kernel over both tables and merge (grid.py).

The flat host-side entry stream (NeighborLists) and the neighbors_to
tables are built lazily on first use, as on the uniform fast path.
"""

from __future__ import annotations

import os
import threading
import time
import weakref

import numpy as np

from . import faults, telemetry

#: Optional phase-record sink: a list that every build appends
#: ``(label, seconds)`` tuples to (bench/recommit_bench.py installs
#: one to capture per-phase timings without parsing stdout).
_PHASE_SINK = None


def _phase_timer():
    """Phase-boundary logger: prints with DCCRG_TIMING=1, records into
    :data:`_PHASE_SINK` when one is installed, and emits the phases as
    ``hybrid.<label>`` telemetry spans when tracing is on (so an
    adapt/recommit epoch's internal cost split — classification, row
    layout, send/recv lists — lands in the same timeline as the
    ``grid.recommit`` span wrapping it)."""
    sink = _PHASE_SINK
    echo = os.environ.get("DCCRG_TIMING") == "1"
    trace = telemetry.trace_enabled()
    if sink is None and not echo and not trace:
        return lambda label: None
    state = {"t": time.perf_counter()}

    def mark(label):
        now = time.perf_counter()
        dt = now - state["t"]
        if echo:
            print(f"[hybrid] {label}: {dt:.3f}s", flush=True)
        if sink is not None:
            sink.append((label, dt))
        if trace:
            telemetry.record_span("hybrid." + label.replace(" ", "_"), dt)
        state["t"] = now

    return mark


def _fill_chunked(view, value, chunk_bytes=64 << 20):
    """Fill a (possibly huge) array chunk-wise: same result as a full
    ``arr[:] = value``, but each slice stays within one hot TLB/cache
    window instead of streaming the whole multi-GB extent at once."""
    flat = view.reshape(-1)
    step = max(1, chunk_bytes // max(1, flat.itemsize))
    for i in range(0, flat.size, step):
        flat[i:i + step] = value


class PlanArena:
    """Per-grid pool of the large plan-table buffers, reused across
    structure epochs.

    The recommit cost at scale is dominated by memory-system pressure,
    not arithmetic: every epoch used to allocate multi-GB fresh
    ``np.full`` tables, fault in every page, and (after the post-build
    ``malloc_trim``) hand the pages back — so the next epoch paid the
    faults again. The arena keeps the table backing stores alive as
    plain numpy buffers (grown geometrically, so steady-state epochs
    allocate nothing) and rotates them between plan generations:

    - :meth:`begin` opens a build and reclaims the buffers of every
      plan generation that is no longer *protected* (the live plan and
      the active transaction's rollback snapshot stay protected — an
      aborted build can never have scribbled on a plan a rollback may
      restore, pinned by tests/test_recommit.py);
    - :meth:`take` hands out a reclaimed-or-fresh buffer view, filled
      chunk-wise when a fill value is given;
    - :meth:`bind` transfers ownership of everything taken to the
      newly built plan. Lazy table thunks append to the same ownership
      list after the fact, so late-materialized to-tables are pooled
      too. A build that dies before ``bind`` leaves its takes in the
      pending list, which the next ``begin`` reclaims.
    """

    def __init__(self):
        self._free = {}      # dtype str -> [1-D raw buffers]
        self._owned = []     # [(weakref(plan), [buffers])]
        self._pending = []   # buffers taken by the in-flight build
        self.hits = 0        # takes served from the pool
        self.misses = 0      # takes that allocated fresh pages
        # a background build (DCCRG_BG_RECOMMIT) takes from the pool on
        # its worker thread while the LIVE plan's lazy table thunks may
        # take on the step loop's thread — the free lists need a lock.
        # Builds themselves stay serialized (one in flight per grid).
        self._lock = threading.RLock()
        #: set by the background worker for its build's duration: fresh
        #: allocations are page-touched at take time, so a grown
        #: table's cold-first-touch faults land in the worker, never on
        #: the step loop at swap (the shape-transition stall)
        self.prefault = False

    def begin(self, protect=()):
        """Open a build: reclaim every unprotected generation."""
        protected = {id(p) for p in protect if p is not None}
        with self._lock:
            survivors = []
            for ref, bufs in self._owned:
                plan = ref()
                if plan is not None and id(plan) in protected:
                    survivors.append((ref, bufs))
                else:
                    for b in bufs:
                        self._free.setdefault(b.dtype.str, []).append(b)
            self._owned = survivors
            for b in self._pending:
                self._free.setdefault(b.dtype.str, []).append(b)
            pending = []
            self._pending = pending
        # generation rotation is the arena's hot event: the swap count
        # plus pool-efficiency gauges make a cold (miss-heavy) epoch
        # visible in the same exposition as the recommit spans
        telemetry.inc("dccrg_arena_swaps_total")
        telemetry.set_gauge("dccrg_arena_pool_hits", self.hits)
        telemetry.set_gauge("dccrg_arena_pool_misses", self.misses)
        return pending

    def take(self, shape, dtype, fill=None, owner=None):
        """A ``shape``/``dtype`` array backed by a pooled buffer (the
        smallest free one that fits; fresh rounded-up allocation
        otherwise). ``owner`` is the pending list to register the
        backing buffer on (defaults to the current build's)."""
        dtype = np.dtype(dtype)
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        fresh = False
        with self._lock:
            pool = self._free.get(dtype.str, ())
            best = None
            for i, b in enumerate(pool):
                if b.size >= n and (best is None or b.size < pool[best].size):
                    best = i
            if best is not None:
                buf = pool.pop(best)
                self.hits += 1
            else:
                # geometric growth: the next power-of-two element
                # count, so a drifting refined region re-allocates
                # O(log) times ever
                cap = max(1 << max(0, int(n - 1).bit_length()), 1)
                buf = self._alloc(cap, dtype)
                self.misses += 1
                fresh = True
            (self._pending if owner is None else owner).append(buf)
        view = buf[:n].reshape(shape)
        if fill is not None:
            _fill_chunked(view, fill)
        elif fresh and self.prefault and owner is None:
            # background build of a GROWN table with no fill pass:
            # touch one byte per page of the USED extent now, on the
            # worker, so even a sparsely-written table never faults on
            # the step loop. Gated on owner is None — only the build's
            # own takes: a LIVE plan's lazy thunk materializing on the
            # step loop mid-build (owner=its plan's list) must never
            # pay a page-touch pass there, which is the exact stall
            # the flag exists to remove.
            flat = view.reshape(-1).view(np.uint8)
            flat[::4096] = flat[::4096]
        return view

    @staticmethod
    def _alloc(count, dtype):
        """Fresh backing store. Plain np.empty: the pages are faulted
        in by the first fill, exactly once. (An anonymous MAP_POPULATE
        mmap was measured here and lost — it touches every page during
        populate AND again on the fill, and this host's first-touch
        throughput at high RSS is the whole bottleneck.)"""
        return np.empty(count, dtype=dtype)

    def current_owner(self):
        """The in-flight build's ownership list: lazy thunks register
        their takes on it so post-``bind`` materialization stays owned
        by the plan the thunk belongs to."""
        return self._pending

    def bind(self, plan):
        """Transfer the in-flight build's buffers to ``plan``; returns
        the ownership list so lazy thunks can keep appending to it."""
        with self._lock:
            owned = self._pending
            self._owned.append((weakref.ref(plan), owned))
            self._pending = []
        return owned

    def stats(self) -> dict:
        with self._lock:
            pooled = sum(b.nbytes for bufs in self._free.values()
                         for b in bufs)
            owned = sum(b.nbytes for _r, bufs in self._owned for b in bufs)
        return {"hits": self.hits, "misses": self.misses,
                "free_bytes": int(pooled), "owned_bytes": int(owned)}


def _per_dim_radius(neighborhoods) -> np.ndarray:
    """Per-dimension max |offset| over all neighborhoods (x, y, z)."""
    rho = np.zeros(3, dtype=np.int64)
    for offs in neighborhoods.values():
        o = np.asarray(offs, dtype=np.int64).reshape(-1, 3)
        rho = np.maximum(rho, np.abs(o).max(axis=0))
    return rho


def _check_offsets(neighborhoods) -> np.ndarray:
    """The symmetrized union offset set {+-o} over all neighborhoods.

    Easiness must be symmetric: a cell's to-sources sit at the negated
    offsets, and a same-level to-source is what lets the lazy
    neighbors_to tables stay closed-form."""
    alls = [np.asarray(o, dtype=np.int64).reshape(-1, 3)
            for o in neighborhoods.values()]
    cat = np.concatenate(alls + [-a for a in alls])
    return np.unique(cat, axis=0)


class _LevelBlock:
    """Per-(refinement level >= 1) neighbor-position cache.

    For the contiguous block of level-l cells in the sorted cell list,
    ``lookup(offset)`` returns ``(pos, valid, exist)``: the position in
    the cell list of each cell's same-level neighbor at the given
    cell-unit offset, whether that neighbor slot is inside the grid,
    and whether it exists as a level-l leaf."""

    # level lattices above this are looked up by binary search instead
    # of a position lattice (numpy path; the native batch switches
    # strategy at the larger _PLAT_MAX_NATIVE — its lattice lives in
    # the arena, so the fill cost is paid on warm pages)
    _PLAT_MAX = 1 << 25
    _PLAT_MAX_NATIVE = 1 << 27

    def __init__(self, mapping, periodic, cells, level, a, b, arena=None):
        self.a, self.b = a, b
        self.level = level
        self.cells = cells
        nx, ny, nz = (int(v) for v in mapping.length.get())
        self.dims = (nx << level, ny << level, nz << level)
        self.first = np.int64(mapping._level_first[level])
        self.size = 1 << (mapping.max_refinement_level - level)
        self.periodic = periodic
        self._arena = arena
        lin = (cells[a:b] - np.uint64(self.first)).astype(np.int64)
        self.lin = lin
        nxl, nyl, nzl = self.dims
        self.x = lin % nxl
        self.y = (lin // nxl) % nyl
        self.z = lin // (nxl * nyl)
        self._cache = {}
        self._batch = None  # (pos_all, valid_all, off key -> batch row)
        # all level-l cells are contiguous in the sorted cell array, so
        # a direct lin -> position lattice replaces the per-offset
        # binary search over the whole grid (the hot part of easy-block
        # classification) when the level lattice fits in memory
        n_lat = nxl * nyl * nzl
        from . import native
        if native.lib is None and n_lat <= self._PLAT_MAX:
            self._plat = np.full(n_lat, -1, dtype=np.int32)
            self._plat[lin] = np.arange(a, b, dtype=np.int32)
        else:
            self._plat = None

    def precompute(self, offs_batch):
        """Batched native lookup of the whole offset set in one call
        (one lattice build amortized over every offset, positions as
        int32); no-op without the native lib — ``lookup`` then runs
        the per-offset numpy path with identical plan-level results."""
        from . import native

        if native.lib is None or self.b > 2**31 - 2:
            return
        offs_batch = np.ascontiguousarray(offs_batch,
                                          dtype=np.int64).reshape(-1, 3)
        kb, m = len(offs_batch), self.b - self.a
        take = (self._arena.take if self._arena is not None
                else lambda shape, dtype: np.empty(shape, dtype))
        pos = take((kb, m), np.int32)
        valid = take((kb, m), bool)
        exist = take((kb, m), bool)
        n_lat = int(np.prod(np.asarray(self.dims, dtype=np.int64)))
        plat = (take((n_lat,), np.int32)
                if n_lat <= self._PLAT_MAX_NATIVE else None)
        native.level_lookup(
            self.dims, self.periodic, self.lin, self.a, self.cells, self.b,
            self.first, offs_batch, plat, pos, valid, exist,
        )
        rows = {}
        for j, off in enumerate(offs_batch):
            key = (int(off[0]), int(off[1]), int(off[2]))
            self._cache[key] = (pos[j], valid[j], exist[j])
            rows[key] = j
        self._batch = (pos, valid, rows)

    def batch_rows(self, offs):
        """(pos_all, valid_all, sel) of the precomputed batch covering
        every offset in ``offs`` — the zero-copy form dn_easy_tables
        consumes — or None when no batch covers them."""
        if self._batch is None:
            return None
        pos, valid, rows = self._batch
        sel = np.empty(len(offs), dtype=np.int64)
        for j, o in enumerate(offs):
            row = rows.get((int(o[0]), int(o[1]), int(o[2])))
            if row is None:
                return None
            sel[j] = row
        return pos, valid, sel

    def lookup(self, off):
        key = (int(off[0]), int(off[1]), int(off[2]))
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        nxl, nyl, nzl = self.dims
        xs = self.x + key[0]
        ys = self.y + key[1]
        zs = self.z + key[2]
        valid = np.ones(len(xs), dtype=bool)
        for arr, nl, per in ((xs, nxl, self.periodic[0]),
                             (ys, nyl, self.periodic[1]),
                             (zs, nzl, self.periodic[2])):
            if per:
                arr %= nl
            else:
                valid &= (arr >= 0) & (arr < nl)
        lin_n = np.where(valid, xs + nxl * (ys + nyl * zs), 0)
        if self._plat is not None:
            p32 = self._plat[lin_n]
            exist = (p32 >= 0) & valid
            pos = np.where(exist, p32, 0).astype(np.int64)
        else:
            nid = (self.first + lin_n).astype(np.uint64)
            pos = np.minimum(np.searchsorted(self.cells, nid),
                             len(self.cells) - 1)
            exist = (self.cells[pos] == nid) & valid
            pos = pos.astype(np.int64)
        out = (pos, valid, exist)
        self._cache[key] = out
        return out


def build_hybrid_plan(mapping, topology, neighborhoods, cells, owner, n_dev,
                      cap=None, reuse=None, arena=None, changed_hint=None):
    """All plan pieces for a refined grid.

    Returns ``(layout, hood_data)`` like uniform.build_uniform_plan:
    layout holds local_ids / ghost_ids / n_local / n_inner / L / R /
    row_of_pos / scale_rows; hood_data maps hood id -> dict with the
    split gather tables, a lazy neighbors_to thunk, and the
    send/receive lists.

    ``arena`` is the grid's :class:`PlanArena` (the caller must have
    opened it with ``begin``); the big tables are taken from it so
    recommits run on warm pages. ``changed_hint`` is ``(prev_cells,
    changed_ids)``: when ``prev_cells`` is identical (the object) to
    the reuse cache's cell list, ``changed_ids`` replaces the
    O(n log n) set difference between the epochs' cell lists — the
    dirty-set propagation from ``stop_refining``.
    """
    from .grid import DEFAULT_NEIGHBORHOOD_ID
    from .neighbors import find_neighbors_of
    from .amr import _box_dilate
    from .uniform import _NeighborMaps
    from . import native

    mark = _phase_timer()
    if arena is None:
        arena = PlanArena()
        arena.begin()
    owned = arena.current_owner()

    dims = tuple(int(v) for v in mapping.length.get())
    nx, ny, nz = dims
    n0 = nx * ny * nz
    if n0 >= 2**31 - 2:
        raise ValueError(f"hybrid fast path limited to < 2^31 level-0 cells, got {n0}")
    size0 = 1 << mapping.max_refinement_level
    periodic = tuple(topology.is_periodic(d) for d in range(3))
    owner = np.asarray(owner, dtype=np.int32)
    cells = np.asarray(cells, dtype=np.uint64)
    n = len(cells)
    # the in-place table writers emit int32 position sentinels
    use_native = native.lib is not None and n < 2**31 - 2

    # level-major ids: the level-0 subset is exactly the sorted prefix
    # of ids <= n0 (dccrg_mapping.hpp:154-209)
    n_lvl0 = int(np.searchsorted(cells, np.uint64(n0), side="right"))
    lvl0_gidx = cells[:n_lvl0].astype(np.int64) - 1
    present = arena.take((n0,), bool, fill=False)
    present[lvl0_gidx] = True
    pos0 = arena.take((n0,), np.int64, fill=-1)  # slot -> position in `cells`
    pos0[lvl0_gidx] = np.arange(n_lvl0)

    # --- level-0 classification: refined slots box-dilated ------------
    rho = _per_dim_radius(neighborhoods)
    lat = _box_dilate(
        (~present).reshape(nz, ny, nx),  # axis0=z, axis1=y, axis2=x
        (rho[2], rho[1], rho[0]),
        (periodic[2], periodic[1], periodic[0]),
    )
    hard_lat = lat.reshape(-1)
    far = present & ~hard_lat
    far_slots = np.nonzero(far)[0]
    hard0_slots = np.nonzero(present & hard_lat)[0]

    # owner per level-0 slot (refined slots hold garbage, only ever
    # indexed through far sources whose windows are always present)
    owner0 = arena.take((n0,), np.int32, fill=0)
    owner0[lvl0_gidx] = owner[:n_lvl0]

    maps = _NeighborMaps(dims, periodic)

    # --- per-level (>= 1) classification: easy vs hard ----------------
    check_offs = _check_offsets(neighborhoods)
    blocks = []  # (_LevelBlock, easy bool array over the block)
    hard_parts = [pos0[hard0_slots]]
    max_lvl = mapping.max_refinement_level
    for l in range(1, max_lvl + 1):
        first = np.uint64(mapping._level_first[l])
        last = (np.uint64(mapping._level_first[l + 1]) if l < max_lvl
                else np.uint64(mapping.last_cell) + np.uint64(1))
        a = int(np.searchsorted(cells, first))
        b = int(np.searchsorted(cells, last))
        if a == b:
            continue
        blk = _LevelBlock(mapping, periodic, cells, l, a, b, arena=arena)
        # one native batch resolves every symmetrized offset for the
        # whole block (classification, easy tables, boundary edges and
        # the lazy to-tables all draw on this cache)
        blk.precompute(check_offs)
        easy = np.ones(b - a, dtype=bool)
        for off in check_offs:
            _pos, valid, exist = blk.lookup(off)
            easy &= exist | ~valid
        blocks.append((blk, easy))
        hard_parts.append(a + np.nonzero(~easy)[0])

    hard_pos = np.concatenate(hard_parts)
    hard_pos.sort(kind="stable")
    hard_cells = cells[hard_pos]
    mark(f"classify (hard {len(hard_pos)}/{n})")
    faults.fire("hybrid.recommit", phase="classified")

    # --- hard streams (generic engine on the hard shell) --------------
    # Epoch-to-epoch reuse: a hard cell whose whole search box is
    # untouched since the previous commit has an IDENTICAL neighbor
    # stream — only the positions shift, and those remap with one
    # searchsorted. The previous epoch's streams are cached by cell ID
    # (reuse dict, kept by the Grid), the changed region is the set
    # difference of the two cell sets box-dilated by the search
    # radius + 1 on the level-0 lattice, and only the dirty subset of
    # the hard shell reruns the generic engine — the reference's
    # incremental rebuild cost (dccrg.hpp:10642-10690).
    size0_log2 = mapping.max_refinement_level
    hood_fp = tuple(sorted(
        (hid, offs.tobytes()) for hid, offs in neighborhoods.items()))

    def lvl0_gidx_of(ids):
        idx = np.asarray(mapping.get_indices(ids), dtype=np.int64) >> size0_log2
        return idx[:, 0] + nx * (idx[:, 1] + ny * idx[:, 2])

    reusable = None
    if reuse and reuse.get("fp") == (dims, hood_fp):
        prev_cells = reuse["cells"]
        if changed_hint is not None and changed_hint[0] is prev_cells:
            # dirty-set propagation from stop_refining: the commit
            # already knows exactly which ids appeared/disappeared, so
            # the O(n log n) set difference over the full 8M-cell
            # lists is skipped (an owner-only rebuild passes an empty
            # set: repartitions reuse every stream)
            changed = np.asarray(changed_hint[1], dtype=np.uint64)
        else:
            changed = np.concatenate([
                np.setdiff1d(cells, prev_cells, assume_unique=True),
                np.setdiff1d(prev_cells, cells, assume_unique=True),
            ])
        if len(changed):
            lat_ch = np.zeros(n0, dtype=bool)
            lat_ch[lvl0_gidx_of(changed)] = True
            dirty = _box_dilate(
                lat_ch.reshape(nz, ny, nx),
                (int(rho[2]) + 1, int(rho[1]) + 1, int(rho[0]) + 1),
                (periodic[2], periodic[1], periodic[0]),
            ).reshape(-1)
        else:
            dirty = np.zeros(n0, dtype=bool)
        clean_hard = hard_cells[~dirty[lvl0_gidx_of(hard_cells)]]
        reusable = np.intersect1d(clean_hard, reuse["hard_ids"],
                                  assume_unique=True)
        if len(reusable) == 0:
            reusable = None

    streams = {}
    new_cache = {"fp": (dims, hood_fp), "cells": cells,
                 "hard_ids": hard_cells, "streams": {}}
    if reusable is None:
        fresh_hard, fresh_pos = hard_cells, hard_pos
    else:
        fm = ~np.isin(hard_cells, reusable, assume_unique=True)
        fresh_hard, fresh_pos = hard_cells[fm], hard_pos[fm]
        # one position remap for the whole epoch: old position -> new
        # position (every reused entry's source AND neighbor survive —
        # their boxes are untouched), plus a reusable-source mask over
        # old positions; per-hood selection is then pure gathers
        prev_cells = reuse["cells"]
        old2new = native.sorted_positions(cells, prev_cells)
        if old2new is None:
            old2new = np.searchsorted(cells, prev_cells)
        reus_old = np.zeros(len(prev_cells), dtype=bool)
        rpos = native.sorted_positions(prev_cells, reusable)
        if rpos is None:
            rpos = np.searchsorted(prev_cells, reusable)
        reus_old[rpos] = True
    for hid, offs in neighborhoods.items():
        src, nbr, off, item = find_neighbors_of(
            mapping, topology, cells, fresh_hard, offs
        )
        off = off.astype(np.int64)
        spos = fresh_pos[src]
        npos = np.searchsorted(cells, nbr)
        if reusable is not None:
            merged = native.stream_remap_merge(
                old2new, reus_old, reuse["streams"][hid],
                (spos, npos, off, item))
            if merged is not None:
                spos, npos, off, item = merged
            else:
                ps_pos, pn_pos, po, pi = reuse["streams"][hid]
                keep = reus_old[ps_pos]
                spos_b = old2new[ps_pos[keep]]
                npos_b = old2new[pn_pos[keep]]
                off_b, item_b = po[keep], pi[keep]
                # both pieces are sorted by source position and share
                # no source (a cell is wholly fresh or wholly reused),
                # so a linear merge replaces the N log N sort; within-
                # source (item, sibling-rank) order is preserved
                # piecewise
                na, nb = len(spos), len(spos_b)
                at = np.searchsorted(spos_b, spos) + np.arange(na)
                bt = np.searchsorted(spos, spos_b) + np.arange(nb)
                m_spos = np.empty(na + nb, dtype=spos.dtype)
                m_npos = np.empty(na + nb, dtype=npos.dtype)
                m_off = np.empty((na + nb,) + off.shape[1:], dtype=off.dtype)
                m_item = np.empty(na + nb, dtype=item.dtype)
                for dst_arr, a_arr, b_arr in ((m_spos, spos, spos_b),
                                              (m_npos, npos, npos_b),
                                              (m_off, off, off_b),
                                              (m_item, item, item_b)):
                    dst_arr[at] = a_arr
                    dst_arr[bt] = b_arr
                spos, npos, off, item = m_spos, m_npos, m_off, m_item
        new_cache["streams"][hid] = (spos, npos, off, item)
        streams[hid] = (spos, npos, off, item)
    if reuse is not None:
        reuse.clear()
        reuse.update(new_cache)
    # the reuse cache was just swapped IN PLACE: a fault here pins that
    # the transaction snapshot restores its previous contents too
    faults.fire("hybrid.recommit", phase="cached")
    mark(f"hard streams (reused {0 if reusable is None else len(reusable)}"
         f"/{len(hard_cells)})")

    # --- boundary classification + ghost sets -------------------------
    # every cross-device of-edge (c -> v) makes both endpoints outer
    # (c via its of-list, v via its to-list) and creates two ghost
    # reads: device(c) reads v, device(v) reads c. Edges are enumerated
    # once, at their source's class (far lattice / easy block / hard
    # stream), which covers the full edge set.
    outer = np.zeros(n, dtype=bool)
    ghost_reader = [np.empty(0, np.int32)]
    ghost_pos = [np.empty(0, np.int64)]

    def note_cross(sp, npos, default):
        if default:
            outer[sp] = True
            outer[npos] = True
        ghost_reader.append(owner[sp])
        ghost_pos.append(npos)
        ghost_reader.append(owner[npos])
        ghost_pos.append(sp)

    if n_dev > 1:
        for hid, offs in neighborhoods.items():
            default = hid == DEFAULT_NEIGHBORHOOD_ID
            for o in np.asarray(offs, dtype=np.int64).reshape(-1, 3):
                ng, valid = maps.shift(o)
                m = far & valid
                cross = np.nonzero(m & (owner0[ng] != owner0))[0]
                if len(cross):
                    note_cross(pos0[cross], pos0[ng[cross]], default)
                for blk, easy in blocks:
                    pos_n, _valid, exist = blk.lookup(o)
                    sel = np.nonzero(
                        easy & exist & (owner[pos_n] != owner[blk.a:blk.b])
                    )[0]
                    if len(sel):
                        note_cross(blk.a + sel, pos_n[sel], default)
            s_p, s_n, _, _ = streams[hid]
            cm = np.nonzero(owner[s_p] != owner[s_n])[0]
            if len(cm):
                note_cross(s_p[cm], s_n[cm], default)
    mark("classification")
    g_r = np.concatenate(ghost_reader)
    g_p = np.concatenate(ghost_pos)

    # --- row layout ----------------------------------------------------
    local_ids, ghost_ids, ghost_pos_sorted = [], [], []
    n_inner = np.zeros(n_dev, np.int64)
    for d in range(n_dev):
        mine = owner == d
        inner = cells[mine & ~outer]
        outerc = cells[mine & outer]
        local_ids.append(np.concatenate([inner, outerc]))
        n_inner[d] = len(inner)
        gp = np.unique(g_p[g_r == d])
        ghost_pos_sorted.append(gp)
        ghost_ids.append(cells[gp])

    from .grid import bucket_capacity

    if cap is None:
        cap = lambda name, needed: bucket_capacity(needed)
    n_local = np.array([len(x) for x in local_ids], dtype=np.int64)
    n_ghost = np.array([len(x) for x in ghost_ids], dtype=np.int64)
    L = cap("L", max(1, int(n_local.max())))
    G = int(n_ghost.max()) if n_dev > 1 else 0
    G = cap("G", G) if G else 0
    R = L + G + 1  # final row = permanent zero pad

    # every cell is local to exactly one device, so the scatter below
    # writes every entry — no -1 pre-fill pass needed on the arena view
    row_of_pos = arena.take((n,), np.int32)
    for d in range(n_dev):
        lpos = np.searchsorted(cells, local_ids[d])
        row_of_pos[lpos] = np.arange(len(local_ids[d]), dtype=np.int32)

    def resolve_rows(pos_arr, dev_arr):
        """Row of each cell (by position) on the given reader device:
        local row when the reader owns it, ghost row otherwise."""
        pos_arr = np.asarray(pos_arr, dtype=np.int64)
        dev_arr = np.asarray(dev_arr)
        rows = np.empty(len(pos_arr), dtype=np.int32)
        loc = owner[pos_arr] == dev_arr
        rows[loc] = row_of_pos[pos_arr[loc]]
        rm = np.nonzero(~loc)[0]
        for d in np.unique(dev_arr[rm]):
            mm = rm[dev_arr[rm] == d]
            gps = ghost_pos_sorted[d]
            gi = np.minimum(np.searchsorted(gps, pos_arr[mm]), max(len(gps) - 1, 0))
            if len(mm) and (len(gps) == 0 or np.any(gps[gi] != pos_arr[mm])):
                raise AssertionError(
                    "ghost coverage bug: neighbor without a row on its "
                    "reader's device"
                )
            rows[mm] = (L + gi).astype(np.int32)
        return rows

    far_pos = pos0[far_slots]
    far_dev = owner[far_pos].astype(np.int64)
    far_rowidx = far_dev * L + row_of_pos[far_pos]

    row_of_pos0 = arena.take((n0,), np.int32, fill=0)
    row_of_pos0[lvl0_gidx] = row_of_pos[:n_lvl0]

    # per-row cell size in index units (far/easy rows; hard rows get
    # explicit offsets, pad rows never pass a mask)
    scale_rows = arena.take((n_dev * L,), np.int32, fill=0)
    scale_rows[far_rowidx] = size0
    easy_rowidx = {}
    for blk, easy in blocks:
        ei = np.nonzero(easy)[0]
        ridx = owner[blk.a + ei].astype(np.int64) * L + row_of_pos[blk.a + ei]
        easy_rowidx[blk.level] = (ei, ridx)
        scale_rows[ridx] = blk.size
    mark("row layout")

    # --- gather tables per hood (split far+easy / hard) ---------------
    hood_data = {}
    # rows covered by the far/easy full-width writes below: the pad
    # fill only needs the complement (hard + pad rows, ~the surface),
    # saving a full GB-scale memory pass per hood table at large grids
    covered = arena.take((n_dev * L,), bool, fill=False)
    covered[far_rowidx] = True
    for _blk_c, _easy_c in blocks:
        covered[easy_rowidx[_blk_c.level][1]] = True
    uncovered_rows = np.nonzero(~covered)[0]
    del covered

    for hid, offs_in in neighborhoods.items():
        offs = np.asarray(offs_in, dtype=np.int64).reshape(-1, 3)
        k = len(offs)
        s_p, s_n, s_off, s_item = streams[hid]
        nE = len(s_p)

        # arena-held tables: far + easy + uncovered partition the rows,
        # so every entry is written below — no full-table pre-fill pass
        rows_t = arena.take((n_dev * L, k), np.int32)
        mask_t = arena.take((n_dev * L, k), bool)
        rows_t[uncovered_rows] = R - 1  # far/easy rows written in full
        mask_t[uncovered_rows] = False

        # far rows: closed-form lattice rows written straight into the
        # table at far_rowidx (native one-pass builder when available —
        # no [n0, k] intermediate, no gather + scatter passes); only
        # the cross-device fixups (the partition surface) come back to
        # the host
        fix = None
        if use_native:
            fix = native.far_tables(
                dims, periodic, offs, far_slots, far_rowidx, row_of_pos0,
                owner0 if n_dev > 1 else None, R - 1, rows_t, mask_t,
            )
        if fix is not None:
            if len(fix):
                ci, cj = fix // k, fix % k
                nslot = (-2 - rows_t[far_rowidx[ci], cj]).astype(np.int64)
                rows_t[far_rowidx[ci], cj] = resolve_rows(
                    pos0[nslot], far_dev[ci])
            mark(f"tables[{hid}]: far direct ({len(fix)} fixups)")
        else:
            fr = np.empty((len(far_slots), k), dtype=np.int32)
            fm = np.empty((len(far_slots), k), dtype=bool)
            for j, o in enumerate(offs):
                ng, valid = maps.shift(o)
                vf = valid[far_slots]
                rows = np.full(len(far_slots), R - 1, dtype=np.int32)
                vv = np.nonzero(vf)[0]
                rows[vv] = resolve_rows(
                    pos0[ng[far_slots][vv]], far_dev[vv]
                )
                fr[:, j] = rows
                fm[:, j] = vf
            rows_t[far_rowidx] = fr
            mask_t[far_rowidx] = fm
            del fr, fm
            mark(f"tables[{hid}]: far scatter")

        # easy rows: level-l index arithmetic, all offsets batched
        for blk, easy in blocks:
            ei, ridx = easy_rowidx[blk.level]
            E = len(ei)
            if E == 0:
                continue
            batch = blk.batch_rows(offs) if use_native else None
            if batch is not None:
                pos_all, valid_all, sel = batch
                edev32 = (np.ascontiguousarray(owner[blk.a + ei])
                          if n_dev > 1 else None)
                fix = native.easy_tables(
                    ei, ridx, sel, pos_all, valid_all, blk.b - blk.a,
                    row_of_pos, owner if n_dev > 1 else None, edev32,
                    R - 1, rows_t, mask_t,
                )
                if len(fix):
                    ce, cj = fix // k, fix % k
                    p = (-2 - rows_t[ridx[ce], cj]).astype(np.int64)
                    rows_t[ridx[ce], cj] = resolve_rows(
                        p, owner[blk.a + ei[ce]].astype(np.int64))
                mark(f"tables[{hid}]: easy block l{blk.level} "
                     f"({len(fix)} fixups)")
                continue
            edev = owner[blk.a + ei].astype(np.int64)
            posm = np.empty((E, k), dtype=np.int64)
            validm = np.empty((E, k), dtype=bool)
            for j, o in enumerate(offs):
                pos_n, valid, _exist = blk.lookup(o)
                posm[:, j] = pos_n[ei]
                validm[:, j] = valid[ei]
            rows = np.full(E * k, R - 1, dtype=np.int32)
            vv = np.nonzero(validm.reshape(-1))[0]
            if len(vv):
                rows[vv] = resolve_rows(
                    posm.reshape(-1)[vv], np.repeat(edev, k)[vv]
                )
            rows_t[ridx] = rows.reshape(E, k)
            mask_t[ridx] = validm
            mark(f"tables[{hid}]: easy block l{blk.level}")

        # hard rows: compact per-device tables from the stream
        hard_rows_dev = hard_nbr_dev = hard_offs_dev = hard_mask_dev = None
        if nE and use_native:
            # fused native writer: shape probe, then grouping + entry
            # scatter + pad fill in one sequential pass — every table
            # byte written exactly once (the numpy path below pays a
            # GB-scale pad fill plus a fancy-indexed scatter)
            nG, s_need, counts = native.hard_counts(
                s_p, owner if n_dev > 1 else None, n_dev)
            S_hard = cap(("S_hard", hid), max(1, int(s_need)))
            Hmax = cap(("Hmax", hid), max(1, int(counts.max())))
            mark(f"tables[{hid}]: hard grouping (H {int(counts.max())}"
                 f"/{Hmax}, S {int(s_need)}/{S_hard})")
            hard_rows_dev = arena.take((n_dev, Hmax), np.int32)
            hard_nbr_dev = arena.take((n_dev, Hmax, S_hard), np.int32)
            hard_offs_dev = arena.take((n_dev, Hmax, S_hard, 3), np.int32)
            hard_mask_dev = arena.take((n_dev, Hmax, S_hard), bool)
            fix = native.hard_fill(
                s_p, s_n, s_off, owner if n_dev > 1 else None, row_of_pos,
                n_dev, Hmax, S_hard, L, R - 1,
                hard_rows_dev, hard_nbr_dev, hard_offs_dev, hard_mask_dev,
            )
            if len(fix):
                flat = hard_nbr_dev.reshape(-1)
                rdev = fix // (Hmax * S_hard)  # reader device of the entry
                p = (-2 - flat[fix]).astype(np.int64)
                flat[fix] = resolve_rows(p, rdev)
            mark(f"tables[{hid}]: hard assembly ({len(fix)} fixups)")
        elif nE:
            # slot = rank within the (contiguous, source-sorted) group
            changed = np.empty(nE, dtype=bool)
            changed[0] = True
            changed[1:] = s_p[1:] != s_p[:-1]
            gstart = np.maximum.accumulate(np.where(changed, np.arange(nE), 0))
            slot = np.arange(nE) - gstart
            S_hard = cap(("S_hard", hid), max(1, int(slot.max()) + 1))
            # the stream is grouped by source cell (contiguous runs),
            # so the unique (dev, row) set falls out of the run starts —
            # no O(nE log nE) sort over the 26x-larger entry stream
            grp = np.cumsum(changed) - 1  # entry -> group [0, nG)
            gsel = np.nonzero(changed)[0]  # one entry per source cell
            g_dev = owner[s_p[gsel]].astype(np.int64)
            g_row = row_of_pos[s_p[gsel]]
            counts = np.bincount(g_dev, minlength=n_dev)
            # per-device dense position: consecutive per device in
            # stream (= cell-id) order
            gorder = np.argsort(g_dev, kind="stable")  # nG only
            dense_idx = np.empty(len(gsel), dtype=np.int64)
            dev_first = np.concatenate([[0], np.cumsum(counts)[:-1]])
            dense_idx[gorder] = (
                np.arange(len(gsel)) - dev_first[g_dev[gorder]]
            )
            Hmax = cap(("Hmax", hid), max(1, int(counts.max())))
            hard_rows_dev = arena.take((n_dev, Hmax), np.int32,
                                       fill=L)  # pad=L: dropped
            hard_nbr_dev = arena.take((n_dev, Hmax, S_hard), np.int32,
                                      fill=R - 1)
            hard_offs_dev = arena.take((n_dev, Hmax, S_hard, 3), np.int32,
                                       fill=0)
            hard_mask_dev = arena.take((n_dev, Hmax, S_hard), bool,
                                       fill=False)
            hard_rows_dev[g_dev, dense_idx] = g_row.astype(np.int32)
            e_dev = g_dev[grp]
            e_pos = dense_idx[grp]
            hard_nbr_dev[e_dev, e_pos, slot] = resolve_rows(s_n, owner[s_p])
            hard_offs_dev[e_dev, e_pos, slot] = s_off.astype(np.int32)
            hard_mask_dev[e_dev, e_pos, slot] = True
            mark(f"tables[{hid}]: hard assembly")

        offs_const = offs.astype(np.int32)  # [k, 3], CELL units (x scale_rows)

        def offs_thunk(mask_t=mask_t, offs_const=offs_const, k=k):
            # far/easy per-slot offsets (hard rows carry theirs in the
            # compact hard tables; host queries use the engine); runs
            # after bind, so the take lands on the plan's owned list
            out = arena.take((n_dev * L, k, 3), np.int32, owner=owned)
            np.multiply(mask_t[:, :, None], offs_const[None, :, :], out=out)
            out *= scale_rows[:, None, None]
            return out.reshape(n_dev, L, k, 3)

        hood_data[hid] = {
            "nbr_rows": rows_t.reshape(n_dev, L, k),
            "nbr_offs": offs_thunk,
            "offs_const": offs_const,
            "nbr_mask": mask_t.reshape(n_dev, L, k),
            "hard_rows": hard_rows_dev,
            "hard_nbr_rows": hard_nbr_dev,
            "hard_offs": hard_offs_dev,
            "hard_mask": hard_mask_dev,
        }
        mark(f"tables hood {hid}")

    # arena tables are all written at this point: a fault here pins
    # that a rolled-back plan's (protected) buffers were never touched
    faults.fire("hybrid.recommit", phase="tables")

    # --- send / receive lists -----------------------------------------
    from .uniform import build_pair_tables

    pair_compact = build_pair_tables(
        ghost_pos_sorted, n_dev,
        lambda keys: owner[keys],
        lambda p_s, keys: row_of_pos[keys],
        lambda q_s, keys, gpos: (L + gpos).astype(np.int32),
        lambda needed: cap(("M", "hybrid"), needed),
    )
    for hid in neighborhoods:
        hood_data[hid]["pair_compact"] = pair_compact
    mark("send/recv lists")

    # --- lazy neighbors_to tables -------------------------------------
    is_hard_target = np.zeros(n, dtype=bool)
    is_hard_target[hard_pos] = True
    lvl_of_pos = np.zeros(n, dtype=np.int64)
    for blk, _easy in blocks:
        lvl_of_pos[blk.a:blk.b] = blk.level

    def make_to_thunk(hid, offs_in):
        offs = np.asarray(offs_in, dtype=np.int64).reshape(-1, 3)
        k = len(offs)

        def thunk():
            s_p, s_n, s_off, s_item = streams[hid]
            # inverted hard entries: keep when the TARGET is hard, or
            # when source and target levels differ (a same-level source
            # of a far/easy target is covered closed-form below; a
            # cross-level source never is)
            keep = is_hard_target[s_n] | (lvl_of_pos[s_p] != lvl_of_pos[s_n])
            tv, tc = s_n[keep], s_p[keep]
            toff = -s_off[keep]
            titem = s_item[keep]
            # same-level sources of hard targets that are far/easy
            # (enumerated from the target side: source at -o exists,
            # same level, and is not itself hard)
            ex_v, ex_c, ex_off, ex_item = [], [], [], []
            if len(hard0_slots):
                for j, o in enumerate(offs):
                    ng, valid = maps.shift((-int(o[0]), -int(o[1]), -int(o[2])))
                    cslot = ng[hard0_slots]
                    ok = valid[hard0_slots] & far[cslot]
                    if ok.any():
                        hs = hard0_slots[ok]
                        ex_v.append(pos0[hs])
                        ex_c.append(pos0[cslot[ok]])
                        ex_off.append(
                            np.broadcast_to(
                                (-o * size0).astype(np.int64), (int(ok.sum()), 3)
                            )
                        )
                        ex_item.append(np.full(int(ok.sum()), j, dtype=np.int64))
            for blk, easy in blocks:
                hi = np.nonzero(~easy)[0]  # hard level-l targets
                if len(hi) == 0:
                    continue
                src_is_easy = np.zeros(len(cells), dtype=bool)
                src_is_easy[blk.a + np.nonzero(easy)[0]] = True
                for j, o in enumerate(offs):
                    pos_n, valid, exist = blk.lookup((-int(o[0]), -int(o[1]), -int(o[2])))
                    # source must exist as an easy level-l leaf
                    src_pos = pos_n[hi]
                    ok = exist[hi] & src_is_easy[src_pos]
                    if ok.any():
                        ex_v.append(blk.a + hi[ok])
                        ex_c.append(src_pos[ok])
                        ex_off.append(
                            np.broadcast_to(
                                (-o * blk.size).astype(np.int64), (int(ok.sum()), 3)
                            )
                        )
                        ex_item.append(np.full(int(ok.sum()), j, dtype=np.int64))
            if ex_v:
                tv = np.concatenate([tv] + ex_v)
                tc = np.concatenate([tc] + ex_c)
                toff = np.concatenate([toff] + ex_off)
                titem = np.concatenate([titem] + ex_item)
            # compact per target row, ordered by (source pos, item).
            # Hard target rows have no closed-form slots, so their
            # entries start at slot 0; far/easy target rows already
            # hold closed-form same-level entries in slots [0, k), so
            # their (cross-level) entries start at slot k.
            order = np.lexsort((titem, tc, tv))
            tv, tc, toff = tv[order], tc[order], toff[order]
            nT = len(tv)
            if nT:
                changed = np.empty(nT, dtype=bool)
                changed[0] = True
                changed[1:] = tv[1:] != tv[:-1]
                gstart = np.maximum.accumulate(np.where(changed, np.arange(nT), 0))
                tslot = np.arange(nT) - gstart
                tslot += np.where(is_hard_target[tv], 0, k)
                T_hard = cap(("T_hard", hid), int(tslot.max()) + 1)
            else:
                tslot = np.empty(0, dtype=np.int64)
                T_hard = 0
            T = max(k, T_hard, 1)
            # lazy materialization: these takes run after bind and land
            # on the owning plan's arena list
            to_rows = arena.take((n_dev * L, T), np.int32, fill=R - 1,
                                 owner=owned)
            to_offs = arena.take((n_dev * L, T, 3), np.int32, fill=0,
                                 owner=owned)
            to_mask = arena.take((n_dev * L, T), bool, fill=False,
                                 owner=owned)
            # far rows: to-neighbor at slot j is the level-0 cell at -o
            for j, o in enumerate(offs):
                ng, valid = maps.shift((-int(o[0]), -int(o[1]), -int(o[2])))
                vf = valid[far_slots]
                vv = np.nonzero(vf)[0]
                if len(vv):
                    rw = resolve_rows(pos0[ng[far_slots][vv]], far_dev[vv])
                    to_rows[far_rowidx[vv], j] = rw
                    to_mask[far_rowidx[vv], j] = True
                    to_offs[far_rowidx[vv], j] = (-o * size0).astype(np.int32)
            # easy rows: to-neighbor at slot j is the level-l cell at -o
            for blk, easy in blocks:
                ei, ridx = easy_rowidx[blk.level]
                edev = owner[blk.a + ei].astype(np.int64)
                for j, o in enumerate(offs):
                    pos_n, valid, exist = blk.lookup((-int(o[0]), -int(o[1]), -int(o[2])))
                    v = valid[ei]
                    vv = np.nonzero(v)[0]
                    if len(vv):
                        rw = resolve_rows(pos_n[ei[vv]], edev[vv])
                        to_rows[ridx[vv], j] = rw
                        to_mask[ridx[vv], j] = True
                        to_offs[ridx[vv], j] = (-o * blk.size).astype(np.int32)
            if nT:
                vdev = owner[tv].astype(np.int64)
                vrow = vdev * L + row_of_pos[tv]
                to_rows[vrow, tslot] = resolve_rows(tc, owner[tv])
                to_mask[vrow, tslot] = True
                to_offs[vrow, tslot] = toff.astype(np.int32)
            return (
                to_rows.reshape(n_dev, L, T),
                to_offs.reshape(n_dev, L, T, 3),
                to_mask.reshape(n_dev, L, T),
            )

        return thunk

    for hid, offs_in in neighborhoods.items():
        hood_data[hid]["to_thunk"] = make_to_thunk(hid, offs_in)

    layout = dict(
        local_ids=local_ids, ghost_ids=ghost_ids, n_local=n_local,
        n_inner=n_inner, L=L, R=R, row_of_pos=row_of_pos,
        scale_rows=scale_rows.reshape(n_dev, L),
    )
    return layout, hood_data
