"""Grid geometries: map cell ids/indices to physical coordinates.

Equivalents of the reference's L3 layer with a uniform interface
(get_start / get_end / get_level_0_cell_length / get_length /
get_center / get_min / get_max / get_cell / get_indices /
get_real_coordinate / file (de)serialization):

- ``NoGeometry``    — logical coords == physical (unit cells at origin),
  geometry id 0 (dccrg_no_geometry.hpp:46-558).
- ``CartesianGeometry`` — uniform cuboid cells from ``start`` +
  ``level_0_cell_length`` parameters, geometry id 1
  (dccrg_cartesian_geometry.hpp:51-813).
- ``StretchedCartesianGeometry`` — per-dimension monotone coordinate
  arrays (length+1 boundary values per dim), geometry id 2
  (dccrg_stretched_cartesian_geometry.hpp:48-830).

All coordinate queries are vectorized over arrays of cell ids and are
pure numpy on the host; ``DenseGrid``/Pallas hot paths derive their own
on-device coordinate arrays from these parameters instead of calling
back into Python.
"""

from __future__ import annotations

import struct

import numpy as np

from .mapping import Mapping
from .topology import GridTopology
from .types import ERROR_INDEX, as_cell_array

# batch size above which per-cell queries dispatch to the native C++
# kernels (below it, Python call overhead dominates the native win)
_NATIVE_BATCH = 4096


class _GeometryBase:
    """Shared implementation: everything derives from per-dimension
    level-0 cell boundary coordinates + uniform subdivision within a
    level-0 cell.

    The NumPy paths and the native kernels compute with the SAME
    formulas (same operation order), so results are bit-identical
    regardless of batch size or native availability — asserted by
    tests/test_native.py."""

    geometry_id: int = -1

    def __init__(self, mapping: Mapping, topology: GridTopology):
        self.mapping = mapping
        self.topology = topology

    # subclasses must provide level-0 boundary coordinate arrays,
    # one per dimension, each of length length[d]+1 (monotone increasing)
    def _boundaries(self, dimension: int) -> np.ndarray:
        raise NotImplementedError

    def _native(self, n: int):
        """The native module when available and worth dispatching to."""
        if n >= _NATIVE_BATCH:
            from . import native

            if native.lib is not None:
                return native
        return None

    # --- extents ------------------------------------------------------

    def get_start(self) -> np.ndarray:
        return np.array([self._boundaries(d)[0] for d in range(3)])

    def get_end(self) -> np.ndarray:
        return np.array([self._boundaries(d)[-1] for d in range(3)])

    # --- per-cell queries --------------------------------------------

    def _cell_level_and_l0(self, cells):
        """refinement level, level-0 index per dim, within-level-0 fractional
        position of min corner, and fractional extent, for each cell."""
        cells = as_cell_array(cells)
        lvl = np.atleast_1d(np.asarray(self.mapping.get_refinement_level(cells), np.int64))
        bad = lvl < 0
        lvl_safe = np.where(bad, 0, lvl)
        idx = np.atleast_2d(self.mapping.get_indices(np.where(bad, np.uint64(1), cells)))
        scale = np.uint64(1) << np.uint64(self.mapping.max_refinement_level)
        l0 = (idx // scale).astype(np.int64)  # level-0 cell index per dim
        # position within the level-0 cell, as a fraction in [0, 1)
        frac = (idx % scale).astype(np.float64) / float(scale)
        extent = 1.0 / (1 << lvl_safe).astype(np.float64)  # cell edge / level-0 edge
        return lvl, bad, l0, frac, extent

    def _min_and_length_flat(self, cells):
        """(min corner, edge lengths) in one structure pass (1-d input).

        Dispatches to the native C++ kernel for large batches (the
        geometry micro-benchmark hot path); NumPy is the reference
        implementation and fallback."""
        arr = np.atleast_1d(np.asarray(cells))
        native = self._native(len(arr))
        if native is not None:
            return native.geometry_min_len(
                self.mapping, [self._boundaries(d) for d in range(3)], arr
            )
        lvl, bad, l0, frac, extent = self._cell_level_and_l0(cells)
        mins = np.empty(l0.shape, dtype=np.float64)
        lens = np.empty(l0.shape, dtype=np.float64)
        for d in range(3):
            b = self._boundaries(d)
            lo = b[np.minimum(l0[:, d], len(b) - 2)]
            hi = b[np.minimum(l0[:, d] + 1, len(b) - 1)]
            mins[:, d] = lo + frac[:, d] * (hi - lo)
            lens[:, d] = (hi - lo) * extent
        mins[bad] = np.nan
        lens[bad] = np.nan
        return mins, lens

    def _min_and_length(self, cells):
        """N-d aware wrapper: results have shape cells.shape + (3,)."""
        arr = np.asarray(cells)
        scalar = np.isscalar(cells) or arr.ndim == 0
        flat = arr.reshape(-1)
        mins, lens = self._min_and_length_flat(flat)
        shape = ((1,) if scalar else arr.shape) + (3,)
        return mins.reshape(shape), lens.reshape(shape), scalar

    def get_min(self, cells) -> np.ndarray:
        """Min corner coordinate of each cell; NaN rows for invalid ids."""
        mins, _, scalar = self._min_and_length(cells)
        return mins[0] if scalar else mins

    def get_length(self, cells) -> np.ndarray:
        """Edge lengths of each cell; NaN rows for invalid ids."""
        _, lens, scalar = self._min_and_length(cells)
        return lens[0] if scalar else lens

    def get_max(self, cells) -> np.ndarray:
        mins, lens, scalar = self._min_and_length(cells)
        out = mins + lens
        return out[0] if scalar else out

    def get_center(self, cells) -> np.ndarray:
        arr = np.asarray(cells)
        scalar = np.isscalar(cells) or arr.ndim == 0
        flat = np.atleast_1d(arr).reshape(-1)
        native = self._native(len(flat))
        if native is not None:
            out = native.geometry_centers(
                self.mapping, [self._boundaries(d) for d in range(3)], flat
            )
        else:
            # same formula and operation order as dn_geometry_centers:
            # lo + (frac + extent/2) * (hi - lo)
            lvl, bad, l0, frac, extent = self._cell_level_and_l0(flat)
            out = np.empty(l0.shape, dtype=np.float64)
            for d in range(3):
                b = self._boundaries(d)
                lo = b[np.minimum(l0[:, d], len(b) - 2)]
                hi = b[np.minimum(l0[:, d] + 1, len(b) - 1)]
                out[:, d] = lo + (frac[:, d] + 0.5 * extent) * (hi - lo)
            out[bad] = np.nan
        out = out.reshape(((1,) if scalar else arr.shape) + (3,))
        return out[0] if scalar else out

    # --- coordinate -> cell ------------------------------------------

    def get_real_coordinate(self, coordinate) -> np.ndarray:
        """Wrap a coordinate into the grid under periodicity; NaN in
        non-periodic dimensions outside the grid
        (dccrg_cartesian_geometry.hpp:523-566)."""
        coordinate = np.asarray(coordinate, dtype=np.float64)
        scalar = coordinate.ndim == 1
        coord = np.atleast_2d(coordinate).copy()
        start, end = self.get_start(), self.get_end()
        for d in range(3):
            c = coord[:, d]
            inside = (c >= start[d]) & (c <= end[d])
            if self.topology.is_periodic(d):
                glen = end[d] - start[d]
                below = c < start[d]
                above = c > end[d]
                c = np.where(below, c + glen * np.ceil((start[d] - c) / glen), c)
                c = np.where(above, c - glen * np.ceil((c - end[d]) / glen), c)
                coord[:, d] = c
            else:
                coord[:, d] = np.where(inside, c, np.nan)
        return coord[0] if scalar else coord

    def get_indices_from_coordinate(self, coordinate) -> np.ndarray:
        """Smallest-cell indices of a coordinate; ERROR_INDEX outside
        (dccrg_cartesian_geometry.hpp:576-609).

        Intentional divergence from the reference: a coordinate exactly
        on the grid end clamps into the last cell here, whereas the
        reference's floor arithmetic produces an out-of-range index
        (and thus error_cell from get_cell) for that boundary point.
        """
        coordinate = np.asarray(coordinate, dtype=np.float64)
        scalar = coordinate.ndim == 1
        coord = np.atleast_2d(self.get_real_coordinate(coordinate))
        scale = 1 << self.mapping.max_refinement_level
        out = np.full(coord.shape, ERROR_INDEX, dtype=np.uint64)
        for d in range(3):
            b = self._boundaries(d)
            c = coord[:, d]
            ok = ~np.isnan(c)
            cc = np.where(ok, c, b[0])
            # level-0 cell containing the coordinate
            l0 = np.clip(np.searchsorted(b, cc, side="right") - 1, 0, len(b) - 2)
            lo, hi = b[l0], b[l0 + 1]
            sub = np.floor((cc - lo) / (hi - lo) * scale).astype(np.int64)
            sub = np.clip(sub, 0, scale - 1)
            out[:, d] = np.where(ok, (l0 * scale + sub).astype(np.uint64), ERROR_INDEX)
        return out[0] if scalar else out

    def get_cell(self, refinement_level, coordinate):
        """Cell of given refinement level at a physical location
        (dccrg_cartesian_geometry.hpp:497-508)."""
        indices = self.get_indices_from_coordinate(coordinate)
        return self.mapping.get_cell_from_indices(indices, refinement_level)

    # --- file format --------------------------------------------------

    def data_size(self) -> int:
        return len(self.to_bytes())

    def to_bytes(self) -> bytes:
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"{type(self).__name__}(id={self.geometry_id})"


class NoGeometry(_GeometryBase):
    """Logical coordinates: unit level-0 cells with the grid at the
    origin. Geometry id 0 (dccrg_no_geometry.hpp:55)."""

    geometry_id = 0

    def _boundaries(self, dimension: int) -> np.ndarray:
        n = int(self.mapping.length.get()[dimension])
        return np.arange(n + 1, dtype=np.float64)

    def to_bytes(self) -> bytes:
        return struct.pack("<i", self.geometry_id)

    def spec(self):
        """(kind, params) for Grid.set_geometry / reconstruction."""
        return "none", {}


class CartesianGeometry(_GeometryBase):
    """Uniform cuboid cells: ``start`` corner + ``level_0_cell_length``.
    Geometry id 1 (dccrg_cartesian_geometry.hpp:51-106)."""

    geometry_id = 1

    def __init__(self, mapping, topology, start=(0.0, 0.0, 0.0), level_0_cell_length=(1.0, 1.0, 1.0)):
        super().__init__(mapping, topology)
        self.set(start, level_0_cell_length)

    def set(self, start, level_0_cell_length) -> None:
        start = np.asarray(start, dtype=np.float64)
        l0len = np.asarray(level_0_cell_length, dtype=np.float64)
        if start.shape != (3,) or l0len.shape != (3,):
            raise ValueError("start and level_0_cell_length must be 3-vectors")
        if np.any(l0len <= 0):
            raise ValueError(f"level_0_cell_length must be > 0, got {l0len}")
        self.start = start.copy()
        self.level_0_cell_length = l0len.copy()
        self._len_tbl = None  # invalidate the per-level length cache

    def get_level_0_cell_length(self) -> np.ndarray:
        return self.level_0_cell_length.copy()

    def _boundaries(self, dimension: int) -> np.ndarray:
        n = int(self.mapping.length.get()[dimension])
        return self.start[dimension] + self.level_0_cell_length[dimension] * np.arange(
            n + 1, dtype=np.float64
        )

    # Faster closed-form overrides (no searchsorted / boundary arrays;
    # the geometry lookup throughputs in BASELINE.md hit these paths).

    def _length_table(self):
        """[max_ref_lvl + 1, 3] edge lengths per level (tiny, cached)."""
        tbl = getattr(self, "_len_tbl", None)
        n = self.mapping.max_refinement_level + 1
        if tbl is None or tbl.shape[0] != n:
            tbl = self.level_0_cell_length[None, :] / (
                1 << np.arange(n, dtype=np.int64)
            ).astype(np.float64)[:, None]
            self._len_tbl = tbl
        return tbl

    def get_length(self, cells) -> np.ndarray:
        """Edge lengths from the refinement level alone — uniform cells
        need no index math (cf. dccrg_cartesian_geometry.hpp:226-280).
        NumPy and native paths read the same per-level table, so they
        are bit-identical."""
        arr = np.asarray(cells)
        scalar = np.isscalar(cells) or arr.ndim == 0
        flat = as_cell_array(arr.reshape(-1))
        native = self._native(len(flat))
        if native is not None:
            lens = native.cell_lengths(self.mapping, self._length_table(), flat)
        else:
            lvl = np.atleast_1d(
                np.asarray(self.mapping.get_refinement_level(flat), np.int64)
            )
            bad = lvl < 0
            lens = self._length_table()[np.where(bad, 0, lvl)]
            if bad.any():
                lens[bad] = np.nan
        out = lens.reshape(((1,) if scalar else arr.shape) + (3,))
        return out[0] if scalar else out

    def to_bytes(self) -> bytes:
        return struct.pack("<i", self.geometry_id) + self.start.tobytes() + self.level_0_cell_length.tobytes()

    def spec(self):
        """(kind, params) for Grid.set_geometry / reconstruction."""
        return "cartesian", {
            "start": tuple(float(v) for v in self.start),
            "level_0_cell_length": tuple(float(v) for v in self.level_0_cell_length),
        }


class StretchedCartesianGeometry(_GeometryBase):
    """Per-dimension monotone coordinate arrays: dimension d has
    ``length[d] + 1`` boundary values; level-0 cell i spans
    ``[coords[d][i], coords[d][i+1]]``, refined cells subdivide that
    span uniformly. Geometry id 2
    (dccrg_stretched_cartesian_geometry.hpp:48-210)."""

    geometry_id = 2

    def __init__(self, mapping, topology, coordinates=None):
        super().__init__(mapping, topology)
        if coordinates is None:
            # default: unit cells (same as NoGeometry)
            coordinates = [
                np.arange(int(mapping.length.get()[d]) + 1, dtype=np.float64) for d in range(3)
            ]
        self.set(coordinates)

    def set(self, coordinates) -> None:
        # copy: external mutation must not bypass monotonicity validation
        coords = [np.array(c, dtype=np.float64) for c in coordinates]
        if len(coords) != 3:
            raise ValueError("need one coordinate array per dimension")
        for d in range(3):
            expect = int(self.mapping.length.get()[d]) + 1
            if coords[d].ndim != 1 or len(coords[d]) != expect:
                raise ValueError(
                    f"dimension {d}: need {expect} coordinates "
                    f"(length+1), got {coords[d].shape}"
                )
            if np.any(np.diff(coords[d]) <= 0):
                raise ValueError(f"dimension {d}: coordinates must be strictly increasing")
        self.coordinates = coords

    @classmethod
    def from_cartesian(cls, geom: CartesianGeometry) -> "StretchedCartesianGeometry":
        """Clone a Cartesian geometry
        (dccrg_stretched_cartesian_geometry.hpp:223-251)."""
        coords = [geom._boundaries(d) for d in range(3)]
        return cls(geom.mapping, geom.topology, coords)

    def _boundaries(self, dimension: int) -> np.ndarray:
        return self.coordinates[dimension]

    def to_bytes(self) -> bytes:
        # id, 3 x u64 coordinate counts, then the coordinate arrays —
        # byte-identical to the reference's record
        # (dccrg_stretched_cartesian_geometry.hpp:652-713)
        out = [struct.pack("<i", self.geometry_id),
               struct.pack("<3Q", *(len(self.coordinates[d])
                                    for d in range(3)))]
        for d in range(3):
            out.append(self.coordinates[d].tobytes())
        return b"".join(out)

    def spec(self):
        """(kind, params) for Grid.set_geometry / reconstruction."""
        return "stretched", {"coordinates": [c.copy() for c in self.coordinates]}


def geometry_from_buffer(data, offset: int, mapping: Mapping,
                         topology: GridTopology):
    """Parse the geometry record starting at ``offset``: returns
    ``(geometry, record_size)``. The record is self-describing via its
    id — NO length prefix, exactly the reference's layout (geometry
    ids per dccrg_no_geometry.hpp:55, dccrg_cartesian_geometry.hpp:106,
    dccrg_stretched_...hpp:78; write sequences :620-672 and
    :652-713)."""
    (gid,) = struct.unpack_from("<i", data, offset)
    if gid == 0:
        return NoGeometry(mapping, topology), 4
    if gid == 1:
        vals = np.frombuffer(data, dtype=np.float64, count=6,
                             offset=offset + 4)
        return CartesianGeometry(mapping, topology, vals[:3], vals[3:]), 52
    if gid == 2:
        counts = struct.unpack_from("<3Q", data, offset + 4)
        coords = []
        off = offset + 4 + 24
        for d in range(3):
            n = int(counts[d])
            coords.append(np.frombuffer(data, dtype=np.float64, count=n,
                                        offset=off).copy())
            off += 8 * n
        return (StretchedCartesianGeometry(mapping, topology, coords),
                off - offset)
    raise ValueError(f"unknown geometry id {gid}")


def geometry_from_bytes(data: bytes, mapping: Mapping, topology: GridTopology):
    """Reconstruct a geometry from exactly its file record (inverse of
    ``to_bytes``)."""
    geom, _size = geometry_from_buffer(data, 0, mapping, topology)
    return geom
