"""Cell-to-device partitioning: the Zoltan replacement.

The reference delegates partitioning to Zoltan (RCB / RIB / HSFC /
graph / hypergraph, dccrg.hpp:8482-8720) plus optional Hilbert-SFC
initial placement (dccrg.hpp:8147-8220). On TPU the partition maps
cells to mesh devices; we provide:

- ``block``  — contiguous equal-count ranges of cell-id order (the
  reference's default initial placement, dccrg.hpp:8089-8146),
- ``morton`` / ``hilbert`` — space-filling-curve order for locality
  (the HSFC/USE_SFC equivalent; Hilbert via the classic transpose
  algorithm),
- ``rcb`` — recursive coordinate bisection (Zoltan RCB),
- ``cut`` — connectivity-aware: RCB boxes refined by a greedy
  majority-neighbor sweep over the real neighbor edges (the role of
  Zoltan PHG's ``PHG_CUT_OBJECTIVE=CONNECTIVITY``, the reference's
  hierarchical default, dccrg.hpp:7834-7842),
- optional per-cell weights (``set_cell_weight`` semantics,
  dccrg.hpp:6318-6380): cuts equalize total weight instead of count,
- pin requests (``pin()`` semantics, dccrg.hpp:5913-6139): forced
  placements applied after the automatic partition.

All functions are host-side numpy; they run at structure-change events
only.
"""

from __future__ import annotations

import numpy as np

from . import faults
from .mapping import Mapping

PARTITION_METHODS = ("block", "morton", "hilbert", "rcb", "cut")


def refine_cut(owner, w, src, dst, n_parts, rounds=8, tol=1.1):
    """Greedy connectivity refinement (the role of Zoltan PHG's
    ``PHG_CUT_OBJECTIVE=CONNECTIVITY``, the reference's hierarchical
    default, dccrg.hpp:7834-7842): sweep cells whose neighbors are
    majority-remote to the device owning the majority, highest gain
    first, while every destination stays under ``tol`` x the balanced
    load; a source whose load has fallen to the ``(2 - tol)`` x floor
    stops being pulled from (loads update between destination sweeps,
    so the floor is respected to within one destination's headroom).
    ``src``/``dst`` are cell positions of the neighbor edges (both
    directions counted as given). Each sweep is vectorized over the
    boundary set only — O(cut surface x n_parts) memory, never
    O(grid x n_parts)."""
    owner = np.asarray(owner, dtype=np.int32).copy()
    n = len(owner)
    if n == 0 or len(src) == 0 or n_parts == 1:
        return owner
    src = np.asarray(src, dtype=np.int64)
    dst = np.asarray(dst, dtype=np.int64)
    w = np.asarray(w, dtype=np.float64)
    target = w.sum() / n_parts
    hi_cap, lo_cap = target * tol, target * (2.0 - tol)
    for _ in range(rounds):
        # only cells with at least one cross-part edge can gain: the
        # per-part neighbor counts are built over that boundary set, so
        # memory is O(cut surface x n_parts), never O(grid x n_parts)
        cross = owner[src] != owner[dst]
        comp = np.full(n, -1, dtype=np.int64)
        cidx = np.unique(src[cross])
        if len(cidx) == 0:
            break
        comp[cidx] = np.arange(len(cidx))
        esel = comp[src] >= 0
        cm = np.bincount(
            comp[src[esel]] * n_parts + owner[dst[esel]],
            minlength=len(cidx) * n_parts,
        ).reshape(len(cidx), n_parts)
        ar = np.arange(len(cidx))
        best = np.argmax(cm, axis=1).astype(np.int32)
        gain = cm[ar, best] - cm[ar, owner[cidx]]
        load = np.bincount(owner, weights=w, minlength=n_parts)
        keep = (gain > 0) & (best != owner[cidx])
        cand = cidx[keep]
        cbest = best[keep]
        cgain = gain[keep]
        if len(cand) == 0:
            break
        order = np.argsort(-cgain, kind="stable")
        cand, cbest = cand[order], cbest[order]
        moved = 0
        for d in range(n_parts):
            sel = cand[cbest == d]
            if len(sel) == 0:
                continue
            # loads are updated between destinations, so a source
            # pulled from by several destinations in one sweep still
            # respects the (2 - tol) floor
            sel = sel[load[owner[sel]] > lo_cap]
            room = hi_cap - load[d]
            if room <= 0 or len(sel) == 0:
                continue
            take = sel[: np.searchsorted(np.cumsum(w[sel]), room, "right")]
            if len(take):
                np.subtract.at(load, owner[take], w[take])
                load[d] += w[take].sum()
                owner[take] = d
                moved += len(take)
        if moved == 0:
            break
    return _swap_pass(owner, w, src, dst, n_parts, hi_cap, lo_cap)


def _swap_pass(owner, w, src, dst, n_parts, hi_cap, lo_cap, rounds=4,
               max_swaps=50000):
    """KL-style boundary exchange after the greedy sweep (the tail of
    Zoltan PHG's refinement, dccrg.hpp:7834-7842): the greedy pass only
    MOVES cells with strict-majority gain, so tied boundaries — e.g. a
    jagged interface where each cell individually gains nothing — stay
    put. Swapping a cross-edge PAIR (a in p, b in q -> a in q, b in p)
    keeps loads balanced to |w[b] - w[a]| and can still reduce the cut:
    pair gain = gain(a->q) + gain(b->p) - 2 x (a,b multiplicity), the
    classic Kernighan-Lin correction. Gains are exact at the start of
    each round; within a round a used-mask keeps swapped cells (whose
    neighbors' gains went stale) from moving twice, and a round that
    fails to reduce the total cut is reverted, so the pass can never
    hand back a worse partition."""
    n = len(owner)
    if n == 0 or len(src) == 0 or n_parts == 1:
        return owner
    for _ in range(rounds):
        cross = owner[src] != owner[dst]
        cut_before = int(cross.sum())
        if cut_before == 0:
            break
        comp = np.full(n, -1, dtype=np.int64)
        cidx = np.unique(src[cross])  # both directions present
        comp[cidx] = np.arange(len(cidx))
        esel = comp[src] >= 0
        cm = np.bincount(
            comp[src[esel]] * n_parts + owner[dst[esel]],
            minlength=len(cidx) * n_parts,
        ).reshape(len(cidx), n_parts)
        # undirected cross pairs with (directed) multiplicity
        a, b = src[cross], dst[cross]
        key = np.minimum(a, b) * n + np.maximum(a, b)
        uk, mult = np.unique(key, return_counts=True)
        ua, ub = uk // n, uk % n
        m_dir = mult // 2  # each undirected adjacency is listed twice
        p, q = owner[ua], owner[ub]
        g = ((cm[comp[ua], q] - cm[comp[ua], p])
             + (cm[comp[ub], p] - cm[comp[ub], q])
             - 2 * m_dir)
        sel = g > 0
        if not sel.any():
            break
        ua, ub, g = ua[sel], ub[sel], g[sel]
        order = np.argsort(-g, kind="stable")[:max_swaps]
        prev_owner = owner.copy()
        load = np.bincount(owner, weights=w, minlength=n_parts)
        used = np.zeros(n, dtype=bool)
        swapped = 0
        for i in order:
            A, B = ua[i], ub[i]
            if used[A] or used[B]:
                continue
            pp, qq = owner[A], owner[B]
            if pp == qq:
                continue
            dl = w[B] - w[A]
            # equal-weight swaps never change the balance, so they are
            # legal even when a load already sits outside the band
            if dl != 0 and not (lo_cap <= load[pp] + dl <= hi_cap
                                and lo_cap <= load[qq] - dl <= hi_cap):
                continue
            owner[A], owner[B] = qq, pp
            load[pp] += dl
            load[qq] -= dl
            used[A] = used[B] = True
            swapped += 1
        if swapped == 0:
            break
        if int((owner[src] != owner[dst]).sum()) >= cut_before:
            # stale-gain conflicts made the round a wash: revert
            owner = prev_owner
            break
    return owner


def _index_centers(mapping: Mapping, cells: np.ndarray) -> np.ndarray:
    """Cell centers in smallest-cell index units (geometry-free: RCB
    cuts in index space, which is affine to any of the geometries'
    physical space per dimension)."""
    idx = np.atleast_2d(mapping.get_indices(np.asarray(cells, dtype=np.uint64)))
    size = np.atleast_1d(mapping.get_cell_length_in_indices(np.asarray(cells, dtype=np.uint64)))
    return idx.astype(np.float64) + size.astype(np.float64)[:, None] / 2


def _rcb_assign(centers: np.ndarray, shares, w: np.ndarray):
    """Recursive coordinate bisection (Zoltan's RCB, the cut-minimizing
    geometric partitioner the reference exposes via LB_METHOD=RCB,
    dccrg.hpp:5629-5880): recursively split at the weighted median of
    the widest extent, producing compact boxes whose surface — the
    halo traffic — stays near-minimal on refined grids too.

    Returns the part index (into ``shares``) per row of ``centers``."""
    out = np.zeros(len(centers), dtype=np.int64)
    shares = np.asarray(shares, dtype=np.float64)

    def rec(sel, lo, hi):
        if hi - lo == 1 or len(sel) == 0:
            out[sel] = lo
            return
        mid = (lo + hi) // 2
        span = shares[lo:hi].sum()
        frac = shares[lo:mid].sum() / span if span > 0 else 0.5
        c = centers[sel]
        d = int(np.argmax(c.max(axis=0) - c.min(axis=0)))
        order = np.argsort(c[:, d], kind="stable")
        ww = w[sel][order]
        if ww.sum() <= 0:
            ww = np.ones(len(ww), dtype=np.float64)
        cum = np.cumsum(ww)
        k = int(np.searchsorted(cum - ww / 2, frac * cum[-1], side="left"))
        rec(sel[order[:k]], lo, mid)
        rec(sel[order[k:]], mid, hi)

    rec(np.arange(len(centers)), 0, len(shares))
    return out


def morton_key(mapping: Mapping, cells: np.ndarray) -> np.ndarray:
    """Morton (z-order) key of each cell's min corner, bit-interleaved
    at smallest-cell resolution. Keys of nested cells sort adjacently,
    so contiguous key ranges are compact blocks."""
    idx = np.atleast_2d(mapping.get_indices(np.asarray(cells, dtype=np.uint64)))
    bits = max(int(x).bit_length() for x in mapping.get_index_length())
    if 3 * bits > 63:
        raise ValueError("grid too large for 63-bit Morton keys")
    from . import native

    if native.lib is not None:
        return native.sfc_keys(idx, bits, "morton")
    key = np.zeros(len(idx), dtype=np.uint64)
    for b in range(bits):
        for d in range(3):
            key |= ((idx[:, d] >> np.uint64(b)) & np.uint64(1)) << np.uint64(3 * b + d)
    return key


def hilbert_key(mapping: Mapping, cells: np.ndarray) -> np.ndarray:
    """Hilbert-curve key of each cell's min corner (3-D, transpose
    algorithm), the locality-preserving order the reference gets from
    the optional sfc++ library (dccrg.hpp:62-64, 8147-8220)."""
    idx = np.atleast_2d(mapping.get_indices(np.asarray(cells, dtype=np.uint64))).astype(np.uint64)
    bits = max(int(x).bit_length() for x in mapping.get_index_length())
    if 3 * bits > 63:
        raise ValueError("grid too large for 63-bit Hilbert keys")
    from . import native

    if native.lib is not None:
        return native.sfc_keys(idx, bits, "hilbert")
    x = idx.copy()  # [n, 3] "transpose" form, modified in place
    n = np.uint64(1) << np.uint64(bits)
    # Gray-decode: inverse undo excess work (Skilling's algorithm)
    m = n >> np.uint64(1)
    q = np.uint64(m)
    while q > 1:
        p = np.uint64(q - 1)
        for i in range(3):
            has = (x[:, i] & q) != 0
            # invert low bits of x[0] where bit set
            x[:, 0] = np.where(has, x[:, 0] ^ p, x[:, 0])
            # exchange low bits of x[i] and x[0] where bit unset
            tt = np.where(~has, (x[:, 0] ^ x[:, i]) & p, np.uint64(0))
            x[:, 0] ^= tt
            x[:, i] ^= tt
        q >>= np.uint64(1)
    # Gray encode
    for i in range(1, 3):
        x[:, i] ^= x[:, i - 1]
    t = np.zeros(len(x), dtype=np.uint64)
    q = np.uint64(m)
    while q > 1:
        has = (x[:, 2] & q) != 0
        t = np.where(has, t ^ np.uint64(q - 1), t)
        q >>= np.uint64(1)
    for i in range(3):
        x[:, i] ^= t
    # interleave transpose-form coordinates into the key (MSB first,
    # dimension 0 contributes the highest bit of each group)
    key = np.zeros(len(x), dtype=np.uint64)
    for b in range(bits - 1, -1, -1):
        for d in range(3):
            key = (key << np.uint64(1)) | ((x[:, d] >> np.uint64(b)) & np.uint64(1))
    return key


def _split_by_weight(order, w, shares):
    """Cut ``order`` (cell positions in curve order) into len(shares)
    contiguous runs with cumulative weight proportional to ``shares``
    (device counts per part). Returns the part index per position in
    ``order``."""
    n = len(order)
    part = np.zeros(n, dtype=np.int64)
    if n == 0 or len(shares) <= 1:
        return part
    wo = w[order]
    if wo.sum() <= 0:  # all-zero weights: fall back to equal counts
        wo = np.ones(n, dtype=np.float64)
    cum = np.cumsum(wo)
    total = cum[-1]
    bounds = np.cumsum(np.asarray(shares, dtype=np.float64))
    bounds = bounds / bounds[-1] * max(total, 1e-300)
    mid = cum - wo / 2
    part = np.searchsorted(bounds, mid, side="right")
    return np.minimum(part, len(shares) - 1)


def partition_cells_hierarchical(
    mapping: Mapping,
    cells: np.ndarray,
    n_parts: int,
    levels,
    weights: np.ndarray | None = None,
    pins: dict | None = None,
    edges=None,
) -> np.ndarray:
    """Hierarchical partition (Zoltan hierarchical replacement,
    dccrg.hpp:5629-5880): each level splits every current device group
    into sub-groups of ``processes`` devices using that level's curve
    method. On TPU the natural hierarchy is (host, chip): e.g. levels
    ``[{"processes": 4, "method": "block"}, {"processes": 1, "method":
    "hilbert"}]`` first cuts coarse blocks across hosts, then
    Hilbert-orders within each host's chips.

    ``levels``: list of dicts with keys ``processes`` (devices per part
    after this level's split) and optional ``method``.
    """
    cells = np.asarray(cells, dtype=np.uint64)
    n = len(cells)
    if weights is None:
        w = np.ones(n, dtype=np.float64)
    else:
        w = np.asarray(weights, dtype=np.float64)

    # groups: list of (device_lo, device_hi, cell positions array)
    groups = [(0, n_parts, np.arange(n))]
    plan_levels = [dict(lv) for lv in levels]
    if not plan_levels or int(plan_levels[-1].get("processes", 1)) != 1:
        plan_levels.append({"processes": 1})  # finish at single devices

    for lv in plan_levels:
        per = max(1, int(lv.get("processes", 1)))
        method = lv.get("method", "morton")
        if method not in PARTITION_METHODS:
            raise ValueError(f"unknown partition method {method!r}")
        next_groups = []
        for lo, hi, pos in groups:
            span = hi - lo
            if span <= per:
                next_groups.append((lo, hi, pos))
                continue
            shares = [per] * (span // per) + ([span % per] if span % per else [])
            sub = cells[pos]
            if method in ("rcb", "cut"):
                assign = _rcb_assign(_index_centers(mapping, sub), shares, w[pos])
                if (method == "cut" and edges is not None and len(pos) > 1
                        and len(set(shares)) == 1):
                    # refine within this group over the edges whose
                    # both endpoints belong to it (local positions via
                    # the sorted group index); refine_cut balances to
                    # equal targets, so only equal device shares refine
                    sp = np.sort(pos)
                    at = np.searchsorted(sp, pos)
                    loc_s = np.searchsorted(sp, edges[0])
                    loc_d = np.searchsorted(sp, edges[1])
                    loc_s_c = np.minimum(loc_s, len(sp) - 1)
                    loc_d_c = np.minimum(loc_d, len(sp) - 1)
                    m = (sp[loc_s_c] == edges[0]) & (sp[loc_d_c] == edges[1])
                    a_sorted = np.empty(len(sp), dtype=np.int32)
                    a_sorted[at] = assign.astype(np.int32)
                    refined = refine_cut(a_sorted, w[sp], loc_s_c[m],
                                         loc_d_c[m], len(shares))
                    assign = refined[at]
                parts = [pos[assign == pi] for pi in range(len(shares))]
            else:
                if method == "block":
                    curve = np.argsort(sub, kind="stable")
                elif method == "morton":
                    curve = np.argsort(morton_key(mapping, sub), kind="stable")
                else:
                    curve = np.argsort(hilbert_key(mapping, sub), kind="stable")
                part_in_order = _split_by_weight(pos[curve], w, shares)
                parts = [pos[curve[part_in_order == pi]] for pi in range(len(shares))]
            dev_lo = lo
            for pi, share in enumerate(shares):
                next_groups.append((dev_lo, dev_lo + share, parts[pi]))
                dev_lo += share
        groups = next_groups

    owner = np.empty(n, dtype=np.int32)
    for lo, hi, pos in groups:
        owner[pos] = lo  # hi == lo + 1 after the final level
    if pins:
        for cid, dest in pins.items():
            p = np.searchsorted(cells, np.uint64(cid))
            if p < n and cells[p] == np.uint64(cid):
                if not 0 <= int(dest) < n_parts:
                    raise ValueError(f"pin of cell {cid} to invalid device {dest}")
                owner[p] = int(dest)
    return owner


def partition_cells(
    mapping: Mapping,
    cells: np.ndarray,
    n_parts: int,
    method: str = "morton",
    weights: np.ndarray | None = None,
    pins: dict | None = None,
    edges=None,
) -> np.ndarray:
    """Owner (device index) for each cell.

    Contiguous ranges in the chosen order, cut at equal cumulative
    weight; ``pins`` (cell id -> device) override afterwards, matching
    the reference's pin-after-Zoltan merge (dccrg.hpp:8552-8576).

    ``method="cut"`` is the connectivity-aware option (Zoltan
    graph/hypergraph role): RCB compact boxes refined by
    :func:`refine_cut` over the neighbor ``edges`` — a ``(src_pos,
    dst_pos)`` pair of cell-position arrays, supplied by the grid from
    its existing neighbor lists at balance time. Without edges (fresh
    initialize, before any neighbor engine ran) it degrades to plain
    RCB.
    """
    cells = np.asarray(cells, dtype=np.uint64)
    n = len(cells)
    if method not in PARTITION_METHODS:
        raise ValueError(f"unknown partition method {method!r}, have {PARTITION_METHODS}")
    faults.fire("partition.compute", mode=method)

    if weights is not None:
        w = np.asarray(weights, dtype=np.float64)
        if w.shape != (n,):
            raise ValueError(f"weights must have shape ({n},), got {w.shape}")
        if np.any(w < 0):
            raise ValueError("cell weights must be >= 0")

    if n_parts == 1:
        return np.zeros(n, dtype=np.int32)  # nothing to order or cut
    if weights is None:
        w = np.ones(n, dtype=np.float64)

    if method in ("rcb", "cut"):
        centers = _index_centers(mapping, cells)
        owner = _rcb_assign(centers, [1] * n_parts, w).astype(np.int32)
        if method == "cut" and edges is not None:
            owner = refine_cut(owner, w, edges[0], edges[1], n_parts)
    else:
        if method == "block":
            order = np.arange(n)
        elif method == "morton":
            order = np.argsort(morton_key(mapping, cells), kind="stable")
        else:
            order = np.argsort(hilbert_key(mapping, cells), kind="stable")

        cum = np.cumsum(w[order])
        total = cum[-1] if n else 0.0
        owner_in_order = (
            np.minimum((cum - w[order] / 2) / max(total, 1e-300) * n_parts, n_parts - 1)
        ).astype(np.int32) if n else np.empty(0, np.int32)
        owner = np.empty(n, dtype=np.int32)
        owner[order] = owner_in_order

    if pins:
        pin_ids = np.array(sorted(pins.keys()), dtype=np.uint64)
        pos = np.searchsorted(cells, pin_ids)
        ok = (pos < n) & (cells[np.minimum(pos, n - 1)] == pin_ids)
        for pid, p in zip(pin_ids[ok], pos[ok]):
            dest = int(pins[int(pid)])
            if not 0 <= dest < n_parts:
                raise ValueError(f"pin of cell {pid} to invalid device {dest}")
            owner[p] = dest
    return owner
