"""Fleet execution layer: batched many-grid multiplexing.

The production story for this framework is not one 512^3 grid — it is
THOUSANDS of concurrent small/medium scenario runs per chip (the
reference dccrg is the grid layer of simulation codes launched as
fleets of independent runs). On an accelerator the idiomatic form is a
**batch axis over same-shape grids**: N independent uniform grids are
stacked along a leading batch dimension into ONE jitted device program
(a ``jax.vmap`` of the single-grid step over the stacked field
arrays), so N scenarios share one compile, one dispatch, and one HBM
residency pass per step instead of N.

:class:`GridBatch` is that execution layer. Jobs are **bucketed** by
``(shape, periodicity, field schema, step kernel, #params)`` — the
same shape-keyed discipline as the grid's compiled-program caches — so
wildly different scenarios (different dt, seeds, step counts,
priorities) land in shared compiles; per-job parameters (dt etc.)
ride as batched scalars through the vmap. Batch capacities are
rounded with :func:`~dccrg_tpu.grid.bucket_capacity` so a drained,
backfilled bucket keeps its program.

**Per-job isolation** is the contract that makes a multi-tenant batch
safe (pinned by tests/test_fleet.py):

- the numerics watchdog is evaluated **per batch slot**
  (:meth:`GridBatch.finite_slots` — one ``[B]`` bool vector, one
  device round-trip for the whole fleet);
- NaN trips, injected OOMs and requeues touch ONLY the tripped slot:
  a slot rolls back from its own per-job checkpoint
  (:func:`dccrg_tpu.resilience.load_checkpoint_into` into the
  bucket's scratch grid, scattered into the slot) while every other
  slot's bits are untouched — the vmapped step has no cross-batch
  ops, and slot updates go through per-slot selects that preserve
  neighbor bytes exactly;
- a job's fleet-run final state is **bitwise identical** to running
  it alone (``Grid.run_steps``), because the batched gather delivers
  the same neighbor bytes the grid's own stencil paths do.

The job queue, admission, drain/backfill, per-job checkpoint stems,
preemption and retention GC live in
:class:`dccrg_tpu.scheduler.FleetScheduler`; ``python -m
dccrg_tpu.fleet`` runs a job file through it (see
:func:`_main`). Env knobs: ``DCCRG_FLEET_MAX_BATCH`` (slots per
bucket, default 128), ``DCCRG_FLEET_QUANTUM`` (steps per batched
dispatch between scheduler polls, default 8).
"""

from __future__ import annotations

import hashlib
import logging
import os

import numpy as np

import jax
import jax.numpy as jnp

from . import checkpoint as checkpoint_mod
from . import faults, integrity, warmstart
from .grid import DEFAULT_NEIGHBORHOOD_ID, Grid, default_mesh

logger = logging.getLogger("dccrg_tpu.fleet")

#: slot sentinel: a DMR shadow replica of the job in
#: ``GridBatch.shadow_of[slot]`` — occupies a slot (so admission
#: cannot reuse it) without being a schedulable job itself
SHADOW = type("_ShadowSlot", (), {"__repr__": lambda s: "<shadow>"})()


def max_batch_default(default: int = 128) -> int:
    """The ``DCCRG_FLEET_MAX_BATCH`` env knob: maximum batch slots per
    bucket (one bucket = one compiled device program)."""
    try:
        return max(1, int(os.environ.get("DCCRG_FLEET_MAX_BATCH", "")
                          or default))
    except ValueError:
        return default


def quantum_default(default: int = 8) -> int:
    """The ``DCCRG_FLEET_QUANTUM`` env knob: steps per batched
    dispatch between scheduler polls. Larger quanta amortize dispatch
    overhead; smaller quanta tighten the watchdog/checkpoint/preempt
    poll cadence (all of which run at quantum boundaries)."""
    try:
        return max(1, int(os.environ.get("DCCRG_FLEET_QUANTUM", "")
                          or default))
    except ValueError:
        return default


# ---------------------------------------------------------------------
# the step-kernel registry (the CLI's serializable kernel names)
# ---------------------------------------------------------------------

FLEET_KERNELS: dict = {}


class JobSpecError(ValueError):
    """A job record that can NEVER become a valid :class:`FleetJob`
    (missing name, malformed lengths, ...). A ValueError subclass so
    pre-existing job-file handling keeps working; typed so the
    streaming-intake front door can quarantine the record with a
    structured reason instead of retrying a permanent failure."""


class UnknownKernelError(KeyError):
    """A job names a kernel the registry (including the lazily
    imported model zoo) does not know. A KeyError subclass for
    backward compatibility; typed so admission-time validation can
    classify it as a permanent (quarantine) fault rather than a
    transient one."""

    def __init__(self, job: str, kernel, registered):
        self.job = str(job)
        self.kernel = kernel
        self.registered = sorted(registered)
        super().__init__(
            f"job {self.job!r}: unknown kernel {kernel!r} "
            f"(registered: {self.registered})")

    def __str__(self) -> str:  # KeyError quotes its arg; keep prose
        return self.args[0]


def register_kernel(name: str, fn) -> None:
    """Register a grid step kernel under a name job files can
    reference. The kernel has the standard grid-kernel signature
    ``kernel(cell_fields, nbr_fields, offs, mask, *params) ->
    {field: new_values}`` with per-job ``params`` as scalars."""
    FLEET_KERNELS[str(name)] = fn


# per-kernel job defaults: schema, field lists, params and a seeded
# default init — what lets a job file (or a bare FleetJob("x",
# kernel="mhd")) name a model-zoo kernel without spelling out its
# 8-field schema. Registered by dccrg_tpu.models on import.
FLEET_KERNEL_SPECS: dict = {}


def register_kernel_spec(name: str, *, cell_data, fields_in,
                         fields_out, params=(0.1,), init=None) -> None:
    """Register the job defaults of a named kernel: its ``cell_data``
    schema, ``fields_in``/``fields_out`` lists, default ``params``
    and (optionally) a seeded default init ``fn(grid, seed)`` used in
    place of :func:`seeded_random_init` (kernels with positivity or
    stability preconditions — MHD needs positive pressure — register
    one so the generic random fill never feeds them garbage)."""
    FLEET_KERNEL_SPECS[str(name)] = {
        "cell_data": dict(cell_data),
        "fields_in": tuple(fields_in),
        "fields_out": tuple(fields_out),
        "params": tuple(float(p) for p in params),
        "init": init,
    }


def _kernel_spec(name: str):
    """The registered spec for a kernel name, lazily importing the
    model zoo once on a miss (importing ``dccrg_tpu.models`` is what
    registers the zoo kernels)."""
    spec = FLEET_KERNEL_SPECS.get(name)
    if spec is None and name not in FLEET_KERNELS:
        from . import models  # noqa: F401 - registers the zoo

        spec = FLEET_KERNEL_SPECS.get(name)
    return spec


def _diffuse_kernel(c, nbr, offs, mask, dt):
    """Explicit neighbor-coupling relaxation of ``rho`` (the bench/
    fuzz workhorse): rho += dt * sum_nbr (rho_nbr - rho)."""
    rho = c["rho"]
    s = jnp.sum(jnp.where(mask, nbr["rho"], 0.0), axis=1)
    deg = jnp.sum(mask, axis=1).astype(rho.dtype)
    return {"rho": rho + dt * (s - deg * rho)}


def _advect_x_kernel(c, nbr, offs, mask, cfl):
    """First-order upwind advection of ``rho`` along +x, selecting the
    upwind neighbor through the slot offsets."""
    up = (offs[..., 0] < 0) & (offs[..., 1] == 0) & (offs[..., 2] == 0)
    upv = jnp.sum(jnp.where(up & mask, nbr["rho"], 0.0), axis=1)
    return {"rho": (1.0 - cfl) * c["rho"] + cfl * upv}


register_kernel("diffuse", _diffuse_kernel)
register_kernel("advect_x", _advect_x_kernel)


# Bulk-executor (DCCRG_BULK=pallas) variants: the roll-plan Pallas
# executor consumes SlotwiseKernel flux functions (one stencil leg at
# a time), so registry names that should be bulk-capable register a
# slot-wise twin here. Slot accumulation re-associates the neighbor
# sum, so a bulk bucket matches its table-gather twin to float
# re-association (the parity suite uses allclose, not digests).
FLEET_BULK_KERNELS: dict = {}


def register_bulk_kernel(name: str, slotwise) -> None:
    """Register the SlotwiseKernel twin of a named step kernel; a
    GridBatch bucket whose job names this kernel can then select the
    roll-plan Pallas bulk executor under ``DCCRG_BULK=pallas``."""
    FLEET_BULK_KERNELS[str(name)] = slotwise


def _make_diffuse_slotwise():
    from .grid import SlotwiseKernel

    def init(c, dt):
        return jnp.zeros(c["rho"].shape, c["rho"].dtype)

    def slot(acc, c, nbr, offs, mask, dt):
        return acc + jnp.where(mask, nbr["rho"] - c["rho"], 0.0)

    def finish(acc, c, dt):
        return {"rho": c["rho"] + dt * acc}

    return SlotwiseKernel(init, slot, finish)


def _make_advect_x_slotwise():
    from .grid import SlotwiseKernel

    def init(c, cfl):
        return jnp.zeros(c["rho"].shape, c["rho"].dtype)

    def slot(acc, c, nbr, offs, mask, cfl):
        up = (offs[..., 0] < 0) & (offs[..., 1] == 0) & (offs[..., 2] == 0)
        return acc + jnp.where(up & mask, nbr["rho"], 0.0)

    def finish(acc, c, cfl):
        return {"rho": (1.0 - cfl) * c["rho"] + cfl * acc}

    return SlotwiseKernel(init, slot, finish)


register_bulk_kernel("diffuse", _make_diffuse_slotwise())
register_bulk_kernel("advect_x", _make_advect_x_slotwise())


# ---------------------------------------------------------------------
# jobs
# ---------------------------------------------------------------------

class FleetJob:
    """One scenario run: an independent uniform grid with its own
    schema, kernel, parameters, step count, priority and checkpoint
    stem. Jobs whose :meth:`bucket_key` matches share one batched
    device program; everything else about them may differ.

    ``kernel`` is a registry name (:data:`FLEET_KERNELS`) or a
    grid-kernel callable; ``params`` are per-job float scalars passed
    to it as batched extras. ``init`` is a ``fn(grid)`` that fills the
    fields (default: a seeded uniform-random fill — the same bytes a
    solo run initializes with). The ``name`` doubles as the job's
    :class:`~dccrg_tpu.supervise.CheckpointStore` stem, so it must be
    unique within a scheduler."""

    def __init__(self, name, *, length=(16, 16, 16), kernel="diffuse",
                 n_steps=10, cell_data=None, fields_in=None,
                 fields_out=None, params=None, priority=0,
                 periodic=(True, True, True), hood_len=1,
                 checkpoint_every=8, max_retries=3, seed=0, init=None,
                 redundancy=1, slo_ms=None):
        self.name = str(name)
        self.length = tuple(int(v) for v in length)
        self.kernel = kernel
        self.n_steps = int(n_steps)
        # a registered kernel spec (the model zoo) supplies schema,
        # field-list and param defaults the caller left unset; kernels
        # without one keep the classic single-rho defaults
        spec = None if callable(kernel) else _kernel_spec(str(kernel))
        if cell_data is None:
            cell_data = (spec["cell_data"] if spec is not None
                         else {"rho": jnp.float32})
        if fields_in is None:
            fields_in = spec["fields_in"] if spec is not None else ("rho",)
        if fields_out is None:
            fields_out = (spec["fields_out"] if spec is not None
                          else ("rho",))
        if params is None:
            params = spec["params"] if spec is not None else (0.1,)
        self.cell_data = {}
        for fname, spec in cell_data.items():
            if isinstance(spec, tuple):
                shape, dtype = spec
            else:
                shape, dtype = (), spec
            self.cell_data[fname] = (tuple(shape), jnp.dtype(dtype))
        self.fields_in = tuple(fields_in)
        self.fields_out = tuple(fields_out)
        self.params = tuple(float(p) for p in params)
        self.priority = int(priority)
        self.periodic = tuple(bool(p) for p in periodic)
        self.hood_len = int(hood_len)
        self.checkpoint_every = int(checkpoint_every)
        self.max_retries = int(max_retries)
        self.seed = int(seed)
        self.init = init
        # redundancy=2: dual modular redundancy (DMR) — the scheduler
        # steps the job in TWO slots and bitwise-compares their
        # digests at every quantum boundary; a mismatch is a CORRUPT
        # trip (see dccrg_tpu.integrity)
        self.redundancy = max(1, int(redundancy))
        # latency SLO: a completion deadline in milliseconds, measured
        # from the job's first admission to the scheduler queue. The
        # scheduler's SLOPolicy prefers jobs whose PROJECTED completion
        # (telemetry quantum-latency EWMA x remaining quanta) would
        # blow the deadline, and sheds best-effort neighbors out of a
        # bucket whose measured quantum latency blows the tightest
        # admitted SLO. None = best-effort (pure priority admission).
        self.slo_ms = None if slo_ms is None else float(slo_ms)
        self.slo_t0 = None  # policy-clock time of the first add()
        # scheduler-owned runtime state
        self.steps_done = 0
        self.retries = 0
        self.requeues = 0
        self.rollbacks = 0
        self.transient_retries = 0
        self.trips = []  # [(kind, at_step)]
        self.status = "queued"
        self.digest = None
        self.last_save_step = None
        self._last_trip_step = -1
        # integrity runtime state: the slot fingerprint recorded at
        # the end of the last quantum ({field: uint32[2]}), reset by
        # every sanctioned slot rewrite (admission, restore)
        self._fp = None

    def resolved_kernel(self):
        if callable(self.kernel):
            return self.kernel
        fn = FLEET_KERNELS.get(str(self.kernel))
        if fn is None:
            _kernel_spec(str(self.kernel))  # zoo registration on miss
            fn = FLEET_KERNELS.get(str(self.kernel))
        if fn is None:
            raise UnknownKernelError(self.name, self.kernel,
                                     FLEET_KERNELS)
        return fn

    def bucket_key(self):
        """The compile-sharing key: jobs with equal keys stack into
        one batched program. Parameters, seeds, priorities and step
        counts are NOT part of it (they ride as batched scalars or
        scheduler state). Every field's dtype IS part of it (via the
        schema triples): a bfloat16 job can never share a compiled
        program — or a ``[capacity, R]`` state allocation — with a
        float32 bucket."""
        schema = tuple(sorted(
            (n, tuple(shape), str(jnp.dtype(dtype)))
            for n, (shape, dtype) in self.cell_data.items()))
        # a registry name buckets by that name; a callable buckets by
        # its own identity (two jobs share a program only when they
        # share the function object)
        return (self.length, self.periodic, self.hood_len, schema,
                self.kernel,
                self.fields_in, self.fields_out, len(self.params))

    def apply_init(self, grid) -> None:
        """Fill ``grid``'s fields with this job's initial state —
        byte-identical whether the grid is a fleet scratch grid or a
        solo run's own."""
        if self.init is not None:
            self.init(grid)
        else:
            spec = (None if callable(self.kernel)
                    else FLEET_KERNEL_SPECS.get(str(self.kernel)))
            fn = spec.get("init") if spec is not None else None
            (fn if fn is not None else seeded_random_init)(
                grid, self.seed)
        grid.update_copies_of_remote_neighbors()


def seeded_random_init(grid, seed: int) -> None:
    """The default job init: a seeded uniform-random fill of every
    field (deterministic in (schema, cell count, seed))."""
    rng = np.random.default_rng(seed)
    cells = grid.plan.cells
    for name in sorted(grid.fields):
        shape, dtype = grid.fields[name]
        vals = (rng.random((len(cells),) + shape) * 100.0).astype(dtype)
        grid.set(name, cells, vals)


def template_grid(job: FleetJob, device=None) -> Grid:
    """The single-device uniform grid a job describes — the bucket's
    template/scratch grid, and the solo baseline's grid."""
    if device is None:
        device = jax.devices()[0]
    return (Grid(cell_data=dict(job.cell_data))
            .set_initial_length(job.length)
            .set_maximum_refinement_level(0)
            .set_neighborhood_length(job.hood_len)
            .set_periodic(*job.periodic)
            .initialize(default_mesh([device])))


def run_solo(job: FleetJob, device=None) -> str:
    """Run ``job`` alone through the ordinary ``Grid.run_steps`` path
    and return its final-state digest
    (:func:`dccrg_tpu.checkpoint.state_digest`) — the one-grid-at-a-
    time baseline every fleet-run job must match bitwise."""
    g = template_grid(job, device)
    job.apply_init(g)
    extras = tuple(jnp.float32(p) for p in job.params)
    kernel = job.resolved_kernel()
    if job.n_steps:
        g.run_steps(kernel, job.fields_in, job.fields_out, job.n_steps,
                    extra_args=extras)
    return checkpoint_mod.state_digest(g)


# ---------------------------------------------------------------------
# the batched execution layer
# ---------------------------------------------------------------------

# compiled fleet programs, shared across GridBatch instances (and
# therefore across drained/recreated buckets) by (bucket key,
# capacity). FIFO-bounded: the cache outlives batches.
_FLEET_PROGRAMS: dict = {}
_FLEET_PROGRAMS_MAX = 64


class GridBatch:
    """N independent same-shape uniform grids stacked along a leading
    batch axis into one jitted device program.

    The batch owns one **template grid** (also its checkpoint scratch
    grid) whose plan supplies the neighbor gather tables, and per-field
    state arrays of shape ``[capacity, R, *field_shape]``. The step
    program is ``vmap`` of the single-grid table-gather step with
    per-job parameters as batched scalars, run under
    ``lax.fori_loop`` with a per-slot step **budget**: slot ``k``
    advances ``budget[k]`` steps this dispatch and its bytes are
    FROZEN afterwards (a per-slot select keeps the old array bits),
    which is how jobs at different step counts, finished jobs and
    tripped/masked slots coexist in one program."""

    def __init__(self, proto: FleetJob, capacity: int, device=None,
                 skeleton=False):
        self.key = proto.bucket_key()
        self.capacity = int(capacity)
        self.device = device
        self.grid = template_grid(proto, device)
        plan = self.grid.plan
        self.L = int(plan.L)
        self.R = int(plan.R)
        self.n_own = int(plan.n_local[0])
        hood = plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
        # [L, S] rows / mask and the mask-zeroed [L, S, 3] offsets —
        # exactly the neighbor bytes the grid's own stencil paths
        # deliver (invalid slots point at the permanent zero pad row)
        self._rows = np.asarray(hood.nbr_rows[0])
        self._mask = np.asarray(hood.nbr_mask[0])
        self._offs = np.asarray(hood.nbr_offs[0])
        self.fields_in = proto.fields_in
        self.fields_out = proto.fields_out
        self.kernel = proto.resolved_kernel()
        # the DCCRG_BULK=pallas twin (SlotwiseKernel) when the job
        # names a bulk-capable registry kernel; callables have no twin
        self.bulk_kernel = (None if callable(proto.kernel)
                            else FLEET_BULK_KERNELS.get(str(proto.kernel)))
        self.n_extra = len(proto.params)
        self.schema = dict(self.grid.fields)
        # the SDC invariant sets: fields the device fingerprints (32-
        # bit element types bitcast losslessly; SCALAR 16-bit fields —
        # bf16 state — widen each element to its own uint32 word,
        # which matches the host packer's one-padded-word-per-row
        # layout only when the row IS one element, so vector 16-bit
        # fields stay out) and fields the kernel provably conserves
        # under this bucket's periodicity
        self.fp_fields = tuple(
            n for n in sorted(self.schema)
            if jnp.dtype(self.schema[n][1]).itemsize == 4
            or (jnp.dtype(self.schema[n][1]).itemsize == 2
                and self.schema[n][0] == ()))
        self.conserved = integrity.conserved_fields(
            proto.kernel, proto.periodic, proto.fields_out)
        # DMR shadow replicas: shadow slot -> primary slot
        self.shadow_of: dict = {}
        #: host invariants of the last integrity-on dispatch
        #: ({"fp_in"/"fp_out": {field: [B, 2]}, "cs_in"/"cs_out":
        #: {field: [B]}}), None with DCCRG_INTEGRITY=0
        self.last_inv = None
        self.slots: list = [None] * self.capacity
        self._extras = np.zeros((self.capacity, self.n_extra),
                                dtype=np.float32)
        self.state = {}
        # a skeleton batch carries only the program-construction
        # inputs (plan tables, schema, kernel) — no [capacity, R, ...]
        # state allocation. The warm-start pool builds one per
        # manifested key to pre-compile programs without touching HBM.
        if not skeleton:
            for name, (shape, dtype) in self.schema.items():
                z = jnp.zeros((self.capacity, self.R) + shape,
                              dtype=dtype)
                if device is not None:
                    z = jax.device_put(z, device)
                self.state[name] = z
        self.dispatches = 0

    # -- program construction (shared per bucket key) -----------------

    def _program_key(self):
        # the integrity flag is part of the cache key: with
        # DCCRG_INTEGRITY=0 the quantum program is BIT-IDENTICAL to
        # the pre-SDC one (no fingerprint ops, no extra outputs) —
        # the negative pin of the SDC defense, not a cheaper check
        int_on = integrity.integrity_enabled()
        # DCCRG_BULK=pallas buckets whose kernel has a registered bulk
        # twin step through the roll-plan Pallas executor (the fleet
        # quantum is then a batched bulk pass instead of a vmapped
        # table gather); the mode is part of the program key so bulk
        # and table programs never alias
        from .ops import roll_executor

        want_bulk = (roll_executor.bulk_mode() == "pallas"
                     and self.bulk_kernel is not None)
        return (self.key, self.capacity, int_on, want_bulk)

    def _programs(self):
        key = self._program_key()
        hit = _FLEET_PROGRAMS.get(key)
        if hit is not None:
            return hit
        # a pre-compiled program from the warm-start pool is the
        # exact tuple _build_programs would produce, with the trace +
        # compile already paid on the background thread (None when no
        # DCCRG_COMPILE_CACHE pool is active — the negative pin)
        hit = warmstart.take_prewarmed(key, device=self.device)
        if hit is None:
            hit = self._build_programs(key)
        if len(_FLEET_PROGRAMS) >= _FLEET_PROGRAMS_MAX:
            _FLEET_PROGRAMS.pop(next(iter(_FLEET_PROGRAMS)))
        _FLEET_PROGRAMS[key] = hit
        return hit

    def _build_programs(self, key):
        int_on, want_bulk = key[2], key[3]
        from .ops import roll_executor

        bulk_step = None
        if want_bulk:
            bulk_step = roll_executor.make_fleet_bulk_step(
                self.grid, self.bulk_kernel, self.fields_in,
                self.fields_out, self.n_extra, self.capacity)
        rows = jnp.asarray(self._rows)
        mask = jnp.asarray(self._mask)
        offs = jnp.asarray(self._offs)
        L, fin, fout = self.L, self.fields_in, self.fields_out
        kernel, n_extra = self.kernel, self.n_extra

        def step_one(state, ex):
            cell = {n: state[n][:L] for n in fin}
            nbr = {n: state[n][rows] for n in fin}
            extras = tuple(ex[i] for i in range(n_extra))
            out = kernel(cell, nbr, offs, mask, *extras)
            new = dict(state)
            for n in fout:
                new[n] = state[n].at[:L].set(out[n].astype(state[n].dtype))
            return new

        vstep = (bulk_step if bulk_step is not None
                 else jax.vmap(step_one, in_axes=(0, 0)))

        def loop(state, extras, budget, q):
            def body(i, st):
                new = vstep(st, extras)
                live = i < budget  # [B]: per-slot step budget

                def sel(a, b):
                    m = live.reshape((-1,) + (1,) * (a.ndim - 1))
                    return jnp.where(m, a, b)

                # exhausted/masked slots keep their OLD array bits —
                # the per-slot freeze the isolation contract rests on
                return {n: sel(new[n], st[n]) for n in st}

            return jax.lax.fori_loop(0, q, body, state)

        watched = [n for n in sorted(self.schema)
                   if jnp.issubdtype(self.schema[n][1], jnp.inexact)]
        fp_fields, conserved = self.fp_fields, self.conserved
        # locals only: a `self` capture would pin every batch (its
        # [capacity, R, ...] device arrays included) in the
        # module-global program cache for the process lifetime
        cap = self.capacity

        def finite(state):
            ok = jnp.ones((cap,), bool)
            for n in watched:
                v = state[n][:, :L]
                ok = ok & jnp.isfinite(v).reshape(v.shape[0], -1).all(axis=1)
            return ok

        def measure(state):
            # per-slot invariants over the OWNED rows, PACKED into two
            # stacked arrays (one device->host transfer each instead
            # of one per field): exact uint32 fingerprint pairs
            # [F, B, 2] in fp_fields order, float conservation sums
            # [C, B] in conserved order
            fp = (jnp.stack([
                jax.vmap(lambda a: integrity.device_fingerprint(a, L))(
                    state[n]) for n in fp_fields])
                if fp_fields else jnp.zeros((0, cap, 2), jnp.uint32))
            cs = (jnp.stack([
                jnp.sum(state[n][:, :L].reshape(state[n].shape[0], -1),
                        axis=1, dtype=jnp.float32) for n in conserved])
                if conserved else jnp.zeros((0, cap), jnp.float32))
            return fp, cs

        if int_on:
            def run_quantum(state, extras, budget, q):
                # the device computes its own fingerprint of the input
                # AND output state in the same dispatch/HBM residency
                # pass as the step — the in-program invariant
                fp_in, cs_in = measure(state)
                out = loop(state, extras, budget, q)
                fp_out, cs_out = measure(out)
                return out, (fp_in, fp_out, cs_in, cs_out)

            fp_now = jax.jit(lambda state: measure(state)[0])
        else:
            run_quantum, fp_now = loop, None

        # the bulk flag rides the cache entry: the solo-path shadow
        # audit must know whether this program's arithmetic is the
        # table kernel's (bitwise-comparable to Grid.run_steps) or the
        # bulk twin's (matches only to float re-association)
        return (jax.jit(run_quantum), jax.jit(finite), fp_now,
                bulk_step is not None)

    # -- slot management ----------------------------------------------

    def free_slot(self):
        """Lowest free slot index, or None when the batch is full."""
        try:
            return self.slots.index(None)
        except ValueError:
            return None

    @property
    def jobs(self):
        """``[(slot, job)]`` of the occupied slots (DMR shadow
        replicas excluded — they are not schedulable jobs)."""
        return [(i, j) for i, j in enumerate(self.slots)
                if j is not None and j is not SHADOW]

    def admit(self, job: FleetJob, from_grid: bool = True):
        """Place ``job`` into the lowest free slot. With ``from_grid``
        (default) the template/scratch grid's current field data —
        just initialized or just restored from the job's checkpoint —
        is scattered into the slot."""
        slot = self.free_slot()
        if slot is None:
            raise RuntimeError("batch is full")
        self.slots[slot] = job
        self._extras[slot] = np.asarray(job.params, dtype=np.float32)
        if from_grid:
            self.read_grid(slot)
        return slot

    def clear(self, slot: int) -> None:
        """Free a slot (job finished/failed/requeued) together with
        any DMR shadow replicas attached to it. The bytes stay as
        they are — budget 0 freezes them and the next occupant
        overwrites every row."""
        self.slots[slot] = None
        for sh, primary in list(self.shadow_of.items()):
            if primary == slot:
                self.slots[sh] = None
                del self.shadow_of[sh]

    # -- DMR shadow replicas ------------------------------------------

    def admit_shadow(self, primary: int):
        """Occupy a free slot with a SHADOW replica of ``primary``:
        same state bytes, same extras, same budgets every quantum —
        the dual-modular-redundancy pair whose digests the scheduler
        compares at every quantum boundary. Returns the shadow slot,
        or None when the batch has no room (the job then runs
        unreplicated)."""
        slot = self.free_slot()
        if slot is None:
            return None
        self.slots[slot] = SHADOW
        self.shadow_of[slot] = primary
        self._extras[slot] = self._extras[primary]
        self.sync_shadow(primary)
        return slot

    def shadows(self, primary: int) -> list:
        """The shadow slots replicating ``primary``."""
        return [sh for sh, pr in self.shadow_of.items() if pr == primary]

    def sync_shadow(self, primary: int) -> None:
        """Re-copy ``primary``'s rows into its shadow slots bit-exactly
        (admission, and after any sanctioned primary rewrite — a
        rollback or migration — so the replicas re-diverge only
        through real corruption)."""
        for sh in self.shadows(primary):
            for n in self.schema:
                self.state[n] = self.state[n].at[sh].set(
                    self.state[n][primary])

    def read_grid(self, slot: int) -> None:
        """Scatter the scratch grid's field data into ``slot``
        (admission and per-slot restore). Only the target slot's rows
        change; every other slot's bits are preserved exactly."""
        for n in self.schema:
            self.state[n] = self.state[n].at[slot].set(self.grid.data[n][0])

    def write_grid(self, slot: int) -> Grid:
        """Gather ``slot``'s field data into the scratch grid (per-slot
        checkpoint save) and return it."""
        sh = self.grid._sharding()
        for n in self.schema:
            self.grid.data[n] = jax.device_put(self.state[n][slot][None], sh)
        return self.grid

    def extract(self, slot: int) -> dict:
        """Host copies of ``slot``'s field arrays (``[R, *shape]``)."""
        return {n: np.asarray(self.state[n][slot]) for n in self.schema}

    def insert(self, slot: int, host_state: dict) -> None:
        """Write :meth:`extract`-shaped host arrays into ``slot``
        bit-exactly — the migration/audit primitive (bucket rebuilds,
        shadow re-execution). Only the target slot's rows change."""
        for n, arr in host_state.items():
            self.state[n] = self.state[n].at[slot].set(arr)

    # -- the batched dispatch -----------------------------------------

    def step(self, budget) -> int:
        """Advance slot ``k`` by ``budget[k]`` steps in ONE jitted
        batched dispatch; returns the quantum length (max budget).
        Slots with budget 0 (empty, finished, tripped-and-masked) are
        frozen bit-exactly. With integrity on, the dispatch also
        returns the fused per-slot invariants (entry/exit
        fingerprints + conservation sums), published on
        :attr:`last_inv` as host arrays."""
        # quantum boundaries are the fleet's step boundaries: a
        # structure plan a background recommit finished for the scratch
        # grid installs here, never mid-quantum (DCCRG_BG_RECOMMIT —
        # the same swap discipline as Grid.run_steps). Distributed-AMR
        # grids (enable_distributed_amr) must never reach this site
        # with a deferred build: their install is an epoch-fenced
        # COLLECTIVE (distamr commit phase), and a per-host quantum
        # boundary cannot host a collective swap — one host installing
        # while a peer keeps stepping the old plan is exactly the
        # divergence the fenced protocol exists to prevent.
        if self.grid.bg_pending():
            if getattr(self.grid, "_amr_group", None) is not None:
                raise RuntimeError(
                    "distributed-AMR grid reached a per-host swap site "
                    "with a deferred plan build; the fenced collective "
                    "install (distamr) must commit it instead")
            self.grid.bg_install()
        budget = np.asarray(budget, dtype=np.int32)
        q = int(budget.max()) if len(budget) else 0
        if q <= 0:
            return 0
        fn, _finite, fp_now, _bulk = self._programs()
        out = fn(self.state, jnp.asarray(self._extras),
                 jnp.asarray(budget), jnp.int32(q))
        if fp_now is None:  # DCCRG_INTEGRITY=0: the pre-SDC program
            self.state, self.last_inv = out, None
        else:
            self.state, inv = out
            fp_in, fp_out, cs_in, cs_out = jax.device_get(inv)
            self.last_inv = {
                "fp_in": {n: fp_in[i]
                          for i, n in enumerate(self.fp_fields)},
                "fp_out": {n: fp_out[i]
                           for i, n in enumerate(self.fp_fields)},
                "cs_in": {n: cs_in[i]
                          for i, n in enumerate(self.conserved)},
                "cs_out": {n: cs_out[i]
                           for i, n in enumerate(self.conserved)},
            }
        self.dispatches += 1
        return q

    def bulk_active(self) -> bool:
        """Whether this bucket's quantum program steps through the
        roll-plan Pallas bulk executor (DCCRG_BULK=pallas with a
        registered bulk twin that proved eligible). Bulk arithmetic
        matches the table kernel only to float re-association, so
        bitwise cross-program comparisons (the solo-path shadow
        audit) must not span the two."""
        return self._programs()[3]

    def finite_slots(self) -> np.ndarray:
        """Per-slot numerics watchdog: ``[capacity]`` bool, True where
        every watched (inexact) field element of the slot is finite.
        One device round-trip for the whole fleet; a poisoned slot
        cannot hide behind its neighbors."""
        _fn, finite, _fp, _bulk = self._programs()
        return np.asarray(finite(self.state))

    def fingerprint_slots(self) -> dict:
        """Per-slot integrity fingerprints of the CURRENT state:
        ``{field: uint32[capacity, 2]}``. The pairs are exact
        order-independent sums, so they compare bitwise against the
        fused in-dispatch fingerprints (:attr:`last_inv`) — any
        difference means the slot's bytes changed outside a sanctioned
        path. Raises RuntimeError with integrity off (there is no
        fingerprint program then, by design)."""
        _fn, _finite, fp_now, _bulk = self._programs()
        if fp_now is None:
            raise RuntimeError(
                "fingerprint_slots needs DCCRG_INTEGRITY enabled")
        stack = np.asarray(fp_now(self.state))
        return {n: stack[i] for i, n in enumerate(self.fp_fields)}

    def slot_fingerprint(self, slot: int) -> dict:
        """One slot's ``{field: (s1, s2)}`` from
        :meth:`fingerprint_slots`."""
        return {n: (int(v[slot, 0]), int(v[slot, 1]))
                for n, v in self.fingerprint_slots().items()}

    def poison(self, slot: int, fld: str, cells, value) -> None:
        """Write ``value`` into ``fld`` at ``cells`` of ONE slot — the
        fleet-scoped fault-injection landing pad
        (:func:`dccrg_tpu.faults.poison_fleet`)."""
        _dev, rows = self.grid._host_rows(cells)
        self.state[fld] = self.state[fld].at[slot, rows].set(value)

    def flip(self, slot: int, fld: str, cells, bit: int) -> None:
        """Land a FINITE bit-flip in ``fld`` at ``cells`` of ONE slot
        — the silent-corruption landing pad
        (:func:`dccrg_tpu.faults.flip_fleet`). Invisible to
        :meth:`finite_slots` by construction; only the integrity
        layer can see it."""
        _dev, rows = self.grid._host_rows(cells)
        vals = np.asarray(self.state[fld][slot, rows])
        self.state[fld] = self.state[fld].at[slot, rows].set(
            faults.flip_values(vals, bit))

    def digest(self, slot: int) -> str:
        """SHA-256 over the slot's OWNED cell bytes — matches
        :func:`dccrg_tpu.checkpoint.state_digest` of a solo grid
        holding the same state."""
        h = hashlib.sha256()
        for name in sorted(self.schema):
            shape, dtype = self.schema[name]
            h.update(repr((name, tuple(shape), str(dtype))).encode())
            h.update(np.ascontiguousarray(
                np.asarray(self.state[name][slot])[:self.n_own]).tobytes())
        return h.hexdigest()


# ---------------------------------------------------------------------
# CLI: python -m dccrg_tpu.fleet <jobs.json> | --demo N
# ---------------------------------------------------------------------

def job_from_row(row: dict, *, validate_kernel: bool = False) -> FleetJob:
    """Parse ONE job record into a :class:`FleetJob` — the single
    validation/kernel-spec-registry path shared by job files
    (:func:`_jobs_from_spec`) and the streaming-intake spool
    (``dccrg_tpu/intake.py``). Per-job keys: ``name`` (required,
    unique), ``n`` (cube edge) or ``length`` [x, y, z], ``kernel``
    (registry name), ``steps``, ``params`` (list of floats; ``dt`` is
    shorthand for one), ``priority``, ``seed``, ``checkpoint_every``,
    ``periodic`` [bool, bool, bool], ``redundancy`` (2 = DMR: two
    slots step the job and their digests are compared every
    quantum), ``slo_ms`` (completion-deadline milliseconds for the
    scheduler's latency-SLO admission; absent = best-effort).

    Malformed records raise the typed :class:`JobSpecError`;
    ``validate_kernel=True`` additionally resolves the kernel name
    eagerly so an unknown kernel surfaces HERE as the typed
    :class:`UnknownKernelError` (the intake quarantine reason)
    instead of a raw ``KeyError`` at first dispatch."""
    if not isinstance(row, dict):
        raise JobSpecError(f"job row is not a mapping: {row!r}")
    if "name" not in row:
        raise JobSpecError(f"job row without a name: {row}")
    try:
        length = (tuple(int(v) for v in row["length"])
                  if "length" in row else (int(row.get("n", 16)),) * 3)
        if len(length) != 3 or any(v < 1 for v in length):
            raise JobSpecError(
                f"job {row['name']!r}: bad length {length}")
        params = row.get("params")
        if params is None and "dt" in row:
            params = [float(row["dt"])]
        # params None falls through to the kernel's registered spec
        # default (the model zoo) or the classic (0.1,) in FleetJob
        job = FleetJob(
            row["name"], length=length,
            kernel=row.get("kernel", "diffuse"),
            n_steps=int(row.get("steps", 10)), params=params,
            priority=int(row.get("priority", 0)),
            seed=int(row.get("seed", 0)),
            periodic=tuple(row.get("periodic", (True, True, True))),
            checkpoint_every=int(row.get("checkpoint_every", 8)),
            redundancy=int(row.get("redundancy", 1)),
            slo_ms=row.get("slo_ms"),
        )
    except JobSpecError:
        raise
    except (TypeError, ValueError, KeyError) as e:
        raise JobSpecError(
            f"job {row.get('name')!r}: malformed record: {e}") from e
    if validate_kernel and not callable(job.kernel):
        job.resolved_kernel()  # UnknownKernelError on a registry miss
    return job


def _jobs_from_spec(spec: dict) -> list:
    """Parse a job-file dict (``{"jobs": [{...}]}``) into
    :class:`FleetJob` objects via :func:`job_from_row` (one shared
    validation path — see its docstring for the per-job keys)."""
    return [job_from_row(row) for row in spec.get("jobs", [])]


def _main(argv=None) -> int:
    """``python -m dccrg_tpu.fleet jobs.json [--workdir DIR]`` — run a
    fleet job file through :class:`~dccrg_tpu.scheduler
    .FleetScheduler` (``--demo N`` synthesizes N diffuse jobs
    instead). Prints one JSON row per finished job plus a summary;
    exits 75 (resumable) when preempted mid-fleet — rerun with the
    same workdir to resume every requeued job from its emergency
    checkpoint."""
    import argparse
    import json
    import sys
    import tempfile
    import time

    ap = argparse.ArgumentParser(prog="python -m dccrg_tpu.fleet",
                                 description=_main.__doc__)
    ap.add_argument("jobs_file", nargs="?", default=None,
                    help="JSON job file ({'jobs': [{...}]})")
    ap.add_argument("--demo", type=int, default=None, metavar="N",
                    help="synthesize N diffuse jobs instead of a file")
    ap.add_argument("--n", type=int, default=16,
                    help="--demo grid edge length (default 16)")
    ap.add_argument("--steps", type=int, default=20,
                    help="--demo steps per job (default 20)")
    ap.add_argument("--workdir", default=None,
                    help="checkpoint directory (default: a temp dir)")
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--quantum", type=int, default=None)
    ap.add_argument("--keep-last", type=int, default=None)
    ap.add_argument("--no-resume", action="store_true",
                    help="ignore existing checkpoints in the workdir")
    ap.add_argument("--autopilot", action="store_true",
                    help="enable the telemetry-driven self-tuning "
                         "controller (same as DCCRG_AUTOPILOT=1; "
                         "decisions journal to DCCRG_DECISION_FILE)")
    args = ap.parse_args(argv)
    if args.autopilot:
        os.environ["DCCRG_AUTOPILOT"] = "1"

    from .scheduler import FleetPreemptedError, FleetScheduler

    if args.demo is not None:
        jobs = [FleetJob(f"demo{i:04d}", length=(args.n,) * 3,
                         n_steps=args.steps, params=(0.05,), seed=i,
                         priority=i % 3)
                for i in range(args.demo)]
    elif args.jobs_file:
        with open(args.jobs_file) as f:
            jobs = _jobs_from_spec(json.load(f))
    else:
        ap.error("either a jobs file or --demo N is required")

    workdir = args.workdir or tempfile.mkdtemp(prefix="dccrg_fleet_")
    sched = FleetScheduler(
        workdir, jobs, max_batch=args.max_batch, quantum=args.quantum,
        keep_last=args.keep_last, resume=not args.no_resume,
        install_signal_handlers=True)
    t0 = time.perf_counter()
    try:
        report = sched.run()
    except FleetPreemptedError as e:
        print(json.dumps({"preempted": True,
                          "requeued": e.requeued,
                          "workdir": workdir}), flush=True)
        return e.exit_code
    wall = time.perf_counter() - t0
    from . import telemetry

    reg = telemetry.registry()
    done = failed = steps = 0
    for name in sorted(report):
        row = dict(report[name], name=name)
        # the per-job end-of-run summary comes from the telemetry
        # registry (the same series dump_prometheus exposes), not
        # ad-hoc prints: quantum-latency quantiles, trip/rollback
        # counters, and throughput over the fleet wall
        h = reg.histogram("dccrg_fleet_quantum_seconds", job=name)
        row.update({
            "quantum_p50_ms": (round(h.quantile(0.5) * 1e3, 3)
                               if h is not None and h.total else None),
            "quantum_p99_ms": (round(h.quantile(0.99) * 1e3, 3)
                               if h is not None and h.total else None),
            "trips_total": int(reg.counter_total(
                "dccrg_fleet_trips_total", job=name)),
            "rollbacks_total": int(reg.counter_total(
                "dccrg_fleet_rollbacks_total", job=name)),
            "steps_per_s": (round(row["steps"] / wall, 3)
                            if wall > 0 else None),
        })
        print(json.dumps(row), flush=True)
        done += row["status"] == "done"
        failed += row["status"] == "failed"
        steps += row["steps"]
    summary = {
        "jobs": len(report), "done": done, "failed": failed,
        "steps_total": steps, "wall_s": round(wall, 3),
        "runs_per_s": round(done / wall, 3) if wall > 0 else None,
        "workdir": workdir}
    if sched.autopilot is not None:
        ap_state = sched.autopilot
        summary["autopilot"] = {
            "decisions": ap_state.seq,
            "quantum": ap_state.quantum,
            "audit_every": ap_state.audit_every,
            "learned_capacities": dict(ap_state.capacity),
        }
    print(json.dumps({"summary": summary}), flush=True)
    return 0 if failed == 0 else 1


if __name__ == "__main__":  # pragma: no cover - exercised via CLI
    import sys

    # standalone gotcha (ROUND6_NOTES): the image's site hook may have
    # pre-imported jax pointed at a dead accelerator tunnel; force the
    # CPU backend unless the caller opted out
    if os.environ.get("DCCRG_FLEET_BACKEND", "cpu") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    # `python -m dccrg_tpu.fleet` loads this FILE as __main__ — a
    # second module instance with its own registry dicts. The model
    # zoo registers into the canonical `dccrg_tpu.fleet` module, so
    # run the CLI through that instance or a zoo kernel named by the
    # job file would be "unknown" here
    from dccrg_tpu import fleet as _canonical

    sys.exit(_canonical._main())
