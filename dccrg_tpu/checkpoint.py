"""Parallel checkpoint / restart.

BYTE-COMPATIBLE implementation of the reference's .dc file format
(dccrg.hpp:1109-2426; layout documented at :1125-1142; conformance
pinned by tests/test_golden.py::test_reference_write_sequence_loads,
which replays the reference's write calls with struct.pack and loads
the result):

    [user header bytes]
    uint64 endianness magic 0x1234567890abcdef        (:1243)
    mapping record: 3 x uint64 level-0 lengths + int32 max_ref_lvl
    uint32 neighborhood length
    topology record: 3 x uint8 periodicity
    geometry record: int32 geometry id + parameters
    uint64 total cell count
    (uint64 cell id, uint64 data byte offset) pairs
    per-cell payloads

The reference writes with collective MPI-IO file views; here the host
owns the replicated structure and payloads stream through bounded
chunks: each chunk is gathered ON DEVICE for the chunk's cells and only
that slice crosses to the host (save, with a one-deep prefetch pipeline
overlapping chunk k+1's device pull with chunk k's file write), or is
scattered from a memory map that pages in on demand (load) — a >=64^3
multi-field grid never materializes the full interleaved payload
matrix. The format itself is pinned by a golden-file fixture
(tests/data/golden.dc + tests/test_golden.py: byte-identical re-save). The per-cell payload
is the grid's fields in sorted-name order — the same role as the
user's ``get_mpi_datatype()`` serialization boundary (sender/receiver
= -1 during save/load, dccrg.hpp:1106-1107).

**Restart needs nothing but the file**: :func:`load_grid` (and
``Grid.from_file``) reconstructs mapping, topology, geometry and the
AMR cell set from the metadata — the reference's
``start_loading_grid_data`` (dccrg.hpp:1815-2105: read metadata,
create_level_0_cells, load_cells refinement sweeps) — then streams the
payloads in. The legacy :func:`load_grid_data` keeps the
load-into-prepared-grid API, validating the file against the grid.

**Variable-size payloads** (two-pass, dccrg.hpp:2108-2123 and
tests/particles/cell.hpp:50-84): a field may be declared variable with
a count field: ``variable={"pos": "count"}`` stores only the first
``count`` rows of each cell's ``pos`` buffer. Loading reads the
fixed-size parts (including the counts) in pass one and the ragged
payloads in pass two, exactly the reference's size-fields-first
contract.
"""

from __future__ import annotations

import struct

import numpy as np

from . import faults

ENDIAN_MAGIC = 0x1234567890ABCDEF
CHUNK = 1 << 19  # cells per streamed payload chunk

# Integrity wrappers live in resilience.py: save_checkpoint writes
# these same bytes atomically (temp + fsync + rename) plus a CRC32
# sidecar <file>.crc; load_checkpoint verifies it. The .dc byte layout
# here stays pinned by the golden-file tests either way.


def _payload_spec_of(fields, variable=None):
    """Split a ``{name: (shape, dtype)}`` field spec into fixed and
    variable parts.

    Returns ``(fixed_spec, fixed_bytes, var_spec)`` where fixed_spec is
    [(name, shape, dtype, nbytes)] in sorted-name order, and var_spec
    is [(name, count_field, row_shape, dtype, row_bytes, capacity)]
    for fields declared variable (stored truncated to their per-cell
    count)."""
    variable = variable or {}
    fixed, var = [], []
    total = 0
    for n in sorted(fields):
        shape, dtype = fields[n]
        dtype = np.dtype(dtype)
        if n in variable:
            if not shape:
                raise ValueError(f"variable field {n!r} must have a row axis")
            row_shape = tuple(shape[1:])
            row_bytes = int(np.prod(row_shape, dtype=np.int64)) * dtype.itemsize if row_shape else dtype.itemsize
            var.append((n, variable[n], row_shape, dtype, row_bytes, int(shape[0])))
        else:
            nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if shape else dtype.itemsize
            fixed.append((n, tuple(shape), dtype, nbytes))
            total += nbytes
    for n, cf, *_ in var:
        if cf not in fields or fields[cf][0] != ():
            raise ValueError(f"count field {cf!r} of {n!r} must be a scalar field")
        if cf in variable:
            raise ValueError(f"count field {cf!r} cannot itself be variable")
    return fixed, total, var


def _payload_spec(grid, variable=None):
    return _payload_spec_of(grid.fields, variable)


def parse_metadata(data, header_size: int = 0):
    """Parse a .dc file's metadata block (the format documented above):
    returns (mapping, hood_len, topology, geometry, cells, offsets,
    payload_start). Shared by load paths and dc_to_vtk. ``data`` is a
    bytes-like (a memory map works)."""
    from .geometry import geometry_from_buffer
    from .mapping import Mapping
    from .topology import GridTopology

    pos = header_size
    (magic,) = struct.unpack_from("<Q", data, pos)
    pos += 8
    if magic != ENDIAN_MAGIC:
        raise ValueError(
            f"bad endianness magic {magic:#x}: file written on an "
            "incompatible architecture or wrong header_size"
        )
    mapping = Mapping.from_bytes(bytes(data[pos : pos + 28]))
    pos += 28
    (hood_len,) = struct.unpack_from("<I", data, pos)
    pos += 4
    topology = GridTopology.from_bytes(bytes(data[pos : pos + 3]))
    pos += 3
    # the geometry record is self-describing via its id — no length
    # prefix, exactly the reference's layout (dccrg.hpp:1312-1323)
    try:
        geometry, geom_len = geometry_from_buffer(data, pos, mapping, topology)
    except (ValueError, struct.error):
        # struct.error covers a truncated record so the fallback (or
        # its 'unrecognized geometry record' diagnostic) still fires
        # legacy files from this repo before round 4 carried a u32
        # record-length prefix here; its value (>= 4) can never be a
        # valid geometry id, so falling back on that signature is
        # unambiguous
        try:
            (legacy_len,) = struct.unpack_from("<I", data, pos)
            (legacy_gid,) = struct.unpack_from("<i", data, pos + 4)
        except struct.error:
            raise ValueError(
                "unrecognized geometry record (file truncated mid-record)"
            ) from None
        if legacy_gid == 2:
            # legacy stretched records carried no coordinate counts;
            # sizes come from the mapping's level-0 lengths
            from .geometry import StretchedCartesianGeometry

            coords, off = [], pos + 8
            for d in range(3):
                n = int(mapping.length.get()[d]) + 1
                coords.append(np.frombuffer(
                    data, dtype=np.float64, count=n, offset=off).copy())
                off += 8 * n
            geometry = StretchedCartesianGeometry(mapping, topology, coords)
            geom_len = off - pos - 4
        else:
            try:
                geometry, geom_len = geometry_from_buffer(
                    data, pos + 4, mapping, topology)
            except (ValueError, struct.error):
                raise ValueError(
                    "unrecognized geometry record (neither the reference "
                    ".dc layout nor this repo's legacy length-prefixed "
                    "form)"
                )
        if geom_len != legacy_len:
            raise ValueError(
                f"legacy geometry length prefix {legacy_len} does not "
                f"match the parsed record ({geom_len} bytes)"
            )
        geom_len += 4
    pos += geom_len
    (n_cells,) = struct.unpack_from("<Q", data, pos)
    pos += 8
    pairs = np.frombuffer(data, dtype=np.uint64, count=2 * n_cells, offset=pos).reshape(-1, 2)
    cells = pairs[:, 0].copy()
    offsets = pairs[:, 1].copy()
    return mapping, hood_len, topology, geometry, cells, offsets, pos + 16 * n_cells


def _chunk_payload(grid, ids, fixed_spec, cell_bytes, reader=None):
    """The interleaved fixed-field payload for one chunk of cells,
    gathered on device so only the chunk crosses to the host.
    ``reader`` overrides the row source (the multi-process save passes
    grid._shard_read so reads stay on addressable shards)."""
    read = reader or (lambda n, d, r: np.asarray(grid.data[n][d, r]))
    dev, rows = grid._host_rows(ids)
    payload = np.empty((len(ids), cell_bytes), dtype=np.uint8)
    col = 0
    for name, shape, dtype, nbytes in fixed_spec:
        vals = np.ascontiguousarray(read(name, dev, rows))
        payload[:, col : col + nbytes] = vals.reshape(len(ids), -1).view(np.uint8)
        col += nbytes
    return payload


def _chunk_bytes(grid, cells, counts, start, fixed_spec, fixed_bytes,
                 var_spec, reader=None, idx=None):
    """Serialize one chunk of cells to bytes (device gather + host
    assembly) — runs on the prefetch thread so the NEXT chunk's device
    pull overlaps the file write of the current one. The multi-process
    save passes explicit cell positions (``idx``) and a shard-local
    ``reader`` so its slice writes share THIS byte-layout code — the
    two paths cannot drift apart."""
    idx = (np.arange(start, min(start + CHUNK, len(cells)))
           if idx is None else idx)
    ids = cells[idx]
    fixed = _chunk_payload(grid, ids, fixed_spec, fixed_bytes, reader)
    if not var_spec:
        return fixed.tobytes()
    read = reader or (lambda n, d, r: np.asarray(grid.data[n][d, r]))
    dev, rows = grid._host_rows(ids)
    var_host = {
        name: np.ascontiguousarray(read(name, dev, rows))
        for name, *_ in var_spec
    }
    nc = len(ids)
    var_nbytes = {
        name: counts[name][idx].astype(np.int64) * row_bytes
        for name, count_field, row_shape, dtype, row_bytes, cap in var_spec
    }
    return _interleave(nc, fixed, var_host, var_nbytes, fixed_bytes, var_spec)


def _interleave(nc, fixed, var_host, var_nbytes, fixed_bytes, var_spec):
    """Interleave fixed parts and ragged variable rows per cell —
    vectorized (repeat/cumsum scatter), no per-cell Python loop."""
    cell_total = np.full(nc, fixed_bytes, dtype=np.int64)
    for nb in var_nbytes.values():
        cell_total += nb
    out = np.empty(int(cell_total.sum()), dtype=np.uint8)
    cell_off = np.cumsum(cell_total) - cell_total
    out[cell_off[:, None] + np.arange(fixed_bytes, dtype=np.int64)] = fixed
    field_off = cell_off + fixed_bytes
    for name, *_ in var_spec:
        nb = var_nbytes[name]
        tot = int(nb.sum())
        if tot:
            vb = var_host[name].reshape(nc, -1).view(np.uint8)
            pos = np.arange(tot, dtype=np.int64) - np.repeat(
                np.cumsum(nb) - nb, nb
            )
            src_row = np.repeat(np.arange(nc, dtype=np.int64), nb)
            out[np.repeat(field_off, nb) + pos] = vb[src_row, pos]
        field_off = field_off + nb
    return out.tobytes()


def save_grid_data(grid, filename: str, header: bytes = b"",
                   variable=None) -> None:
    """Write the grid and all cell data (dccrg.hpp:1109-1736), payloads
    streamed in bounded chunks with the device pull of chunk k+1
    overlapping the file write of chunk k (the reference overlaps via
    collective MPI-IO file views, dccrg.hpp:1594-1659; here a one-deep
    prefetch pipeline gives the same pull/write concurrency on the
    single controller). ``variable={"field": "count_field"}`` stores
    that field truncated to each cell's count (two-pass loadable
    ragged payloads, dccrg.hpp:2108-2123)."""
    from concurrent.futures import ThreadPoolExecutor

    cells = grid.get_cells()
    fixed_spec, fixed_bytes, var_spec = _payload_spec(grid, variable)

    meta = bytearray()
    meta += header
    meta += struct.pack("<Q", ENDIAN_MAGIC)
    meta += grid.mapping.to_bytes()
    meta += struct.pack("<I", grid._hood_len)
    meta += grid.topology.to_bytes()
    geom = grid.geometry.to_bytes()
    meta += geom  # self-describing record, no length prefix
    meta += struct.pack("<Q", len(cells))

    # per-cell byte sizes (variable fields contribute count * row).
    # Counts must be REPLICATED for the offset table; on multi-process
    # meshes the psum device gather with identical (plan-derived) args
    # on every process is globally consistent, unlike host get()
    sizes = np.full(len(cells), fixed_bytes, dtype=np.uint64)
    counts = {}
    for name, count_field, row_shape, dtype, row_bytes, cap in var_spec:
        c = _replicated_pull(grid, count_field, cells).astype(np.int64)
        if np.any(c < 0) or np.any(c > cap):
            raise ValueError(f"count field {count_field!r} out of range for {name!r}")
        counts[name] = c
        sizes += (c * row_bytes).astype(np.uint64)

    offset0 = len(meta) + 16 * len(cells)
    offsets = offset0 + np.concatenate(
        [[np.uint64(0)], np.cumsum(sizes)[:-1]]
    ).astype(np.uint64)

    if grid._multiproc:
        _save_process_slice(grid, filename, bytes(meta), cells, offsets,
                            sizes, counts, fixed_spec, fixed_bytes, var_spec)
        return

    starts = list(range(0, len(cells), CHUNK))
    with open(filename, "wb") as f, ThreadPoolExecutor(1) as pool:
        f.write(bytes(meta))
        pairs = np.empty((len(cells), 2), dtype=np.uint64)
        pairs[:, 0] = cells
        pairs[:, 1] = offsets
        f.write(pairs.tobytes())
        fut = None
        for i, start in enumerate(starts):
            if fut is None:
                fut = pool.submit(_chunk_bytes, grid, cells, counts, start,
                                  fixed_spec, fixed_bytes, var_spec)
            buf = fut.result()
            fut = (pool.submit(_chunk_bytes, grid, cells, counts,
                               starts[i + 1], fixed_spec, fixed_bytes,
                               var_spec)
                   if i + 1 < len(starts) else None)
            # fault-injection site: a mid-stream write failure leaves a
            # torn file — resilience.save_checkpoint's atomic rename
            # guarantees it never carries the final checkpoint name
            faults.fire("checkpoint.chunk", chunk=i, path=filename)
            f.write(buf)


def _replicated_pull(grid, field, cells):
    """Per-cell host values with identical results on every process:
    single-controller grids read directly; multi-process grids use the
    chunked psum device gather, whose (replicated) index args make the
    collective consistent across processes — the role of the
    reference's allgathered cell lists (dccrg.hpp:1109-1736)."""
    if not grid._multiproc:
        return grid.get(field, cells)
    out = []
    for start in range(0, len(cells), CHUNK):
        ids = cells[start : start + CHUNK]
        dev, rows = grid._host_rows(ids)
        out.append(grid._device_gather(field, dev, rows))
    return np.concatenate(out)


def _save_process_slice(grid, filename, meta, cells, offsets, sizes, counts,
                        fixed_spec, fixed_bytes, var_spec):
    """Multi-process save: every process writes its OWN cells' payload
    ranges into the shared file — the reference's collective MPI-IO
    write with per-rank file views (dccrg.hpp:1594-1659). Process 0
    writes the (replicated) metadata and cell/offset table; payload
    ranges are grouped into contiguous runs (one run per process under
    block partitions) so writes are large and few."""
    import jax

    writes_meta = getattr(grid, "_ckpt_writes_meta",
                          jax.process_index() == 0)
    local = grid._proc_local_dev[grid.plan.owner]
    my = np.flatnonzero(local)
    end = int(offsets[-1] + sizes[-1]) if len(cells) else len(meta) + 16 * len(cells)
    if writes_meta:
        with open(filename, "wb") as f:
            f.write(meta)
            pairs = np.empty((len(cells), 2), dtype=np.uint64)
            pairs[:, 0] = cells
            pairs[:, 1] = offsets
            f.write(pairs.tobytes())
            f.truncate(end)  # pre-size so every process can pwrite
    if jax.process_count() > 1:  # not under a faked test split
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"dccrg_save:{filename}")
    from concurrent.futures import ThreadPoolExecutor

    with open(filename, "r+b") as f, ThreadPoolExecutor(1) as pool:
        # runs of consecutive local cells share one write; the same
        # one-deep prefetch pipeline as the single-controller path, so
        # the shard pull of piece k+1 overlaps the file write of k
        if len(my):
            brk = np.flatnonzero(np.diff(my) != 1) + 1
            pieces = [
                (int(offsets[run[0]] if s == 0 else 0), s == 0,
                 run[s : s + CHUNK])
                for run in np.split(my, brk)
                for s in range(0, len(run), CHUNK)
            ]

            def assemble(piece):
                return _chunk_bytes(grid, cells, counts, 0, fixed_spec,
                                    fixed_bytes, var_spec,
                                    reader=grid._shard_read, idx=piece[2])

            fut = pool.submit(assemble, pieces[0])
            for i, (off_here, is_run_start, _idx) in enumerate(pieces):
                buf = fut.result()
                if i + 1 < len(pieces):
                    fut = pool.submit(assemble, pieces[i + 1])
                if is_run_start:
                    f.seek(off_here)
                f.write(buf)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"dccrg_save_done:{filename}")


def _grid_skeleton_matches(grid, mapping, hood_len, topology, geometry):
    if mapping != grid.mapping:
        raise ValueError(f"file grid {mapping} does not match {grid.mapping}")
    if topology != grid.topology:
        raise ValueError("file periodicity does not match the grid")
    if hood_len != grid._hood_len:
        raise ValueError(
            f"file neighborhood length {hood_len} != grid {grid._hood_len}"
        )
    if geometry.geometry_id != grid.geometry.geometry_id:
        raise ValueError("file geometry kind does not match the grid")
    if geometry.to_bytes() != grid.geometry.to_bytes():
        raise ValueError(
            "file geometry parameters do not match the grid (same kind, "
            "different start/cell lengths or coordinate arrays)"
        )


def _scatter_payloads(grid, raw, cells, offsets, fixed_spec, fixed_bytes,
                      var_spec):
    """Stream payloads from ``raw`` (memory map) into fresh device
    arrays. Two passes when variable fields exist: fixed parts (incl.
    counts) first, then the ragged rows (dccrg.hpp:2108-2123)."""
    from .grid import put_sharded

    hosts = {}
    for name, (shape, dtype) in grid.fields.items():
        hosts[name] = np.zeros((grid.n_dev, grid.plan.R) + shape, dtype=dtype)

    if grid._multiproc:
        # each process scatters only its own cells' payloads: the final
        # put_sharded serves only addressable shards, so foreign rows
        # in `hosts` are never consumed (per-rank collective read,
        # dccrg.hpp:2108-2390)
        keep = grid._proc_local_dev[grid.plan.owner[
            np.searchsorted(grid.plan.cells, cells)]]
        cells = cells[keep]
        offsets = offsets[keep]

    # pass 1: fixed-size parts at each cell's offset
    for start in range(0, len(cells), CHUNK):
        ids = cells[start : start + CHUNK]
        offs = offsets[start : start + CHUNK].astype(np.int64)
        dev, rows = grid._host_rows(ids)
        idx = offs[:, None] + np.arange(fixed_bytes, dtype=np.int64)[None, :]
        payload = raw[idx]
        col = 0
        for name, shape, dtype, nbytes in fixed_spec:
            vals = payload[:, col : col + nbytes].copy().view(dtype).reshape(
                (len(ids),) + shape
            )
            hosts[name][dev, rows] = vals
            col += nbytes

    # pass 2: ragged rows, sized by the counts read in pass 1
    for name, count_field, row_shape, dtype, row_bytes, cap in var_spec:
        for start in range(0, len(cells), CHUNK):
            ids = cells[start : start + CHUNK]
            offs = offsets[start : start + CHUNK].astype(np.int64)
            dev, rows = grid._host_rows(ids)
            c = hosts[count_field][dev, rows].astype(np.int64)
            if np.any(c < 0) or np.any(c > cap):
                raise ValueError(
                    f"corrupt counts for variable field {name!r} in file"
                )
            # variable fields follow the fixed block; earlier variable
            # fields (sorted order) of the same cell come first
            base = offs + fixed_bytes
            for vn, vcf, _rs, _dt, vrb, _cap in var_spec:
                if vn == name:
                    break
                base = base + hosts[vcf][dev, rows].astype(np.int64) * vrb
            # vectorized ragged read: fancy-index gathers over row
            # sub-blocks (repeat/cumsum, the save side's pattern) —
            # no per-cell Python (the reference's multi-pass collective
            # read has no serial tail either, dccrg.hpp:2108-2390).
            # The byte-index matrix costs index-dtype-size bytes per
            # payload byte, so it is built in bounded sub-blocks with
            # the narrowest index dtype the file size allows.
            total = int(c.sum())
            if total == 0:
                continue
            cell_of_row = np.repeat(np.arange(len(ids)), c)
            row_within = (np.arange(total, dtype=np.int64)
                          - np.repeat(np.cumsum(c) - c, c))
            starts = base[cell_of_row] + row_within * row_bytes
            idt = np.uint32 if raw.size < (1 << 32) else np.int64
            span = np.arange(row_bytes, dtype=idt)[None, :]
            blk = max(1, (8 << 20) // row_bytes)  # <=64 MB of u32 idx
            for s in range(0, total, blk):
                e = min(s + blk, total)
                idx = starts[s:e, None].astype(idt) + span
                vals = raw[idx].copy().view(dtype).reshape(
                    (e - s,) + row_shape)
                hosts[name][dev[cell_of_row[s:e]], rows[cell_of_row[s:e]],
                            row_within[s:e]] = vals

    for name in grid.fields:
        grid.data[name] = put_sharded(hosts[name], grid._sharding())


def load_grid_data(grid, filename: str, header_size: int = 0,
                   variable=None) -> bytes:
    """Rebuild structure and data from a file written by
    save_grid_data into an ALREADY-CONSTRUCTED grid whose parameters
    are validated against the file (a mismatched restart fails loudly
    rather than corrupting). Returns the user header. For restart from
    nothing but the file, use :func:`load_grid` / ``Grid.from_file``."""
    raw = np.memmap(filename, dtype=np.uint8, mode="r")
    header = bytes(raw[:header_size])
    mapping, hood_len, topology, geometry, cells, offsets, _ = parse_metadata(
        raw, header_size
    )
    _grid_skeleton_matches(grid, mapping, hood_len, topology, geometry)
    fixed_spec, fixed_bytes, var_spec = _payload_spec(grid, variable)
    grid.load_cells(cells)
    _scatter_payloads(grid, raw, cells, offsets, fixed_spec, fixed_bytes, var_spec)
    return header


def load_grid(filename: str, cell_data, mesh=None, header_size: int = 0,
              variable=None, load_balancing_method: str | None = None):
    """Restart from nothing but the file: reconstruct mapping,
    topology, geometry, neighborhood length and the AMR cell set from
    the metadata (the reference's start_loading_grid_data,
    dccrg.hpp:1815-2105), partition the cells, stream the payloads in.

    ``cell_data`` is the field spec (the user's side of the
    serialization contract, as with the reference's Cell_Data type);
    returns ``(grid, header)``."""
    from .grid import Grid

    raw = np.memmap(filename, dtype=np.uint8, mode="r")
    header = bytes(raw[:header_size])
    mapping, hood_len, topology, geometry, cells, offsets, _ = parse_metadata(
        raw, header_size
    )
    kind, params = geometry.spec()
    grid = (
        Grid(cell_data=cell_data)
        .set_initial_length(tuple(int(v) for v in mapping.length.get()))
        .set_maximum_refinement_level(mapping.max_refinement_level)
        .set_periodic(*(topology.is_periodic(d) for d in range(3)))
        .set_neighborhood_length(hood_len)
        .set_geometry(kind, **params)
    )
    if load_balancing_method is not None:
        grid.set_load_balancing_method(load_balancing_method)
    grid.initialize(mesh)
    fixed_spec, fixed_bytes, var_spec = _payload_spec(grid, variable)
    grid.load_cells(cells)
    _scatter_payloads(grid, raw, cells, offsets, fixed_spec, fixed_bytes, var_spec)
    return grid, header
