"""Parallel checkpoint / restart.

BYTE-COMPATIBLE implementation of the reference's .dc file format
(dccrg.hpp:1109-2426; layout documented at :1125-1142; conformance
pinned by tests/test_golden.py::test_reference_write_sequence_loads,
which replays the reference's write calls with struct.pack and loads
the result):

    [user header bytes]
    uint64 endianness magic 0x1234567890abcdef        (:1243)
    mapping record: 3 x uint64 level-0 lengths + int32 max_ref_lvl
    uint32 neighborhood length
    topology record: 3 x uint8 periodicity
    geometry record: int32 geometry id + parameters
    uint64 total cell count
    (uint64 cell id, uint64 data byte offset) pairs
    per-cell payloads

The reference writes with collective MPI-IO file views; here the host
owns the replicated structure and payloads stream through bounded
chunks: each chunk is gathered ON DEVICE for the chunk's cells and only
that slice crosses to the host (save, with a one-deep prefetch pipeline
overlapping chunk k+1's device pull with chunk k's file write), or is
scattered from a memory map that pages in on demand (load) — a >=64^3
multi-field grid never materializes the full interleaved payload
matrix. The format itself is pinned by a golden-file fixture
(tests/data/golden.dc + tests/test_golden.py: byte-identical re-save). The per-cell payload
is the grid's fields in sorted-name order — the same role as the
user's ``get_mpi_datatype()`` serialization boundary (sender/receiver
= -1 during save/load, dccrg.hpp:1106-1107).

**Restart needs nothing but the file**: :func:`load_grid` (and
``Grid.from_file``) reconstructs mapping, topology, geometry and the
AMR cell set from the metadata — the reference's
``start_loading_grid_data`` (dccrg.hpp:1815-2105: read metadata,
create_level_0_cells, load_cells refinement sweeps) — then streams the
payloads in. The legacy :func:`load_grid_data` keeps the
load-into-prepared-grid API, validating the file against the grid.

**Variable-size payloads** (two-pass, dccrg.hpp:2108-2123 and
tests/particles/cell.hpp:50-84): a field may be declared variable with
a count field: ``variable={"pos": "count"}`` stores only the first
``count`` rows of each cell's ``pos`` buffer. Loading reads the
fixed-size parts (including the counts) in pass one and the ragged
payloads in pass two, exactly the reference's size-fields-first
contract.
"""

from __future__ import annotations

import os
import struct
import zlib

import numpy as np

from . import faults

ENDIAN_MAGIC = 0x1234567890ABCDEF
CHUNK = 1 << 19  # cells per streamed payload chunk

# Integrity wrappers live in resilience.py: save_checkpoint writes
# these same bytes atomically (temp + fsync + rename) plus a CRC32
# sidecar <file>.crc; load_checkpoint verifies it. The .dc byte layout
# here stays pinned by the golden-file tests either way.


def _payload_spec_of(fields, variable=None):
    """Split a ``{name: (shape, dtype)}`` field spec into fixed and
    variable parts.

    Returns ``(fixed_spec, fixed_bytes, var_spec)`` where fixed_spec is
    [(name, shape, dtype, nbytes)] in sorted-name order, and var_spec
    is [(name, count_field, row_shape, dtype, row_bytes, capacity)]
    for fields declared variable (stored truncated to their per-cell
    count)."""
    variable = variable or {}
    fixed, var = [], []
    total = 0
    for n in sorted(fields):
        shape, dtype = fields[n]
        dtype = np.dtype(dtype)
        if n in variable:
            if not shape:
                raise ValueError(f"variable field {n!r} must have a row axis")
            row_shape = tuple(shape[1:])
            row_bytes = int(np.prod(row_shape, dtype=np.int64)) * dtype.itemsize if row_shape else dtype.itemsize
            var.append((n, variable[n], row_shape, dtype, row_bytes, int(shape[0])))
        else:
            nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if shape else dtype.itemsize
            fixed.append((n, tuple(shape), dtype, nbytes))
            total += nbytes
    for n, cf, *_ in var:
        if cf not in fields or fields[cf][0] != ():
            raise ValueError(f"count field {cf!r} of {n!r} must be a scalar field")
        if cf in variable:
            raise ValueError(f"count field {cf!r} cannot itself be variable")
    return fixed, total, var


def _payload_spec(grid, variable=None, names=None):
    """``names`` restricts the spec to a subset of the grid's fields —
    the delta-checkpoint path serializes only the dirty fields, in the
    same sorted-name interleave the full format uses (a delta file IS
    a valid .dc file of the sub-schema)."""
    if names is None:
        return _payload_spec_of(grid.fields, variable)
    fields = {n: grid.fields[n] for n in names}
    variable = {n: cf for n, cf in (variable or {}).items() if n in fields}
    return _payload_spec_of(fields, variable)


def parse_metadata(data, header_size: int = 0):
    """Parse a .dc file's metadata block (the format documented above):
    returns (mapping, hood_len, topology, geometry, cells, offsets,
    payload_start). Shared by load paths and dc_to_vtk. ``data`` is a
    bytes-like (a memory map works)."""
    from .geometry import geometry_from_buffer
    from .mapping import Mapping
    from .topology import GridTopology

    pos = header_size
    (magic,) = struct.unpack_from("<Q", data, pos)
    pos += 8
    if magic != ENDIAN_MAGIC:
        raise ValueError(
            f"bad endianness magic {magic:#x}: file written on an "
            "incompatible architecture or wrong header_size"
        )
    mapping = Mapping.from_bytes(bytes(data[pos : pos + 28]))
    pos += 28
    (hood_len,) = struct.unpack_from("<I", data, pos)
    pos += 4
    topology = GridTopology.from_bytes(bytes(data[pos : pos + 3]))
    pos += 3
    # the geometry record is self-describing via its id — no length
    # prefix, exactly the reference's layout (dccrg.hpp:1312-1323)
    try:
        geometry, geom_len = geometry_from_buffer(data, pos, mapping, topology)
    except (ValueError, struct.error):
        # struct.error covers a truncated record so the fallback (or
        # its 'unrecognized geometry record' diagnostic) still fires
        # legacy files from this repo before round 4 carried a u32
        # record-length prefix here; its value (>= 4) can never be a
        # valid geometry id, so falling back on that signature is
        # unambiguous
        try:
            (legacy_len,) = struct.unpack_from("<I", data, pos)
            (legacy_gid,) = struct.unpack_from("<i", data, pos + 4)
        except struct.error:
            raise ValueError(
                "unrecognized geometry record (file truncated mid-record)"
            ) from None
        if legacy_gid == 2:
            # legacy stretched records carried no coordinate counts;
            # sizes come from the mapping's level-0 lengths
            from .geometry import StretchedCartesianGeometry

            coords, off = [], pos + 8
            for d in range(3):
                n = int(mapping.length.get()[d]) + 1
                coords.append(np.frombuffer(
                    data, dtype=np.float64, count=n, offset=off).copy())
                off += 8 * n
            geometry = StretchedCartesianGeometry(mapping, topology, coords)
            geom_len = off - pos - 4
        else:
            try:
                geometry, geom_len = geometry_from_buffer(
                    data, pos + 4, mapping, topology)
            except (ValueError, struct.error):
                raise ValueError(
                    "unrecognized geometry record (neither the reference "
                    ".dc layout nor this repo's legacy length-prefixed "
                    "form)"
                )
        if geom_len != legacy_len:
            raise ValueError(
                f"legacy geometry length prefix {legacy_len} does not "
                f"match the parsed record ({geom_len} bytes)"
            )
        geom_len += 4
    pos += geom_len
    (n_cells,) = struct.unpack_from("<Q", data, pos)
    pos += 8
    pairs = np.frombuffer(data, dtype=np.uint64, count=2 * n_cells, offset=pos).reshape(-1, 2)
    cells = pairs[:, 0].copy()
    offsets = pairs[:, 1].copy()
    return mapping, hood_len, topology, geometry, cells, offsets, pos + 16 * n_cells


def payload_columns(raw, meta, fields, variable=None) -> dict:
    """Per-field fixed-column bytes of a parsed .dc buffer:
    ``{name: uint8[n_cells, nbytes]}`` gathered from each cell's
    offset-table position — the read-side mirror of
    :func:`_chunk_payload`'s interleave, used by the offline
    integrity audit (:func:`dccrg_tpu.integrity.file_fingerprint`) to
    re-derive a payload fingerprint without reconstructing a grid.
    Ragged (variable) fields are skipped: their per-cell extents sit
    between the fixed blocks and a corrupted count would make the
    walk ambiguous."""
    fixed_spec, _fixed_bytes, _var = _payload_spec_of(fields, variable)
    offs = meta[5].astype(np.int64)
    n = len(offs)
    out = {}
    col = 0
    buf = np.asarray(raw, dtype=np.uint8)
    for name, _shape, _dtype, nbytes in fixed_spec:
        span = np.arange(nbytes, dtype=np.int64)[None, :]
        idx = offs[:, None] + col + span
        if n and int(idx.max()) >= buf.size:
            raise ValueError(
                f"payload column {name!r} extends past the end of the "
                "buffer (truncated file?)")
        out[name] = buf[idx]
        col += nbytes
    return out


def _chunk_payload(grid, ids, fixed_spec, cell_bytes, reader=None):
    """The interleaved fixed-field payload for one chunk of cells,
    gathered on device so only the chunk crosses to the host.
    ``reader`` overrides the row source (the multi-process save passes
    grid._shard_read so reads stay on addressable shards)."""
    read = reader or (lambda n, d, r: np.asarray(grid.data[n][d, r]))
    dev, rows = grid._host_rows(ids)
    payload = np.empty((len(ids), cell_bytes), dtype=np.uint8)
    col = 0
    for name, shape, dtype, nbytes in fixed_spec:
        vals = np.ascontiguousarray(read(name, dev, rows))
        payload[:, col : col + nbytes] = vals.reshape(len(ids), -1).view(np.uint8)
        col += nbytes
    return payload


def _chunk_bytes(grid, cells, counts, start, fixed_spec, fixed_bytes,
                 var_spec, reader=None, idx=None):
    """Serialize one chunk of cells to bytes (device gather + host
    assembly) — runs on the prefetch thread so the NEXT chunk's device
    pull overlaps the file write of the current one. The multi-process
    save passes explicit cell positions (``idx``) and a shard-local
    ``reader`` so its slice writes share THIS byte-layout code — the
    two paths cannot drift apart."""
    idx = (np.arange(start, min(start + CHUNK, len(cells)))
           if idx is None else idx)
    ids = cells[idx]
    fixed = _chunk_payload(grid, ids, fixed_spec, fixed_bytes, reader)
    if not var_spec:
        return fixed.tobytes()
    read = reader or (lambda n, d, r: np.asarray(grid.data[n][d, r]))
    dev, rows = grid._host_rows(ids)
    var_host = {
        name: np.ascontiguousarray(read(name, dev, rows))
        for name, *_ in var_spec
    }
    nc = len(ids)
    var_nbytes = {
        name: counts[name][idx].astype(np.int64) * row_bytes
        for name, count_field, row_shape, dtype, row_bytes, cap in var_spec
    }
    return _interleave(nc, fixed, var_host, var_nbytes, fixed_bytes, var_spec)


def _interleave(nc, fixed, var_host, var_nbytes, fixed_bytes, var_spec):
    """Interleave fixed parts and ragged variable rows per cell —
    vectorized (repeat/cumsum scatter), no per-cell Python loop."""
    cell_total = np.full(nc, fixed_bytes, dtype=np.int64)
    for nb in var_nbytes.values():
        cell_total += nb
    out = np.empty(int(cell_total.sum()), dtype=np.uint8)
    cell_off = np.cumsum(cell_total) - cell_total
    out[cell_off[:, None] + np.arange(fixed_bytes, dtype=np.int64)] = fixed
    field_off = cell_off + fixed_bytes
    for name, *_ in var_spec:
        nb = var_nbytes[name]
        tot = int(nb.sum())
        if tot:
            vb = var_host[name].reshape(nc, -1).view(np.uint8)
            pos = np.arange(tot, dtype=np.int64) - np.repeat(
                np.cumsum(nb) - nb, nb
            )
            src_row = np.repeat(np.arange(nc, dtype=np.int64), nb)
            out[np.repeat(field_off, nb) + pos] = vb[src_row, pos]
        field_off = field_off + nb
    return out.tobytes()


def save_grid_data(grid, filename: str, header: bytes = b"",
                   variable=None, *, sidecar: bool = False,
                   sidecar_chunk_bytes: int | None = None,
                   fields=None, sidecar_extra=None) -> None:
    """Write the grid and all cell data (dccrg.hpp:1109-1736), payloads
    streamed in bounded chunks with the device pull of chunk k+1
    overlapping the file write of chunk k (the reference overlaps via
    collective MPI-IO file views, dccrg.hpp:1594-1659; here a one-deep
    prefetch pipeline gives the same pull/write concurrency on the
    single controller). ``variable={"field": "count_field"}`` stores
    that field truncated to each cell's count (two-pass loadable
    ragged payloads, dccrg.hpp:2108-2123).

    Multi-process meshes take the TWO-PHASE-COMMIT path
    (:func:`_save_process_slice`): slices land in ``<file>.mp-tmp``,
    per-rank CRCs are collected at a commit barrier, and the committing
    rank verifies + renames — atomic under rank death. ``sidecar=True``
    additionally has the committing rank write the resilience CRC32
    sidecar (with the per-rank slice table); on the single-controller
    path the sidecar is resilience.save_checkpoint's job and these
    kwargs are ignored.

    ``fields`` restricts the save to a subset of the grid's fields —
    the incremental-checkpoint (delta) path: the file is a valid .dc
    of the sub-schema, byte-layout shared with full saves.
    ``sidecar_extra`` (a dict) is merged into the committing rank's
    sidecar record (the delta parent link)."""
    from concurrent.futures import ThreadPoolExecutor

    cells = grid.get_cells()
    fixed_spec, fixed_bytes, var_spec = _payload_spec(grid, variable,
                                                      names=fields)

    meta = bytearray()
    meta += header
    meta += struct.pack("<Q", ENDIAN_MAGIC)
    meta += grid.mapping.to_bytes()
    meta += struct.pack("<I", grid._hood_len)
    meta += grid.topology.to_bytes()
    geom = grid.geometry.to_bytes()
    meta += geom  # self-describing record, no length prefix
    meta += struct.pack("<Q", len(cells))

    # per-cell byte sizes (variable fields contribute count * row).
    # Counts must be REPLICATED for the offset table; on multi-process
    # meshes the psum device gather with identical (plan-derived) args
    # on every process is globally consistent, unlike host get()
    sizes = np.full(len(cells), fixed_bytes, dtype=np.uint64)
    counts = {}
    for name, count_field, row_shape, dtype, row_bytes, cap in var_spec:
        c = _replicated_pull(grid, count_field, cells).astype(np.int64)
        if np.any(c < 0) or np.any(c > cap):
            raise ValueError(f"count field {count_field!r} out of range for {name!r}")
        counts[name] = c
        sizes += (c * row_bytes).astype(np.uint64)

    offset0 = len(meta) + 16 * len(cells)
    offsets = offset0 + np.concatenate(
        [[np.uint64(0)], np.cumsum(sizes)[:-1]]
    ).astype(np.uint64)

    if grid._multiproc:
        _save_process_slice(grid, filename, bytes(meta), cells, offsets,
                            sizes, counts, fixed_spec, fixed_bytes, var_spec,
                            header_size=len(header), sidecar=sidecar,
                            sidecar_chunk_bytes=sidecar_chunk_bytes,
                            sidecar_extra=sidecar_extra)
        return

    starts = list(range(0, len(cells), CHUNK))
    with open(filename, "wb") as f, ThreadPoolExecutor(1) as pool:
        f.write(bytes(meta))
        pairs = np.empty((len(cells), 2), dtype=np.uint64)
        pairs[:, 0] = cells
        pairs[:, 1] = offsets
        f.write(pairs.tobytes())
        fut = None
        for i, start in enumerate(starts):
            if fut is None:
                fut = pool.submit(_chunk_bytes, grid, cells, counts, start,
                                  fixed_spec, fixed_bytes, var_spec)
            buf = fut.result()
            fut = (pool.submit(_chunk_bytes, grid, cells, counts,
                               starts[i + 1], fixed_spec, fixed_bytes,
                               var_spec)
                   if i + 1 < len(starts) else None)
            # fault-injection site: a mid-stream write failure leaves a
            # torn file — resilience.save_checkpoint's atomic rename
            # guarantees it never carries the final checkpoint name
            faults.fire("checkpoint.chunk", chunk=i, path=filename)
            f.write(buf)


def _replicated_pull(grid, field, cells):
    """Per-cell host values with identical results on every process:
    single-controller grids read directly; multi-process grids use the
    chunked psum device gather, whose (replicated) index args make the
    collective consistent across processes — the role of the
    reference's allgathered cell lists (dccrg.hpp:1109-1736).

    A :func:`~dccrg_tpu.background.freeze_grid_mp` snapshot carries the
    pull PRE-COMPUTED (``_frozen_pulls``, taken on the caller thread at
    freeze time): the chunked gather is an XLA collective, and the
    async writer thread must never dispatch device work."""
    frozen = getattr(grid, "_frozen_pulls", None)
    if frozen is not None and field in frozen \
            and len(frozen[field]) == len(cells):
        return frozen[field]
    if not grid._multiproc:
        return grid.get(field, cells)
    out = []
    for start in range(0, len(cells), CHUNK):
        ids = cells[start : start + CHUNK]
        dev, rows = grid._host_rows(ids)
        out.append(grid._device_gather(field, dev, rows))
    return np.concatenate(out)


MP_TMP_SUFFIX = ".mp-tmp"


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(int(pid), 0)
    except (ProcessLookupError, OverflowError, ValueError):
        return False
    except PermissionError:  # pragma: no cover - exists, not ours
        return True
    return True


def stale_temp_files(dirpath: str) -> list:
    """Orphaned save/salvage temp files in ``dirpath``, left behind by
    a run that died or was preempted mid-save: ``<f>.mp-tmp`` (an
    unfinished two-phase multi-process save — the atomic rename never
    happened, so the bytes under the final name are still the previous
    intact checkpoint), and ``<f>.tmp.<pid>`` / ``<f>.salvage.<pid>`` /
    ``<f>.chain.<pid>`` (a delta-chain reconstruction scratch file)
    whose owning pid is no longer alive. Delta saves share the same
    temp discipline — ``<f>.dcd.tmp.<pid>`` and ``<f>.dcd.mp-tmp``
    match through the generic patterns. Never matches a finished
    checkpoint (keyframe or delta) or its sidecar. Only call between
    runs (or from the process that owns the saves): an ``.mp-tmp`` of
    a save in flight in ANOTHER process is indistinguishable from a
    stale one."""
    out = []
    try:
        names = sorted(os.listdir(dirpath))
    except OSError:
        return out
    for name in names:
        path = os.path.join(dirpath, name)
        if not os.path.isfile(path):
            continue
        if name.endswith(MP_TMP_SUFFIX):
            out.append(path)
            continue
        for marker in (".tmp.", ".salvage.", ".chain."):
            idx = name.rfind(marker)
            if idx < 0:
                continue
            pid = name[idx + len(marker):]
            if pid.isdigit() and not _pid_alive(int(pid)):
                out.append(path)
            break
    return out

def state_digest(grid, fields=None) -> str:
    """Deterministic SHA-256 over the grid's OWNED cell bytes — the
    exact payload rows a checkpoint serializes (per device, rows
    ``[0, n_local[d])``; ghost and pad rows excluded), field-name
    sorted with the name/shape/dtype folded in. Two grids with the
    same structure digest equal iff every owned field byte is equal,
    so the fleet isolation tests (and bench parity checks) compare
    'final field bytes identical' without writing checkpoint files.
    Process-local on multi-process meshes: each rank digests its own
    addressable shards (compare per rank, or gather host-side).

    Gather-mode independent BY CONSTRUCTION: the digest reads only the
    owned payload rows, which every gather mode (roll, tables,
    overlap) leaves in the same layout — pinned by the SDC suite
    (tests/test_integrity.py), because the shadow-audit comparator
    assumes a mode-dependent digest can never raise a false alarm."""
    import hashlib

    h = hashlib.sha256()
    names = sorted(fields if fields is not None else grid.fields)
    for name in names:
        shape, dtype = grid.fields[name]
        h.update(repr((name, tuple(shape), str(dtype))).encode())
        arr = grid.data[name]
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        for s in shards:
            d = s.index[0].start or 0
            n_own = int(grid.plan.n_local[d])
            h.update(np.ascontiguousarray(
                np.asarray(s.data)[0, :n_own]).tobytes())
    return h.hexdigest()


# Faked-split CRC staging: {tmp_path: {dev: (rank, [crc per run])}}.
# REAL multi-process meshes never touch this — their CRCs cross ranks
# through the device all-gather at the commit barrier; the table only
# bridges the SEQUENTIAL per-rank passes of the faked test protocol
# (tests/test_multiprocess.py runs rank 0's pass, then rank 1's, in
# one process). The meta-writing pass resets the entry, so an aborted
# earlier attempt can never leak stale CRCs into a later save.
_MP_CRC_STAGE: dict = {}


def _device_runs(n_dev, owner, offsets, sizes):
    """Per-device contiguous payload runs ``[(dev, positions, lo,
    hi)]`` in device order — derived from the replicated plan only, so
    every process computes the IDENTICAL run table (the shared frame of
    reference the commit-time CRC exchange needs; the reference gets
    the same from its allgathered cell lists, dccrg.hpp:1594-1659)."""
    runs = []
    offs = offsets.astype(np.int64)
    szs = sizes.astype(np.int64)
    for d in range(n_dev):
        pos = np.flatnonzero(owner == d)
        if not len(pos):
            continue
        brk = np.flatnonzero(np.diff(pos) != 1) + 1
        for seg in np.split(pos, brk):
            runs.append((d, seg, int(offs[seg[0]]),
                         int(offs[seg[-1]] + szs[seg[-1]])))
    return runs


def _crc_kv_key(base, rank):
    return f"dccrg_crc:{base}:{rank}"


def _post_run_crcs_kv(grid, runs, local_crcs, rank, base):
    """Writer-thread half of the commit-time CRC exchange: post this
    rank's per-run CRC32s to the coordination KV under the
    attempt-tagged key BEFORE the commit barrier, so once the barrier
    releases every posted record is visible to the committer. Pure
    gRPC — no XLA collective — which is what lets an
    :class:`~dccrg_tpu.background.AsyncSaver` writer thread run the
    whole two-phase save without dispatching device work
    (:func:`~dccrg_tpu.background.freeze_grid_mp`'s contract). The
    record is CRC-framed (:func:`~dccrg_tpu.coord.seal_record`): a
    rank that dies mid-post reads as a torn record, which the
    committer treats exactly like a dead rank."""
    import json

    from . import coord

    by_dev: dict = {}
    for gri, (d, _seg, _lo, _hi) in enumerate(runs):
        by_dev.setdefault(d, []).append(gri)
    payload = {str(d): [int(local_crcs[g]) & 0xFFFFFFFF for g in gris]
               for d, gris in by_dev.items() if grid._proc_local_dev[d]}
    rec = coord.seal_record(
        json.dumps({"rank": int(rank), "devs": payload}, sort_keys=True))
    client = coord._coordination_client()
    key = _crc_kv_key(base, rank)
    try:
        client.key_value_set(key, rec, allow_overwrite=True)
    except TypeError:  # older jaxlib without the kwarg
        client.key_value_set(key, rec)


def _read_run_crcs_kv(grid, by_dev, base):
    """Committer half of the KV CRC exchange: merge every rank's posted
    record into the ``{dev: (rank, [crc, ...])}`` table. A rank that
    never posted (died before the commit barrier) or posted a torn
    record simply leaves its devices absent — the committer's
    missing-slice check turns that into a
    :class:`~dccrg_tpu.coord.CheckpointCommitError` naming it."""
    import json

    import jax

    from . import coord

    client = coord._coordination_client()
    out: dict = {}
    for r in range(jax.process_count()):
        key = _crc_kv_key(base, r)
        try:
            rec = client.blocking_key_value_get(key, 10_000)
        except Exception:  # dead before posting: devices stay absent
            continue
        try:
            msg = json.loads(coord.unseal_record(rec, key=key))
        except coord.TornRecordError:
            continue  # torn post == dead rank to the committer
        for ds, crcs in msg["devs"].items():
            out[int(ds)] = (int(msg["rank"]), [int(c) for c in crcs])
    return out


def _gather_run_crcs(grid, runs, local_crcs, rank, tmp, real,
                     via_kv=False, base=""):
    """Collect every rank's per-run CRC32s into one replicated table
    ``{dev: (rank, [crc, ...])}``.

    Real multi-process meshes exchange through ``comm.host_all_gather``
    at the commit barrier: each process uploads a [n_dev, 1 + 2K]
    uint32 row block for its own devices — rank+1, then (present, crc)
    per run, so a never-written run is distinguishable from any
    legitimate CRC value — and the gather replicates the full table to
    every rank. uint32 on purpose: with ``jax_enable_x64`` off (JAX's
    default; the library never flips it) 64-bit dtypes are silently
    canonicalized to 32 bits inside the device put, which would wrap
    half of all CRC32 values and make healthy ranks look dead. Faked
    test splits merge the in-process stage table instead (their passes
    run sequentially — there is nothing to gather *from* yet when the
    first pass runs). ``via_kv`` (a freeze_grid_mp snapshot's async
    save) swaps the device all-gather for the coordination-KV records
    every rank posted before the commit barrier — no collective, so
    the exchange is legal on a writer thread."""
    by_dev: dict = {}
    for gri, (d, _seg, _lo, _hi) in enumerate(runs):
        by_dev.setdefault(d, []).append(gri)
    if real and via_kv:
        return _read_run_crcs_kv(grid, by_dev, base)
    if not real:
        stage = _MP_CRC_STAGE.setdefault(tmp, {})
        for d, gris in by_dev.items():
            if grid._proc_local_dev[d]:
                stage[d] = (rank, [local_crcs[g] for g in gris])
        return dict(stage)
    from . import comm

    K = max((len(v) for v in by_dev.values()), default=0)
    table = np.zeros((grid.n_dev, 1 + 2 * K), dtype=np.uint32)
    for d, gris in by_dev.items():
        if grid._proc_local_dev[d]:
            table[d, 0] = rank + 1
            for k, g in enumerate(gris):
                table[d, 1 + 2 * k] = 1  # presence marker
                table[d, 2 + 2 * k] = local_crcs[g]
    full = comm.host_all_gather(grid.mesh, table)[0]
    out = {}
    for d, gris in by_dev.items():
        if full[d, 0] > 0:
            out[d] = (int(full[d, 0]) - 1,
                      [int(full[d, 2 + 2 * k]) for k in range(len(gris))
                       if full[d, 1 + 2 * k] == 1])
    return out


def _save_process_slice(grid, filename, meta, cells, offsets, sizes, counts,
                        fixed_spec, fixed_bytes, var_spec, header_size=0,
                        sidecar=False, sidecar_chunk_bytes=None,
                        sidecar_extra=None):
    """Two-phase-commit multi-process save.

    Every process writes its OWN cells' payload runs — the reference's
    collective MPI-IO write with per-rank file views
    (dccrg.hpp:1594-1659) — but into ``<file>.mp-tmp``, never the final
    name, recording a CRC32 per run as it streams:

    1. **prepare** — the meta-writing rank lays down the (replicated)
       metadata + cell/offset table and pre-sizes the temp file; a
       timeout-guarded barrier releases the slice writers; every rank
       pwrites its runs (same one-deep prefetch pipeline as the
       single-controller path) and fsyncs.
    2. **commit** — a second barrier collects every rank's run CRCs
       (comm.host_all_gather on real meshes); the committing rank
       re-reads the temp file, verifies EVERY slice against its
       writer's CRC (raising :class:`~dccrg_tpu.coord
       .CheckpointCommitError` naming the dead/torn ranks on any
       mismatch or missing slice), fsyncs, and atomically renames.

    A rank death or I/O fault at ANY rank/phase therefore leaves
    either the old or the new checkpoint bitwise intact under the
    final name; a lost rank turns into a
    :class:`~dccrg_tpu.coord.BarrierTimeoutError` instead of a hang.
    With ``sidecar``, the committing rank also writes the resilience
    CRC32 sidecar extended with the per-rank slice table ``[dev, rank,
    lo, hi, crc]`` so a salvage load can name the dead rank's cells."""
    import jax

    from . import coord

    real = jax.process_count() > 1  # vs. a faked test split
    rank = coord.process_rank(grid)
    writes_meta = getattr(grid, "_ckpt_writes_meta", None)
    if writes_meta is None:
        writes_meta = (jax.process_index() == 0) if real else True
    commits = getattr(grid, "_ckpt_commits", None)
    if commits is None:
        commits = writes_meta
    tmp = filename + MP_TMP_SUFFIX
    # per-grid save-attempt epoch in every barrier tag: ranks ENTER the
    # save collectively even when a previous attempt failed at
    # different points on different ranks, so tagging by attempt
    # re-aligns the whole barrier sequence on a collective retry
    # (coord.barrier's per-tag counters cover everything else).
    # A freeze_grid_mp snapshot counts through its SOURCE grid
    # (_mp_epoch_src): bumping only the shallow copy would hand the
    # next save the same attempt number and collide its barrier tags
    attempt_src = getattr(grid, "_mp_epoch_src", None) or grid
    attempt = getattr(attempt_src, "_mp_save_epoch", 0) + 1
    attempt_src._mp_save_epoch = attempt
    grid._mp_save_epoch = attempt
    base = f"{os.path.basename(filename)}#{attempt}"
    end = int(offsets[-1] + sizes[-1]) if len(cells) else len(meta)
    runs = _device_runs(grid.n_dev, grid.plan.owner, offsets, sizes)

    # -- phase 1: prepare — meta + slice runs into the temp file ------
    faults.fire("checkpoint.mp", phase="meta", rank=rank, path=filename)
    if writes_meta:
        _MP_CRC_STAGE.pop(tmp, None)  # fresh attempt (faked protocol)
        with open(tmp, "wb") as f:
            f.write(meta)
            pairs = np.empty((len(cells), 2), dtype=np.uint64)
            pairs[:, 0] = cells
            pairs[:, 1] = offsets
            f.write(pairs.tobytes())
            f.truncate(end)  # pre-size so every process can pwrite
            f.flush()
            os.fsync(f.fileno())
    coord.barrier(f"save_prepare:{base}")

    from concurrent.futures import ThreadPoolExecutor

    mine = [g for g, r in enumerate(runs) if grid._proc_local_dev[r[0]]]
    local_crcs: dict = {g: 0 for g in mine}
    with open(tmp, "r+b") as f, ThreadPoolExecutor(1) as pool:
        # runs of consecutive local cells share one seek; the same
        # one-deep prefetch pipeline as the single-controller path, so
        # the shard pull of piece k+1 overlaps the file write of k
        pieces = [
            (g, s == 0, runs[g][1][s : s + CHUNK], runs[g][2])
            for g in mine
            for s in range(0, len(runs[g][1]), CHUNK)
        ]

        def assemble(piece):
            return _chunk_bytes(grid, cells, counts, 0, fixed_spec,
                                fixed_bytes, var_spec,
                                reader=grid._shard_read, idx=piece[2])

        fut = pool.submit(assemble, pieces[0]) if pieces else None
        for i, (g, is_run_start, _idx, lo) in enumerate(pieces):
            buf = fut.result()
            if i + 1 < len(pieces):
                fut = pool.submit(assemble, pieces[i + 1])
            faults.fire("checkpoint.mp", phase="slice", rank=rank,
                        piece=i, path=filename)
            if is_run_start:
                f.seek(lo)
            f.write(buf)
            local_crcs[g] = zlib.crc32(buf, local_crcs[g])
        f.flush()
        os.fsync(f.fileno())
    faults.fire("checkpoint.mp", phase="written", rank=rank, path=filename)

    # -- phase 2: commit barrier, CRC exchange, verify + publish ------
    via_kv = real and bool(getattr(grid, "_ckpt_crc_via_kv", False))
    if via_kv:
        # post BEFORE the barrier: once it releases, every surviving
        # rank's record is already readable (KV writes are ordered
        # before the poster's barrier arrival)
        _post_run_crcs_kv(grid, runs, local_crcs, rank, base)
    coord.barrier(f"save_commit:{base}")
    crc_table = _gather_run_crcs(grid, runs, local_crcs, rank, tmp, real,
                                 via_kv=via_kv, base=base)
    status_key = f"dccrg_commit:{base}"
    client = coord._coordination_client() if real else None
    if commits:
        # the metadata + offset table is REPLICATED state — the
        # committing rank recomputes its exact bytes locally, so a tear
        # in the meta region needs no CRC exchange to be caught
        pairs = np.empty((len(cells), 2), dtype=np.uint64)
        pairs[:, 0] = cells
        pairs[:, 1] = offsets
        # crc32 reads the buffer protocol directly: no tobytes() copy
        # of a table that is ~2 GB at the 512^3 scale
        meta_crc = zlib.crc32(pairs, zlib.crc32(meta))
        commit_err = None
        try:
            _commit_process_slices(grid, filename, tmp, runs, crc_table,
                                   header_size, sidecar,
                                   sidecar_chunk_bytes, rank,
                                   meta_crc & 0xFFFFFFFF,
                                   len(meta) + 16 * len(cells),
                                   sidecar_extra=sidecar_extra)
        except faults.InjectedRankDeath:
            raise  # a dead rank coordinates nothing
        except Exception as e:  # noqa: BLE001 - re-raised below
            commit_err = e
        _MP_CRC_STAGE.pop(tmp, None)
        if client is not None:
            # publish the outcome BEFORE the done barrier: peers read
            # it right after and learn of an abort immediately instead
            # of mistaking a live-but-aborted committer for a dead one.
            # allow_overwrite: a restarted job (fresh Grid, reset
            # attempt epoch) may legitimately reuse a key — a stale
            # value from a previous incarnation must not crash a save
            # that already published its rename
            status = ("ok" if commit_err is None
                      else f"commit aborted on rank {rank}: {commit_err}")
            try:
                client.key_value_set(status_key, status,
                                     allow_overwrite=True)
            except TypeError:  # older jaxlib without the kwarg
                try:
                    client.key_value_set(status_key, status)
                except Exception:  # pragma: no cover - key collision
                    pass
        if commit_err is not None:
            try:
                coord.barrier(f"save_done:{base}")
            except Exception:  # the abort outranks a straggling peer
                pass
            raise commit_err
    coord.barrier(f"save_done:{base}")
    if not commits and client is not None:
        try:
            status = client.blocking_key_value_get(status_key, 10_000)
        except Exception:  # committer gone: the barrier outcome governs
            status = None
        if status is not None and status != "ok":
            raise coord.CheckpointCommitError(
                f"{filename}: {status}; the previous checkpoint is "
                "untouched")


def _commit_process_slices(grid, filename, tmp, runs, crc_table,
                           header_size, sidecar, sidecar_chunk_bytes, rank,
                           meta_crc, payload_start, sidecar_extra=None):
    """The committing rank's half of the two-phase save: verify the
    replicated metadata block (against ``meta_crc``, recomputed
    locally) and every payload slice of the temp file against its
    writer's CRC, then atomically publish (old-sidecar drop, rename,
    dir fsync, new sidecar) — the same rename discipline as
    resilience.save_checkpoint's single-controller path."""
    from . import coord, resilience

    faults.fire("checkpoint.mp", phase="commit", rank=rank, path=filename)
    by_dev: dict = {}
    for gri, (d, _seg, lo, hi) in enumerate(runs):
        by_dev.setdefault(d, []).append((gri, lo, hi))
    missing = sorted(d for d in by_dev if d not in crc_table
                     or len(crc_table[d][1]) != len(by_dev[d]))
    if missing:
        raise coord.CheckpointCommitError(
            f"{filename}: commit aborted — no slice CRCs from device(s) "
            f"{missing} (their rank died before the commit barrier); the "
            "previous checkpoint is untouched",
            ranks=[crc_table[d][0] for d in missing if d in crc_table])
    # ONE sequential pass over the temp file yields all three CRC
    # layouts: the metadata block (= chunk 0 of the tiling), the
    # sidecar's chunk tiling, and the per-rank slice spans (globally
    # sorted for the streaming overlay, then unpermuted)
    entries = [(d, k, lo, hi)
               for d in sorted(by_dev)
               for k, (_gri, lo, hi) in enumerate(by_dev[d])]
    order = sorted(range(len(entries)), key=lambda i: entries[i][2])
    cb = sidecar_chunk_bytes or resilience.CRC_CHUNK
    file_bytes = os.path.getsize(tmp)
    chunk_ranges = resilience._chunk_ranges(payload_start, file_bytes, cb)
    chunk_crcs, sorted_crcs = resilience._stream_crcs(
        tmp, chunk_ranges, [(entries[i][2], entries[i][3]) for i in order],
        cb)
    got = [0] * len(entries)
    for k, i in enumerate(order):
        got[i] = sorted_crcs[k]
    if chunk_crcs[0] != meta_crc:
        raise coord.CheckpointCommitError(
            f"{filename}: commit aborted — the metadata/offset-table "
            "block of the temp file does not match its replicated bytes "
            "(torn prepare write); the previous checkpoint is untouched")
    slices = []  # [dev, rank, lo, hi, crc] rows for the sidecar
    torn = []
    for i, (d, k, lo, hi) in enumerate(entries):
        wrank, want = crc_table[d]
        if got[i] != (want[k] & 0xFFFFFFFF):
            torn.append((d, wrank))
        slices.append([int(d), int(wrank), int(lo), int(hi),
                       int(want[k] & 0xFFFFFFFF)])
    if torn:
        devs = sorted({d for d, _r in torn})
        ranks = sorted({r for _d, r in torn})
        raise coord.CheckpointCommitError(
            f"{filename}: commit aborted — slice(s) of device(s) {devs} "
            f"(written by rank(s) {ranks}) fail their CRC32 in the temp "
            "file (torn write / rank died mid-slice); the previous "
            "checkpoint is untouched", ranks=ranks)
    rec = None
    if sidecar:
        rec = {"format": resilience.SIDECAR_FORMAT, "chunk_bytes": cb,
               "file_bytes": file_bytes, "payload_start": payload_start,
               "header_size": header_size, "crc32": chunk_crcs,
               "slices": slices}
        if sidecar_extra:
            rec.update(sidecar_extra)
    # drop any previous sidecar BEFORE the rename (same reasoning as
    # resilience.save_checkpoint: never a new file under a stale
    # record), keeping its bytes to restore if the rename itself fails
    side = resilience.sidecar_path(filename)
    old_side = None
    if os.path.exists(side):
        with open(side, "rb") as sf:
            old_side = sf.read()
        os.unlink(side)
    try:
        os.replace(tmp, filename)
    except OSError:
        resilience._restore_sidecar(side, old_side)
        raise
    resilience._fsync_dir(os.path.dirname(os.path.abspath(filename)))
    faults.fire("checkpoint.mp", phase="publish", rank=rank, path=filename)
    if rec is not None:
        resilience._write_sidecar_record(side, rec)


def _grid_skeleton_matches(grid, mapping, hood_len, topology, geometry):
    if mapping != grid.mapping:
        raise ValueError(f"file grid {mapping} does not match {grid.mapping}")
    if topology != grid.topology:
        raise ValueError("file periodicity does not match the grid")
    if hood_len != grid._hood_len:
        raise ValueError(
            f"file neighborhood length {hood_len} != grid {grid._hood_len}"
        )
    if geometry.geometry_id != grid.geometry.geometry_id:
        raise ValueError("file geometry kind does not match the grid")
    if geometry.to_bytes() != grid.geometry.to_bytes():
        raise ValueError(
            "file geometry parameters do not match the grid (same kind, "
            "different start/cell lengths or coordinate arrays)"
        )


def _scatter_payloads(grid, raw, cells, offsets, fixed_spec, fixed_bytes,
                      var_spec):
    """Stream payloads from ``raw`` (memory map) into fresh device
    arrays. Two passes when variable fields exist: fixed parts (incl.
    counts) first, then the ragged rows (dccrg.hpp:2108-2123)."""
    from .grid import put_sharded

    hosts = {}
    for name, (shape, dtype) in grid.fields.items():
        hosts[name] = np.zeros((grid.n_dev, grid.plan.R) + shape, dtype=dtype)

    if grid._multiproc:
        # each process scatters only its own cells' payloads: the final
        # put_sharded serves only addressable shards, so foreign rows
        # in `hosts` are never consumed (per-rank collective read,
        # dccrg.hpp:2108-2390)
        keep = grid._proc_local_dev[grid.plan.owner[
            np.searchsorted(grid.plan.cells, cells)]]
        cells = cells[keep]
        offsets = offsets[keep]

    # pass 1: fixed-size parts at each cell's offset
    for start in range(0, len(cells), CHUNK):
        ids = cells[start : start + CHUNK]
        offs = offsets[start : start + CHUNK].astype(np.int64)
        dev, rows = grid._host_rows(ids)
        idx = offs[:, None] + np.arange(fixed_bytes, dtype=np.int64)[None, :]
        payload = raw[idx]
        col = 0
        for name, shape, dtype, nbytes in fixed_spec:
            vals = payload[:, col : col + nbytes].copy().view(dtype).reshape(
                (len(ids),) + shape
            )
            hosts[name][dev, rows] = vals
            col += nbytes

    # pass 2: ragged rows, sized by the counts read in pass 1
    for name, count_field, row_shape, dtype, row_bytes, cap in var_spec:
        for start in range(0, len(cells), CHUNK):
            ids = cells[start : start + CHUNK]
            offs = offsets[start : start + CHUNK].astype(np.int64)
            dev, rows = grid._host_rows(ids)
            c = hosts[count_field][dev, rows].astype(np.int64)
            if np.any(c < 0) or np.any(c > cap):
                raise ValueError(
                    f"corrupt counts for variable field {name!r} in file"
                )
            # variable fields follow the fixed block; earlier variable
            # fields (sorted order) of the same cell come first
            base = offs + fixed_bytes
            for vn, vcf, _rs, _dt, vrb, _cap in var_spec:
                if vn == name:
                    break
                base = base + hosts[vcf][dev, rows].astype(np.int64) * vrb
            # vectorized ragged read: fancy-index gathers over row
            # sub-blocks (repeat/cumsum, the save side's pattern) —
            # no per-cell Python (the reference's multi-pass collective
            # read has no serial tail either, dccrg.hpp:2108-2390).
            # The byte-index matrix costs index-dtype-size bytes per
            # payload byte, so it is built in bounded sub-blocks with
            # the narrowest index dtype the file size allows.
            total = int(c.sum())
            if total == 0:
                continue
            cell_of_row = np.repeat(np.arange(len(ids)), c)
            row_within = (np.arange(total, dtype=np.int64)
                          - np.repeat(np.cumsum(c) - c, c))
            starts = base[cell_of_row] + row_within * row_bytes
            idt = np.uint32 if raw.size < (1 << 32) else np.int64
            span = np.arange(row_bytes, dtype=idt)[None, :]
            blk = max(1, (8 << 20) // row_bytes)  # <=64 MB of u32 idx
            for s in range(0, total, blk):
                e = min(s + blk, total)
                idx = starts[s:e, None].astype(idt) + span
                vals = raw[idx].copy().view(dtype).reshape(
                    (e - s,) + row_shape)
                hosts[name][dev[cell_of_row[s:e]], rows[cell_of_row[s:e]],
                            row_within[s:e]] = vals

    for name in grid.fields:
        grid.data[name] = put_sharded(hosts[name], grid._sharding())
    # a wholesale load resets the delta-checkpoint baseline: every
    # field's saved bytes may now differ from the previous chain's
    grid._mark_ckpt_dirty()


def load_grid_data(grid, filename: str, header_size: int = 0,
                   variable=None) -> bytes:
    """Rebuild structure and data from a file written by
    save_grid_data into an ALREADY-CONSTRUCTED grid whose parameters
    are validated against the file (a mismatched restart fails loudly
    rather than corrupting). Returns the user header. For restart from
    nothing but the file, use :func:`load_grid` / ``Grid.from_file``."""
    raw = np.memmap(filename, dtype=np.uint8, mode="r")
    header = bytes(raw[:header_size])
    mapping, hood_len, topology, geometry, cells, offsets, _ = parse_metadata(
        raw, header_size
    )
    _grid_skeleton_matches(grid, mapping, hood_len, topology, geometry)
    fixed_spec, fixed_bytes, var_spec = _payload_spec(grid, variable)
    grid.load_cells(cells)
    _scatter_payloads(grid, raw, cells, offsets, fixed_spec, fixed_bytes, var_spec)
    _load_done_barrier()
    return header


def _load_done_barrier():
    """On real multi-process meshes, hold every rank until all have
    finished scattering their slices — a fast rank must not proceed to
    overwrite/replace the file while a peer is still reading it. A
    no-op (one process_count check) on a single controller; tagged
    without the filename because salvage loads read per-rank temp
    names that must not desynchronize the barrier sequence.

    Best effort by design: THIS rank's load already completed, so a
    peer that cannot answer (died mid-recovery — exactly when a
    survivor restores from checkpoint) must not turn a successful
    local load into a failure. The timeout is logged loudly instead."""
    import jax

    if jax.process_count() > 1:
        import logging

        from . import coord

        try:
            coord.barrier("load_done")
        except Exception as e:  # noqa: BLE001 - load is locally done
            logging.getLogger("dccrg_tpu.checkpoint").warning(
                "load_done barrier did not complete (%s); this rank's "
                "load IS complete — do not overwrite the file until "
                "the lost peers are accounted for", e)


def load_grid(filename: str, cell_data, mesh=None, header_size: int = 0,
              variable=None, load_balancing_method: str | None = None):
    """Restart from nothing but the file: reconstruct mapping,
    topology, geometry, neighborhood length and the AMR cell set from
    the metadata (the reference's start_loading_grid_data,
    dccrg.hpp:1815-2105), partition the cells, stream the payloads in.

    ``cell_data`` is the field spec (the user's side of the
    serialization contract, as with the reference's Cell_Data type);
    returns ``(grid, header)``."""
    from .grid import Grid

    raw = np.memmap(filename, dtype=np.uint8, mode="r")
    header = bytes(raw[:header_size])
    mapping, hood_len, topology, geometry, cells, offsets, _ = parse_metadata(
        raw, header_size
    )
    kind, params = geometry.spec()
    grid = (
        Grid(cell_data=cell_data)
        .set_initial_length(tuple(int(v) for v in mapping.length.get()))
        .set_maximum_refinement_level(mapping.max_refinement_level)
        .set_periodic(*(topology.is_periodic(d) for d in range(3)))
        .set_neighborhood_length(hood_len)
        .set_geometry(kind, **params)
    )
    if load_balancing_method is not None:
        grid.set_load_balancing_method(load_balancing_method)
    grid.initialize(mesh)
    fixed_spec, fixed_bytes, var_spec = _payload_spec(grid, variable)
    grid.load_cells(cells)
    _scatter_payloads(grid, raw, cells, offsets, fixed_spec, fixed_bytes, var_spec)
    _load_done_barrier()
    return grid, header
