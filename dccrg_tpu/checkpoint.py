"""Parallel checkpoint / restart.

Logical equivalent of the reference's .dc file format
(dccrg.hpp:1109-2426; layout documented at :1125-1142):

    [user header bytes]
    uint64 endianness magic 0x1234567890abcdef        (:1243)
    mapping record: 3 x uint64 level-0 lengths + int32 max_ref_lvl
    uint32 neighborhood length
    topology record: 3 x uint8 periodicity
    geometry record: int32 geometry id + parameters
    uint64 total cell count
    (uint64 cell id, uint64 data byte offset) pairs
    per-cell payloads

The reference writes with collective MPI-IO file views; here the host
owns the replicated structure and device data is pulled once and
written with buffered file I/O (payloads are a single contiguous
vectorized write, not a per-cell loop). The per-cell payload is the
concatenation of the grid's fields in sorted-name order — the same
role as the user's ``get_mpi_datatype()`` serialization boundary
(sender/receiver = -1 during save/load, dccrg.hpp:1106-1107).

Restart rebuilds the grid structure with ``load_cells`` (the
reference's refinement-sweep reconstruction, dccrg.hpp:3669-3738) and
scatters payloads back to the devices.
"""

from __future__ import annotations

import struct

import numpy as np

ENDIAN_MAGIC = 0x1234567890ABCDEF


def _payload_spec_of(fields):
    """(names, itemsize per cell, per-field (name, shape, dtype, nbytes))
    for a ``{name: (shape, dtype)}`` field spec. The per-cell payload is
    the fields in sorted-name order — the serialization contract shared
    by save/load and the standalone dc2vtk converter."""
    names = sorted(fields)
    spec = []
    total = 0
    for n in names:
        shape, dtype = fields[n]
        nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize if shape else np.dtype(dtype).itemsize
        spec.append((n, tuple(shape), np.dtype(dtype), nbytes))
        total += nbytes
    return names, total, spec


def _payload_spec(grid):
    return _payload_spec_of(grid.fields)


def parse_metadata(data: bytes, header_size: int = 0):
    """Parse a .dc file's metadata block (the format documented above):
    returns (mapping, hood_len, topology, geometry, cells, offsets,
    payload_start). Shared by load_grid_data and dc_to_vtk."""
    from .geometry import geometry_from_bytes
    from .mapping import Mapping
    from .topology import GridTopology

    pos = header_size
    (magic,) = struct.unpack_from("<Q", data, pos)
    pos += 8
    if magic != ENDIAN_MAGIC:
        raise ValueError(
            f"bad endianness magic {magic:#x}: file written on an "
            "incompatible architecture or wrong header_size"
        )
    mapping = Mapping.from_bytes(data[pos : pos + 28])
    pos += 28
    (hood_len,) = struct.unpack_from("<I", data, pos)
    pos += 4
    topology = GridTopology.from_bytes(data[pos : pos + 3])
    pos += 3
    (geom_len,) = struct.unpack_from("<I", data, pos)
    pos += 4
    geometry = geometry_from_bytes(data[pos : pos + geom_len], mapping, topology)
    pos += geom_len
    (n_cells,) = struct.unpack_from("<Q", data, pos)
    pos += 8
    pairs = np.frombuffer(data, dtype=np.uint64, count=2 * n_cells, offset=pos).reshape(-1, 2)
    cells = pairs[:, 0].copy()
    offsets = pairs[:, 1].copy()
    return mapping, hood_len, topology, geometry, cells, offsets, pos + 16 * n_cells


def save_grid_data(grid, filename: str, header: bytes = b"") -> None:
    """Write the grid and all cell data (dccrg.hpp:1109-1736)."""
    cells = grid.get_cells()
    names, cell_bytes, spec = _payload_spec(grid)

    meta = bytearray()
    meta += header
    meta += struct.pack("<Q", ENDIAN_MAGIC)
    meta += grid.mapping.to_bytes()
    meta += struct.pack("<I", grid._hood_len)
    meta += grid.topology.to_bytes()
    geom = grid.geometry.to_bytes()
    meta += struct.pack("<I", len(geom)) + geom
    meta += struct.pack("<Q", len(cells))

    offset0 = len(meta) + 16 * len(cells)
    offsets = offset0 + np.arange(len(cells), dtype=np.uint64) * np.uint64(cell_bytes)

    # payload matrix [n_cells, cell_bytes]: fields in sorted-name order
    payload = np.empty((len(cells), cell_bytes), dtype=np.uint8)
    col = 0
    for name, shape, dtype, nbytes in spec:
        vals = np.ascontiguousarray(grid.get(name, cells))
        payload[:, col : col + nbytes] = vals.reshape(len(cells), -1).view(np.uint8)
        col += nbytes

    with open(filename, "wb") as f:
        f.write(bytes(meta))
        pairs = np.empty((len(cells), 2), dtype=np.uint64)
        pairs[:, 0] = cells
        pairs[:, 1] = offsets
        f.write(pairs.tobytes())
        f.write(payload.tobytes())


def load_grid_data(grid, filename: str, header_size: int = 0) -> bytes:
    """Rebuild structure and data from a file written by
    save_grid_data (dccrg.hpp:1762-2426). Returns the user header.

    The grid must be constructed with the same field spec; its length /
    refinement / periodicity / geometry are validated against the file
    (the reference re-creates them from the file; we assert parity so a
    mismatched restart fails loudly rather than corrupting)."""
    with open(filename, "rb") as f:
        data = f.read()

    header = data[:header_size]
    mapping, hood_len, topology, geometry, cells, offsets, _ = parse_metadata(
        data, header_size
    )

    if mapping != grid.mapping:
        raise ValueError(f"file grid {mapping} does not match {grid.mapping}")
    if topology != grid.topology:
        raise ValueError("file periodicity does not match the grid")
    if hood_len != grid._hood_len:
        raise ValueError(
            f"file neighborhood length {hood_len} != grid {grid._hood_len}"
        )
    if geometry.geometry_id != grid.geometry.geometry_id:
        raise ValueError("file geometry kind does not match the grid")
    if geometry.to_bytes() != grid.geometry.to_bytes():
        raise ValueError(
            "file geometry parameters do not match the grid (same kind, "
            "different start/cell lengths or coordinate arrays)"
        )

    names, cell_bytes, spec = _payload_spec(grid)
    grid.load_cells(cells)

    # vectorized gather of all payloads (offsets are contiguous as
    # written, but honor them individually for format fidelity)
    raw = np.frombuffer(data, dtype=np.uint8)
    idx = offsets[:, None].astype(np.int64) + np.arange(cell_bytes, dtype=np.int64)[None, :]
    payload = raw[idx]
    col = 0
    for name, shape, dtype, nbytes in spec:
        vals = payload[:, col : col + nbytes].copy().view(dtype).reshape((len(cells),) + shape)
        grid.set(name, cells, vals)
        col += nbytes
    return header
