"""Dense fast path for uniform (refinement-level-0) grids.

The reference treats a uniform grid as the special case of its general
machinery; on TPU the uniform case deserves the opposite: fields are
dense ``[nx, ny, nz, ...]`` arrays sharded over an up-to-3-D device
mesh, and halo exchange is six ``lax.ppermute`` slab sends inside
``shard_map`` — the pattern the BASELINE.json north star names for
``update_copies_of_remote_neighbors()``'s hot path. Per-cell stencil
loops (advection fluxes tests/advection/solve.hpp:44-266, game of life,
Poisson relaxation) become fused array code / Pallas kernels over the
padded local block.

Cell ids remain interoperable with ``Grid``/``Mapping``: the cell at
dense index (i, j, k) is level-0 cell ``1 + i + j*nx + k*nx*ny``
(dccrg_mapping.hpp:154-209), so a user can move between the paths.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .compat import shard_map as _shard_map

AXES = ("x", "y", "z")


def dense_mesh(devices=None, shape=None) -> Mesh:
    """3-D mesh over the given devices; defaults to all devices laid
    out along x (factor further with ``shape=(px, py, pz)``)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        shape = (n, 1, 1)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")
    return Mesh(np.array(devices).reshape(shape), AXES)


class DenseGrid:
    """Uniform Cartesian grid with dense sharded storage.

    Parameters
    ----------
    length : (nx, ny, nz) level-0 cell counts; each must be divisible
        by the mesh extent along its axis.
    fields : dict name -> dtype (scalar per cell) or (shape, dtype).
    periodic : per-dimension wrap, as GridTopology.
    start / cell_length : Cartesian geometry parameters
        (dccrg_cartesian_geometry.hpp:51-88).
    """

    def __init__(
        self,
        length,
        fields,
        mesh: Mesh | None = None,
        periodic=(False, False, False),
        start=(0.0, 0.0, 0.0),
        cell_length=None,
    ):
        self.length = tuple(int(v) for v in length)
        self.periodic = tuple(bool(p) for p in periodic)
        self.mesh = mesh if mesh is not None else dense_mesh()
        if tuple(self.mesh.axis_names) != AXES:
            raise ValueError(f"DenseGrid needs a mesh with axes {AXES}")
        self.mesh_shape = tuple(self.mesh.shape[a] for a in AXES)
        for d in range(3):
            if self.length[d] % self.mesh_shape[d] != 0:
                raise ValueError(
                    f"grid length {self.length[d]} not divisible by mesh "
                    f"extent {self.mesh_shape[d]} along {AXES[d]}"
                )
        self.block = tuple(self.length[d] // self.mesh_shape[d] for d in range(3))
        self.start = np.asarray(start, dtype=np.float64)
        if cell_length is None:
            cell_length = tuple(1.0 / self.length[d] for d in range(3))
        self.cell_length = np.asarray(cell_length, dtype=np.float64)

        self.fields = {}
        self.arrays = {}
        for name, spec in fields.items():
            if isinstance(spec, tuple):
                shape, dtype = spec
            else:
                shape, dtype = (), spec
            self.fields[name] = (tuple(shape), jnp.dtype(dtype))
            self.arrays[name] = jnp.zeros(
                self.length + tuple(shape), dtype=dtype, device=self.sharding()
            )

    def sharding(self):
        return NamedSharding(self.mesh, P(*AXES))

    @property
    def n_cells(self) -> int:
        return self.length[0] * self.length[1] * self.length[2]

    # -- coordinates ---------------------------------------------------

    def cell_centers(self, dim: int) -> jnp.ndarray:
        """1-D array of cell-center coordinates along ``dim``."""
        return jnp.asarray(
            self.start[dim] + (np.arange(self.length[dim]) + 0.5) * self.cell_length[dim]
        )

    def init_fields(self, fn) -> None:
        """Set fields from ``fn(x, y, z) -> dict`` evaluated on cell
        centers (broadcast 3-D arrays), sharded evaluation."""
        x = self.cell_centers(0)[:, None, None]
        y = self.cell_centers(1)[None, :, None]
        z = self.cell_centers(2)[None, None, :]
        vals = fn(x, y, z)
        for name, v in vals.items():
            shape, dtype = self.fields[name]
            self.arrays[name] = jax.device_put(
                jnp.broadcast_to(v, self.length + shape).astype(dtype), self.sharding()
            )

    # -- halo padding (the ppermute ghost-slab exchange) ---------------

    def pad_with_halo(self, block: jnp.ndarray, halo: int, boundary: float = 0.0):
        """Inside shard_map: pad a local block with ``halo`` cells from
        the six mesh neighbors (lax.ppermute per direction); global
        non-periodic boundaries are filled with ``boundary``.

        This is the TPU lowering of update_copies_of_remote_neighbors()
        for uniform grids (dccrg.hpp:978, 10703-11209): one collective
        permute of face slabs per direction instead of per-peer
        MPI_Isend/Irecv of per-cell struct datatypes.
        """
        for d in range(3):
            n = self.mesh_shape[d]
            size = block.shape[d]
            hi_slab = lax.slice_in_dim(block, size - halo, size, axis=d)
            lo_slab = lax.slice_in_dim(block, 0, halo, axis=d)
            if n == 1:
                if self.periodic[d]:
                    from_lo, from_hi = hi_slab, lo_slab
                else:
                    from_lo = jnp.full_like(hi_slab, boundary)
                    from_hi = jnp.full_like(lo_slab, boundary)
            else:
                fwd = [(i, (i + 1) % n) for i in range(n if self.periodic[d] else n - 1)]
                bwd = [((i + 1) % n, i) for i in range(n if self.periodic[d] else n - 1)]
                from_lo = lax.ppermute(hi_slab, AXES[d], fwd)  # my low halo: left nbr's high slab
                from_hi = lax.ppermute(lo_slab, AXES[d], bwd)
                if not self.periodic[d]:
                    # edge devices received zeros; overwrite with boundary
                    pos = lax.axis_index(AXES[d])
                    from_lo = jnp.where(pos == 0, jnp.full_like(from_lo, boundary), from_lo)
                    from_hi = jnp.where(
                        pos == n - 1, jnp.full_like(from_hi, boundary), from_hi
                    )
            block = jnp.concatenate([from_lo, block, from_hi], axis=d)
        return block

    # -- stencil driver ------------------------------------------------

    def make_step(self, fn, fields_in, fields_out, halo: int = 1, boundary=0.0,
                  extra_specs=()):
        """Compile ``fn`` into a jitted distributed step.

        ``fn(blocks: dict, *extra) -> dict`` receives halo-padded local
        blocks ``[bx+2h, by+2h, bz+2h, ...]`` for every name in
        ``fields_in`` and must return interior updates ``[bx, by, bz, ...]``
        for every name in ``fields_out``. Runs under shard_map over the
        3-D mesh; returns ``step(arrays: dict, *extra) -> dict``.
        """
        fields_in = tuple(fields_in)
        fields_out = tuple(fields_out)
        mesh = self.mesh

        def body(*args):
            ins = args[: len(fields_in)]
            extra = args[len(fields_in):]
            padded = {
                n: self.pad_with_halo(b, halo, boundary) for n, b in zip(fields_in, ins)
            }
            out = fn(padded, *extra)
            return tuple(out[n] for n in fields_out)

        spec = P(*AXES)
        mapped = _shard_map(
            body,
            mesh=mesh,
            in_specs=(spec,) * len(fields_in) + tuple(extra_specs),
            out_specs=(spec,) * len(fields_out),
        )

        @jax.jit
        def step(arrays, *extra):
            res = mapped(*(arrays[n] for n in fields_in), *extra)
            out = dict(arrays)
            for n, v in zip(fields_out, res):
                out[n] = v
            return out

        return step

    # -- interop with the id-addressed world ---------------------------

    def cell_id_of_index(self, i, j, k):
        """Level-0 cell id at dense index (dccrg_mapping.hpp:154-209)."""
        nx, ny = self.length[0], self.length[1]
        return 1 + np.uint64(i) + np.uint64(j) * nx + np.uint64(k) * nx * ny

    def to_host(self, name: str) -> np.ndarray:
        return np.asarray(self.arrays[name])
