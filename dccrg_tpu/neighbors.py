"""Host-side neighbor resolution under AMR.

Re-implements the semantics of the reference's neighbor engine
(dccrg.hpp:4236-4897: ``indices_from_neighborhood``,
``find_neighbors_of``, ``find_neighbors_to``) with a fundamentally
different algorithm: instead of walking a per-cell 6-link graph, we
binary-search candidate ids in the sorted replicated cell list,
vectorized over (cells x neighborhood items) with numpy. The *results*
match the reference:

- A neighborhood is a list of integer offset triples in units of the
  cell's own edge length; offset (hx,hy,hz) denotes the axis-aligned
  window of the cell's own size at that displacement.
- Per window the neighbor is: the same-level cell occupying the window,
  or the coarser (level-1) cell containing it, or the 8 finer (level+1)
  cells inside it enumerated in z-order (x fastest) — dccrg's
  "expand to all siblings" rule (dccrg.hpp:4680-4713).
- Each distinct (neighbor, offset) relation is recorded once: a
  coarser neighbor covering several neighborhood windows would repeat
  with an identical min-corner offset, so those exact duplicates are
  collapsed (see _dedup_entries; stencil kernels must see each physical
  face once — the reference's advection DEBUG check asserts the same,
  tests/advection/solve.hpp:236-266). Distinct offsets for the same
  neighbor (periodic wrap-around) are all kept.
- Recorded offsets are the displacement of the neighbor's min corner
  from the cell's min corner in smallest-cell index units, *logical*
  (not wrapped) across periodic boundaries — what the reference's
  offset bookkeeping accumulates and what stencil kernels consume
  (e.g. advection face detection, tests/advection/solve.hpp:76-120).
- ``neighbors_to`` (cells that consider a given cell their neighbor) is
  obtained by exact inversion of the full neighbors_of relation, which
  by construction satisfies the consistency the reference's DEBUG
  verifier checks (dccrg.hpp:12516-12750).

Validity requirement (enforced by the AMR commit, not here): the cell
set exactly tiles the grid and refinement levels differ by at most 1
within any cell's neighborhood.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .mapping import Mapping
from .topology import GridTopology

# Maximum addressable index extent for the vectorized engine: signed
# 63-bit arithmetic is used for offset windows.
_MAX_INDEX = 2**62


def face_masks(cell_ilen, nbr_ilen, offs, mask):
    """Per-dimension (plus, minus) face masks for gathered stencil
    blocks — the reference's face-detection offset arithmetic
    (tests/advection/solve.hpp:76-120): a neighbor at logical offset
    ``o`` with index length ``nl`` is a face neighbor in dimension d
    when ``o_d`` equals the cell's index length (+d side) or ``-nl``
    (-d side) and the windows overlap in both other dimensions.

    Works on [L, S]-shaped device blocks (jnp) and on flat [E]-shaped
    host arrays (numpy) alike: ``cell_ilen`` broadcastable against
    ``nbr_ilen``, ``offs[..., 3]``, boolean ``mask``."""
    ci = cell_ilen
    overlap = [(offs[..., d] < ci) & (offs[..., d] > -nbr_ilen) for d in range(3)]
    faces = []
    for d in range(3):
        others = [overlap[e] for e in range(3) if e != d]
        both = others[0] & others[1] & mask
        faces.append(((offs[..., d] == ci) & both,
                      (offs[..., d] == -nbr_ilen) & both))
    return faces


def make_neighborhood(length: int) -> np.ndarray:
    """Default neighborhood offsets (dccrg.hpp:8017-8076): the 6 face
    offsets for length 0 (-z, -y, -x, +x, +y, +z order), else the full
    cube of radius ``length`` without (0,0,0), z-major x-fastest."""
    if length < 0:
        raise ValueError(f"neighborhood length must be >= 0, got {length}")
    if length == 0:
        return np.array(
            [[0, 0, -1], [0, -1, 0], [-1, 0, 0], [1, 0, 0], [0, 1, 0], [0, 0, 1]],
            dtype=np.int64,
        )
    r = np.arange(-length, length + 1, dtype=np.int64)
    z, y, x = np.meshgrid(r, r, r, indexing="ij")
    items = np.stack([x.ravel(), y.ravel(), z.ravel()], axis=1)
    return items[np.any(items != 0, axis=1)]


def validate_neighborhood(offsets: np.ndarray, default_length: int) -> np.ndarray:
    """User-neighborhood validation (dccrg.hpp:6573-6606): offsets must
    be unique, nonzero, and within the default neighborhood radius."""
    offsets = np.asarray(offsets, dtype=np.int64).reshape(-1, 3)
    if len(offsets) == 0:
        raise ValueError("neighborhood must contain at least one offset")
    if np.any(np.all(offsets == 0, axis=1)):
        raise ValueError("neighborhood must not contain the (0,0,0) offset")
    limit = max(default_length, 1)
    if np.any(np.abs(offsets) > limit):
        raise ValueError(
            f"neighborhood offsets must be within the default neighborhood "
            f"(max |offset| {limit}), got {offsets[np.any(np.abs(offsets) > limit, axis=1)][0]}"
        )
    if len(np.unique(offsets, axis=0)) != len(offsets):
        raise ValueError("neighborhood offsets must be unique")
    return offsets


@dataclass
class NeighborLists:
    """Flat ragged neighbors_of / neighbors_to for a cell set.

    ``of_*`` arrays: one entry per (cell, neighborhood item, neighbor).
    ``of_source`` indexes the queried cell array; ``of_neighbor`` holds
    neighbor cell ids; ``of_offset`` the [n,3] int64 logical offsets;
    ``of_item`` which neighborhood item produced the entry.
    ``to_*`` arrays: the inverted relation (see module docstring).
    """

    of_source: np.ndarray
    of_neighbor: np.ndarray
    of_offset: np.ndarray
    of_item: np.ndarray
    to_source: np.ndarray
    to_neighbor: np.ndarray
    to_offset: np.ndarray


class StructureError(RuntimeError):
    """The cell set violates grid invariants (gap, overlap, or a
    refinement-level jump > 1 inside a neighborhood)."""


def find_neighbors_of(
    mapping: Mapping,
    topology: GridTopology,
    all_cells_sorted: np.ndarray,
    query_cells: np.ndarray,
    neighborhood: np.ndarray,
):
    """neighbors_of for ``query_cells`` against the complete cell set.

    Returns flat arrays (source_index, neighbor_id, offset[ n,3 ],
    item_index) sorted by (source, item, z-order sibling rank).

    ``all_cells_sorted`` must be the complete sorted leaf-cell set of
    the grid (replicated structure).

    Dispatches to the native C++ engine (dccrg_tpu/native) when built;
    the NumPy implementation below is the reference and fallback.
    """
    from . import native

    if native.lib is not None and len(np.atleast_1d(query_cells)) > 0:
        index_length = mapping.get_index_length().astype(np.int64)
        if not np.any(index_length >= _MAX_INDEX):
            out = native.find_neighbors_of(
                mapping, topology, all_cells_sorted, query_cells, neighborhood
            )
            return _dedup_entries(mapping, query_cells, *out)
    return _dedup_entries(mapping, query_cells, *_find_neighbors_of_numpy(
        mapping, topology, all_cells_sorted, query_cells, neighborhood
    ))


def _dedup_entries(mapping, query_cells, src, nbr, off, item):
    """Collapse exact-duplicate (source, neighbor, offset) entries.

    A neighbor one level coarser than the queried cell covers up to 4
    neighborhood windows, and every one of those items records it with
    the same min-corner offset. Stencil kernels must see each physical
    neighbor relation once (the reference's advection DEBUG check
    asserts face-detected neighbors match the unique
    get_face_neighbors_of set, tests/advection/solve.hpp:236-266), so
    the first entry — lowest item index — is kept. A neighbor CAN
    legitimately recur with different offsets (periodic wrap-around
    self-neighbors), which is preserved.

    Only entries whose neighbor is COARSER than the source can be
    exact duplicates (same-level and finer neighbors are unique per
    window, and wrap-around recurrences differ in offset), so the
    uniqueness pass runs on that usually-tiny subset."""
    if len(src) == 0:
        return src, nbr, off, item
    query_cells = np.atleast_1d(np.asarray(query_cells, dtype=np.uint64))
    src_lvl = mapping.get_refinement_level(query_cells)
    nbr_lvl = mapping.get_refinement_level(nbr)
    cand = nbr_lvl < src_lvl[src]
    if not cand.any():
        return src, nbr, off, item
    ci = np.nonzero(cand)[0]
    key = np.stack(
        [src[ci].astype(np.int64), nbr[ci].astype(np.int64),
         off[ci, 0], off[ci, 1], off[ci, 2]], axis=1,
    )
    _, first = np.unique(key, axis=0, return_index=True)
    keep = np.ones(len(src), dtype=bool)
    keep[ci] = False
    keep[ci[first]] = True
    idx = np.nonzero(keep)[0]
    return src[idx], nbr[idx], off[idx], item[idx]


def _find_neighbors_of_numpy(
    mapping: Mapping,
    topology: GridTopology,
    all_cells_sorted: np.ndarray,
    query_cells: np.ndarray,
    neighborhood: np.ndarray,
):
    """Pure-NumPy neighbor resolution (reference implementation)."""
    query_cells = np.asarray(query_cells, dtype=np.uint64)
    neighborhood = np.asarray(neighborhood, dtype=np.int64).reshape(-1, 3)
    n, k = len(query_cells), len(neighborhood)
    if n == 0 or k == 0:
        empty = np.empty(0, dtype=np.int64)
        return empty, np.empty(0, dtype=np.uint64), np.empty((0, 3), dtype=np.int64), empty

    index_length = mapping.get_index_length().astype(np.int64)
    if np.any(index_length >= _MAX_INDEX):
        raise StructureError("grid index space too large for the vectorized engine")

    lvl = mapping.get_refinement_level(query_cells)  # [n]
    if np.any(lvl < 0):
        raise ValueError("invalid cell id in query")
    size = (1 << (mapping.max_refinement_level - lvl)).astype(np.int64)  # [n]
    base = mapping.get_indices(query_cells).astype(np.int64)  # [n,3]

    periodic = np.array([topology.is_periodic(d) for d in range(3)])

    # window min corners, logical: [n, k, 3]
    win = base[:, None, :] + neighborhood[None, :, :] * size[:, None, None]
    # wrap / validity
    inside = np.ones((n, k), dtype=bool)
    wrapped = win.copy()
    for d in range(3):
        if periodic[d]:
            wrapped[:, :, d] = np.mod(win[:, :, d], index_length[d])
        else:
            inside &= (win[:, :, d] >= 0) & (win[:, :, d] < index_length[d])
    wrapped = np.where(inside[:, :, None], wrapped, 0)

    exists = lambda ids: all_cells_sorted[
        np.minimum(np.searchsorted(all_cells_sorted, ids), len(all_cells_sorted) - 1)
    ] == ids if len(all_cells_sorted) else np.zeros(ids.shape, bool)

    lvl_b = np.broadcast_to(lvl[:, None], (n, k))
    # same-level slot cell at the window min corner
    slot = mapping.get_cell_from_indices(
        wrapped.reshape(-1, 3).astype(np.uint64), lvl_b.reshape(-1)
    ).reshape(n, k)
    have_same = exists(slot) & inside

    # coarser (level-1) cell containing the window
    lvl_up = np.maximum(lvl_b - 1, 0)
    coarse = mapping.get_cell_from_indices(
        wrapped.reshape(-1, 3).astype(np.uint64), lvl_up.reshape(-1)
    ).reshape(n, k)
    have_coarse = exists(coarse) & inside & ~have_same & (lvl_b > 0)

    # finer: the 8 children of the slot cell
    need_fine = inside & ~have_same & ~have_coarse
    if np.any(need_fine & (lvl_b >= mapping.max_refinement_level)):
        bad = np.argwhere(need_fine & (lvl_b >= mapping.max_refinement_level))[0]
        raise StructureError(
            f"no neighbor found for cell {query_cells[bad[0]]} at offset "
            f"{neighborhood[bad[1]]}: grid does not tile the domain"
        )

    src_i, item_i = np.nonzero(have_same)
    out_src = [src_i]
    out_nbr = [slot[have_same]]
    out_off = [(neighborhood[item_i] * size[src_i, None])]
    out_item = [item_i]

    if np.any(have_coarse):
        src_i, item_i = np.nonzero(have_coarse)
        csize = 2 * size[src_i]
        # coarse cell min corner (aligned down), relative to window min
        cmin = (wrapped[src_i, item_i] // csize[:, None]) * csize[:, None]
        rel = cmin - wrapped[src_i, item_i]  # components in {-s, 0}
        out_src.append(src_i)
        out_nbr.append(coarse[have_coarse])
        out_off.append(neighborhood[item_i] * size[src_i, None] + rel)
        out_item.append(item_i)

    if np.any(need_fine):
        src_i, item_i = np.nonzero(need_fine)
        half = size[src_i] // 2  # child edge length
        kk = np.arange(8, dtype=np.int64)
        dx = (kk & 1)[None, :] * half[:, None]
        dy = ((kk >> 1) & 1)[None, :] * half[:, None]
        dz = ((kk >> 2) & 1)[None, :] * half[:, None]
        child_rel = np.stack([dx, dy, dz], axis=-1)  # [m, 8, 3]
        child_idx = wrapped[src_i, item_i][:, None, :] + child_rel
        children = mapping.get_cell_from_indices(
            child_idx.reshape(-1, 3).astype(np.uint64),
            np.repeat(lvl[src_i] + 1, 8),
        ).reshape(-1, 8)
        ok = exists(children)
        if not np.all(ok):
            bad = np.argwhere(~ok)[0]
            raise StructureError(
                f"cell {query_cells[src_i[bad[0]]]} offset {neighborhood[item_i[bad[0]]]}: "
                f"window neither tiled by level {lvl[src_i[bad[0]]] + 1} cells nor coarser "
                f"(2:1 balance violated or grid has gaps)"
            )
        out_src.append(np.repeat(src_i, 8))
        out_nbr.append(children.reshape(-1))
        base_off = neighborhood[item_i] * size[src_i, None]
        out_off.append((base_off[:, None, :] + child_rel).reshape(-1, 3))
        out_item.append(np.repeat(item_i, 8))

    src = np.concatenate(out_src)
    nbr = np.concatenate(out_nbr)
    off = np.concatenate(out_off)
    item = np.concatenate(out_item)

    # order: by (source, neighborhood item, z-order within item)
    order = np.lexsort((np.arange(len(src)), item, src))
    return src[order], nbr[order], off[order], item[order]


def find_neighbors_to_subset(
    mapping: Mapping,
    topology: GridTopology,
    all_cells_sorted: np.ndarray,
    query_cells: np.ndarray,
    neighborhood: np.ndarray,
):
    """neighbors_to for a SUBSET of cells without building (and
    inverting) the full neighbors_of stream: for each query cell ``v``,
    the cells ``c`` with ``v`` in their neighbors_of.

    Direct enumeration: ``v`` is in c's window at item ``o`` iff
    ``c`` exists as a leaf, levels differ by <= 1, and v's box
    intersects the window ``[c.base + o*size_c, +size_c)``.
    (Intersection is sufficient: window resolution — same-level cell,
    containing coarser cell, or contained finer cells,
    dccrg.hpp:4744-4897 — then necessarily yields ``v`` because boxes
    at these sizes are aligned and ``v`` is a leaf.) Candidate window
    bases are the <= 3-per-dimension size_c-aligned positions
    overlapping v's box, enumerated per (item, source level).

    Returns ``(src_index, source_id, offset)`` flat arrays where
    ``src_index`` indexes ``query_cells``, ``offset`` is the recorded
    to-offset (``-of_offset``), ordered per query cell by (source
    position, item) — the order produced by inverting the full stream.
    Exact (source, offset) duplicates from a coarser source covering
    several windows are collapsed to the lowest item, mirroring
    _dedup_entries.
    """
    query_cells = np.atleast_1d(np.asarray(query_cells, dtype=np.uint64))
    neighborhood = np.asarray(neighborhood, dtype=np.int64).reshape(-1, 3)
    m = len(query_cells)
    empty = (np.empty(0, np.int64), np.empty(0, np.uint64),
             np.empty((0, 3), np.int64))
    if m == 0 or len(neighborhood) == 0 or len(all_cells_sorted) == 0:
        return empty

    index_length = mapping.get_index_length().astype(np.int64)
    if np.any(index_length >= _MAX_INDEX):
        raise StructureError("grid index space too large for the vectorized engine")
    periodic = np.array([topology.is_periodic(d) for d in range(3)])

    v_lvl = mapping.get_refinement_level(query_cells)
    if np.any(v_lvl < 0):
        raise ValueError("invalid cell id in query")
    v_size = (1 << (mapping.max_refinement_level - v_lvl)).astype(np.int64)
    v_base = mapping.get_indices(query_cells).astype(np.int64)

    exists = lambda ids: all_cells_sorted[
        np.minimum(np.searchsorted(all_cells_sorted, ids), len(all_cells_sorted) - 1)
    ] == ids

    # fast path: a query cell is "easy" when every possible to-source
    # is provably same-level; its to-list is then closed-form (the cell
    # at -o per item, offset -o*size). Finer sources reach at most the
    # +-hood slots, so a level-0 cell (no coarser cells exist) is easy
    # when its same-level neighbor exists at every valid +-offset. A
    # deeper cell can additionally have a COARSER source out to twice
    # the hood radius (the source's windows scale with ITS edge
    # length), so it must pass the same test over the doubled box —
    # any coarser leaf in that box would cover one of its slots.
    def same_level_at(off_arr):
        """(ids, valid, exist) of the same-level cells at v + off*size."""
        tgt = v_base + off_arr * v_size[:, None]
        ok = np.ones(m, dtype=bool)
        wrapped = tgt.copy()
        for d in range(3):
            if periodic[d]:
                wrapped[:, d] = np.mod(tgt[:, d], index_length[d])
            else:
                ok &= (tgt[:, d] >= 0) & (tgt[:, d] < index_length[d])
        ids = mapping.get_cell_from_indices(
            np.where(ok[:, None], wrapped, 0).astype(np.uint64), v_lvl
        )
        return ids, ok, exists(ids) & ok

    # the probe must cover every slot a source's window can originate
    # from — the FULL box of per-dim radius rho, not just the listed
    # offsets: for a sparse hood like [[2,0,0]] a finer source's
    # half-size windows reach the query from the unprobed +-1 slot.
    rho = np.abs(neighborhood).max(axis=0)

    def box_test(radius_scale, restrict):
        nonlocal easy
        box = [np.arange(-radius_scale * r, radius_scale * r + 1, dtype=np.int64)
               for r in rho]
        if np.prod([float(len(b)) for b in box]) > 360:
            easy &= ~restrict  # huge hood: fall back to full enumeration
            return
        for ox in box[0]:
            for oy in box[1]:
                for oz in box[2]:
                    if ox == oy == oz == 0:
                        continue
                    if not easy[restrict].any():
                        return
                    _ids, ok, ex = same_level_at(
                        np.array([[ox, oy, oz]], dtype=np.int64)
                    )
                    easy &= ~(restrict & ~(ex | ~ok))

    easy = np.ones(m, dtype=bool)
    box_test(1, np.ones(m, dtype=bool))
    deep = v_lvl > 0
    if deep.any():
        # deeper cells: a COARSER source's windows scale with its own
        # (doubled) edge length, reaching out to twice the hood radius
        box_test(2, deep)
    out_q, out_src, out_off, out_item = [], [], [], []
    if easy.any():
        for j, o in enumerate(neighborhood):
            ids, ok, ex = same_level_at(-o[None, :])
            sel = np.nonzero(easy & ex)[0]
            if len(sel):
                out_q.append(sel)
                out_src.append(ids[sel])
                out_off.append(-o[None, :] * v_size[sel, None])
                out_item.append(np.full(len(sel), j, dtype=np.int64))
    if easy.all():
        if not out_q:
            return empty
        q = np.concatenate(out_q)
        src = np.concatenate(out_src)
        off = np.concatenate(out_off)
        item = np.concatenate(out_item)
        src_pos = np.searchsorted(all_cells_sorted, src)
        order = np.lexsort((item, src_pos, q))
        return q[order], src[order], off[order]

    # hard queries: candidate-window enumeration — native C++ when
    # available, the NumPy loop below otherwise (identical raw entries)
    from . import native

    hard_idx = np.nonzero(~easy)[0]
    if native.lib is not None and len(hard_idx):
        hq, hsrc, hoff, hitem = native.find_neighbors_to_subset_raw(
            mapping, topology, all_cells_sorted, query_cells[hard_idx],
            neighborhood,
        )
        out_q.append(hard_idx[hq])
        out_src.append(hsrc)
        out_off.append(hoff)
        out_item.append(hitem)
        easy = np.ones(m, dtype=bool)  # skip the NumPy enumeration below
    for j, o in enumerate(neighborhood):
        for dlvl in (-1, 0, 1):
            c_lvl = v_lvl + dlvl
            # easy queries were answered closed-form above
            sel = (c_lvl >= 0) & (c_lvl <= mapping.max_refinement_level) & ~easy
            if not sel.any():
                continue
            qi = np.nonzero(sel)[0]
            sc = (1 << (mapping.max_refinement_level - c_lvl[qi])).astype(np.int64)
            vb, sv = v_base[qi], v_size[qi]
            # per-dim aligned window bases overlapping [vb, vb+sv):
            # w in [vb - sc + 1, vb + sv - 1], w % sc == 0
            w_lo = -(-(vb - sc[:, None] + 1) // sc[:, None]) * sc[:, None]
            counts = (vb + sv[:, None] - 1 - w_lo) // sc[:, None] + 1  # [q,3] >= 0
            cmax = int(counts.max(initial=0))
            if cmax <= 0:
                continue
            # expand the per-dim candidate grids
            steps = np.arange(cmax, dtype=np.int64)
            w_d = [w_lo[:, d, None] + steps[None, :] * sc[:, None] for d in range(3)]
            ok_d = [steps[None, :] < counts[:, d, None] for d in range(3)]
            # cartesian product via broadcasting: [q, cx, cy, cz]
            wx = w_d[0][:, :, None, None]
            wy = w_d[1][:, None, :, None]
            wz = w_d[2][:, None, None, :]
            okm = (ok_d[0][:, :, None, None] & ok_d[1][:, None, :, None]
                   & ok_d[2][:, None, None, :])
            qq, ix, iy, iz = np.nonzero(okm)
            if len(qq) == 0:
                continue
            w = np.stack([w_d[0][qq, ix], w_d[1][qq, iy], w_d[2][qq, iz]], axis=1)
            scq = sc[qq]
            c_base = w - o[None, :] * scq[:, None]  # logical
            # wrap / validity of the SOURCE cell position
            ok = np.ones(len(qq), dtype=bool)
            c_wrapped = c_base.copy()
            for d in range(3):
                if periodic[d]:
                    c_wrapped[:, d] = np.mod(c_base[:, d], index_length[d])
                else:
                    ok &= (c_base[:, d] >= 0) & (c_base[:, d] + scq < index_length[d] + 1)
            # the window itself must be inside the grid for non-periodic
            for d in range(3):
                if not periodic[d]:
                    ok &= (w[:, d] >= 0) & (w[:, d] < index_length[d])
            if not ok.any():
                continue
            qq, w, scq, c_wrapped = qq[ok], w[ok], scq[ok], c_wrapped[ok]
            cl = c_lvl[qi][qq]
            c_ids = mapping.get_cell_from_indices(
                c_wrapped.astype(np.uint64), cl
            )
            # source must exist as a leaf (a wrap-around source CAN be
            # the query cell itself: the stream keeps self entries on
            # tiny periodic dims)
            ex = exists(c_ids)
            if not ex.any():
                continue
            qq, w, scq, c_ids = qq[ex], w[ex], scq[ex], c_ids[ex]
            # recorded of_offset = v.min - c.min in c's logical frame:
            # v.base - c_base_logical = v.base - (w - o*sc)
            of_off = v_base[qi][qq] - w + o[None, :] * scq[:, None]
            out_q.append(qi[qq])
            out_src.append(c_ids)
            out_off.append(-of_off)
            out_item.append(np.full(len(qq), j, dtype=np.int64))

    if not out_q:
        return empty
    q = np.concatenate(out_q)
    src = np.concatenate(out_src)
    off = np.concatenate(out_off)
    item = np.concatenate(out_item)
    # dedup exact (query, source, offset) repeats, keep lowest item
    key = np.stack([q, src.astype(np.int64), off[:, 0], off[:, 1], off[:, 2]], axis=1)
    order0 = np.lexsort((item, key[:, 4], key[:, 3], key[:, 2], key[:, 1], key[:, 0]))
    ks = key[order0]
    first = np.ones(len(ks), dtype=bool)
    first[1:] = np.any(ks[1:] != ks[:-1], axis=1)
    keep = order0[first]
    q, src, off, item = q[keep], src[keep], off[keep], item[keep]
    # order per query cell by (source position, item) — stream parity
    src_pos = np.searchsorted(all_cells_sorted, src)
    order = np.lexsort((item, src_pos, q))
    return q[order], src[order], off[order]


def build_neighbor_lists(
    mapping: Mapping,
    topology: GridTopology,
    all_cells_sorted: np.ndarray,
    neighborhood: np.ndarray,
) -> NeighborLists:
    """neighbors_of for every cell in the grid, plus the inverted
    neighbors_to relation."""
    src, nbr, off, item = find_neighbors_of(
        mapping, topology, all_cells_sorted, all_cells_sorted, neighborhood
    )
    # invert: v in neighbors_of(c) with offset o  =>  c in neighbors_to(v)
    # with offset -o (displacement of c's min corner from v's).
    nbr_row = np.searchsorted(all_cells_sorted, nbr)
    to_src = nbr_row
    to_nbr = all_cells_sorted[src]
    to_off = -off
    order = np.lexsort((np.arange(len(to_src)), to_src))
    return NeighborLists(
        of_source=src,
        of_neighbor=nbr,
        of_offset=off,
        of_item=item,
        to_source=to_src[order],
        to_neighbor=to_nbr[order],
        to_offset=to_off[order],
    )


def verify_tiling(mapping: Mapping, all_cells_sorted: np.ndarray) -> None:
    """DEBUG-style invariant check (cf. dccrg.hpp:12516-12750): the cell
    set exactly tiles the index space — total volume matches and no two
    cells overlap (sufficient together with uniqueness)."""
    cells = np.asarray(all_cells_sorted, dtype=np.uint64)
    if len(np.unique(cells)) != len(cells):
        raise StructureError("duplicate cell ids")
    lvl = mapping.get_refinement_level(cells)
    if np.any(lvl < 0):
        raise StructureError("invalid cell id in cell set")
    size = (1 << (mapping.max_refinement_level - lvl)).astype(object)
    total = int(np.sum(size**3))
    expect = int(np.prod(mapping.get_index_length().astype(object)))
    if total != expect:
        raise StructureError(f"cells cover volume {total}, grid volume is {expect}")
    # overlap check: no cell's ancestor may also be present
    for up in range(1, mapping.max_refinement_level + 1):
        sub = cells[lvl >= up]
        if len(sub) == 0:
            continue
        anc = sub
        for _ in range(up):
            anc = mapping.get_parent(anc)
        pos = np.searchsorted(cells, anc)
        pos = np.minimum(pos, len(cells) - 1)
        if np.any(cells[pos] == anc):
            raise StructureError("overlapping cells: an ancestor of a cell is also present")
