"""dccrg_tpu — a TPU-native distributed cartesian cell-refinable grid.

A from-scratch JAX/XLA/Pallas framework with the capabilities of the
reference dccrg library (header-only C++/MPI/Zoltan; see SURVEY.md):

- global 64-bit cell addressing under adaptive 2:1-balanced octree
  refinement (``Mapping``),
- per-cell user data as SoA JAX arrays sharded over a TPU device mesh,
- neighbor resolution for arbitrary rectangular neighborhoods,
- halo exchange lowered to XLA collectives (``lax.ppermute`` /
  ``lax.all_to_all``) under ``shard_map``,
- adaptive mesh refinement and load balancing as host-side replanning
  events,
- parallel checkpoint/restart,
- a resilience layer (checksummed atomic checkpoints, a numerics
  watchdog with auto-rollback, OOM-aware gather-mode fallback and
  hang-proof device probing) with deterministic fault injection,
- a distributed-coordination layer (``coord``: timeout-guarded
  barriers, two-phase-commit multi-process checkpoints, cross-rank
  trip consensus, guarded ``jax.distributed`` bring-up),
- preemption-aware run supervision (``supervise``: SIGTERM/SIGINT
  emergency checkpoints with a resumable exit code, a step-hang
  deadline watchdog, auto-resume from the newest verified checkpoint
  and keep-last-K/keep-every-N retention GC),
- a fleet serving layer (``fleet``/``scheduler``: N independent
  same-shape scenario runs stacked along a batch axis into one
  jitted device program, fronted by a priority job queue with
  per-job checkpoint stems, per-slot NaN/OOM isolation and
  preemption-requeue — ``python -m dccrg_tpu.fleet``),
- a silent-data-corruption defense (``integrity``: in-program
  fingerprint/conservation invariants fused into the fleet quantum
  program, sampled shadow-execution audits, DMR job replication, a
  CORRUPT trip class with per-victim rollback and consensus, device
  quarantine with bit-exact survivor migration, and offline at-rest
  fingerprint audits — ``python -m dccrg_tpu.resilience audit``),
- a telemetry subsystem (``telemetry``: process-wide counter/gauge/
  histogram registry with Prometheus text exposition, a low-overhead
  ring-buffered span tracer over every hot boundary — step dispatch,
  halo exchange, adapt/recommit, checkpoint phases, fleet quanta —
  with rank-tagged JSONL traces that merge across processes, and
  strictly best-effort exporters; ``DCCRG_TRACE=1``, ``python -m
  dccrg_tpu.telemetry``) feeding latency-SLO fleet admission
  (``scheduler.SLOPolicy``: per-job ``slo_ms`` deadlines, EWMA
  quantum-latency projection, over-latency bucket shedding),
- a production autopilot (``autopilot``: an opt-in deterministic
  controller, ``DCCRG_AUTOPILOT=1``, tuning fleet quantum length,
  per-stem checkpoint cadence, audit cadence and initial bucket
  capacity within hard bounds from the telemetry the system already
  records — with every decision journaled as a structured record
  that ``python -m dccrg_tpu.autopilot explain|replay`` reconstructs
  and re-derives from the journal alone).

Reference: /root/reference (dccrg.hpp and friends). This package is a
re-design for TPU, not a translation: structure (cell lists, neighbor
tables, partition) is replicated host state rebuilt at structure-change
events; data (cell payloads) lives in HBM and only moves through
compiled collectives.
"""

from .types import ERROR_CELL, ERROR_INDEX
from .length import GridLength
from .topology import GridTopology
from .mapping import Mapping
from .geometry import NoGeometry, CartesianGeometry, StretchedCartesianGeometry
from .grid import (DEFAULT_NEIGHBORHOOD_ID, Grid, SlotwiseKernel,
                   default_mesh, ghost_split_enabled)
from .dense import DenseGrid, dense_mesh
from .verify import VerificationError, verify_all
from .txn import (GridInvariantError, MutationAbortedError, MutationError,
                  grid_transaction)
from .faults import FaultPlan
from .coord import (BarrierTimeoutError, CheckpointCommitError,
                    DistributedInitError, Membership, PeerDeadError,
                    barrier, distributed_init, trip_consensus)
from .resilience import (CheckpointCorruptionError, DeviceProbeError,
                         NumericsError, ResilienceExhaustedError,
                         ResilientRunner, guarded_step, load_checkpoint,
                         save_checkpoint, safe_devices)
from .supervise import (RESUMABLE_EXIT, CheckpointStore, PreemptedError,
                        StepTimeoutError, SupervisedRunner,
                        gc_checkpoints, resume_latest)
from .fleet import FleetJob, GridBatch
from .scheduler import (FleetPreemptedError, FleetScheduler,
                        OwnershipLostError, SLOPolicy)
from .intake import (IntakeError, IntakeRetryExhausted, StreamIntake,
                     submit as submit_job)
from .integrity import IntegrityError, register_conserved
from . import telemetry
from .telemetry import LogHistogram
from . import autopilot
from .autopilot import Autopilot
from .warmstart import WarmCacheError, WarmPool

__version__ = "0.1.0"

__all__ = [
    "ERROR_CELL",
    "ERROR_INDEX",
    "GridLength",
    "GridTopology",
    "Mapping",
    "NoGeometry",
    "CartesianGeometry",
    "StretchedCartesianGeometry",
    "Grid",
    "SlotwiseKernel",
    "DenseGrid",
    "DEFAULT_NEIGHBORHOOD_ID",
    "default_mesh",
    "ghost_split_enabled",
    "dense_mesh",
    "VerificationError",
    "verify_all",
    "GridInvariantError",
    "MutationAbortedError",
    "MutationError",
    "grid_transaction",
    "FaultPlan",
    "BarrierTimeoutError",
    "CheckpointCommitError",
    "DistributedInitError",
    "Membership",
    "PeerDeadError",
    "OwnershipLostError",
    "barrier",
    "distributed_init",
    "trip_consensus",
    "CheckpointCorruptionError",
    "DeviceProbeError",
    "NumericsError",
    "ResilienceExhaustedError",
    "ResilientRunner",
    "guarded_step",
    "load_checkpoint",
    "save_checkpoint",
    "safe_devices",
    "RESUMABLE_EXIT",
    "CheckpointStore",
    "PreemptedError",
    "StepTimeoutError",
    "SupervisedRunner",
    "gc_checkpoints",
    "resume_latest",
    "FleetJob",
    "GridBatch",
    "FleetPreemptedError",
    "FleetScheduler",
    "IntakeError",
    "IntakeRetryExhausted",
    "StreamIntake",
    "submit_job",
    "IntegrityError",
    "register_conserved",
    "SLOPolicy",
    "LogHistogram",
    "telemetry",
    "autopilot",
    "Autopilot",
    "WarmCacheError",
    "WarmPool",
]
