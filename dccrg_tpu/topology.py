"""Grid topology: per-dimension periodicity.

Equivalent of the reference's ``Grid_Topology`` (dccrg_topology.hpp:38):
three booleans stating whether the grid wraps around in x/y/z, plus the
binary file representation used by checkpoint files (3 uint8 values,
dccrg_topology.hpp:108-222).
"""

from __future__ import annotations

import numpy as np


class GridTopology:
    def __init__(self, periodic=(False, False, False)):
        self._periodic = [False, False, False]
        self.set_periodicity(periodic)

    def set_periodicity(self, periodic) -> None:
        periodic = list(periodic)
        if len(periodic) != 3:
            raise ValueError(f"periodicity must be 3 values, got {periodic!r}")
        self._periodic = [bool(p) for p in periodic]

    def is_periodic(self, dimension: int) -> bool:
        if dimension not in (0, 1, 2):
            raise ValueError(f"dimension must be 0..2, got {dimension}")
        return self._periodic[dimension]

    @property
    def periodic(self) -> tuple:
        return tuple(self._periodic)

    # --- file format (reference: dccrg_topology.hpp:108-222) ---------
    # 3 bytes, one per dimension, nonzero = periodic.

    def data_size(self) -> int:
        return 3

    def to_bytes(self) -> bytes:
        return bytes(np.array(self._periodic, dtype=np.uint8))

    @classmethod
    def from_bytes(cls, data: bytes) -> "GridTopology":
        if len(data) != 3:
            raise ValueError(f"topology record must be 3 bytes, got {len(data)}")
        arr = np.frombuffer(data, dtype=np.uint8)
        return cls(tuple(bool(v) for v in arr))

    def __eq__(self, other) -> bool:
        return isinstance(other, GridTopology) and self._periodic == other._periodic

    def __repr__(self) -> str:
        return f"GridTopology(periodic={tuple(self._periodic)})"
