"""Fleet job scheduler: a priority queue over batched grid buckets.

:class:`FleetScheduler` turns :mod:`dccrg_tpu.fleet`'s batched
execution layer into a multi-tenant serving loop, reusing the
per-run lifecycle machinery of :mod:`dccrg_tpu.supervise` PER JOB:

- **admission**: jobs pop in priority order and land in the
  :class:`~dccrg_tpu.fleet.GridBatch` bucket their
  ``(shape, schema, kernel)`` key selects — created on demand with a
  :func:`~dccrg_tpu.grid.bucket_capacity`-rounded slot count (capped
  by ``DCCRG_FLEET_MAX_BATCH``) so the compiled program survives
  drain and backfill; a job that does not fit waits in the queue and
  **backfills** the next slot a finishing/failing/requeued job frees;
- **checkpoints**: every job owns a
  :class:`~dccrg_tpu.supervise.CheckpointStore` stem (its name) in
  ONE shared directory — periodic per-job saves (dirty-field deltas
  chained to keyframes, exactly the single-run data plane) happen at
  quantum boundaries when a job crosses its ``checkpoint_every``
  cadence, followed by per-stem retention GC
  (:func:`~dccrg_tpu.supervise.gc_checkpoints`, which treats each
  stem as an independent sequence);
- **isolation trips**: the per-slot numerics watchdog
  (:meth:`~dccrg_tpu.fleet.GridBatch.finite_slots`) rolls a tripped
  job back from ITS OWN newest verifying checkpoint in place
  (bounded retries, then ``failed``); a job-scoped injected OOM
  (:meth:`~dccrg_tpu.faults.FaultPlan.resource_exhausted` with
  ``job=``) **requeues** only that job — it re-admits from its
  checkpoint, possibly into a different slot or bucket instance,
  while every neighbor slot's bytes stay frozen-exact. A REAL
  (unattributed) ``RESOURCE_EXHAUSTED`` from the batched dispatch
  requeues the lower-priority half of the bucket's jobs to shrink
  the working set;
- **SDC defense** (:mod:`dccrg_tpu.integrity`): every batched
  dispatch returns fused per-slot entry/exit fingerprints and
  conservation sums (``DCCRG_INTEGRITY``, on by default); the
  scheduler compares them exactly (integer fingerprints) or against
  the expected drift (conservation sums) every quantum, runs a
  sampled **shadow-execution audit** every ``DCCRG_AUDIT_EVERY``
  ticks (re-execute one slot's last quantum from its pre-quantum
  state in a spare slot or the solo path, compare bitwise), and
  bitwise-compares **DMR** replicas (``FleetJob(redundancy=2)``) at
  every quantum boundary. A CORRUPT verdict rolls back ONLY the
  victim from its own checkpoint chain (the NaN discipline, bounded
  retries) and marks the batch's device lane suspect; a lane
  exceeding ``DCCRG_QUARANTINE_AFTER`` verdicts is **quarantined** —
  its buckets rebuild on surviving lanes with every admitted job
  migrated bit-exactly;
- **preemption**: the loop polls the supervision layer's preempt
  flag (SIGTERM/SIGINT handlers, :func:`~dccrg_tpu.supervise
  .request_preempt`, or a faked
  :meth:`~dccrg_tpu.faults.FaultPlan.preempt_signal`) at quantum
  boundaries; on preemption every admitted job takes an emergency
  keyframe into its own stem and is requeued, then
  :class:`FleetPreemptedError` surfaces with the resumable exit code
  75 — rerunning the scheduler over the same directory resumes every
  job from its checkpoint (``resume=True``), bitwise identical to an
  uninterrupted fleet;
- **elastic multi-host fleet** (``rank_aware=True`` /
  ``DCCRG_RANK_AWARE=1``): schedulers on several hosts serve ONE job
  set over a shared checkpoint directory. Each rank heartbeats a
  :class:`~dccrg_tpu.coord.Membership` lease, every admitted job
  records an owner rank + **lease epoch** in the shared KV
  (:class:`JobLeases`), and leases renew at tick boundaries. A rank
  observing a peer's lease EXPIRED (no renewal for ``DCCRG_LEASE_S``
  of the observer's own clock) **reclaims** the job: a
  compare-and-set on the next epoch's claim key means exactly one
  survivor wins, and the winner re-admits the job from its
  checkpoint stem through the proven ``_load_newest``/``_admit_into``
  path — bitwise identical to an uninterrupted run. Fencing: the
  epoch is checked before EVERY save publish, so a paused-then-
  resumed zombie owner gets a typed :class:`OwnershipLostError` and
  drops the job locally (no rollback side effects, no stale
  checkpoint ever lands over the reclaimer's chain). The pending
  queue partitions across live ranks (deterministic hash +
  load-balance by projected completion from the SLO EWMAs); a
  shrunk fleet degrades to single-host serving with a logged
  membership transition, and a rejoining rank re-enters the
  partition at the next tick. OFF by default: without the flag no
  membership/lease object exists and scheduling is bitwise identical
  to the rank-unaware scheduler (the negative pin).
"""

from __future__ import annotations

import heapq
import itertools
import logging
import os
import time
import zlib
from contextlib import nullcontext

import numpy as np

from . import autopilot as autopilot_mod
from . import (coord, faults, integrity, resilience, supervise,
               telemetry, warmstart)
from .fleet import (SHADOW, FleetJob, GridBatch, max_batch_default,
                    quantum_default)
from .grid import bucket_capacity

logger = logging.getLogger("dccrg_tpu.scheduler")


def rank_aware_default(default: bool = False) -> bool:
    """The ``DCCRG_RANK_AWARE`` env knob: ``1`` makes the fleet
    scheduler rank-aware (membership heartbeats, lease-based job
    ownership, orphan reclaim). Off (default): no membership or lease
    object exists and scheduling is bitwise identical to the
    rank-unaware scheduler."""
    v = os.environ.get("DCCRG_RANK_AWARE", "")
    if v == "":
        return default
    return v not in ("0", "off", "false", "no")


class OwnershipLostError(RuntimeError):
    """This rank's lease on a fleet job was FENCED by a higher epoch:
    a survivor reclaimed the job (this rank's renewals stopped for
    ``DCCRG_LEASE_S`` — paused, partitioned, or presumed dead) and
    owns its checkpoint stem now. The job must be dropped locally
    WITHOUT rollback side effects — publishing anything over the
    reclaimer's chain is exactly what the epoch fence exists to
    prevent."""

    def __init__(self, job, rank, held_epoch, current):
        super().__init__(
            f"lease on fleet job {job!r} lost: rank {rank} holds epoch "
            f"{held_epoch}, but the shared KV records {current!r} — a "
            "survivor reclaimed the job; dropping it locally (the "
            "reclaimer's checkpoint chain is the live one)")
        self.job = str(job)
        self.rank = int(rank)
        self.held_epoch = held_epoch
        self.current = current


class JobLeases:
    """Lease-based job ownership with epoch fencing over the
    coordination KV store (:func:`dccrg_tpu.coord.default_kv`).

    KV layout per job name::

        <prefix>/<name>          -> "<rank>:<epoch>:<beat>"
        <prefix>/<name>@<epoch>  -> "<rank>"   (the reclaim claim)
        <prefix>/done/<name>     -> "<status>:<rank>:<steps>:<digest>"

    The lease value's ``beat`` bumps on every renewal; expiry is
    judged by OBSERVER aging (the :class:`~dccrg_tpu.coord.Membership`
    discipline — the observer's own clock ages a value it saw stop
    changing, no cross-host clock comparison). Takeover is a
    compare-and-set: :meth:`try_reclaim` CAS-creates the claim key
    for the NEXT epoch, and the KV's first-writer-wins guarantees
    exactly one survivor wins a given epoch. :meth:`check` is the
    fencing gate consulted before every save publish and renewal —
    a claim key above the held epoch (or a higher-epoch lease record)
    raises the typed :class:`OwnershipLostError`, so a zombie whose
    renew overwrote the lease VALUE still cannot publish: the claim
    key it can never un-create convicts it."""

    def __init__(self, kv, rank: int, *, lease_s=None,
                 clock=time.monotonic, prefix: str = "dccrg/job"):
        self.kv = kv
        self.rank = int(rank)
        self.lease_s = (coord.lease_seconds() if lease_s is None
                        else float(lease_s))
        self.clock = clock
        self.prefix = str(prefix)
        self.owned: dict = {}   # name -> held epoch
        self._beat = 0
        self._watch: dict = {}  # name -> [raw value, first-seen clock]

    def _key(self, name) -> str:
        return f"{self.prefix}/{name}"

    def census(self):
        """One-call snapshot of every lease/claim/done key under the
        prefix, or None when the KV cannot list (callers then fall
        back to per-key reads). On the real coordination service an
        ABSENT key costs a full blocking-get timeout, so the tick
        path reads the census once instead of per-key; publish-time
        fencing (:meth:`check` from ``_save_job``/``_finish``) stays
        on fresh per-key reads."""
        return coord.prefix_census(self.kv, self.prefix)

    def _read(self, key, census=None):
        return census.get(key) if census is not None \
            else self.kv.get(key)

    @staticmethod
    def _parse(raw):
        try:
            r, e, b = str(raw).split(":")
            return int(r), int(e), int(b)
        except (ValueError, TypeError, AttributeError):
            return None

    def _write(self, name, epoch) -> None:
        self._beat += 1
        self.kv.set(self._key(name),
                    f"{self.rank}:{int(epoch)}:{self._beat}")

    def acquire(self, name) -> int:
        """Own ``name`` at admission; returns the held epoch. A fresh
        job CAS-creates epoch 1; this rank's own surviving record (a
        restarted scheduler, a requeue) is adopted after the fencing
        check. A lease held by ANOTHER rank raises
        :class:`OwnershipLostError` — expiry takeovers go through
        :meth:`try_reclaim`, never through admission."""
        name = str(name)
        held = self.owned.get(name)
        if held is not None:
            self.check(name)
            self._write(name, held)
            return held
        if self.kv.create(self._key(name), f"{self.rank}:1:0"):
            self.owned[name] = 1
            return 1
        raw = self.kv.get(self._key(name))
        cur = self._parse(raw)
        if cur is not None and cur[0] == self.rank:
            self.owned[name] = cur[1]
            self.check(name)
            self._write(name, cur[1])
            return cur[1]
        raise OwnershipLostError(name, self.rank, None, raw)

    def check(self, name, census=None) -> None:
        """The fencing gate (consulted before EVERY save publish):
        raise :class:`OwnershipLostError` — and forget the lease
        locally — when a reclaimer's claim key for the next epoch
        exists or the lease record carries a higher epoch / another
        rank at ours. ``census`` serves the reads on the tick path;
        publish-time callers pass None for fresh per-key reads."""
        name = str(name)
        held = self.owned.get(name)
        if held is None:
            raise OwnershipLostError(
                name, self.rank, None,
                self._read(self._key(name), census))
        claim = self._read(f"{self._key(name)}@{held + 1}", census)
        if claim is not None:
            self.owned.pop(name, None)
            raise OwnershipLostError(
                name, self.rank, held,
                f"epoch {held + 1} claimed by rank {claim}")
        cur = self._parse(self._read(self._key(name), census))
        if cur is not None and (cur[1] > held
                                or (cur[1] == held
                                    and cur[0] != self.rank)):
            self.owned.pop(name, None)
            raise OwnershipLostError(name, self.rank, held,
                                     f"{cur[0]}:{cur[1]}")

    def renew(self, name, census=None) -> None:
        """Renew one owned lease (tick boundaries); the fencing check
        runs first, so a fenced zombie learns before it writes."""
        self.check(name, census)
        self._write(name, self.owned[str(name)])

    def renew_owned(self, census=None) -> list:
        """Renew every owned lease; returns the ``[(name, error)]``
        fenced ones (reclaimed while this rank was paused)."""
        lost = []
        for name in sorted(self.owned):
            try:
                self.renew(name, census)
            except OwnershipLostError as e:
                lost.append((name, e))
        return lost

    def release(self, name) -> None:
        """Stop renewing (the job finished; the done marker, not the
        lease, is its terminal record)."""
        self.owned.pop(str(name), None)

    def holder(self, name, census=None):
        """The rank the KV currently records as owner, or None."""
        cur = self._parse(self._read(self._key(str(name)), census))
        return None if cur is None else cur[0]

    def expired_holder(self, name, census=None):
        """Observer-aged expiry: the OTHER rank whose lease on
        ``name`` has not changed for ``lease_s``, else None. A fresh
        observer grants the current value a full lease of grace."""
        name = str(name)
        raw = self._read(self._key(name), census)
        if raw is None:
            return None
        now = self.clock()
        rec = self._watch.get(name)
        if rec is None or rec[0] != raw:
            self._watch[name] = rec = [raw, now]
        cur = self._parse(raw)
        if cur is None or cur[0] == self.rank:
            return None
        return cur[0] if now - rec[1] >= self.lease_s else None

    def try_reclaim(self, name):
        """Fenced takeover of an expired lease: CAS-create the claim
        key for the NEXT epoch of the lease value this observer
        actually watched expire (exactly one survivor can — the KV's
        first-writer-wins IS the compare-and-set), then rewrite the
        lease record at that epoch. Returns the new held epoch, or
        None when another survivor won — a takeover that already
        happened shows as a moved value, which must age a fresh full
        lease before anyone may claim it again."""
        name = str(name)
        rec = self._watch.get(name)
        raw = (rec[0] if rec is not None
               else self.kv.get(self._key(name)))
        cur = self._parse(raw)
        if cur is None:
            # the owner died before its lease record ever landed
            if self.kv.create(self._key(name), f"{self.rank}:1:0"):
                self.owned[name] = 1
                return 1
            return None
        live = self.kv.get(self._key(name))
        if live != raw:
            # the record moved since expiry was judged (another
            # survivor's takeover, or a late renew): not ours to take
            if live is not None:
                self._watch[name] = [live, self.clock()]
            return None
        now = self.clock()
        nxt = cur[1] + 1
        for _ in range(64):  # bound far above any real claim chain
            if self.kv.create(f"{self._key(name)}@{nxt}",
                              str(self.rank)):
                break
            # the claim key exists but the lease record we just read
            # is UNMOVED: either its creator won microseconds ago and
            # is about to rewrite the record, or it died in the two-
            # write window (claim created, record never rewritten) —
            # which would otherwise leave the job unreclaimable
            # FOREVER (every survivor's CAS at this epoch loses).
            # Give the claimant one full lease from first sight of
            # its claim, then escalate past the orphaned epoch.
            ck = f"{self._key(name)}@{nxt}"
            rec = self._watch.get(ck)
            if rec is None:
                self._watch[ck] = [self.kv.get(ck), now]
                return None
            if now - rec[1] < self.lease_s:
                return None
            nxt += 1
        else:
            return None
        self.owned[name] = nxt
        self._watch.pop(name, None)
        self._write(name, nxt)
        return nxt


class SLOPolicy:
    """Latency-SLO admission + shedding, fed by telemetry.

    The scheduler reports every bucket's measured quantum dispatch
    latency into :meth:`observe`; the policy keeps a per-bucket-key
    EWMA and turns it into two decisions:

    - **admission order** (:meth:`admission_key`): a job with a
      ``slo_ms`` deadline whose PROJECTED completion — remaining
      quanta x the EWMA latency of its bucket key, measured from its
      first enqueue — would violate the deadline jumps the priority
      queue (most-violated first); everything else keeps the plain
      ``(priority, FIFO)`` order, so a fleet without SLOs (or without
      latency pressure) admits byte-identically to the priority-only
      baseline;
    - **shedding** (:meth:`shed_victims`): when a bucket's measured
      quantum latency blows the TIGHTEST admitted slot SLO (negative
      slack), the least-urgent cohabitants — best-effort jobs first,
      lowest priority first, then the loosest-slack SLO jobs, never
      the tightest — are requeued so the scheduler can rebuild the
      bucket smaller (half capacity: fewer slots per dispatch = lower
      quantum latency for the jobs that stay).

    Deterministic by construction: ``clock`` is injectable (the
    pinned tests drive a fake clock and hand-fed observations) and
    the EWMA state is plain floats."""

    def __init__(self, quantum=None, alpha=0.25, clock=time.monotonic,
                 shed_cooldown=4):
        self.quantum = (quantum_default() if quantum is None
                        else max(1, int(quantum)))
        self.alpha = float(alpha)
        self.clock = clock
        #: ticks a bucket is left alone after a shed rebuild (the
        #: fresh, smaller bucket must re-measure before re-shedding)
        self.shed_cooldown = int(shed_cooldown)
        self._ewma: dict = {}  # bucket key -> EWMA quantum seconds
        #: warm-start hook (``WarmPool.projection_cost``): extra
        #: up-front seconds to charge a bucket key whose first
        #: dispatch will pay a cold compile — 0.0 once pre-warmed.
        #: None (the default) leaves every projection untouched.
        self.warm_cost = None

    def observe(self, key, seconds: float) -> None:
        """Fold one measured quantum dispatch latency into the
        bucket key's EWMA."""
        e = self._ewma.get(key)
        self._ewma[key] = (float(seconds) if e is None
                           else (1.0 - self.alpha) * e
                           + self.alpha * float(seconds))

    def quantum_latency(self, key):
        """The EWMA quantum latency of ``key`` (None: unmeasured)."""
        return self._ewma.get(key)

    def reset_key(self, key) -> None:
        """Forget a bucket key's EWMA (after a shed rebuild: the
        smaller bucket must be measured fresh, not judged by its
        predecessor's latency)."""
        self._ewma.pop(key, None)

    def projected_completion_s(self, job) -> float:
        """Projected seconds to finish ``job``: remaining quanta x
        the EWMA latency of its bucket key (0 when unmeasured — no
        data never reorders the queue), plus — when a warm-start pool
        is attached — the bucket's measured cold-compile cost while
        it is not yet pre-warmed: the compile storm is charged up
        front instead of discovered mid-tick."""
        key = job.bucket_key()
        extra = 0.0 if self.warm_cost is None else float(
            self.warm_cost(key))
        lat = self._ewma.get(key)
        if lat is None:
            return extra
        remaining = max(0, job.n_steps - job.steps_done)
        quanta = -(-remaining // self.quantum)  # ceil
        return quanta * lat + extra

    def slack_s(self, job):
        """Seconds of SLO budget left after the projected completion
        (None for best-effort jobs; negative = projected violation)."""
        if job.slo_ms is None or job.slo_t0 is None:
            return None
        budget = job.slo_ms / 1e3 - (self.clock() - job.slo_t0)
        return budget - self.projected_completion_s(job)

    def admission_key(self, job, seq):
        """Sort key for one admission pass: SLO-violating jobs first
        (most negative slack first), then the priority-FIFO
        baseline."""
        slack = self.slack_s(job)
        if slack is not None and slack < 0.0:
            return (0, slack, -job.priority, seq)
        return (1, 0.0, -job.priority, seq)

    def shed_victims(self, key, jobs) -> list:
        """The ``[(slot, job)]`` to requeue out of a bucket whose
        measured quantum latency blows its tightest admitted SLO —
        empty when the bucket is unmeasured, single-job, SLO-free, or
        every SLO still has slack. At most half the jobs shed, and
        the tightest-slack SLO job never does (shedding it would
        serve nobody)."""
        if len(jobs) <= 1 or self._ewma.get(key) is None:
            return []
        slacks = {j.name: self.slack_s(j) for _s, j in jobs}
        slo = [(s, j) for s, j in jobs if slacks[j.name] is not None]
        if not slo or min(slacks[j.name] for _s, j in slo) >= 0.0:
            return []
        # least urgent first: best-effort (no SLO) by ascending
        # priority, then SLO jobs by DESCENDING slack; the tightest
        # stays, and at most half the bucket sheds
        order = sorted(
            jobs, key=lambda e: ((0, e[1].priority, -e[0])
                                 if slacks[e[1].name] is None
                                 else (1, -slacks[e[1].name], -e[0])))
        return order[:min(len(jobs) // 2, len(jobs) - 1)]

    def lane_shed_victims(self, groups):
        """Cross-bucket (mixed-kernel) shedding for one device lane.

        ``groups`` is ``[(index, key, jobs)]`` — one entry per bucket
        sharing the lane (distinct kernels land in distinct buckets,
        so a lane serving a mixed fleet dispatches every group each
        tick and a deadline job pays the SUM of the cohabiting
        buckets' quantum latencies per quantum of its own). When a
        deadline job's slack measured against that lane latency is
        negative while its own bucket alone would still meet the
        deadline — the cohabitants, not the bucket, are the problem —
        the best-effort jobs of the OTHER groups are the victims
        (lowest priority first). Returns ``(trigger_job, victims)``
        with victims ``[(index, slot, job)]``, or None when there is
        no cross-bucket pressure (fewer than two groups, unmeasured
        latencies, no SLO job, or no best-effort cohabitant): a
        single-kernel or SLO-free fleet never sheds across buckets —
        the negative pin."""
        if len(groups) < 2:
            return None
        lats = {i: self._ewma.get(key) for i, key, _jobs in groups}
        if any(lat is None for lat in lats.values()):
            return None
        lane_lat = sum(lats.values())
        best = None
        for i, key, jobs in groups:
            for _slot, j in jobs:
                if j.slo_ms is None or j.slo_t0 is None:
                    continue
                remaining = max(0, j.n_steps - j.steps_done)
                quanta = -(-remaining // self.quantum)  # ceil
                budget = j.slo_ms / 1e3 - (self.clock() - j.slo_t0)
                lane_slack = budget - quanta * lane_lat
                own_slack = budget - quanta * lats[i]
                if lane_slack < 0.0 <= own_slack and (
                        best is None or lane_slack < best[0]):
                    best = (lane_slack, i, j)
        if best is None:
            return None
        _slack, keep, trigger = best
        victims = []
        for i, _key, jobs in groups:
            if i == keep:
                continue
            victims += [(i, slot, j) for slot, j in jobs
                        if j.slo_ms is None]
        if not victims:
            return None
        victims.sort(key=lambda e: (e[2].priority, -e[1]))
        return trigger, victims


class FleetPreemptedError(RuntimeError):
    """The fleet stopped at a quantum boundary on a preemption signal;
    every admitted job saved an emergency keyframe into its own stem
    and was requeued. ``exit_code`` is the resumable 75
    (:data:`~dccrg_tpu.supervise.RESUMABLE_EXIT`); rerun the
    scheduler over the same checkpoint directory to resume."""

    exit_code = supervise.RESUMABLE_EXIT

    def __init__(self, requeued):
        super().__init__(
            f"fleet preempted; {len(requeued)} job(s) emergency-"
            f"checkpointed and requeued (exit code {self.exit_code})")
        self.requeued = list(requeued)


class FleetScheduler:
    """Admit, multiplex, checkpoint and drain a fleet of
    :class:`~dccrg_tpu.fleet.FleetJob` runs (see module docstring).

    ``checkpoint_dir`` holds every job's numbered checkpoint stem.
    Knobs (None = env default): ``max_batch``
    (``DCCRG_FLEET_MAX_BATCH``), ``quantum``
    (``DCCRG_FLEET_QUANTUM``), ``keep_last`` (``DCCRG_KEEP_LAST``) /
    ``keep_every`` (per-stem retention). ``resume`` (default) restores
    a job with existing checkpoints from its newest verifying one
    instead of reinitializing. ``devices`` spreads bucket instances
    round-robin over a device list (default: the default device).
    ``slo_policy`` injects a custom :class:`SLOPolicy` (fake clock /
    tuned EWMA for the deterministic tests); the default one is fed
    by the telemetry-measured quantum latencies and drives both the
    SLO admission reorder and the over-latency bucket shedding.
    ``autopilot`` injects a :class:`~dccrg_tpu.autopilot.Autopilot`
    controller (fake clock for the deterministic tests); with None
    one is constructed only under ``DCCRG_AUTOPILOT=1`` — otherwise
    ``self.autopilot`` stays None and every autopilot hook is a
    skipped ``if``, leaving scheduling, checkpoint cadence and audit
    cadence bitwise identical to the pre-autopilot behavior (the
    negative pin in tests/test_autopilot.py)."""

    def __init__(self, checkpoint_dir, jobs=(), *, max_batch=None,
                 quantum=None, keep_last=None, keep_every=0,
                 resume=True, devices=None,
                 install_signal_handlers=False, audit_every=None,
                 quarantine_after=None, slo_policy=None,
                 autopilot=None, rank_aware=None, membership=None,
                 intake=None, warm_pool=None):
        self.dir = str(checkpoint_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.max_batch = (max_batch_default() if max_batch is None
                          else max(1, int(max_batch)))
        self.quantum = (quantum_default() if quantum is None
                        else max(1, int(quantum)))
        self.keep_last = (supervise.keep_last_default()
                          if keep_last is None else max(1, int(keep_last)))
        self.keep_every = int(keep_every)
        self.resume = bool(resume)
        self.devices = list(devices) if devices else [None]
        self._install = bool(install_signal_handlers)
        # SDC defense knobs: shadow-audit cadence in scheduler ticks
        # (DCCRG_AUDIT_EVERY, 0 = off) and the per-device corrupt-
        # verdict count that quarantines a lane
        # (DCCRG_QUARANTINE_AFTER, 0 = never)
        self.audit_every = (integrity.audit_every_default()
                            if audit_every is None
                            else max(0, int(audit_every)))
        self.quarantine_after = (integrity.quarantine_after_default()
                                 if quarantine_after is None
                                 else max(0, int(quarantine_after)))
        # per-lane suspect accounting: corrupt verdicts attributed to
        # each entry of `devices` (fingerprint/conservation trips,
        # audit mismatches, DMR divergences)
        self.suspects = [0] * len(self.devices)
        self.quarantined: set = set()  # lane indices taken out
        self.audits = 0
        self.audit_failures = 0
        self._audit_rr = 0
        self._pending_quarantine: set = set()
        # latency-SLO admission: quantum-latency EWMAs measured by the
        # telemetry-instrumented dispatch feed the policy; a custom
        # policy (fake clock, tuned alpha) is injectable for tests
        self.slo = (SLOPolicy(quantum=self.quantum)
                    if slo_policy is None else slo_policy)
        # the self-tuning controller: OFF unless injected or opted in
        # via DCCRG_AUTOPILOT=1 — None means no hook below ever runs
        if autopilot is None and autopilot_mod.autopilot_enabled():
            autopilot = autopilot_mod.Autopilot(
                quantum=self.quantum, audit_every=self.audit_every)
        self.autopilot = autopilot
        #: cumulative job-steps advanced by dispatches (a controller
        #: input: the trip-rate denominator)
        self.steps_total = 0
        self._queue: list = []  # heap of (-priority, seq, job)
        # lane-shed parking lot: cross-bucket SLO victims wait here
        # (keyframed) until their trigger job finishes, instead of
        # being re-admitted by the very next tick's backfill
        self._parked: list = []  # [{job, trigger, max_tick}]
        self._lane_shed_tick: dict = {}  # lane -> last shed tick
        self._seq = itertools.count()
        self._by_name: dict = {}
        self.buckets: dict = {}  # bucket key -> [GridBatch]
        self._stores: dict = {}  # job name -> CheckpointStore
        self._next_dev = 0
        self.report: dict = {}
        self.ticks = 0
        # elastic multi-host fleet: OFF by default — membership and
        # leases stay None and the serving loop takes ZERO new
        # branches, so rank-unaware scheduling (and rank-aware with a
        # single live rank) is bitwise identical to the pre-elastic
        # scheduler (the negative pin in tests/test_fleet_elastic.py)
        if rank_aware is None:
            rank_aware = membership is not None or rank_aware_default()
        self.rank_aware = bool(rank_aware)
        self.membership = None
        self.leases = None
        self._remote: dict = {}  # name -> parked (prio, seq, job) entry
        self._degraded = False
        if self.rank_aware:
            if membership is None:
                import jax

                membership = coord.Membership(int(jax.process_index()),
                                              int(jax.process_count()))
            self.membership = membership
            self.leases = JobLeases(
                membership.kv, membership.rank,
                lease_s=membership.lease_s, clock=membership.clock)
            import jax

            if jax.process_count() > 1:
                # barriers anywhere in this process now name a dead
                # rank (PeerDeadError) instead of blaming a tag.
                # Registered only on REAL multi-process runtimes — an
                # in-process fake fleet (tests, bench --hosts) must
                # not leak its toy membership into the process-global
                # barrier path
                coord.set_membership(membership)
            membership.heartbeat(force=True)
            if membership.clock is time.monotonic:
                # real clock: beats ride a daemon thread, so a
                # seconds-long XLA compile mid-tick is never read as
                # a death (fake-clock tests beat by hand)
                membership.start_auto()
        # streaming intake front door: OFF by default — None means
        # the serving loop takes ZERO new branches (the negative pin
        # in tests/test_intake.py); DCCRG_INTAKE=1 constructs one
        # over DCCRG_INTAKE_SPOOL, or inject a StreamIntake directly
        self.intake = None
        if intake is None and os.environ.get(
                "DCCRG_INTAKE", "") not in ("", "0", "off", "false",
                                            "no"):
            from . import intake as intake_mod

            intake = intake_mod.StreamIntake.from_env(self)
        if intake is not None:
            self.intake = intake
            intake.attach(self)
        # warm-start pool: OFF by default — None means the serving
        # loop takes ZERO new branches (the negative pin in
        # tests/test_warmstart.py); DCCRG_COMPILE_CACHE constructs
        # one over that dir, or inject a warmstart.WarmPool directly
        self.warm = None
        if warm_pool is None:
            warm_pool = warmstart.WarmPool.from_env()
        if warm_pool is not None:
            self.warm = warm_pool
            warm_pool.attach(self)
        for j in jobs:
            self.add(j)

    # -- queue --------------------------------------------------------

    def add(self, job: FleetJob) -> None:
        """Queue a job (higher ``priority`` admits first; FIFO within
        a priority). The name is the checkpoint stem — unique per
        scheduler."""
        known = self._by_name.get(job.name)
        if known is not None and known is not job:
            raise ValueError(
                f"duplicate job name {job.name!r}: the name is the "
                "checkpoint stem and must be unique per scheduler")
        self._by_name[job.name] = job
        job.status = "queued"
        if job.slo_ms is not None and job.slo_t0 is None:
            # the SLO clock starts at the FIRST enqueue (requeues and
            # re-adds keep the original deadline)
            job.slo_t0 = self.slo.clock()
        heapq.heappush(self._queue, (-job.priority, next(self._seq), job))

    def store_for(self, job: FleetJob) -> supervise.CheckpointStore:
        st = self._stores.get(job.name)
        if st is None:
            st = supervise.CheckpointStore(self.dir, stem=job.name)
            self._stores[job.name] = st
        return st

    # -- admission + backfill -----------------------------------------

    def live_lanes(self) -> list:
        """Device-lane indices not quarantined by the SDC layer."""
        return [i for i in range(len(self.devices))
                if i not in self.quarantined]

    def _bucket_for(self, job: FleetJob, pending=None) -> GridBatch:
        """A bucket instance with a free slot for ``job``'s key, or
        None. Creates a new instance (round-robin over the live,
        non-quarantined ``devices`` lanes) sized to the demand visible
        NOW — bucket_capacity-rounded so later fluctuations reuse the
        compile — when every existing one is full and the lane list
        allows another. ``pending`` is the not-yet-admitted job list
        the demand sizing counts (default: the queue — the admission
        pass drains the queue first and passes its remainder)."""
        key = job.bucket_key()
        insts = self.buckets.setdefault(key, [])
        for b in insts:
            if b.free_slot() is not None:
                return b
        lanes = self.live_lanes()
        if len(insts) >= len(lanes):
            return None
        if pending is None:
            pending = [j for _p, _s, j in self._queue]
        # DMR jobs occupy redundancy slots each (primary + shadows):
        # size the bucket for the SLOT demand, not the job count
        same_key = job.redundancy + sum(
            j.redundancy for j in pending
            if j.bucket_key() == key)
        cap = min(self.max_batch, bucket_capacity(same_key))
        if self.autopilot is not None:
            # seed from the recorded OOM/shed history instead of
            # rediscovering the safe capacity by halving every run —
            # floored at the largest single job's slot demand, so a
            # redundancy=2 job's DMR shadow can never be stripped by
            # history learned from a differently-shaped workload
            need = max([job.redundancy] + [
                j.redundancy for j in pending
                if j.bucket_key() == key])
            cap = self.autopilot.seed_capacity(key, cap,
                                               min_capacity=need)
        lane = lanes[self._next_dev % len(lanes)]
        b = GridBatch(job, cap, device=self.devices[lane])
        b.lane = lane
        self._next_dev += 1
        insts.append(b)
        return b

    def _admit_pending(self) -> int:
        """One admission pass: place every queued job that fits
        (SLO-urgency order, then priority; non-fitting jobs go back
        and backfill later). Returns how many were admitted.

        The pass drains the priority heap, re-orders it through
        :meth:`SLOPolicy.admission_key` — jobs whose projected
        completion (quantum-latency EWMA x remaining quanta) violates
        their ``slo_ms`` deadline admit FIRST, most-violated first —
        and admits in that order. With no SLO jobs (or no violation)
        the key degrades to the exact ``(-priority, seq)`` heap order,
        so the priority-only baseline is unchanged (pinned by the
        deterministic reorder test in tests/test_telemetry.py)."""
        with telemetry.span("fleet.admit"):
            items = []
            while self._queue:
                items.append(heapq.heappop(self._queue))
            items.sort(key=lambda it: self.slo.admission_key(
                it[2], it[1]))
            deferred, admitted = [], 0
            for i, item in enumerate(items):
                job = item[2]
                batch = self._bucket_for(
                    job, pending=[it[2] for it in items[i + 1:]])
                if batch is None:
                    deferred.append(item)
                    continue
                if self.leases is not None:
                    # ownership is recorded at ADMISSION: the lease
                    # CAS arbitrates any transient partition
                    # disagreement between ranks — the loser parks
                    # the job and watches the winner's lease instead
                    try:
                        self.leases.acquire(job.name)
                    except OwnershipLostError as e:
                        logger.info(
                            "fleet job %s: admission lost the lease "
                            "race (%s); parking as remote", job.name, e)
                        self._remote[job.name] = item
                        continue
                self._admit_into(batch, job)
                admitted += 1
            for item in deferred:
                heapq.heappush(self._queue, item)
            return admitted

    def _admit_into(self, batch: GridBatch, job: FleetJob) -> None:
        telemetry.inc("dccrg_fleet_admissions_total", job=job.name)
        store = self.store_for(job)
        restored = None
        if self.resume or job.steps_done > 0 or job.requeues:
            restored = self._load_newest(batch, store, job)
        elif store.list():
            # resume=False over a dir holding a PREVIOUS run's stem:
            # purge it now, or the first trip/requeue/preemption would
            # _load_newest the stale (higher-step) state — and the
            # per-save GC would keep those stale files over this
            # run's fresh step-0 keyframe
            self._purge_stem(store, job)
        if restored is None:
            job.apply_init(batch.grid)
            job.steps_done = 0
        else:
            job.steps_done = restored
            # the restored checkpoint IS the last save: the periodic
            # cadence continues from it
            job.last_save_step = restored
        slot = batch.admit(job, from_grid=True)
        job.status = "running"
        # the slot was just (re)written through a sanctioned path:
        # the integrity fingerprint baseline restarts here
        job._fp = None
        if job.redundancy >= 2 and batch.admit_shadow(slot) is None:
            logger.warning(
                "DMR job %s: no free slot for its shadow replica; "
                "running unreplicated", job.name)
        logger.debug("admitted %s at step %d into slot %d", job.name,
                     job.steps_done, slot)
        if restored is None:
            # the rollback target always exists (the ResilientRunner
            # invariant, per job): a step-0 keyframe before stepping
            try:
                self._save_job(batch, slot, job, force_keyframe=True)
            except OwnershipLostError as e:
                self._drop_lost(batch, slot, job, e)

    def _purge_stem(self, store, job) -> None:
        """Delete every checkpoint (and sidecar) of ``job``'s stem —
        the ``resume=False`` contract is a from-scratch run."""
        try:
            store.drain()  # never unlink under an in-flight publish
        except Exception as e:  # noqa: BLE001 - purging anyway
            logger.warning("draining stem %s before purge failed (%s)",
                           job.name, e)
        n = 0
        for _step, path in store.list():
            for p in (path, resilience.sidecar_path(path)):
                try:
                    os.remove(p)
                    n += 1
                except OSError:
                    pass
        logger.warning("resume=False: purged %d stale checkpoint "
                       "file(s) of stem %s", n, job.name)

    def _load_newest(self, batch, store, job):
        """Restore the newest verifying checkpoint of ``job``'s stem
        into the bucket's scratch grid (chain-aware; older entries are
        the fallback, mirroring ``resume_latest``). Returns the
        restored step or None."""
        # drain barrier: never read a stem an async write is still
        # publishing into. A failed write already re-pointed the chain
        # state; the newest-first walk below IS the fallback.
        try:
            store.drain()
        except Exception as e:  # noqa: BLE001 - the walk is the fallback
            logger.error("async save of stem %s failed (%s); rolling "
                         "back to its last durable checkpoint",
                         job.name, e)
        for step, path in store.list():
            try:
                resilience.load_checkpoint_into(batch.grid, path)
            except Exception as e:  # noqa: BLE001 - walk to older
                logger.warning("fleet resume of %s skipped %s (%s)",
                               job.name, path, e)
                continue
            return int(step)
        return None

    # -- elastic multi-host: membership, leases, reclaim --------------

    def _job_cost(self, job) -> float:
        """Projected completion cost for the rank partition: remaining
        quanta x the bucket key's SLO EWMA (1.0 per quantum when
        unmeasured, so unmeasured fleets balance by quantum count)."""
        lat = self.slo.quantum_latency(job.bucket_key())
        remaining = max(1, job.n_steps - job.steps_done)
        quanta = -(-remaining // max(1, self.quantum))  # ceil
        return quanta * (lat if lat is not None else 1.0)

    def _rank_tick(self) -> None:
        """The rank-aware tick-boundary pass: heartbeat + membership
        poll (deadline-bounded — never blocks the serving loop), owned
        lease renewal (a fenced lease drops its job locally, the
        zombie discipline), the remote scan (done markers, lease
        aging, orphan reclaim) and the pending-queue partition."""
        m = self.membership
        with telemetry.span("fleet.membership"):
            m.heartbeat()
            m.poll()
        live = m.live_ranks()
        if len(live) == 1 and m.n_ranks > 1 and not self._degraded:
            self._degraded = True
            logger.warning(
                "fleet membership: all %d peer rank(s) dead — "
                "degrading to single-host serving on rank %d",
                m.n_ranks - 1, m.rank)
        elif self._degraded and len(live) > 1:
            self._degraded = False
            logger.warning(
                "fleet membership: peer rank(s) rejoined — elastic "
                "regrow to %d live rank(s)", len(live))
        # one KV prefix listing serves every tick-path read (absent
        # keys cost a full blocking-get timeout on the real service;
        # publish-time fencing stays on fresh per-key reads)
        census = self.leases.census()
        for name, err in self.leases.renew_owned(census=census):
            self._drop_lost_by_name(name, err)
        holders = self._scan_remote(census)
        self._partition_queue(live, holders, census)

    def _drop_lost_by_name(self, name, err) -> None:
        for b, s, j in self.active_jobs():
            if j.name == name:
                self._drop_lost(b, s, j, err)
                return
        job = self._by_name.get(name)
        if job is not None:
            self._drop_lost(None, None, job, err)

    def _drop_lost(self, batch, slot, job, err) -> None:
        """The zombie discipline: a fenced job is dropped locally
        WITHOUT rollback side effects (no save, no load, no requeue —
        the reclaimer's checkpoint chain is the live one) and tracked
        as remote until its done marker appears."""
        logger.warning("fleet job %s dropped: %s", job.name, err)
        telemetry.inc("dccrg_fleet_ownership_lost_total", job=job.name)
        if batch is not None and slot is not None \
                and batch.slots[slot] is job:
            batch.clear(slot)
        job.status = "lost"
        self.leases.release(job.name)
        if job.name not in self._remote:
            self._remote[job.name] = (-job.priority, next(self._seq),
                                      job)

    def _note_remote_done(self, name, job, raw) -> None:
        parts = (str(raw).split(":", 3) + ["", "", "", ""])[:4]
        status, rank_s, steps_s, digest = parts
        job.status = status
        job.digest = (digest or None) if status == "done" else None
        self.report[name] = {
            "status": status, "steps": int(steps_s or 0),
            "digest": job.digest, "trips": 0, "sdc_trips": 0,
            "retries_final": 0, "requeues": job.requeues,
            "transient_retries": 0, "rollbacks": 0,
            "slo_ms": job.slo_ms, "slo_met": None,
            "owner_rank": int(rank_s or -1), "remote": True,
        }

    def _scan_remote(self, census=None) -> dict:
        """One pass over the jobs other ranks own: resolve done
        markers into report rows, age the live leases, and RECLAIM the
        expired ones — the CAS claim key means exactly one survivor
        wins, and the winner requeues the job locally so the next
        admission pass re-admits it from its checkpoint stem. Returns
        the ``{name: holder_rank}`` census of still-live remote
        leases (the partition's load input)."""
        ls = self.leases
        holders = {}
        for name, entry in list(self._remote.items()):
            job = entry[2]
            raw = ls._read(f"{ls.prefix}/done/{name}", census)
            if raw is not None:
                self._note_remote_done(name, job, raw)
                del self._remote[name]
                continue
            holder = ls.holder(name, census)
            if holder == ls.rank:
                # a job THIS rank holds the lease on must never idle
                # in the remote set (a reclaim raced the partition):
                # requeue it locally — nobody else may serve it
                del self._remote[name]
                job.status = "queued"
                heapq.heappush(self._queue, entry)
                continue
            if holder is None:
                continue  # unclaimed: the partition decides below
            dead = ls.expired_holder(name, census)
            if dead is None or self.membership.state(dead) \
                    != coord.Membership.DEAD:
                # reclaim needs BOTH signals: the job lease expired
                # AND the holder's failure domain is dead by
                # membership — a live rank stalled in a long restore
                # keeps its work (the epoch fence would make a
                # spurious reclaim safe, but not free)
                holders[name] = holder
                continue
            t0 = time.perf_counter()
            with telemetry.span("fleet.reclaim"):
                epoch = ls.try_reclaim(name)
            if epoch is None:
                continue  # another survivor won; visible next tick
            age = round(ls.lease_s, 6)
            logger.warning(
                "fleet job %s: lease of rank %d expired (>= %gs "
                "without renewal); RECLAIMED at epoch %d — re-"
                "admitting from its checkpoint stem", name, dead,
                ls.lease_s, epoch)
            telemetry.inc("dccrg_fleet_reclaims_total", job=name)
            telemetry.observe("dccrg_fleet_reclaim_seconds",
                              time.perf_counter() - t0)
            job.requeues += 1
            job.status = "queued"
            del self._remote[name]
            heapq.heappush(self._queue, entry)
            if self.autopilot is not None:
                self.autopilot.record_reclaim(dead, [name], age)
        return holders

    def _partition_queue(self, live, holders, census=None) -> None:
        """Deterministic rank assignment of every UNCLAIMED pending
        job (queued here, or parked remote with no live lease):
        greedy least-projected-load over the live ranks, biggest job
        first, stable crc32 tiebreaks — every rank derives the same
        map from the same observed inputs, and the admission-time
        lease CAS arbitrates any transient disagreement (the loser
        parks the job back as remote). Jobs another rank holds a LIVE
        lease on are never touched. A single live rank keeps the
        exact heap entries — bitwise the rank-unaware admission
        order."""
        pool = []
        while self._queue:
            pool.append(heapq.heappop(self._queue))
        for name in list(self._remote):
            if (name not in holders and self._remote[name][2].status
                    == "queued"
                    and self.leases.holder(name, census) is None):
                pool.append(self._remote.pop(name))
        if len(live) <= 1:
            for entry in pool:
                heapq.heappush(self._queue, entry)
            return
        loads = {r: 0.0 for r in live}
        me = self.membership.rank
        for name, holder in holders.items():
            if holder in loads:
                loads[holder] += self._job_cost(self._remote[name][2])
        for _b, _s, j in self.active_jobs():
            loads[me] += self._job_cost(j)
        pool.sort(key=lambda e: (-self._job_cost(e[2]),
                                 zlib.crc32(e[2].name.encode()),
                                 e[2].name))
        for entry in pool:
            job = entry[2]
            if job.name in self.leases.owned:
                # a lease THIS rank already holds (a reclaim, a
                # requeue) pins the job local — the partition only
                # places unclaimed work
                loads[me] += self._job_cost(job)
                heapq.heappush(self._queue, entry)
                continue
            tgt = min(live, key=lambda r: (
                loads[r], zlib.crc32(f"{job.name}:{r}".encode())))
            loads[tgt] += self._job_cost(job)
            if tgt == me:
                heapq.heappush(self._queue, entry)
            else:
                self._remote[job.name] = entry

    # -- per-job checkpointing + retention ----------------------------

    def _save_job(self, batch, slot, job, force_keyframe=False) -> None:
        if self.leases is not None:
            # the epoch fence: NEVER publish into a stem a reclaimer
            # owns — a stale owner surfaces the typed
            # OwnershipLostError here, before any bytes move
            self.leases.check(job.name)
        with telemetry.tags(job=job.name):
            g = batch.write_grid(slot)
            store = self.store_for(job)
            steps = job.steps_done

            def _gc():
                # rides the save as its post hook: inline after a sync
                # save, chained onto the writer thread after an async
                # one (DCCRG_ASYNC_SAVE) — so the CRC+fsync+rename of a
                # periodic save overlaps the next quantum's dispatch
                # and GC still never races a publish
                try:
                    supervise.gc_checkpoints(
                        self.dir, keep_last=self.keep_last,
                        keep_every=self.keep_every, stem=job.name,
                        apply=True, assume_ok=steps)
                except OSError as e:  # GC must never kill the fleet
                    logger.warning("per-stem GC failed for %s (%s)",
                                   job.name, e)

            prev_last = job.last_save_step
            store.save(g, steps, dirty_fields=set(job.fields_out),
                       force_keyframe=force_keyframe, post=_gc)
            job.last_save_step = steps
            if store.pending():
                # speculative while the async write is in flight: a
                # writer failure reverts the cadence baseline at the
                # drain barrier (the ResilientRunner._save discipline),
                # so the next save isn't delayed by a checkpoint that
                # never published
                store._saver.add_on_fail(
                    lambda _e, job=job, prev=prev_last:
                    setattr(job, "last_save_step", prev))

    # -- trips: per-slot isolation ------------------------------------

    def _trip(self, batch, slot, job, kind) -> None:
        """One job tripped (NaN in its slot, a CORRUPT integrity
        verdict, or a job-scoped OOM). Neighbors are untouched by
        construction; this job rolls back from its own checkpoint —
        in place for numerics/corrupt trips (the same recovery: the
        checkpoint chain predates the bad bytes either way), via
        requeue for OOMs (the slot is freed so the working set
        shrinks; re-admission restores from the same stem, possibly
        into a different slot or bucket)."""
        job.trips.append((kind, job.steps_done))
        telemetry.inc("dccrg_fleet_trips_total", job=job.name, kind=kind)
        if job.steps_done > job._last_trip_step:
            job.retries = 0  # progress since the last trip
        job._last_trip_step = job.steps_done
        job.retries += 1
        logger.warning(
            "fleet job %s tripped (%s) at step %d; retry %d/%d",
            job.name, kind, job.steps_done, job.retries, job.max_retries)
        if job.retries > job.max_retries:
            self._finish(batch, slot, job, status="failed")
            return
        if kind == "oom":
            # the fault fires BEFORE the dispatch, so the slot state
            # is intact — keyframe it (same premise as _batch_oom /
            # _preempt) so re-admission resumes from here instead of
            # replaying everything since the last periodic save
            try:
                self._save_job(batch, slot, job, force_keyframe=True)
            except OwnershipLostError as e:
                self._drop_lost(batch, slot, job, e)
                return
            batch.clear(slot)
            job.requeues += 1
            self.add(job)
            return
        t0 = time.perf_counter()
        restored = self._load_newest(batch, self.store_for(job), job)
        if restored is None:
            logger.error("fleet job %s has no loadable checkpoint to "
                         "roll back to", job.name)
            self._finish(batch, slot, job, status="failed")
            return
        batch.read_grid(slot)
        # sanctioned rewrite: fingerprint baseline resets, and any DMR
        # shadow re-syncs to the restored bytes (the replicas must
        # re-diverge only through real corruption)
        job._fp = None
        batch.sync_shadow(slot)
        job.rollbacks += 1
        telemetry.inc("dccrg_fleet_rollbacks_total", job=job.name)
        # rollback cost is a controller input (with the trip rate it
        # prices the expected replay a longer checkpoint cadence buys)
        telemetry.observe("dccrg_rollback_seconds",
                          time.perf_counter() - t0)
        job.steps_done = restored
        # re-baseline the cadence like _admit_into: a fallback to an
        # OLDER checkpoint would otherwise leave steps_done -
        # last_save_step negative, suppressing saves over the whole
        # replayed region
        job.last_save_step = restored

    def _finish(self, batch, slot, job, status="done") -> None:
        if self.leases is not None:
            try:
                # the done marker is a publish too: a fenced zombie
                # completing a quantum must not write the terminal
                # record over the job a reclaimer is still serving
                self.leases.check(job.name)
            except OwnershipLostError as e:
                self._drop_lost(batch, slot, job, e)
                return
        if status == "done":
            job.digest = batch.digest(slot)
        job.status = status
        batch.clear(slot)
        telemetry.inc("dccrg_fleet_finished_total", status=status)
        slo_met = None
        if job.slo_ms is not None and job.slo_t0 is not None:
            took_ms = (self.slo.clock() - job.slo_t0) * 1e3
            # a failed job never met its SLO, however fast it failed
            slo_met = bool(status == "done" and took_ms <= job.slo_ms)
            telemetry.inc("dccrg_fleet_slo_total",
                          met=("yes" if slo_met else "no"))
        self.report[job.name] = {
            "status": status, "steps": job.steps_done,
            "digest": job.digest, "trips": len(job.trips),
            "sdc_trips": sum(1 for k, _s in job.trips
                             if k == "corrupt"),
            "retries_final": job.retries, "requeues": job.requeues,
            "transient_retries": job.transient_retries,
            "rollbacks": job.rollbacks,
            "slo_ms": job.slo_ms, "slo_met": slo_met,
        }
        if self.leases is not None:
            # the terminal record peers wait on: the done marker
            # replaces the lease (renewals stop; a done job is never
            # reclaimed)
            self.report[job.name]["owner_rank"] = self.membership.rank
            self.leases.kv.set(
                f"{self.leases.prefix}/done/{job.name}",
                f"{status}:{self.membership.rank}:{job.steps_done}:"
                f"{job.digest or '-'}")
            self.leases.release(job.name)

    # -- one bucket quantum -------------------------------------------

    def _fire_dispatch_faults(self, batch) -> None:
        """Per-job injection points before the batched dispatch:
        transient dispatch errors retry in place (no rollback, the
        supervision-layer discipline); a job-scoped simulated OOM
        requeues exactly that job."""
        if faults.active() is None:
            return
        for slot, job in batch.jobs:
            for attempt in range(3):
                try:
                    faults.fire("supervise.dispatch", step=job.steps_done,
                                job=job.name, attempt=attempt)
                    break
                except faults.InjectedDispatchError as e:
                    job.transient_retries += 1
                    logger.warning(
                        "transient dispatch error for fleet job %s "
                        "(%s); retrying", job.name, e)
                    time.sleep(0.01 * (2 ** attempt))
            else:
                # retries exhausted: the single-run discipline raises
                # (SupervisedRunner._dispatch); the fleet analogue is
                # failing ONLY this job — neighbors keep serving
                logger.error(
                    "fleet job %s: transient dispatch error persisted "
                    "through 3 attempts; failing the job", job.name)
                self._finish(batch, slot, job, status="failed")
                continue
            try:
                faults.fire("step.dispatch", mode="fleet",
                            step=job.steps_done, job=job.name)
            except Exception as e:  # noqa: BLE001 - filtered below
                if not resilience._is_resource_exhausted(e):
                    raise
                logger.warning("fleet job %s dispatch OOM (%s)",
                               job.name, e)
                self._trip(batch, slot, job, "oom")

    def _quantum(self, batch) -> None:
        with telemetry.span("fleet.quantum"):
            self._quantum_inner(batch)

    def _quantum_inner(self, batch) -> None:
        self._fire_dispatch_faults(batch)
        active = batch.jobs
        if not active:
            return
        budget = np.zeros(batch.capacity, dtype=np.int32)
        prev = {}
        for slot, job in active:
            budget[slot] = min(self.quantum,
                               max(0, job.n_steps - job.steps_done))
            prev[slot] = job.steps_done
        # DMR shadow replicas step in lockstep with their primary
        for sh, primary in batch.shadow_of.items():
            budget[sh] = budget[primary]
        # shadow-execution audit: snapshot ONE slot's pre-quantum
        # state at the sampled cadence; after the dispatch the same
        # quantum is re-executed from it and compared bitwise
        audit_slot, audit_pre = self._pick_audit(batch, active, budget)
        t_dispatch = time.perf_counter()
        try:
            batch.step(budget)
        except Exception as e:  # noqa: BLE001 - filtered below
            if not resilience._is_resource_exhausted(e):
                raise
            self._batch_oom(batch, e)
            return
        inv = batch.last_inv  # fused invariants (None: integrity off)
        for slot, job in active:
            job.steps_done += int(budget[slot])
            self.steps_total += int(budget[slot])
        # fleet-scoped fault landing pads (chaos tests): NaN poisons
        # and FINITE silent flips for the steps this quantum advanced
        # each job through
        if faults.active() is not None:
            for slot, job in active:
                for fld, cells, value, _ps in faults.poison_fleet(
                        job.name, prev[slot], job.steps_done):
                    batch.poison(slot, fld,
                                 self._fault_cells(batch, cells), value)
                for fld, cells, bit, _ps in faults.flip_fleet(
                        job.name, prev[slot], job.steps_done):
                    batch.flip(slot, fld,
                               self._fault_cells(batch, cells), bit)
        # per-slot watchdog: a tripped slot rolls back alone
        ok = batch.finite_slots()
        # the finite pull is the quantum's sync point, so the elapsed
        # time IS the measured dispatch latency — recorded per job in
        # the registry (the fleet CLI's p50/p99 source) and folded
        # into the SLO policy's per-bucket EWMA. The EWMA skips a
        # batch instance's FIRST dispatch: it may carry the XLA
        # compile (seconds against millisecond quanta), and judging a
        # healthy bucket by its warmup would shed it spuriously —
        # each shed rebuild compiles again, re-poisoning the freshly
        # reset EWMA in a feedback loop of pointless halvings.
        lat = time.perf_counter() - t_dispatch
        if batch.dispatches > 1:
            self.slo.observe(batch.key, lat)
        elif self.warm is not None:
            # the batch instance's FIRST dispatch: the warm pool
            # classifies it warm (pre-compiled program served) or
            # cold (this latency carried the compile), journals the
            # decision and upserts the persistent manifest
            self.warm.note_dispatch(batch, lat)
        telemetry.observe("dccrg_fleet_quantum_seconds", lat)
        for slot, job in active:
            if budget[slot] > 0:
                telemetry.observe("dccrg_fleet_quantum_seconds", lat,
                                  job=job.name)
        tripped = set()
        for slot, job in active:
            if batch.slots[slot] is job and not ok[slot]:
                tripped.add(slot)
                self._trip(batch, slot, job, "nan")
        # in-program integrity invariants: entry/exit fingerprints +
        # conservation drift, then the current-state fingerprint pass
        # (exact integer sums — bit-comparable across programs)
        if inv is not None:
            self._check_integrity(batch, active, budget, inv, tripped)
        # sampled shadow-execution audit + always-on DMR comparison
        if audit_slot is not None and audit_slot not in tripped:
            self._run_audit(batch, audit_slot, audit_pre,
                            int(budget[audit_slot]), tripped)
        if batch.shadow_of:
            self._check_dmr(batch, tripped)
        # periodic per-job checkpoints + completion (never checkpoint
        # a slot that tripped this quantum: its state just rolled
        # back — the cadence restarts from the restored step)
        for slot, job in batch.jobs:
            if slot in tripped:
                continue
            if job.steps_done >= job.n_steps:
                self._finish(batch, slot, job)
            elif (job.checkpoint_every > 0 and job.last_save_step
                  is not None and job.steps_done - job.last_save_step
                  >= job.checkpoint_every):
                try:
                    self._save_job(batch, slot, job)
                except OwnershipLostError as e:
                    self._drop_lost(batch, slot, job, e)

    def _fault_cells(self, batch, cells):
        """Resolve a fault rule's ``cells=None`` to one seeded local
        cell (shared by the poison and flip landing pads)."""
        if cells is not None:
            return cells
        local = batch.grid.plan.cells
        pick = int(faults.active().rng.integers(0, len(local)))
        return [int(local[pick])]

    # -- SDC detection: invariants, audits, DMR, quarantine -----------

    def _check_integrity(self, batch, active, budget, inv,
                         tripped) -> None:
        """Compare the dispatch's fused invariants per slot:

        - ``fp_in`` vs the exit fingerprint of the PREVIOUS dispatch —
          EXACT: any corruption of the slot's resident bytes between
          the two dispatches (HBM rot, a stray write, an injected
          flip), convicted at the next quantum boundary;
        - conservation-sum drift across the quantum for fields the
          kernel provably conserves — tolerance-bounded: in-compute
          corruption;
        - for slots about to CHECKPOINT or FINISH this tick only, one
          extra current-state fingerprint pass vs ``fp_out`` — EXACT:
          corruption since the dispatch is convicted before the bytes
          can be sealed into a checkpoint or reported as an answer.
          (Steady-state quanta skip this pass: the next quantum's
          ``fp_in`` covers them, and the save/finish guards are what
          make the one-quantum detection window airtight.)

        Any mismatch is a CORRUPT verdict: the victim rolls back
        alone (the NaN discipline) and the batch's device lane takes
        a suspect mark."""
        telemetry.inc("dccrg_integrity_checks_total", where="fleet")
        need_now = set()
        for slot, job in active:
            if slot in tripped or batch.slots[slot] is not job:
                continue
            if (job.steps_done >= job.n_steps
                    or (job.checkpoint_every > 0
                        and job.last_save_step is not None
                        and job.steps_done - job.last_save_step
                        >= job.checkpoint_every)):
                need_now.add(slot)
        fp_now = batch.fingerprint_slots() if need_now else None
        for slot, job in active:
            if slot in tripped or batch.slots[slot] is not job:
                continue
            why = None
            if job._fp is not None:
                for n, pair in job._fp.items():
                    got = inv["fp_in"][n][slot]
                    if int(got[0]) != pair[0] or int(got[1]) != pair[1]:
                        why = (f"fingerprint of field {n!r} changed "
                               "between dispatches (state corrupted "
                               "at rest)")
                        break
            if why is None and slot in need_now:
                for n in batch.fp_fields:
                    if not np.array_equal(fp_now[n][slot],
                                          inv["fp_out"][n][slot]):
                        why = (f"fingerprint of field {n!r} no longer "
                               "matches the dispatch output (state "
                               "corrupted after the step)")
                        break
            if why is None:
                steps = int(budget[slot])
                for n in batch.conserved:
                    s_in = float(inv["cs_in"][n][slot])
                    s_out = float(inv["cs_out"][n][slot])
                    shape, _dt = batch.schema[n]
                    n_el = batch.n_own * int(np.prod(shape, dtype=int)
                                             or 1)
                    tol = integrity.sum_tolerance(s_in, n_el,
                                                  max(1, steps))
                    if abs(s_out - s_in) > tol:
                        why = (f"conservation sum of field {n!r} "
                               f"drifted {abs(s_out - s_in):g} "
                               f"(tolerance {tol:g}) across the "
                               "quantum (in-compute corruption)")
                        break
            if why is not None:
                tripped.add(slot)
                self._sdc_trip(batch, slot, job, why)
            else:
                # the exit fingerprint is the next quantum's expected
                # entry fingerprint (exact, order-independent sums
                # compare bitwise across programs)
                job._fp = {n: (int(inv["fp_out"][n][slot, 0]),
                               int(inv["fp_out"][n][slot, 1]))
                           for n in batch.fp_fields}

    def _pick_audit(self, batch, active, budget):
        """The slot to shadow-audit this tick (round-robin over slots
        actually stepping) and its pre-quantum host state, or
        ``(None, None)`` off-cadence / when nothing steps."""
        if (self.audit_every <= 0
                or self.ticks % self.audit_every != 0):
            return None, None
        stepping = [slot for slot, _j in active if budget[slot] > 0]
        if not stepping:
            return None, None
        slot = stepping[self._audit_rr % len(stepping)]
        self._audit_rr += 1
        return slot, batch.extract(slot)

    def _run_audit(self, batch, slot, pre, steps, tripped) -> None:
        """Re-execute ``slot``'s last quantum from its pre-quantum
        state — in a spare slot of the SAME batch when one is free
        (the same compiled program; every other slot is frozen
        bit-exact by its zero budget), else through the solo
        ``Grid.run_steps`` path on the bucket's scratch grid — and
        compare the results bitwise. A divergence is a CORRUPT verdict
        attributed to this slot and its device lane: either the
        original execution or the state since (an injected flip, HBM
        rot) is wrong, and the checkpoint chain predates both."""
        job = batch.slots[slot]
        if job is None or job is SHADOW or steps <= 0:
            return
        t0 = time.perf_counter()
        try:
            with telemetry.span("integrity.audit"):
                digests = self._audit_digests(batch, slot, pre,
                                              steps, job)
                if digests is None:  # no comparable re-execution path
                    return
                live, shadow = digests
                # an audit counts only once a re-execution actually
                # compared — the bulk-no-spare and OOM skip paths
                # increment their own skip counter instead, so the
                # exposition never reports audits that did not run
                self.audits += 1
                telemetry.inc("dccrg_audits_total")
                # audit cost is a controller input: what one extra
                # re-execution window actually costs this fleet
                telemetry.observe("dccrg_audit_seconds",
                                  time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 - filtered just below
            if not resilience._is_resource_exhausted(e):
                raise
            # an OOM during the EXTRA audit dispatch must never kill
            # the fleet the audit protects: skip this window
            # (no verdict either way); if the pressure is real, the
            # next MAIN dispatch OOMs into _batch_oom's half-capacity
            # rebuild as usual
            logger.warning(
                "shadow audit of job %s skipped: the audit dispatch "
                "itself hit RESOURCE_EXHAUSTED (%s)", job.name, e)
            telemetry.inc("dccrg_audits_skipped_total")
            return
        # the verdict + containment run OUTSIDE the OOM-swallowing
        # try: only the audit's own extra dispatches may be skipped —
        # an OOM inside _sdc_trip's rollback must propagate, never
        # leave a half-applied trip on corrupt state
        if shadow != live:
            self.audit_failures += 1
            telemetry.inc("dccrg_audit_failures_total")
            tripped.add(slot)
            self._sdc_trip(
                batch, slot, job,
                f"shadow re-execution of the last {steps}-step "
                "quantum diverged from the live slot")

    def _audit_digests(self, batch, slot, pre, steps, job):
        import jax
        import jax.numpy as jnp

        live = batch.digest(slot)
        spare = batch.free_slot()
        if spare is not None:
            saved_extras = batch._extras[spare].copy()
            batch.insert(spare, pre)
            batch._extras[spare] = batch._extras[slot]
            bud = np.zeros(batch.capacity, dtype=np.int32)
            bud[spare] = steps
            batch.step(bud)
            shadow = batch.digest(spare)
            batch._extras[spare] = saved_extras
        elif batch.bulk_active():
            # the bucket stepped through the Pallas bulk executor,
            # whose slot-wise arithmetic matches the table kernel only
            # to float re-association — a solo table-path re-execution
            # would ALWAYS diverge bitwise and convict healthy jobs.
            # With no spare slot there is no same-program re-execution
            # to compare against: skip this window (no verdict).
            logger.info(
                "shadow audit of job %s skipped: bucket runs the bulk "
                "executor and no spare slot is free for a same-program "
                "re-execution", job.name)
            telemetry.inc("dccrg_audits_skipped_total")
            return None
        else:
            # solo re-execution: the unbatched path recomputes the
            # same quantum (bitwise identical by the fleet parity
            # contract), diversifying the program the audit trusts.
            # DCCRG_BULK is pinned OFF for the re-execution: the
            # bucket ran the TABLE program (bulk_active() was False
            # above), and a callable SlotwiseKernel job would
            # otherwise let Grid.run_steps compile the bulk executor
            # here — the exact cross-program bitwise mismatch the
            # bulk_active() guard exists to prevent, mirrored.
            sh = batch.grid._sharding()
            for n, arr in pre.items():
                batch.grid.data[n] = jax.device_put(arr[None], sh)
            saved_bulk = os.environ.pop("DCCRG_BULK", None)
            try:
                batch.grid.run_steps(
                    batch.kernel, batch.fields_in, batch.fields_out,
                    steps, extra_args=tuple(
                        jnp.float32(p) for p in job.params))
            finally:
                if saved_bulk is not None:
                    os.environ["DCCRG_BULK"] = saved_bulk
            from . import checkpoint as checkpoint_mod

            shadow = checkpoint_mod.state_digest(batch.grid)
        return live, shadow

    def _check_dmr(self, batch, tripped) -> None:
        """Dual-modular-redundancy comparison: every
        ``redundancy>=2`` job's shadow replica must digest bitwise
        equal to its primary at every quantum boundary. A divergence
        is a CORRUPT verdict for the job (we cannot know which
        replica is wrong — the checkpoint chain predates the split,
        so the rollback repairs either case) and a suspect mark for
        the lane."""
        for sh, primary in list(batch.shadow_of.items()):
            job = batch.slots[primary]
            if job is None or primary in tripped:
                continue
            if batch.digest(primary) != batch.digest(sh):
                tripped.add(primary)
                self._sdc_trip(
                    batch, primary, job,
                    "DMR replicas diverged at the quantum boundary")

    def _sdc_trip(self, batch, slot, job, why) -> None:
        """A CORRUPT verdict: contain (per-slot rollback, the NaN
        discipline) and attribute (suspect accounting on the batch's
        device lane, quarantine after ``quarantine_after`` strikes)."""
        lane = getattr(batch, "lane", 0)
        logger.warning(
            "SDC verdict for fleet job %s (slot %d, device lane %d): "
            "%s", job.name, slot, lane, why)
        self._trip(batch, slot, job, "corrupt")
        if lane < len(self.suspects):
            self.suspects[lane] += 1
            integrity.note_suspect(lane, self.suspects[lane],
                                   quarantined=lane in self.quarantined)
            if (self.quarantine_after > 0
                    and lane not in self.quarantined
                    and self.suspects[lane] >= self.quarantine_after):
                # DEFERRED to the tick boundary: quarantine replaces
                # bucket instances, and this quantum is still
                # iterating the one that tripped
                self._pending_quarantine.add(lane)

    def _quarantine(self, lane: int) -> None:
        """Take device lane ``lane`` out of service: every bucket
        instance on it is rebuilt on a surviving lane with its
        admitted jobs migrated BIT-EXACTLY (the
        :meth:`~dccrg_tpu.fleet.GridBatch.extract`/``insert`` path the
        batch-OOM rebuild uses), and admission never places new
        buckets there again. With no surviving lane the quarantine is
        recorded but the lane keeps serving — failing the whole fleet
        would be worse than suspect answers, and the operator sees
        the log either way."""
        survivors = [i for i in self.live_lanes() if i != lane]
        if not survivors:
            logger.error(
                "device lane %d exceeded the corruption threshold "
                "(%d verdict(s)) but is the ONLY lane; continuing to "
                "serve on suspect hardware", lane, self.suspects[lane])
            return
        self.quarantined.add(lane)
        integrity.note_suspect(lane, self.suspects[lane],
                               quarantined=True)
        moved = 0
        for key, insts in self.buckets.items():
            for i, batch in enumerate(insts):
                if getattr(batch, "lane", 0) != lane:
                    continue
                jobs = batch.jobs
                if not jobs:
                    insts[i] = None
                    continue
                new_lane = survivors[self._next_dev % len(survivors)]
                self._next_dev += 1
                fresh = GridBatch(jobs[0][1], batch.capacity,
                                  device=self.devices[new_lane])
                fresh.lane = new_lane
                for slot, job in jobs:
                    state = batch.extract(slot)
                    new_slot = fresh.admit(job, from_grid=False)
                    fresh.insert(new_slot, state)
                    # the bytes moved bit-exactly, so the fingerprint
                    # baseline survives the migration unchanged
                    if job.redundancy >= 2:
                        fresh.admit_shadow(new_slot)
                    moved += 1
                insts[i] = fresh
            self.buckets[key] = [b for b in insts if b is not None]
        logger.warning(
            "quarantined device lane %d after %d corrupt verdict(s); "
            "migrated %d job(s) bit-exactly to surviving lane(s) %s",
            lane, self.suspects[lane], moved, survivors)

    def _requeue_keyframed(self, batch, victims) -> None:
        """Requeue ``[(slot, job)]`` out of a live bucket: each slot's
        intact state saves a keyframe first, so re-admission resumes
        from here instead of replaying since the last periodic save
        (shared by the batch-OOM and SLO-shed paths)."""
        for slot, job in victims:
            try:
                self._save_job(batch, slot, job, force_keyframe=True)
            except OwnershipLostError as e:
                self._drop_lost(batch, slot, job, e)
                continue
            batch.clear(slot)
            job.requeues += 1
            self.add(job)

    def _rebuild_smaller(self, batch) -> GridBatch:
        """Replace ``batch`` with a half-capacity instance (floored at
        the survivor count) holding every surviving job migrated
        BIT-EXACTLY — the shrink primitive the batch-OOM and SLO-shed
        paths share. Occupancy alone frees neither device memory nor
        dispatch latency: the state arrays and the compiled program
        are both sized ``[capacity, ...]``, and freed slots would be
        backfilled from the queue on the very next tick."""
        survivors = batch.jobs
        new_cap = max(len(survivors), batch.capacity // 2)
        small = GridBatch(survivors[0][1], new_cap, device=batch.device)
        small.lane = getattr(batch, "lane", 0)
        for slot, job in survivors:
            state = batch.extract(slot)
            new_slot = small.admit(job, from_grid=False)
            small.insert(new_slot, state)
            if job.redundancy >= 2 and small.admit_shadow(new_slot) \
                    is None:
                logger.warning(
                    "DMR job %s lost its shadow replica in the "
                    "half-size rebuild; running unreplicated",
                    job.name)
        insts = self.buckets[batch.key]
        insts[insts.index(batch)] = small
        # ANY rebuild changes the bucket's latency characteristics
        # (half the slots, and a fresh compile on the first dispatch):
        # reset the key's SLO EWMA and start the shed cooldown, so
        # the new instance is judged by its own measurements — on the
        # OOM path exactly as on the shed path
        self.slo.reset_key(batch.key)
        small._shed_tick = self.ticks
        return small

    def _batch_oom(self, batch, err) -> None:
        """A REAL (unattributed) RESOURCE_EXHAUSTED from the batched
        dispatch: the whole working set is too big. Requeue the
        lower-priority half of the bucket's jobs (their slot state is
        intact — the dispatch failed wholesale — so each saves a
        keyframe first) and REBUILD the bucket at a smaller capacity
        (:meth:`_rebuild_smaller`); repeated OOMs keep halving until
        a single job's failure is surfaced."""
        active = batch.jobs
        if len(active) <= 1:
            raise resilience.ResilienceExhaustedError(
                f"fleet bucket OOMs even with {len(active)} job(s)"
            ) from err
        by_prio = sorted(active, key=lambda e: (e[1].priority, -e[0]))
        drop = len(active) // 2
        self._requeue_keyframed(batch, by_prio[:drop])
        small = self._rebuild_smaller(batch)
        if self.autopilot is not None:
            self.autopilot.record_oom(batch.key, small.capacity)
        logger.warning(
            "fleet bucket OOM: requeued %d of %d job(s), rebuilt the "
            "bucket at capacity %d (was %d)", drop, len(active),
            small.capacity, batch.capacity)

    # -- latency-SLO shedding -----------------------------------------

    def _shed_for_slo(self, batch) -> None:
        """When ``batch``'s measured quantum latency blows the
        tightest admitted slot SLO (:meth:`SLOPolicy.shed_victims`),
        requeue the least-urgent cohabitants — keyframe first, so
        re-admission resumes from here — and REBUILD the bucket at
        half capacity with the survivors migrated bit-exactly (the
        ``_batch_oom`` discipline: occupancy alone frees no dispatch
        latency — the program is sized ``[capacity, ...]`` — and a
        freed slot would be backfilled next tick). The key's EWMA
        resets so the smaller bucket is judged by its own
        measurements, with a ``shed_cooldown``-tick grace."""
        victims = self.slo.shed_victims(batch.key, batch.jobs)
        if not victims:
            return
        if self.ticks - getattr(batch, "_shed_tick", -10**9) \
                < self.slo.shed_cooldown:
            return
        for _slot, job in victims:
            telemetry.inc("dccrg_fleet_slo_sheds_total", job=job.name)
        self._requeue_keyframed(batch, victims)
        # shed_victims caps at len(jobs)-1, so a survivor always
        # remains for the rebuild
        small = self._rebuild_smaller(batch)
        if self.autopilot is not None:
            self.autopilot.record_shed(batch.key, small.capacity)
        logger.warning(
            "SLO shed: requeued %d job(s) and rebuilt the bucket at "
            "capacity %d (was %d) — measured quantum latency blew "
            "the tightest admitted SLO", len(victims), small.capacity,
            batch.capacity)

    def _shed_for_lane(self) -> None:
        """Cross-bucket SLO shedding (mixed-kernel fleets): when a
        deadline job's projected completion against its LANE's total
        per-tick latency — every cohabiting bucket on the device
        dispatches each tick — violates the deadline while its own
        bucket alone would not, the best-effort jobs of the OTHER
        buckets on that lane are keyframed and PARKED (not requeued:
        the next admission pass would put them straight back) until
        the trigger job finishes. Tick-boundary act, once per lane
        per ``shed_cooldown``; a fleet without SLO jobs or with a
        single bucket per lane never enters the policy."""
        by_lane: dict = {}
        for insts in self.buckets.values():
            for b in insts:
                if b.jobs:
                    by_lane.setdefault(getattr(b, "lane", 0),
                                       []).append(b)
        for lane, batches in sorted(by_lane.items()):
            if len(batches) < 2:
                continue
            if self.ticks - self._lane_shed_tick.get(lane, -10**9) \
                    < self.slo.shed_cooldown:
                continue
            hit = self.slo.lane_shed_victims(
                [(i, b.key, b.jobs) for i, b in enumerate(batches)])
            if hit is None:
                continue
            trigger, victims = hit
            self._lane_shed_tick[lane] = self.ticks
            parked = 0
            for i, slot, job in victims:
                batch = batches[i]
                if batch.slots[slot] is not job:
                    continue
                try:
                    self._save_job(batch, slot, job,
                                   force_keyframe=True)
                except OwnershipLostError as e:
                    self._drop_lost(batch, slot, job, e)
                    continue
                batch.clear(slot)
                job.requeues += 1
                job.status = "parked"
                telemetry.inc("dccrg_fleet_lane_sheds_total",
                              job=job.name)
                self._parked.append({
                    "job": job, "trigger": trigger.name,
                    "max_tick": self.ticks
                    + 8 * max(1, self.slo.shed_cooldown)})
                parked += 1
            if parked:
                logger.warning(
                    "lane %d SLO shed: parked %d best-effort "
                    "cohabitant(s) from other buckets until deadline "
                    "job %s completes", lane, parked, trigger.name)

    def _release_parked(self, force: bool = False) -> None:
        """Re-enqueue lane-shed victims whose trigger finished (or
        whose backstop tick passed; ``force`` releases everything —
        the drain and preemption paths)."""
        if not self._parked:
            return
        still = []
        for entry in self._parked:
            trig = self._by_name.get(entry["trigger"])
            if (force or trig is None
                    or trig.status in ("done", "failed")
                    or self.ticks >= entry["max_tick"]):
                self.add(entry["job"])
            else:
                still.append(entry)
        self._parked = still

    # -- preemption ---------------------------------------------------

    def _preempt(self) -> None:
        requeued = []
        # lane-shed victims already hold park-time keyframes: back to
        # the queue so a resume serves them like any requeued job
        self._release_parked(force=True)
        with telemetry.span("fleet.preempt"):
            for insts in self.buckets.values():
                for batch in insts:
                    for slot, job in batch.jobs:
                        try:
                            self._save_job(batch, slot, job,
                                           force_keyframe=True)
                        except OwnershipLostError as e:
                            self._drop_lost(batch, slot, job, e)
                            continue
                        batch.clear(slot)
                        job.requeues += 1
                        self.add(job)
                        requeued.append(job.name)
            # every emergency keyframe must be DURABLE before the
            # resumable exit — the async writers get no grace after
            # the raise (kill-mid-overlap smoke in ci_debug_leg.sh)
            self._drain_stores(swallow=True)
        telemetry.inc("dccrg_fleet_preempts_total")
        supervise.clear_preempt()
        raise FleetPreemptedError(requeued)

    def _drain_stores(self, swallow: bool = False) -> None:
        """Async-save barrier over every stem this scheduler owns."""
        for name, store in list(self._stores.items()):
            try:
                store.drain()
            except Exception as e:  # noqa: BLE001 - policy filter below
                if not swallow:
                    raise
                logger.error("async save of stem %s failed at drain "
                             "(%s); its last durable checkpoint is the "
                             "resume point", name, e)

    # -- the serving loop ---------------------------------------------

    def active_jobs(self) -> list:
        """``[(batch, slot, job)]`` of every admitted job."""
        return [(b, s, j) for insts in self.buckets.values()
                for b in insts for s, j in b.jobs]

    def run(self, max_ticks=None) -> dict:
        """Serve until the queue and every bucket drain (or
        ``max_ticks`` quantum rounds elapse). Returns the per-job
        report ``{name: {status, steps, digest, trips, ...}}``.
        Raises :class:`FleetPreemptedError` after emergency-saving
        and requeueing every admitted job when preempted."""
        ctx = (supervise.preemption_handlers() if self._install
               else nullcontext())
        with ctx:
            while True:
                if (supervise.preempt_requested()
                        or faults.take_preempt(self.ticks)):
                    self._preempt()
                if faults.active() is not None and faults.take_host_death(
                        self.membership.rank if self.membership else 0,
                        self.ticks):
                    # the in-process honoring of FaultPlan.host_death
                    # (the mp harness lets InjectedRankDeath hard-exit
                    # the OS process — an actual dead host)
                    raise faults.InjectedRankDeath(
                        f"injected host death at tick {self.ticks}")
                if self.rank_aware:
                    self._rank_tick()
                if self.intake is not None:
                    # the streaming front door: scan / crash-recover /
                    # gate / admit before this tick's admission pass
                    # reads the queue
                    self.intake.pump()
                self._release_parked()
                self._admit_pending()
                active = [b for insts in self.buckets.values()
                          for b in insts if b.jobs]
                if not active:
                    if self._parked and not self._queue:
                        # everything else drained: whatever the parked
                        # jobs were yielding to is gone — serve them
                        self._release_parked(force=True)
                        continue
                    if self._queue:
                        raise RuntimeError(
                            "fleet wedged: queued jobs but no bucket "
                            "can admit them")
                    if self.rank_aware and self._remote:
                        # local work drained but the FLEET has not:
                        # idle at a fraction of the heartbeat cadence,
                        # watching the remote leases (the rank tick
                        # above reclaims on expiry) and done markers
                        self.ticks += 1
                        if max_ticks is not None \
                                and self.ticks >= int(max_ticks):
                            break
                        time.sleep(min(0.05,
                                       self.membership.heartbeat_s / 4))
                        continue
                    if self.intake is not None \
                            and not self.intake.idle():
                        # local work drained but the front door has
                        # waiting or in-flight records: idle-continue
                        # at the intake poll cadence
                        self.ticks += 1
                        if max_ticks is not None \
                                and self.ticks >= int(max_ticks):
                            break
                        if self.intake.poll_s > 0:
                            time.sleep(self.intake.poll_s)
                        continue
                    if self.autopilot is not None:
                        # a clean drain: seeded keys that never
                        # OOMed/shed earn their capacity floor back
                        self.autopilot.end_of_run()
                    break
                for batch in active:
                    self._quantum(batch)
                # quarantine at the tick boundary (never mid-quantum:
                # it replaces bucket instances under migration)
                for lane in sorted(self._pending_quarantine):
                    if lane not in self.quarantined:
                        self._quarantine(lane)
                self._pending_quarantine.clear()
                # latency-SLO shedding, also a tick-boundary act (it
                # replaces bucket instances); iterate a snapshot of
                # the CURRENT instances — a _batch_oom mid-tick may
                # already have swapped one out
                for insts in list(self.buckets.values()):
                    for batch in list(insts):
                        if batch.jobs:
                            self._shed_for_slo(batch)
                # cross-bucket (mixed-kernel) lane shedding — same
                # tick-boundary discipline; no-op without SLO jobs or
                # with one bucket per lane
                self._shed_for_lane()
                # autopilot control pass — also a tick-boundary act
                # (it retunes the knobs the NEXT tick dispatches
                # with); None (the default) skips everything
                if self.autopilot is not None:
                    self.autopilot.tick(self)
                self.ticks += 1
                telemetry.maybe_export_metrics()
                if max_ticks is not None and self.ticks >= int(max_ticks):
                    break
        # a write still in flight when serving stops must be durable
        # before the caller reads the report/stores (digest checks,
        # resume over the same dir); failures surface like sync saves'
        self._drain_stores()
        return self.report
