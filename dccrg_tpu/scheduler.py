"""Fleet job scheduler: a priority queue over batched grid buckets.

:class:`FleetScheduler` turns :mod:`dccrg_tpu.fleet`'s batched
execution layer into a multi-tenant serving loop, reusing the
per-run lifecycle machinery of :mod:`dccrg_tpu.supervise` PER JOB:

- **admission**: jobs pop in priority order and land in the
  :class:`~dccrg_tpu.fleet.GridBatch` bucket their
  ``(shape, schema, kernel)`` key selects — created on demand with a
  :func:`~dccrg_tpu.grid.bucket_capacity`-rounded slot count (capped
  by ``DCCRG_FLEET_MAX_BATCH``) so the compiled program survives
  drain and backfill; a job that does not fit waits in the queue and
  **backfills** the next slot a finishing/failing/requeued job frees;
- **checkpoints**: every job owns a
  :class:`~dccrg_tpu.supervise.CheckpointStore` stem (its name) in
  ONE shared directory — periodic per-job saves (dirty-field deltas
  chained to keyframes, exactly the single-run data plane) happen at
  quantum boundaries when a job crosses its ``checkpoint_every``
  cadence, followed by per-stem retention GC
  (:func:`~dccrg_tpu.supervise.gc_checkpoints`, which treats each
  stem as an independent sequence);
- **isolation trips**: the per-slot numerics watchdog
  (:meth:`~dccrg_tpu.fleet.GridBatch.finite_slots`) rolls a tripped
  job back from ITS OWN newest verifying checkpoint in place
  (bounded retries, then ``failed``); a job-scoped injected OOM
  (:meth:`~dccrg_tpu.faults.FaultPlan.resource_exhausted` with
  ``job=``) **requeues** only that job — it re-admits from its
  checkpoint, possibly into a different slot or bucket instance,
  while every neighbor slot's bytes stay frozen-exact. A REAL
  (unattributed) ``RESOURCE_EXHAUSTED`` from the batched dispatch
  requeues the lower-priority half of the bucket's jobs to shrink
  the working set;
- **preemption**: the loop polls the supervision layer's preempt
  flag (SIGTERM/SIGINT handlers, :func:`~dccrg_tpu.supervise
  .request_preempt`, or a faked
  :meth:`~dccrg_tpu.faults.FaultPlan.preempt_signal`) at quantum
  boundaries; on preemption every admitted job takes an emergency
  keyframe into its own stem and is requeued, then
  :class:`FleetPreemptedError` surfaces with the resumable exit code
  75 — rerunning the scheduler over the same directory resumes every
  job from its checkpoint (``resume=True``), bitwise identical to an
  uninterrupted fleet.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import os
import time
from contextlib import nullcontext

import numpy as np

from . import faults, resilience, supervise
from .fleet import (FleetJob, GridBatch, max_batch_default,
                    quantum_default)
from .grid import bucket_capacity

logger = logging.getLogger("dccrg_tpu.scheduler")


class FleetPreemptedError(RuntimeError):
    """The fleet stopped at a quantum boundary on a preemption signal;
    every admitted job saved an emergency keyframe into its own stem
    and was requeued. ``exit_code`` is the resumable 75
    (:data:`~dccrg_tpu.supervise.RESUMABLE_EXIT`); rerun the
    scheduler over the same checkpoint directory to resume."""

    exit_code = supervise.RESUMABLE_EXIT

    def __init__(self, requeued):
        super().__init__(
            f"fleet preempted; {len(requeued)} job(s) emergency-"
            f"checkpointed and requeued (exit code {self.exit_code})")
        self.requeued = list(requeued)


class FleetScheduler:
    """Admit, multiplex, checkpoint and drain a fleet of
    :class:`~dccrg_tpu.fleet.FleetJob` runs (see module docstring).

    ``checkpoint_dir`` holds every job's numbered checkpoint stem.
    Knobs (None = env default): ``max_batch``
    (``DCCRG_FLEET_MAX_BATCH``), ``quantum``
    (``DCCRG_FLEET_QUANTUM``), ``keep_last`` (``DCCRG_KEEP_LAST``) /
    ``keep_every`` (per-stem retention). ``resume`` (default) restores
    a job with existing checkpoints from its newest verifying one
    instead of reinitializing. ``devices`` spreads bucket instances
    round-robin over a device list (default: the default device)."""

    def __init__(self, checkpoint_dir, jobs=(), *, max_batch=None,
                 quantum=None, keep_last=None, keep_every=0,
                 resume=True, devices=None,
                 install_signal_handlers=False):
        self.dir = str(checkpoint_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.max_batch = (max_batch_default() if max_batch is None
                          else max(1, int(max_batch)))
        self.quantum = (quantum_default() if quantum is None
                        else max(1, int(quantum)))
        self.keep_last = (supervise.keep_last_default()
                          if keep_last is None else max(1, int(keep_last)))
        self.keep_every = int(keep_every)
        self.resume = bool(resume)
        self.devices = list(devices) if devices else [None]
        self._install = bool(install_signal_handlers)
        self._queue: list = []  # heap of (-priority, seq, job)
        self._seq = itertools.count()
        self._by_name: dict = {}
        self.buckets: dict = {}  # bucket key -> [GridBatch]
        self._stores: dict = {}  # job name -> CheckpointStore
        self._next_dev = 0
        self.report: dict = {}
        self.ticks = 0
        for j in jobs:
            self.add(j)

    # -- queue --------------------------------------------------------

    def add(self, job: FleetJob) -> None:
        """Queue a job (higher ``priority`` admits first; FIFO within
        a priority). The name is the checkpoint stem — unique per
        scheduler."""
        known = self._by_name.get(job.name)
        if known is not None and known is not job:
            raise ValueError(
                f"duplicate job name {job.name!r}: the name is the "
                "checkpoint stem and must be unique per scheduler")
        self._by_name[job.name] = job
        job.status = "queued"
        heapq.heappush(self._queue, (-job.priority, next(self._seq), job))

    def store_for(self, job: FleetJob) -> supervise.CheckpointStore:
        st = self._stores.get(job.name)
        if st is None:
            st = supervise.CheckpointStore(self.dir, stem=job.name)
            self._stores[job.name] = st
        return st

    # -- admission + backfill -----------------------------------------

    def _bucket_for(self, job: FleetJob) -> GridBatch:
        """A bucket instance with a free slot for ``job``'s key, or
        None. Creates a new instance (round-robin over ``devices``)
        sized to the demand visible NOW — bucket_capacity-rounded so
        later fluctuations reuse the compile — when every existing
        one is full and the device list allows another."""
        key = job.bucket_key()
        insts = self.buckets.setdefault(key, [])
        for b in insts:
            if b.free_slot() is not None:
                return b
        if len(insts) >= len(self.devices):
            return None
        same_key = 1 + sum(1 for _p, _s, j in self._queue
                           if j.bucket_key() == key)
        cap = min(self.max_batch, bucket_capacity(same_key))
        b = GridBatch(job, cap,
                      device=self.devices[self._next_dev % len(self.devices)])
        self._next_dev += 1
        insts.append(b)
        return b

    def _admit_pending(self) -> int:
        """One admission pass: place every queued job that fits
        (priority order; non-fitting jobs go back and backfill
        later). Returns how many were admitted."""
        deferred, admitted = [], 0
        while self._queue:
            item = heapq.heappop(self._queue)
            job = item[2]
            batch = self._bucket_for(job)
            if batch is None:
                deferred.append(item)
                continue
            self._admit_into(batch, job)
            admitted += 1
        for item in deferred:
            heapq.heappush(self._queue, item)
        return admitted

    def _admit_into(self, batch: GridBatch, job: FleetJob) -> None:
        store = self.store_for(job)
        restored = None
        if self.resume or job.steps_done > 0 or job.requeues:
            restored = self._load_newest(batch, store, job)
        elif store.list():
            # resume=False over a dir holding a PREVIOUS run's stem:
            # purge it now, or the first trip/requeue/preemption would
            # _load_newest the stale (higher-step) state — and the
            # per-save GC would keep those stale files over this
            # run's fresh step-0 keyframe
            self._purge_stem(store, job)
        if restored is None:
            job.apply_init(batch.grid)
            job.steps_done = 0
        else:
            job.steps_done = restored
            # the restored checkpoint IS the last save: the periodic
            # cadence continues from it
            job.last_save_step = restored
        slot = batch.admit(job, from_grid=True)
        job.status = "running"
        logger.debug("admitted %s at step %d into slot %d", job.name,
                     job.steps_done, slot)
        if restored is None:
            # the rollback target always exists (the ResilientRunner
            # invariant, per job): a step-0 keyframe before stepping
            self._save_job(batch, slot, job, force_keyframe=True)

    def _purge_stem(self, store, job) -> None:
        """Delete every checkpoint (and sidecar) of ``job``'s stem —
        the ``resume=False`` contract is a from-scratch run."""
        n = 0
        for _step, path in store.list():
            for p in (path, resilience.sidecar_path(path)):
                try:
                    os.remove(p)
                    n += 1
                except OSError:
                    pass
        logger.warning("resume=False: purged %d stale checkpoint "
                       "file(s) of stem %s", n, job.name)

    def _load_newest(self, batch, store, job):
        """Restore the newest verifying checkpoint of ``job``'s stem
        into the bucket's scratch grid (chain-aware; older entries are
        the fallback, mirroring ``resume_latest``). Returns the
        restored step or None."""
        for step, path in store.list():
            try:
                resilience.load_checkpoint_into(batch.grid, path)
            except Exception as e:  # noqa: BLE001 - walk to older
                logger.warning("fleet resume of %s skipped %s (%s)",
                               job.name, path, e)
                continue
            return int(step)
        return None

    # -- per-job checkpointing + retention ----------------------------

    def _save_job(self, batch, slot, job, force_keyframe=False) -> None:
        g = batch.write_grid(slot)
        store = self.store_for(job)
        store.save(g, job.steps_done, dirty_fields=set(job.fields_out),
                   force_keyframe=force_keyframe)
        job.last_save_step = job.steps_done
        try:
            supervise.gc_checkpoints(
                self.dir, keep_last=self.keep_last,
                keep_every=self.keep_every, stem=job.name, apply=True,
                assume_ok=job.steps_done)
        except OSError as e:  # GC must never kill the fleet
            logger.warning("per-stem GC failed for %s (%s)", job.name, e)

    # -- trips: per-slot isolation ------------------------------------

    def _trip(self, batch, slot, job, kind) -> None:
        """One job tripped (NaN in its slot, or a job-scoped OOM).
        Neighbors are untouched by construction; this job rolls back
        from its own checkpoint — in place for numerics trips, via
        requeue for OOMs (the slot is freed so the working set
        shrinks; re-admission restores from the same stem, possibly
        into a different slot or bucket)."""
        job.trips.append((kind, job.steps_done))
        if job.steps_done > job._last_trip_step:
            job.retries = 0  # progress since the last trip
        job._last_trip_step = job.steps_done
        job.retries += 1
        logger.warning(
            "fleet job %s tripped (%s) at step %d; retry %d/%d",
            job.name, kind, job.steps_done, job.retries, job.max_retries)
        if job.retries > job.max_retries:
            self._finish(batch, slot, job, status="failed")
            return
        if kind == "oom":
            # the fault fires BEFORE the dispatch, so the slot state
            # is intact — keyframe it (same premise as _batch_oom /
            # _preempt) so re-admission resumes from here instead of
            # replaying everything since the last periodic save
            self._save_job(batch, slot, job, force_keyframe=True)
            batch.clear(slot)
            job.requeues += 1
            self.add(job)
            return
        restored = self._load_newest(batch, self.store_for(job), job)
        if restored is None:
            logger.error("fleet job %s has no loadable checkpoint to "
                         "roll back to", job.name)
            self._finish(batch, slot, job, status="failed")
            return
        batch.read_grid(slot)
        job.steps_done = restored
        # re-baseline the cadence like _admit_into: a fallback to an
        # OLDER checkpoint would otherwise leave steps_done -
        # last_save_step negative, suppressing saves over the whole
        # replayed region
        job.last_save_step = restored

    def _finish(self, batch, slot, job, status="done") -> None:
        if status == "done":
            job.digest = batch.digest(slot)
        job.status = status
        batch.clear(slot)
        self.report[job.name] = {
            "status": status, "steps": job.steps_done,
            "digest": job.digest, "trips": len(job.trips),
            "retries_final": job.retries, "requeues": job.requeues,
            "transient_retries": job.transient_retries,
        }

    # -- one bucket quantum -------------------------------------------

    def _fire_dispatch_faults(self, batch) -> None:
        """Per-job injection points before the batched dispatch:
        transient dispatch errors retry in place (no rollback, the
        supervision-layer discipline); a job-scoped simulated OOM
        requeues exactly that job."""
        if faults.active() is None:
            return
        for slot, job in batch.jobs:
            for attempt in range(3):
                try:
                    faults.fire("supervise.dispatch", step=job.steps_done,
                                job=job.name, attempt=attempt)
                    break
                except faults.InjectedDispatchError as e:
                    job.transient_retries += 1
                    logger.warning(
                        "transient dispatch error for fleet job %s "
                        "(%s); retrying", job.name, e)
                    time.sleep(0.01 * (2 ** attempt))
            else:
                # retries exhausted: the single-run discipline raises
                # (SupervisedRunner._dispatch); the fleet analogue is
                # failing ONLY this job — neighbors keep serving
                logger.error(
                    "fleet job %s: transient dispatch error persisted "
                    "through 3 attempts; failing the job", job.name)
                self._finish(batch, slot, job, status="failed")
                continue
            try:
                faults.fire("step.dispatch", mode="fleet",
                            step=job.steps_done, job=job.name)
            except Exception as e:  # noqa: BLE001 - filtered below
                if not resilience._is_resource_exhausted(e):
                    raise
                logger.warning("fleet job %s dispatch OOM (%s)",
                               job.name, e)
                self._trip(batch, slot, job, "oom")

    def _quantum(self, batch) -> None:
        self._fire_dispatch_faults(batch)
        active = batch.jobs
        if not active:
            return
        budget = np.zeros(batch.capacity, dtype=np.int32)
        prev = {}
        for slot, job in active:
            budget[slot] = min(self.quantum,
                               max(0, job.n_steps - job.steps_done))
            prev[slot] = job.steps_done
        try:
            batch.step(budget)
        except Exception as e:  # noqa: BLE001 - filtered below
            if not resilience._is_resource_exhausted(e):
                raise
            self._batch_oom(batch, e)
            return
        for slot, job in active:
            job.steps_done += int(budget[slot])
        # fleet-scoped NaN poison (chaos tests): land scheduled
        # poisons for the steps this quantum advanced each job through
        if faults.active() is not None:
            for slot, job in active:
                for fld, cells, value, _ps in faults.poison_fleet(
                        job.name, prev[slot], job.steps_done):
                    if cells is None:
                        local = batch.grid.plan.cells
                        pick = int(faults.active().rng.integers(
                            0, len(local)))
                        cells = [int(local[pick])]
                    batch.poison(slot, fld, cells, value)
        # per-slot watchdog: a tripped slot rolls back alone
        ok = batch.finite_slots()
        tripped = set()
        for slot, job in active:
            if batch.slots[slot] is job and not ok[slot]:
                tripped.add(slot)
                self._trip(batch, slot, job, "nan")
        # periodic per-job checkpoints + completion (never checkpoint
        # a slot that tripped this quantum: its state just rolled
        # back — the cadence restarts from the restored step)
        for slot, job in batch.jobs:
            if slot in tripped:
                continue
            if job.steps_done >= job.n_steps:
                self._finish(batch, slot, job)
            elif (job.checkpoint_every > 0 and job.last_save_step
                  is not None and job.steps_done - job.last_save_step
                  >= job.checkpoint_every):
                self._save_job(batch, slot, job)

    def _batch_oom(self, batch, err) -> None:
        """A REAL (unattributed) RESOURCE_EXHAUSTED from the batched
        dispatch: the whole working set is too big. Requeue the
        lower-priority half of the bucket's jobs (their slot state is
        intact — the dispatch failed wholesale — so each saves a
        keyframe first) and REBUILD the bucket at a smaller capacity:
        occupancy alone frees no device memory (the state arrays and
        the compiled program are both sized ``[capacity, ...]``), and
        the freed slots would be backfilled from the queue on the very
        next tick, re-creating the same working set forever. The
        survivors migrate bit-exactly into the half-size batch;
        repeated OOMs keep halving until a single job's failure is
        surfaced."""
        active = batch.jobs
        if len(active) <= 1:
            raise resilience.ResilienceExhaustedError(
                f"fleet bucket OOMs even with {len(active)} job(s)"
            ) from err
        by_prio = sorted(active, key=lambda e: (e[1].priority, -e[0]))
        drop = len(active) // 2
        for slot, job in by_prio[:drop]:
            self._save_job(batch, slot, job, force_keyframe=True)
            batch.clear(slot)
            job.requeues += 1
            self.add(job)
        survivors = batch.jobs
        new_cap = max(len(survivors), batch.capacity // 2)
        small = GridBatch(survivors[0][1], new_cap, device=batch.device)
        for slot, job in survivors:
            state = batch.extract(slot)
            new_slot = small.admit(job, from_grid=False)
            for name, arr in state.items():
                small.state[name] = small.state[name].at[new_slot].set(arr)
        insts = self.buckets[batch.key]
        insts[insts.index(batch)] = small
        logger.warning(
            "fleet bucket OOM: requeued %d of %d job(s), rebuilt the "
            "bucket at capacity %d (was %d)", drop, len(active),
            new_cap, batch.capacity)

    # -- preemption ---------------------------------------------------

    def _preempt(self) -> None:
        requeued = []
        for insts in self.buckets.values():
            for batch in insts:
                for slot, job in batch.jobs:
                    self._save_job(batch, slot, job, force_keyframe=True)
                    batch.clear(slot)
                    job.requeues += 1
                    self.add(job)
                    requeued.append(job.name)
        supervise.clear_preempt()
        raise FleetPreemptedError(requeued)

    # -- the serving loop ---------------------------------------------

    def active_jobs(self) -> list:
        """``[(batch, slot, job)]`` of every admitted job."""
        return [(b, s, j) for insts in self.buckets.values()
                for b in insts for s, j in b.jobs]

    def run(self, max_ticks=None) -> dict:
        """Serve until the queue and every bucket drain (or
        ``max_ticks`` quantum rounds elapse). Returns the per-job
        report ``{name: {status, steps, digest, trips, ...}}``.
        Raises :class:`FleetPreemptedError` after emergency-saving
        and requeueing every admitted job when preempted."""
        ctx = (supervise.preemption_handlers() if self._install
               else nullcontext())
        with ctx:
            while True:
                if (supervise.preempt_requested()
                        or faults.take_preempt(self.ticks)):
                    self._preempt()
                self._admit_pending()
                active = [b for insts in self.buckets.values()
                          for b in insts if b.jobs]
                if not active:
                    if self._queue:
                        raise RuntimeError(
                            "fleet wedged: queued jobs but no bucket "
                            "can admit them")
                    break
                for batch in active:
                    self._quantum(batch)
                self.ticks += 1
                if max_ticks is not None and self.ticks >= int(max_ticks):
                    break
        return self.report
