"""Crash-safe distributed AMR: an epoch-fenced, abortable cross-rank
structure commit.

The reference dccrg resolves induced 2:1 refinement ACROSS process
boundaries with iterated MPI collectives (dccrg.hpp:9730-10693); a rank
that dies mid-commit takes the job with it. Here structure is
replicated and AMR *requests* are rank-local
(:meth:`~dccrg_tpu.grid.Grid.refine_completely` gates on ``is_local``),
so a multi-process adapt epoch must first exchange every rank's request
view and then install the SAME new structure everywhere — atomically,
against real failure: ``kill -9`` at any phase, a SIGSTOP zombie with a
stale epoch, a wedged or lying KV, a torn proposal record.

:func:`distributed_stop_refining` runs one adapt epoch as a fleet-wide
transaction over the coordination KV (:mod:`dccrg_tpu.coord`
primitives), four fenced phases, each a named fault point
(``amr.propose`` / ``amr.resolve`` / ``amr.install`` with
``phase="prepare"|"commit"``; see :data:`~dccrg_tpu.faults
.DIST_AMR_FAULT_SITES`):

``propose``
    Each rank seals (CRC-framed, :func:`~dccrg_tpu.coord.seal_record`)
    its local request sets, its structure digest, and the one-wave
    induced-refinement frontier it expects to push across its
    ownership boundary (:func:`~dccrg_tpu.amr
    .frontier_induced_refines`) into a proposal record, and the
    records meet at a fenced :func:`~dccrg_tpu.coord.kv_barrier` — the
    barrier doubles as the deadline-bounded proposal exchange.

``resolve``
    Each rank verifies every proposal (CRC frame, fence/attempt echo,
    structure-digest agreement, and the frontier cross-check: the
    declared wave is recomputed from the declared requests against the
    reader's OWN replicated structure — a mismatch convicts the
    proposer of resolving against a different structure epoch), merges
    the request sets, and runs the same deterministic
    :func:`~dccrg_tpu.amr.resolve_adaptation` fixpoint. The result
    digests meet at the resolve barrier and must be identical.

``prepare``
    Each rank mirrors the local commit's bookkeeping (request sets
    cleared, disappearing cells' data preserved) and builds the new
    plan WITHOUT touching the live one — on a
    :class:`~dccrg_tpu.background.PlanBuildWorker` against its own
    arena generation when ``DCCRG_BG_RECOMMIT=1``, inline otherwise.
    Plan digests meet at the prepare barrier and must be identical.

``commit``
    The decision point: the commit barrier, then every rank races its
    verdict onto the round's SINGLE first-writer-wins decision key
    (``kv.create``). Ranks that pass the barrier race ``commit``;
    every abort path races ``abort`` (landed BEFORE the fast-abort
    marker). Whatever record lands first IS the round's outcome, and
    every rank reads it back and obeys: a slow rank whose peers timed
    out and rolled back finds ``abort`` and rolls back too (arrival
    keys are monotonic ghosts — without the verdict it would commit
    alone off a "complete" barrier), and a rank whose commit barrier
    failed just as the round was decided ``commit`` rolls FORWARD and
    installs with the fleet. The epoch fence then advances through a
    create-only per-epoch key — monotonic by construction, so a rank
    that stalls between deciding and publishing can never drag the
    fence backwards — the plan installs, and the decision winner
    garbage-collects every key of rounds the fence has moved past.

Crash consistency: ANY failure before the commit decision — raise,
timeout, dead peer, torn record, stale fence — aborts through
:func:`~dccrg_tpu.txn.cross_rank_transaction`: this rank lands the
``abort`` verdict, rolls back bitwise (old plan, old data, request
sets restored — the epoch is retryable) and posts an abort marker the
peers' barriers fast-abort on, so the whole fleet rolls back
together. Once the verdict is ``commit`` the transaction is past its
point of no return (classic 2PC): a rank that dies installing is a
post-decision death — the survivors install the agreed plan and the
PR-14 lease/reclaim machinery absorbs the corpse's cells — and a rank
whose LOCAL install fails terminates itself (:func:`_fatal_install`)
rather than roll back into permanent structural divergence; the
lease machinery absorbs it the same way. A SIGSTOP zombie that wakes
after the survivors re-formed and committed finds the fence advanced
(:class:`~dccrg_tpu.coord.StaleFenceError`): it rolls back and keeps
serving the OLD plan — rejoining happens through the fleet layer at
the new epoch, never by finishing the stale round. A zombie so stale
its round's keys were garbage-collected reads the missing decision as
``abort`` — same outcome.

A retry after an abort is a COLLECTIVE retry: every participant calls
:func:`distributed_stop_refining` again, and the per-process attempt
counter re-aligns the barrier tags by construction — the same
``#<attempt>`` discipline the two-phase checkpoint save documents in
coord.py. A restarted process whose reset counter re-enters an
attempt that already ran cannot act on its leftover arrival keys: an
aborted attempt left an abort marker (which vetoes barrier completion
— it fast-forwards the straggler one quick typed abort per stale
attempt until it catches the live one) and its verdict on the
decision key, and the commit GC deletes whole rounds once the fence
moves past them. Single-controller grids never construct an
:class:`AmrCommitGroup`, and ``stop_refining`` without one routes to
the unchanged local path — bitwise identical to the pre-refactor
commit (pinned by tests/test_distamr.py).
"""

from __future__ import annotations

import json
import logging
import time
import zlib

import numpy as np

from . import amr, background, coord, faults, telemetry, txn

logger = logging.getLogger("dccrg_tpu.distamr")

#: test hook: called as ``_PHASE_PROBE(phase, rank)`` right before each
#: protocol phase runs — the mp harness's cue point (progress markers,
#: the self-SIGSTOP of the zombie scenario). None in production.
_PHASE_PROBE = None


class AmrProposalError(RuntimeError):
    """A peer's proposal record failed verification BEYOND its CRC
    frame: wrong fence/attempt echo, a structure digest that does not
    match this rank's replicated structure, or a declared induction
    frontier that does not recompute from the declared requests — the
    proposer resolved against a different structure epoch. The round
    must abort collectively; acting on the proposal would commit
    diverged structure. ``rank`` names the proposer."""

    def __init__(self, rank: int, detail: str):
        super().__init__(
            f"AMR proposal from rank {rank} rejected: {detail}")
        self.rank = int(rank)


def _crc(arr, h: int = 0) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes(), h) & 0xFFFFFFFF


def structure_digest(grid) -> int:
    """CRC32 of the live plan's (cells, owner) — the replicated
    structure fingerprint every proposal must echo."""
    return _crc(grid.plan.owner, _crc(grid.plan.cells))


def plan_digest(plan) -> int:
    """CRC32 fingerprint of a constructed plan's structural identity
    (cells, owner, layout extents) — what the prepare barrier
    compares, and what the mp harness asserts survivors kept bitwise
    after an aborted commit."""
    h = _crc(plan.owner, _crc(plan.cells))
    for scalar in (getattr(plan, "R", 0), getattr(plan, "L", 0)):
        h = zlib.crc32(str(int(scalar)).encode(), h) & 0xFFFFFFFF
    return h


class AmrCommitGroup:
    """One rank's handle on the fleet-wide AMR commit protocol.

    Holds the coordination KV, this rank's identity, the expected
    participant set (narrowed by a :class:`~dccrg_tpu.coord.Membership`
    lease view when one is given — a dead rank's requests are dropped
    and its cells absorbed by reclaim, which is how a retry after a
    death makes progress), and the epoch fence every round is gated
    on. Install with :meth:`~dccrg_tpu.grid.Grid
    .enable_distributed_amr`; ``stop_refining`` then routes through
    :func:`distributed_stop_refining`."""

    def __init__(self, grid, *, kv=None, rank=None, n_ranks=None,
                 membership=None, prefix: str = "dccrg/amr",
                 timeout=None, poll_s: float = 0.02):
        self.grid = grid
        self.kv = kv if kv is not None else coord.default_kv()
        if rank is None:
            rank = coord.process_rank(grid)
        self.rank = int(rank)
        if n_ranks is None:
            import jax

            n_ranks = jax.process_count()
        self.n_ranks = max(1, int(n_ranks))
        self.membership = membership
        self.prefix = str(prefix)
        self.timeout = timeout  # None: coord.barrier_timeout() per round
        self.poll_s = max(0.001, float(poll_s))
        self.attempt = 0

    def fence_key(self) -> str:
        return f"{self.prefix}/fence"

    def epoch_key(self, n: int) -> str:
        return f"{self.prefix}/fence/{int(n)}"

    def _mirror_fence(self) -> int:
        try:
            return int(self.kv.get(self.fence_key()))
        except (TypeError, ValueError):
            return 0

    def read_fence(self) -> int:
        """The current epoch fence: the max over the CREATE-only
        per-epoch keys (authoritative — they only accumulate, so this
        read can never observe a regression) and the legacy mirror
        key (what a ``dir_get``-degraded service still serves, and
        what the zombie-fencing tests write directly)."""
        best = self._mirror_fence()
        listing = self.kv.dir_get(f"{self.prefix}/fence/")
        for k in (listing or {}):
            try:
                best = max(best, int(k.rsplit("/", 1)[-1]))
            except ValueError:
                continue
        return best

    def advance_fence(self, target: int) -> int:
        """Publish epoch ``target`` monotonically: CREATE the epoch
        key (first-writer-wins and append-only — a rank that stalled
        between deciding and publishing can never drag the fence
        backwards, which the blind ``set`` this replaces could), then
        refresh the mirror best-effort and only ever upwards. Returns
        the fence now observed."""
        target = int(target)
        self.kv.create(self.epoch_key(target), "1")
        if self._mirror_fence() < target:
            self.kv.set(self.fence_key(), str(target))
        return self.read_fence()

    def local_devs(self):
        """This rank's device ids (what ``is_local`` gates on) — the
        ownership view its proposal declares so peers can recompute
        its frontier."""
        return [int(d) for d in
                np.nonzero(np.asarray(self.grid._proc_local_dev))[0]]

    def expected_ranks(self):
        """The participant set of the NEXT round: every configured
        rank, minus the ones the membership lease view has declared
        dead (their pending requests are lost with them — the
        documented semantics of a mid-epoch death)."""
        if self.membership is not None:
            try:
                self.membership.poll()
            except Exception:  # noqa: BLE001 - view refresh best-effort
                pass
            live = {r for r in self.membership.live_ranks()
                    if 0 <= int(r) < self.n_ranks}
            live.add(self.rank)
            return sorted(live)
        return list(range(self.n_ranks))


class _Attempt:
    """Naming + abort plumbing of one (fence, attempt) round."""

    def __init__(self, group: AmrCommitGroup, fence: int, attempt: int,
                 expected):
        self.group = group
        self.fence = int(fence)
        self.attempt = int(attempt)
        self.expected = list(expected)
        self.timeout = (coord.barrier_timeout() if group.timeout is None
                        else float(group.timeout))

    def tag(self, phase: str) -> str:
        return (f"{self.group.prefix}/b/{self.fence}"
                f"#{self.attempt}/{phase}")

    def key(self, name: str) -> str:
        return (f"{self.group.prefix}/{name}/{self.fence}"
                f"#{self.attempt}")

    def abort_key(self) -> str:
        return self.key("abort")

    def decision_key(self) -> str:
        return self.key("decision")

    def decide(self, want: str, detail: str = "") -> dict:
        """Race this rank's verdict for the round onto the SINGLE
        first-writer-wins decision key and return the verdict that
        actually STANDS (which may be a peer's opposite one — the
        caller must obey it). This is what makes the commit decision
        atomic: a slow rank and a timing-out peer can both reach the
        decision point, but only one record lands, and both act on
        the same one. The read retries a transiently wedged KV; a
        verdict that stays unreadable (or is torn) reads as ABORT —
        keeping the old plan is the one answer a rank may act on
        alone."""
        key = self.decision_key()
        self.group.kv.create(key, coord.seal_record(json.dumps(
            {"decision": str(want), "fence": self.fence,
             "attempt": self.attempt, "rank": self.group.rank,
             "detail": str(detail)[:200]}, sort_keys=True)))
        raw = None
        for _ in range(50):
            raw = self.group.kv.get(key)
            if raw is not None:
                break
            time.sleep(0.02)
        try:
            info = json.loads(coord.unseal_record(raw, key))
            if str(info.get("decision")) in ("commit", "abort"):
                return info
            fallback = f"malformed decision record {info!r}"[:200]
        except Exception as e:  # noqa: BLE001 - torn/unreadable verdict
            fallback = f"unreadable decision record ({type(e).__name__})"
        return {"decision": "abort", "fence": self.fence,
                "attempt": self.attempt, "rank": -1, "detail": fallback}

    def post_abort(self, err: BaseException) -> None:
        """The distributed-rollback announcement
        (:func:`~dccrg_tpu.txn.cross_rank_transaction`'s ``on_abort``):
        FIRST race the round's ABORT verdict onto the decision key —
        so a slow peer that later wakes into a complete-looking
        barrier reads it and rolls back instead of committing alone —
        then land the sealed abort marker every peer blocked in this
        round's barriers fast-aborts on instead of burning its
        deadline."""
        cause = getattr(err, "__cause__", None) or err
        reason = f"{type(cause).__name__}: {cause}"[:200]
        self.group.kv.create(self.decision_key(), coord.seal_record(
            json.dumps({"decision": "abort", "fence": self.fence,
                        "attempt": self.attempt,
                        "rank": self.group.rank, "detail": reason},
                       sort_keys=True)))
        self.group.kv.set(self.abort_key(), coord.seal_record(json.dumps(
            {"rank": self.group.rank, "reason": reason})))

    def barrier(self, phase: str, value: str = "1") -> dict:
        """This round's fenced barrier at ``phase``; returns the
        per-rank values (the built-in all-gather). The fence watch
        reads through :meth:`AmrCommitGroup.read_fence` (the monotonic
        epoch-key max), not the raw mirror key, so a regressed mirror
        can neither spuriously convict a live round nor let a stale
        zombie pass."""
        return coord.kv_barrier(
            self.group.kv, self.tag(phase), self.group.rank,
            self.expected, timeout=self.timeout, value=value,
            poll_s=self.group.poll_s,
            fence=(self.group.read_fence, str(self.fence)),
            abort_key=self.abort_key(), membership=self.group.membership)

    def gc_older_rounds(self) -> None:
        """Best-effort garbage collection after THIS round committed:
        delete every barrier arrival, abort marker, decision record
        and epoch-fence key of rounds STRICTLY older than this fence.
        The current round's keys stay — a slow peer may still be
        reading its decision — and the newest epoch keys stay, so a
        fence read can never regress. Keeps the coordination KV
        bounded across adapt epochs and removes the stale arrivals
        that made tag aliasing possible; a zombie whose whole round
        was collected finds its decision key gone, reads ABORT, and
        stays on its old plan (the fleet-layer rejoin path)."""
        kv = self.group.kv
        prefix = self.group.prefix
        for sub in (f"{prefix}/b/", f"{prefix}/abort/",
                    f"{prefix}/decision/", f"{prefix}/fence/"):
            listing = kv.dir_get(sub)
            for k in (listing or {}):
                if not k.startswith(sub):
                    continue
                head = k[len(sub):].split("#", 1)[0].split("/", 1)[0]
                try:
                    f = int(head)
                except ValueError:
                    continue
                if f < self.fence:
                    kv.delete(k)


def _probe(phase: str, rank: int) -> None:
    if _PHASE_PROBE is not None:
        _PHASE_PROBE(phase, rank)


def _maybe_hang(site: str, phase, rank) -> None:
    hang = faults.take_amr_hang(site, phase=phase, rank=rank)
    if hang:
        time.sleep(min(float(hang), 3600.0))


#: test hook: replaces the process-terminating half of
#: :func:`_fatal_install` so in-process fakes can observe the verdict
#: without dying. Called with the original exception. None in
#: production.
_FATAL_INSTALL = None

#: exit code of a rank whose post-decision install failed — the one
#: failure 2PC cannot roll back (peers committed) and must convert
#: into a death the lease/reclaim machinery absorbs.
INSTALL_FATAL_RC = 86


def _fatal_install(err: BaseException) -> None:
    """A LOCAL failure after the round's verdict landed as COMMIT:
    the peers are installing the new plan, so rolling this rank back
    would leave a permanently structurally diverged survivor (every
    future collective adapt would abort fleet-wide on its stale
    digest, with no in-protocol resync). The only consistent outcome
    is to stop being a survivor: terminate the process and let the
    lease/reclaim machinery absorb it exactly like a post-decision
    death — which is what it is."""
    if _FATAL_INSTALL is not None:
        _FATAL_INSTALL(err)
        return
    import os

    os._exit(INSTALL_FATAL_RC)


def distributed_stop_refining(grid, group: AmrCommitGroup = None):
    """Commit all ranks' refinement requests as one fleet-wide,
    crash-consistent transaction (see module docstring); returns the
    created cells exactly as the local ``stop_refining`` would.

    Any failure before the commit decision raises
    :class:`~dccrg_tpu.txn.CrossRankAbortedError` (or propagates an
    injected rank death raw) with this rank bitwise rolled back and
    the abort announced to the peers; the epoch is collectively
    retryable — every surviving rank calls this again. Once the
    round's verdict is COMMIT, failures roll FORWARD: the plan
    installs even if this rank's commit barrier failed, and a local
    install failure terminates the process (:func:`_fatal_install`)
    instead of leaving a diverged survivor."""
    if group is None:
        group = getattr(grid, "_amr_group", None)
    if group is None:
        raise ValueError("grid has no AmrCommitGroup: call "
                         "enable_distributed_amr() first")
    fence0 = group.read_fence()
    group.attempt += 1
    att = _Attempt(group, fence0, group.attempt, group.expected_ranks())
    t0 = time.perf_counter()
    staged: dict = {}
    try:
        with telemetry.span("grid.adapt.dist"), \
                txn.cross_rank_transaction(
                    grid, op="distributed_stop_refining",
                    rank=group.rank, on_abort=att.post_abort,
                    validate=False):
            _run_round(grid, group, att, staged)
    except txn.CrossRankAbortedError:
        telemetry.inc("dccrg_dist_amr_aborts_total")
        raise
    # the fleet-wide verdict is COMMIT: from here on failures roll
    # forward, never back — see _install_decided
    _install_decided(grid, group, att, staged)
    telemetry.observe("dccrg_dist_amr_commit_seconds",
                      time.perf_counter() - t0)
    telemetry.inc("dccrg_dist_amr_commits_total")
    return staged["res"].new_cells.copy()


def _install_decided(grid, group: AmrCommitGroup, att: _Attempt,
                     staged: dict) -> None:
    """The post-decision half of the commit: publish the new epoch
    (monotonic create-only key — a stalled rank's late publish can
    never regress it), install the prepared plan, verify in DEBUG
    mode, then let the decision winner garbage-collect the rounds the
    fence moved past. Runs OUTSIDE the abortable transaction: the
    round is decided, so 2PC forbids restoring the old plan here — a
    local failure terminates the process instead
    (:func:`_fatal_install`)."""
    try:
        group.advance_fence(att.fence + 1)
        grid._pending_changed_cells = None
        grid._install_plan(staged["plan"],
                           same_cells=staged["same_cells"])
        if getattr(grid, "_debug", False):
            from . import verify as verify_mod

            verify_mod.verify_all(grid, check_pins=False)
    except BaseException as err:  # noqa: BLE001 - divergence is fatal
        logger.critical(
            "rank %d: post-decision install failed (%s: %s) — "
            "terminating: the fleet committed fence %d and a survivor "
            "still serving the old plan would diverge it permanently",
            group.rank, type(err).__name__, err, att.fence + 1)
        telemetry.inc("dccrg_dist_amr_install_fatal_total")
        _fatal_install(err)
        raise
    if int(staged.get("decision", {}).get("rank", -1)) == group.rank:
        # exactly one rank won the decision create: it sweeps, the
        # others skip — GC needs no coordination of its own
        att.gc_older_rounds()


def _run_round(grid, group: AmrCommitGroup, att: _Attempt,
               staged: dict) -> None:
    from .grid import DEFAULT_NEIGHBORHOOD_ID

    offsets = grid.neighborhoods[DEFAULT_NEIGHBORHOOD_ID]

    # ---- propose ----------------------------------------------------
    _probe("propose", group.rank)
    faults.fire("amr.propose", rank=group.rank)
    _maybe_hang("amr.propose", None, group.rank)
    cur = group.read_fence()
    if cur != att.fence:
        # stopped between reading the fence and proposing: a zombie
        # already — lose before writing anything
        raise coord.StaleFenceError(att.tag("propose"), att.fence, cur)
    sdig = structure_digest(grid)
    devs = group.local_devs()
    frontier = amr.frontier_induced_refines(
        grid.mapping, grid.plan.cells, grid.plan.owner, offsets,
        grid._refines, devs, topology=grid.topology)
    record = coord.seal_record(json.dumps({
        "rank": group.rank, "fence": att.fence, "attempt": att.attempt,
        "sdig": sdig, "devs": devs,
        "refines": sorted(int(c) for c in grid._refines),
        "unrefines": sorted(int(c) for c in grid._unrefines),
        "dont_refines": sorted(int(c) for c in grid._dont_refines),
        "dont_unrefines": sorted(int(c) for c in grid._dont_unrefines),
        "frontier": [int(c) for c in frontier],
    }, sort_keys=True))
    if faults.take_torn_record("amr.propose", rank=group.rank):
        # a writer that died mid-write: store a frame whose CRC cannot
        # verify — readers must convict, never parse
        record = record[: max(1, len(record) - 4)]
    # the fenced barrier IS the deadline-bounded proposal exchange
    raw = att.barrier("propose", value=record)

    # ---- resolve ----------------------------------------------------
    _probe("resolve", group.rank)
    faults.fire("amr.resolve", rank=group.rank)
    _maybe_hang("amr.resolve", None, group.rank)
    props = {}
    for r, rec in raw.items():
        payload = coord.unseal_record(rec, key=att.tag("propose")
                                      + f"/{r}")
        props[r] = json.loads(payload)
    merged = {"refines": set(), "unrefines": set(),
              "dont_refines": set(), "dont_unrefines": set()}
    for r, p in sorted(props.items()):
        if (int(p.get("fence", -1)) != att.fence
                or int(p.get("attempt", -1)) != att.attempt
                or int(p.get("rank", -1)) != r):
            raise AmrProposalError(
                r, f"round echo mismatch (fence {p.get('fence')!r}, "
                   f"attempt {p.get('attempt')!r})")
        if int(p.get("sdig", -1)) != sdig:
            raise AmrProposalError(
                r, f"structure digest {p.get('sdig')} != local {sdig} "
                   "— proposer resolved against a different structure "
                   "epoch")
        declared = np.sort(np.asarray(p.get("frontier", []),
                                      dtype=np.uint64))
        recomputed = amr.frontier_induced_refines(
            grid.mapping, grid.plan.cells, grid.plan.owner, offsets,
            set(int(c) for c in p.get("refines", [])),
            p.get("devs", []), topology=grid.topology)
        if not np.array_equal(declared, recomputed):
            raise AmrProposalError(
                r, "declared induction frontier does not recompute "
                   "from the declared requests")
        for name in merged:
            merged[name].update(int(c) for c in p.get(name, []))
    res = amr.resolve_adaptation(
        grid.mapping, grid.plan.cells, grid.plan.owner, offsets,
        merged["refines"], merged["unrefines"],
        merged["dont_refines"], merged["dont_unrefines"],
        pins=grid._pins, weights=grid._weights,
        topology=grid.topology, hood_len=grid._hood_len)
    rdig = _crc(res.owner, _crc(res.cells))
    votes = att.barrier("resolve", value=str(rdig))
    bad = {r: v for r, v in votes.items() if v != str(rdig)}
    if bad:
        raise AmrProposalError(
            min(bad), f"resolve digest disagreement: {bad} != {rdig} "
                      "— the deterministic fixpoint diverged")

    # ---- prepare ----------------------------------------------------
    _probe("prepare", group.rank)
    faults.fire("amr.install", phase="prepare", rank=group.rank)
    _maybe_hang("amr.install", "prepare", group.rank)
    # mirror the local commit's bookkeeping (grid.stop_refining): the
    # request sets are consumed, disappearing cells' data preserved
    # for get_old_data(), all inside the transaction snapshot
    grid._refines.clear()
    grid._unrefines.clear()
    grid._dont_refines.clear()
    grid._dont_unrefines.clear()
    old_ids = np.concatenate([res.refined_parents, res.removed_cells])
    grid._removed_data = {}
    if len(old_ids):
        dev, rows = grid._host_rows(old_ids)
        capn = grid._sticky_cap("removed", len(old_ids))
        for name in grid.fields:
            grid._removed_data[name] = (
                old_ids, grid._device_gather(name, dev, rows, cap=capn))
    else:
        grid._removed_data = {name: (old_ids, None)
                              for name in grid.fields}
    grid._removed_cells = res.removed_cells
    grid._new_cells = res.new_cells
    grid._unrefined_parents = res.unrefined_parents

    old_plan = grid.plan
    same_cells = (len(res.cells) == len(old_plan.cells)
                  and np.array_equal(res.cells, old_plan.cells))
    if same_cells:
        changed_hint = (old_plan.cells, np.empty(0, dtype=np.uint64))
    else:
        changed_hint = (old_plan.cells, res.changed_cells)
    if background.bg_recommit_enabled():
        # the per-rank build runs on this rank's PlanBuildWorker
        # against its own arena generation (live + rollback plans stay
        # protected); the commit still waits for it HERE — the install
        # is collective and cannot ride a per-host step boundary
        worker = background.PlanBuildWorker(
            grid, res.cells, res.owner, changed_hint).start()
        worker.wait()
        if worker.error is not None:
            logger.warning(
                "distributed AMR plan build worker failed (%s: %s); "
                "rebuilding inline", type(worker.error).__name__,
                worker.error)
            plan = grid._construct_plan(res.cells, res.owner,
                                        changed_hint)
        else:
            plan = worker.plan
    else:
        plan = grid._construct_plan(res.cells, res.owner, changed_hint)
    pdig = plan_digest(plan)
    votes = att.barrier("prepare", value=str(pdig))
    bad = {r: v for r, v in votes.items() if v != str(pdig)}
    if bad:
        raise AmrProposalError(
            min(bad), f"prepared plan digest disagreement: {bad} != "
                      f"{pdig}")

    # ---- commit -----------------------------------------------------
    _probe("commit", group.rank)
    faults.fire("amr.install", phase="commit", rank=group.rank)
    _maybe_hang("amr.install", "commit", group.rank)
    staged.update(plan=plan, res=res, same_cells=same_cells, pdig=pdig)
    # the decision point: a rank that dies BEFORE the verdict lands
    # aborts the whole round (the survivors time out / convict the
    # lease, land the abort verdict, and keep the old plan bitwise); a
    # rank that dies AFTER it is a post-decision death — the survivors
    # install and reclaim. The verdict itself is one first-writer-wins
    # record (att.decide), so the barrier outcome alone never commits.
    try:
        att.barrier("commit")
    except faults.InjectedRankDeath:
        # a simulated kill -9: a corpse posts no verdict — the peers
        # must convict it by lease/timeout, which is the invariant
        # under test
        raise
    except Exception as err:
        # the barrier failed LOCALLY, but the round may already be
        # decided: race an abort verdict onto the decision key. Losing
        # to a peer's COMMIT means the fleet is installing — this rank
        # must roll forward with it (a decided commit cannot be rolled
        # back), not restore the old plan and diverge.
        verdict = att.decide(
            "abort", detail=f"{type(err).__name__} at commit barrier")
        if verdict["decision"] == "commit":
            logger.warning(
                "rank %d: commit barrier failed (%s: %s) but the "
                "round's verdict is COMMIT (landed by rank %s) — "
                "rolling forward", group.rank, type(err).__name__,
                err, verdict.get("rank"))
            telemetry.inc("dccrg_dist_amr_commit_overruled_total")
            staged["decision"] = verdict
            return
        raise
    verdict = att.decide("commit", detail="commit barrier passed")
    if verdict["decision"] != "commit":
        # a peer's abort verdict won the race (it gave up on this
        # rank's arrival just as the barrier completed): obey it and
        # roll back with everyone else instead of committing alone
        raise coord.RemoteAbortError(
            att.tag("commit"), rank=int(verdict.get("rank", -1)),
            reason=str(verdict.get("detail", ""))[:200])
    staged["decision"] = verdict
