"""Production autopilot: telemetry-driven self-tuning with an
explainable decision journal.

PR 9 gave the system eyes (per-bucket quantum-latency EWMAs,
trip/rollback/audit counters, span traces) and the serving layers
carry a dozen hand-set knobs (``DCCRG_FLEET_QUANTUM``,
``bucket_capacity``, per-job ``checkpoint_every``,
``DCCRG_AUDIT_EVERY``, ...). This module closes the loop: a
**deterministic controller** wired into
:class:`~dccrg_tpu.scheduler.FleetScheduler` that tunes, within hard
bounds, from nothing but recorded observations:

- **quantum length** against measured SLO slack — long quanta
  amortize dispatch overhead, short quanta bound preemption/rollback
  loss and tighten the watchdog/checkpoint poll cadence — with a
  journal-driven cross-run warm start (``quantum.learn`` at a clean
  drain, ``quantum.warm_start`` on the next run's first tick: the
  capacity.learn/probe discipline applied to the QUANTUM knob);
- **per-stem checkpoint cadence** from measured save cost x observed
  trip rate (Young's first-order optimum,
  ``sqrt(2 * save_cost / trip_rate)`` in step units), extended by the
  MEASURED per-trip recovery cost — the ``dccrg_rollback_seconds``
  histogram feeds Daly's ``sqrt(2 * C * (M + R))`` ``R`` term, so
  replay is no longer priced via save cost alone;
- **audit cadence** up while a device lane's suspect counter is warm
  and back down to the configured baseline after a clean streak;
- **initial bucket capacity** seeded from the recorded OOM/shed
  history instead of rediscovering it by halving every run (the
  journal doubles as the cross-run memory).

The observability half is the headline, not an afterthought: adaptive
policies are only operable when every automatic decision is
reconstructable from recorded observations (Dean & Barroso, "The Tail
at Scale", CACM 2013; Hochschild et al., HotOS'21). Every decision is
therefore emitted as a **structured record** — observed inputs
(metric names + values), rule fired, action taken, expected effect —
into a bounded in-memory ring and an append-only JSONL journal
(``DCCRG_DECISION_FILE``, rank-tagged and merge-able across ranks
exactly like the telemetry traces). ``python -m dccrg_tpu.autopilot
explain`` renders every decision human-readably from the journal
alone, and ``replay`` re-derives each action by feeding the RECORDED
inputs back through the same pure rule functions the live controller
used — any divergence is a bug (journal corruption, nondeterminism,
or a rule edit that silently changed behavior). A periodic
human-readable status snapshot (``DCCRG_STATUS_FILE``) shows the
per-bucket latency EWMAs, live knob values, suspect counters and SLO
slack an operator needs at a glance.

Deterministic by construction, the :class:`~dccrg_tpu.scheduler
.SLOPolicy` discipline: the clock is injectable, every rule is a pure
function of ``(current value, recorded inputs)`` — thresholds and
hard bounds travel INSIDE the recorded inputs so replay needs nothing
but the journal — and the controller's own state (streak counters,
windowed rates) feeds the rules only through those recorded inputs.

OFF BY DEFAULT: without ``DCCRG_AUTOPILOT=1`` the scheduler never
constructs a controller and fleet scheduling, checkpoint cadence and
audit cadence are bitwise identical to the pre-autopilot behavior
(pinned by tests/test_autopilot.py). With it on, the controller is
pure host-side float arithmetic per scheduler tick — no device work,
no extra dispatches (PERF.md quantifies: in the noise).
"""

from __future__ import annotations

import collections
import hashlib
import json
import math
import os
import time

from . import telemetry

logger = __import__("logging").getLogger("dccrg_tpu.autopilot")


# ---------------------------------------------------------------------
# env knobs
# ---------------------------------------------------------------------

def autopilot_enabled(default: bool = False) -> bool:
    """The ``DCCRG_AUTOPILOT`` env knob: ``1`` lets the fleet
    scheduler construct and run the self-tuning controller. Unset
    (default): no controller object exists and every knob keeps its
    configured value — the negative pin."""
    v = os.environ.get("DCCRG_AUTOPILOT", "")
    if v == "":
        return default
    return v not in ("0", "off", "false", "no")


def decision_file_default():
    """The ``DCCRG_DECISION_FILE`` env knob: JSONL journal every
    decision record is appended to (best-effort, like every telemetry
    exporter). A literal ``{rank}`` is substituted with the coord rank
    id; per-rank files merge like traces (records carry the rank)."""
    return os.environ.get("DCCRG_DECISION_FILE") or None


def status_file_default():
    """The ``DCCRG_STATUS_FILE`` env knob: where the periodic
    human-readable status snapshot is (re)written."""
    return os.environ.get("DCCRG_STATUS_FILE") or None


def decision_ring_default(default: int = 4096) -> int:
    """The ``DCCRG_DECISION_RING`` env knob: how many decision records
    the in-memory ring holds (the journal file is unbounded)."""
    try:
        return max(16, int(os.environ.get("DCCRG_DECISION_RING", "")
                           or default))
    except ValueError:
        return default


# ---------------------------------------------------------------------
# the rules: pure functions of (current value, recorded inputs)
# ---------------------------------------------------------------------
#
# Every rule takes the knob's current value and the inputs dict that
# was (or will be) recorded in the decision journal, and returns the
# new value — or None when the rule does not fire on those inputs.
# Thresholds, streaks and hard bounds are all INSIDE the inputs, so
# `replay` can re-derive the action from the journal alone. Rules
# must be deterministic and JSON-faithful (inputs survive a
# json round-trip unchanged).

def _rule_quantum_shorten(before, inp):
    """Negative SLO slack or a warm trip rate: halve the quantum —
    shorter quanta bound preemption/rollback loss and tighten the
    watchdog/checkpoint poll cadence."""
    slack = inp.get("slo_slack_min_s")
    violating = slack is not None and slack < 0.0
    tripping = inp.get("trip_rate", 0.0) > inp.get("trip_warm", 0.02)
    if not (violating or tripping):
        return None
    if inp.get("streak", 1) < inp.get("patience", 1):
        return None
    new = max(int(inp.get("lo", 1)), int(before) // 2)
    return new if new != int(before) else None


def _rule_quantum_lengthen(before, inp):
    """Comfortable slack (or no SLO jobs at all) and a cool trip
    rate, sustained: double the quantum — long quanta amortize
    per-dispatch overhead across more steps."""
    lat = inp.get("quantum_latency_s")
    if lat is None:
        return None  # never lengthen blind: no measured dispatch yet
    if inp.get("trip_rate", 0.0) > inp.get("trip_cool", 0.005):
        return None
    slack = inp.get("slo_slack_min_s")
    if slack is not None and slack < inp.get("slack_factor", 8.0) * lat:
        return None
    if inp.get("streak", 1) < inp.get("patience", 1):
        return None
    new = min(int(inp.get("hi", 64)), int(before) * 2)
    return new if new != int(before) else None


def _rule_ckpt_retune(before, inp):
    """Young/Daly first-order optimal checkpoint interval from
    measured save cost x observed trip rate, in step units. With the
    measured per-trip recovery cost (``rollback_s`` — the chain-aware
    checkpoint load the ``dccrg_rollback_seconds`` histogram times)
    the optimum is Daly's ``sqrt(2 * C * (M + R))`` with ``C =
    save_cost_s/step_seconds``, ``M = 1/trip_rate`` and ``R =
    rollback_s/step_seconds``; without it (no rollback observed yet)
    it degrades to Young's ``sqrt(2 * C / trip_rate)`` exactly. A
    trip-free history pushes the cadence to the upper bound (saves
    cost, trips don't); a deadband suppresses churn."""
    sc = inp.get("save_cost_s")
    st = inp.get("step_seconds")
    if sc is None or st is None or sc <= 0.0 or st <= 0.0:
        return None
    rate = inp.get("trip_rate", 0.0)
    if rate <= 0.0:
        opt = float(inp.get("hi", 256))
    else:
        mtbf_steps = 1.0 / rate
        rb = inp.get("rollback_s")
        if rb is not None and rb > 0.0:
            mtbf_steps += rb / st
        opt = math.sqrt(2.0 * (sc / st) * mtbf_steps)
    new = max(int(inp.get("lo", 1)),
              min(int(inp.get("hi", 256)), int(round(opt))))
    before = int(before)
    if abs(new - before) < max(1, int(before
                                      * inp.get("deadband", 0.25))):
        return None
    return new


def _rule_audit_tighten(before, inp):
    """Fresh suspect verdicts on a device lane: audit more often —
    halve the cadence (or switch audits ON at ``warm_start`` when the
    baseline keeps them off)."""
    if inp.get("new_suspects", 0) <= 0:
        return None
    before = int(before)
    new = (int(inp.get("warm_start", 8)) if before <= 0
           else max(1, before // 2))
    new = min(new, int(inp.get("hi", 16))) if new > 0 else new
    return new if new != before else None


def _rule_audit_relax(before, inp):
    """A sustained clean streak: walk the audit cadence back toward
    the configured baseline (doubling; a zero baseline switches
    audits back off once the cadence passes the envelope top)."""
    if inp.get("clean_streak", 0) < inp.get("relax_after", 8):
        return None
    base = int(inp.get("baseline", 0))
    before = int(before)
    if before == base or before <= 0:
        return None
    new = before * 2
    if base > 0:
        new = min(new, base)
    if new > int(inp.get("hi", 16)):
        new = 0 if base <= 0 else int(inp.get("hi", 16))
    return new if new != before else None


def _rule_capacity_learn(before, inp):
    """An OOM/shed rebuild survived at ``observed_capacity`` slots:
    remember the smallest capacity that has ever had to be halved to
    for this bucket key."""
    obs = int(inp["observed_capacity"])
    if before is None:
        return obs
    new = min(int(before), obs)
    return new if new != int(before) else None


def _rule_capacity_seed(before, inp):
    """A new bucket for a key with recorded OOM/shed history: start
    at the learned surviving capacity instead of rediscovering it by
    halving."""
    learned = inp.get("learned_capacity")
    if learned is None:
        return None
    new = max(int(inp.get("lo", 1)), min(int(before), int(learned)))
    return new if new != int(before) else None


def _rule_quantum_learn(before, inp):
    """The run drained cleanly: journal the converged quantum as
    cross-run memory (the ``capacity.learn`` discipline for the
    QUANTUM knob — the journal record IS the memory,
    ``load_history`` replays it). Fires only when the final value
    differs from what the next run would otherwise start at (the
    previously learned value, else the configured default)."""
    final = inp.get("final_quantum")
    if final is None:
        return None
    final = int(final)
    base = before if before is not None else inp.get("configured")
    if base is not None and int(base) == final:
        return None
    return final


def _rule_quantum_warm_start(before, inp):
    """A prior run journaled its converged quantum for this
    scheduler: start there (clamped to the hard envelope) instead of
    re-converging from the configured default — the ``capacity.seed``
    mirror."""
    learned = inp.get("learned_quantum")
    if learned is None:
        return None
    new = max(int(inp.get("lo", 1)),
              min(int(inp.get("hi", 64)), int(learned)))
    return new if new != int(before) else None


def _rule_capacity_probe(before, inp):
    """A run that completed with NO OOM/shed on a seeded bucket key:
    double the learned capacity back toward the configured default —
    the learned floor is a recoverable observation, not a permanent
    ratchet (one transient co-tenant spike must not pin a key's
    throughput down forever)."""
    if not inp.get("clean_run"):
        return None
    new = int(before) * 2
    cap = inp.get("default_capacity")
    if cap is not None:
        new = min(new, int(cap))
    return new if new != int(before) else None


def _rule_shed_cooldown(before, inp):
    """Retune the SLO-shed cooldown from observed shed churn: a fresh
    shed doubles the cooldown (every shed rebuild costs a compile and
    resets the EWMA — back-to-back sheds are the feedback loop the
    cooldown exists to damp), and a sustained clean streak halves it
    back toward the configured baseline (a calm fleet earns its
    responsiveness back)."""
    before = int(before)
    lo = max(1, int(inp.get("lo", 1)))
    hi = int(inp.get("hi", 64))
    if inp.get("new_sheds", 0) > 0:
        new = min(hi, max(lo, before * 2))
    elif (inp.get("shed_clean_streak", 0) >= inp.get("relax_after", 8)
          and before > max(lo, int(inp.get("baseline", lo)))):
        new = min(hi, max(lo, int(inp.get("baseline", lo)),
                          before // 2))
    else:
        return None
    return new if new != before else None


def _rule_retry_budget(before, inp):
    """Retune a job's trip-retry budget from ITS OWN trip history: a
    job burning consecutive retries at the same step (a deterministic
    blow-up the rollback cannot outrun) fails faster — each replay of
    the doomed window is pure wasted wall — while a job whose trips
    RECOVER (progress after every rollback, no same-step churn) earns
    headroom for the next transient upset."""
    before = int(before)
    lo = max(1, int(inp.get("lo", 1)))
    hi = int(inp.get("hi", 8))
    repeat = int(inp.get("repeat_trips", 0))
    recovered = int(inp.get("recovered", 0))
    if repeat >= 2:
        new = max(lo, min(hi, before - 1))
    elif recovered > 0 and repeat == 0:
        new = min(hi, max(lo, before + 1))
    else:
        return None
    return new if new != before else None


def _rule_intake_gate(before, inp):
    """The streaming-intake backpressure gate with hysteresis: the
    gate CLOSES (1) when the arrival/drain EWMA ratio crosses ``hi``
    or the oldest waiting record's age exceeds ``age_bound_s``, and
    only REOPENS (0) once the ratio has fallen below the strictly
    lower ``lo`` with the queue age back in bounds — the hysteresis
    band (plus the caller's per-EWMA-window evaluation cadence) is
    what keeps the gate from flapping at the saturation boundary.
    Thresholds travel inside the recorded inputs so replay is
    self-contained."""
    state = 1 if before else 0
    ratio = inp.get("ratio")
    age = float(inp.get("queue_age_s", 0.0))
    hi = float(inp.get("hi", 1.2))
    lo = float(inp.get("lo", 0.9))
    bound = float(inp.get("age_bound_s", 30.0))
    over = (ratio is not None and float(ratio) >= hi) or age > bound
    calm = (ratio is None or float(ratio) <= lo) and age <= bound
    if state == 0 and over:
        return 1
    if state == 1 and calm:
        return 0
    return None


def _rule_intake_shed(before, inp):
    """Narrate a journaled graceful shed under intake saturation:
    ``n`` waiting spool records were moved aside because the backlog
    implied an unbounded queue age (``backlog / drain`` beyond the
    bound). The 'knob' is the cumulative shed count — the record
    exists so ``explain`` reconstructs WHAT was shed, from WHICH
    tenant and under WHICH saturation numbers from the journal
    alone."""
    n = int(inp.get("n", 0))
    if n <= 0:
        return None
    return int(before) + n


def _rule_intake_quarantine(before, inp):
    """Narrate a poison-job quarantine: a spool record whose
    admission failed ``attempts`` times (or failed permanently —
    torn frame, malformed spec, unknown kernel) moved to
    ``spool/quarantine/`` with a structured reason instead of
    wedging the stream. The 'knob' is the cumulative quarantine
    count."""
    if not inp.get("name"):
        return None
    return int(before) + 1


def _rule_fleet_reclaim(before, inp):
    """Narrate an elastic-fleet job reclaim in the decision journal:
    ``n`` jobs of a dead rank were taken over (lease expired, epoch
    fence bumped). The 'knob' is the cumulative reclaim count — the
    record exists so ``explain`` reconstructs WHO died, WHAT was
    reclaimed and under WHICH lease bound from the journal alone."""
    n = int(inp.get("n", 0))
    if n <= 0:
        return None
    return int(before) + n


def _rule_warm_cache(before, inp):
    """Narrate one warm-start cache decision: a bucket program was
    served ``warm`` (pre-compiled ahead of the dispatch), compiled
    ``cold`` (first dispatch carried the compile), ``reject``-ed (a
    persisted artifact could not be trusted — epoch drift, registry
    drift, I/O failure — and fell cold) or ``quarantine``-d (a torn
    or corrupt manifest record moved aside). The 'knob' is the
    cumulative decision count — the record exists so ``explain``
    reconstructs every warm claim and every degradation from the
    journal alone."""
    if inp.get("decision") not in ("warm", "cold", "reject",
                                  "quarantine"):
        return None
    return int(before) + 1


def _rule_warm_gc(before, inp):
    """Narrate an applied warm-cache retention GC: ``n`` files
    pruned (least-recently-hit first) under the configured size/age
    bounds. The 'knob' is the cumulative pruned count."""
    n = int(inp.get("n", 0))
    if n <= 0:
        return None
    return int(before) + n


#: rule name -> pure derivation. `replay` and the live controller
#: share these by construction — one source of truth.
RULES = {
    "quantum.shorten": _rule_quantum_shorten,
    "quantum.lengthen": _rule_quantum_lengthen,
    "quantum.learn": _rule_quantum_learn,
    "quantum.warm_start": _rule_quantum_warm_start,
    "checkpoint.retune": _rule_ckpt_retune,
    "audit.tighten": _rule_audit_tighten,
    "audit.relax": _rule_audit_relax,
    "capacity.learn": _rule_capacity_learn,
    "capacity.seed": _rule_capacity_seed,
    "capacity.probe": _rule_capacity_probe,
    "shed.cooldown": _rule_shed_cooldown,
    "retry.budget": _rule_retry_budget,
    "fleet.reclaim": _rule_fleet_reclaim,
    "intake.backpressure": _rule_intake_gate,
    "intake.shed": _rule_intake_shed,
    "intake.quarantine": _rule_intake_quarantine,
    "warmstart.cache": _rule_warm_cache,
    "warmstart.gc": _rule_warm_gc,
}

#: the "expected effect" text journaled with each rule's decisions
EXPECTED = {
    "quantum.shorten": ("shorter quanta bound preemption/rollback "
                        "loss and tighten the poll cadence"),
    "quantum.lengthen": ("longer quanta amortize per-dispatch "
                         "overhead across more steps"),
    "quantum.learn": ("remember the converged quantum so the next "
                      "run starts there instead of re-converging"),
    "quantum.warm_start": ("start at the quantum a prior run "
                           "converged to (journal-driven cross-run "
                           "warm start)"),
    "checkpoint.retune": ("save cost x trip rate optimum (Young): "
                          "minimize save overhead + expected replay"),
    "audit.tighten": ("audit a warm-suspect fleet more often so a "
                      "defective lane convicts sooner"),
    "audit.relax": ("a clean streak earns the baseline audit cost "
                    "back"),
    "capacity.learn": ("remember the bucket capacity that survived "
                       "the OOM/shed so future runs start there"),
    "capacity.seed": ("start at the capacity that survived the "
                      "recorded OOM/shed history instead of "
                      "rediscovering it by halving"),
    "capacity.probe": ("a clean run earns the seeded key headroom "
                       "back toward the configured default — the "
                       "learned floor decays instead of ratcheting"),
    "shed.cooldown": ("damp shed churn: back-to-back shed rebuilds "
                      "cost a compile each and re-poison the fresh "
                      "EWMA; a calm fleet earns responsiveness back"),
    "retry.budget": ("fail deterministic blow-ups faster, grant "
                     "recovering jobs headroom for the next "
                     "transient upset"),
    "fleet.reclaim": ("a dead rank's jobs were reclaimed by lease "
                      "expiry and re-admitted from their checkpoint "
                      "stems on this rank"),
    "intake.backpressure": ("hysteresis gate on spool admission: "
                            "arrivals outrunning drain (or an aged "
                            "queue) pause new admissions until the "
                            "stream calms — the spool is the durable "
                            "buffer, queue age stays bounded"),
    "intake.shed": ("graceful shed under saturation: the backlog "
                    "implied an unbounded queue age, so the newest "
                    "records of the most-backlogged tenant moved "
                    "aside (journaled, re-submittable) instead of "
                    "aging forever behind a closed gate"),
    "intake.quarantine": ("poison-job quarantine: a record that "
                          "cannot admit (K retries exhausted or a "
                          "permanent spec fault) moved to "
                          "spool/quarantine/ with a structured "
                          "reason so the stream keeps draining "
                          "behind it"),
    "warmstart.cache": ("persistent compile cache decision: warm "
                        "serves skip the compile storm, cold/reject/"
                        "quarantine degradations never trust a "
                        "drifted or damaged artifact — no wrong "
                        "program, no silent warm claim"),
    "warmstart.gc": ("size/age-bounded cache retention: prune "
                     "least-recently-hit entries so the cache dir "
                     "stays bounded without touching keys being "
                     "pre-warmed"),
}


def key_id(bucket_key) -> str:
    """A short stable id for a fleet bucket key (callable kernels are
    normalized to their qualname so the id survives process
    restarts — the journal is cross-run memory)."""
    def norm(x):
        if isinstance(x, tuple):
            return tuple(norm(e) for e in x)
        if callable(x):
            return getattr(x, "__qualname__", repr(x))
        return x
    return hashlib.sha1(repr(norm(bucket_key)).encode()).hexdigest()[:12]


# ---------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------

class Autopilot:
    """The deterministic self-tuning controller (see module
    docstring). One instance per :class:`~dccrg_tpu.scheduler
    .FleetScheduler`; the scheduler calls :meth:`tick` at every tick
    boundary, :meth:`seed_capacity` when creating a bucket and
    :meth:`record_oom` / :meth:`record_shed` after shrink rebuilds.

    ``clock`` is injectable (the pinned tests drive a fake clock);
    everything else the controller consumes comes from the telemetry
    registry and the scheduler's own counters, and every value a
    decision depended on is recorded IN the decision.

    ``quantum``/``audit_every`` declare the BASELINES the hard
    envelopes and the audit relax target derive from — pass the
    scheduler's configured values (the ``DCCRG_AUTOPILOT`` env path
    does). The scheduler's LIVE knob values stay the source of
    truth: each tick adopts them and only a journaled rule firing
    ever writes them back."""

    def __init__(self, *, quantum=8, audit_every=0,
                 clock=time.monotonic, decision_file=None,
                 status_file=None, ring=None, ckpt_bounds=(1, 256),
                 trip_warm=0.02, trip_cool=0.005, slack_factor=8.0,
                 shorten_patience=1, lengthen_patience=4,
                 relax_after=8, adjust_every=4, status_every=1,
                 load_history=True):
        self.clock = clock
        self.quantum = max(1, int(quantum))
        self.quantum0 = self.quantum
        self.audit_every = max(0, int(audit_every))
        self.audit0 = self.audit_every
        #: the hard envelopes no decision may leave (the property
        #: test's oracle; each rule also receives its lo/hi INSIDE
        #: the recorded inputs so replay is self-contained)
        self.bounds = {
            "quantum": (1, max(8 * self.quantum0, self.quantum0)),
            "checkpoint_every": (max(1, int(ckpt_bounds[0])),
                                 max(1, int(ckpt_bounds[1]))),
            "audit_every": (0, max(16, self.audit0)),
            "shed_cooldown": (1, 64),
            "max_retries": (1, 8),
        }
        self.trip_warm = float(trip_warm)
        self.trip_cool = float(trip_cool)
        self.slack_factor = float(slack_factor)
        self.shorten_patience = max(1, int(shorten_patience))
        self.lengthen_patience = max(1, int(lengthen_patience))
        self.relax_after = max(1, int(relax_after))
        self.adjust_every = max(1, int(adjust_every))
        self.status_every = max(1, int(status_every))
        self._decision_file = (decision_file_default()
                               if decision_file is None
                               else str(decision_file))
        self._status_file = (status_file_default() if status_file is None
                             else str(status_file))
        self.decisions = collections.deque(
            maxlen=decision_ring_default() if ring is None
            else max(16, int(ring)))
        self.seq = 0
        self._tick = 0
        # learned safe bucket capacities: key_id -> slots. NOT a
        # permanent ratchet: end_of_run() probes seeded keys that
        # survived a clean run back up toward the default
        self.capacity: dict = {}
        self._seeded: set = set()   # keys the learned floor bound
        self._shrunk: set = set()   # keys that OOMed/shed this run
        self._default_seen: dict = {}  # key_id -> configured default
        # windowed observation state feeding the rules. The registry
        # is process-global: baseline the counters/histograms we
        # difference at CONSTRUCTION time, so a controller attached
        # to a fresh scheduler never inherits an earlier run's trips
        # or save costs as a phantom first-tick observation.
        self._last_steps = 0  # sched.steps_total is per-scheduler
        self._last_trips = float(telemetry.registry().counter_total(
            "dccrg_fleet_trips_total"))
        self._save_cost_base = self._save_cost_totals()
        self._rollback_base = self._rollback_totals()
        self._last_suspects = 0
        # shed-churn observation state (the shed.cooldown rule) — the
        # counter is process-global, so baseline at construction like
        # the trip/save-cost series
        self._last_sheds = float(telemetry.registry().counter_total(
            "dccrg_fleet_slo_sheds_total"))
        self._shed_clean = 0
        self._shed0 = None  # the configured cooldown, from first sight
        # per-job trip-history watermarks (the retry.budget rule
        # re-evaluates a job only when its trip count moved)
        self._retry_seen: dict = {}
        #: cumulative elastic-fleet reclaims narrated in the journal
        self.reclaims = 0
        #: streaming-intake control state narrated in the journal:
        #: the backpressure gate (0 = open, 1 = closed) plus the
        #: cumulative shed / quarantine counts
        self.intake_gate = 0
        self.intake_sheds = 0
        self.intake_quarantines = 0
        #: warm-start narration state: cumulative cache decisions
        #: (warm/cold/reject/quarantine) and cumulative GC prunes
        self.warm_events = 0
        self.warm_gcs = 0
        # journal-driven cross-run warm start of the QUANTUM knob
        # (the capacity.learn/probe discipline): load_history recovers
        # the last run's journaled quantum.learn, the first tick
        # applies it through the quantum.warm_start rule
        self.learned_quantum = None
        self._warmed = False
        self._trip_rate = 0.0
        self._clean = 0
        self._q_short = 0
        self._q_long = 0
        if load_history and self._decision_file is not None:
            self.load_history(self._resolved(self._decision_file))

    # -- journal ------------------------------------------------------

    @staticmethod
    def _resolved(path: str) -> str:
        return path.replace("{rank}", str(telemetry._rank()))

    def load_history(self, path: str) -> int:
        """Recover the persistent half of the controller state — the
        per-bucket-key learned capacities and the learned QUANTUM —
        from a prior run's journal, replaying the
        ``capacity.learn``/``capacity.probe``/``quantum.learn``
        records in order (shrinks AND clean-run recoveries both
        apply — the history is not a one-way ratchet). Returns how
        many records informed it. Missing/unreadable files are
        simply no history."""
        n = 0
        for rec in read_journal(path):
            after = rec.get("after")
            if rec.get("rule") == "quantum.learn":
                if isinstance(after, int) and after >= 1:
                    self.learned_quantum = after
                    n += 1
                continue
            if rec.get("rule") not in ("capacity.learn",
                                       "capacity.probe"):
                continue
            knob = rec.get("knob", "")
            if not (knob.startswith("capacity[") and knob.endswith("]")):
                continue
            kid = knob[len("capacity["):-1]
            if not isinstance(after, int) or after < 1:
                continue
            self.capacity[kid] = after
            n += 1
        if n:
            logger.info(
                "autopilot recovered %d capacity record(s) from %s",
                n, path)
        return n

    def _apply(self, rule: str, knob: str, before, inputs: dict):
        """Run ``rule`` on ``(before, inputs)``; when it fires, record
        the decision (ring + journal + metrics) and return the new
        value, else return ``before`` unchanged."""
        after = RULES[rule](before, inputs)
        if after is None:
            return before
        rec = {
            "seq": self.seq,
            "tick": self._tick,
            "ts": time.time(),
            "t": round(float(self.clock()), 6),
            "rank": telemetry._rank(),
            "rule": rule,
            "knob": knob,
            "before": before,
            "after": after,
            "inputs": inputs,
            "expected": EXPECTED.get(rule, ""),
        }
        self.seq += 1
        self.decisions.append(rec)
        telemetry.inc("dccrg_autopilot_decisions_total", rule=rule)
        path = self._decision_file
        if path is not None:
            telemetry._best_effort_write(
                self._resolved(path),
                json.dumps(rec, sort_keys=True) + "\n", append=True)
        logger.info("autopilot %s: %s %s -> %s (%s)", rule, knob,
                    before, after, rec["expected"])
        return after

    # -- observation gathering ----------------------------------------

    @staticmethod
    def _save_cost_totals():
        """``(sum_seconds, count)`` over the periodic save-cost
        histogram series (``dccrg_ckpt_save_seconds`` kinds keyframe/
        delta; the ``emergency`` kind is a deadline-bounded preempt
        save and must not price the periodic cadence)."""
        tot, n = 0.0, 0
        for (nm, lab), h in telemetry.registry().histograms.items():
            if nm != "dccrg_ckpt_save_seconds" \
                    or ("kind", "emergency") in lab:
                continue
            tot += h.sum_seconds
            n += h.total
        return tot, n

    def _save_cost_mean(self):
        """Mean periodic save cost observed SINCE this controller was
        constructed (the registry outlives schedulers), or None when
        nothing was recorded yet."""
        tot, n = self._save_cost_totals()
        tot -= self._save_cost_base[0]
        n -= self._save_cost_base[1]
        return (tot / n) if n > 0 else None

    @staticmethod
    def _rollback_totals():
        """``(sum_seconds, count)`` over every ``dccrg_rollback_
        seconds`` series (the runner's chain-aware checkpoint load and
        the fleet's per-slot restore both observe it)."""
        tot, n = 0.0, 0
        for (nm, _lab), h in telemetry.registry().histograms.items():
            if nm != "dccrg_rollback_seconds":
                continue
            tot += h.sum_seconds
            n += h.total
        return tot, n

    def _rollback_cost_mean(self):
        """Mean measured per-trip recovery cost since construction,
        or None before the first observed rollback — the
        ``checkpoint.retune`` rule's Daly ``R`` term (replay was
        previously priced via save cost only)."""
        tot, n = self._rollback_totals()
        tot -= self._rollback_base[0]
        n -= self._rollback_base[1]
        return (tot / n) if n > 0 else None

    def gather(self, sched) -> dict:
        """One tick's controller inputs, computed from the scheduler's
        state and the telemetry registry. Every value is a JSON
        primitive — the decision journal must round-trip them
        exactly."""
        active = sched.active_jobs()
        slacks = [s for s in (sched.slo.slack_s(j)
                              for _b, _s, j in active) if s is not None]
        slack_min = min(slacks) if slacks else None
        lats = list(sched.slo._ewma.values())
        lat = max(lats) if lats else None
        trips = float(telemetry.registry().counter_total(
            "dccrg_fleet_trips_total"))
        steps = int(getattr(sched, "steps_total", 0))
        d_steps = steps - self._last_steps
        d_trips = trips - self._last_trips
        if d_steps > 0:
            # EWMA of the per-step trip rate over the tick window
            self._trip_rate = (0.7 * self._trip_rate
                               + 0.3 * (d_trips / d_steps))
        self._last_steps, self._last_trips = steps, trips
        suspects = int(sum(sched.suspects))
        new_susp = suspects - self._last_suspects
        self._last_suspects = suspects
        if new_susp > 0:
            self._clean = 0
        else:
            self._clean += 1
        sheds = float(telemetry.registry().counter_total(
            "dccrg_fleet_slo_sheds_total"))
        new_sheds = int(sheds - self._last_sheds)
        self._last_sheds = sheds
        if new_sheds > 0:
            self._shed_clean = 0
        else:
            self._shed_clean += 1
        return {
            "new_sheds": new_sheds,
            "shed_clean_streak": self._shed_clean,
            "slo_slack_min_s": (None if slack_min is None
                                else round(float(slack_min), 9)),
            "quantum_latency_s": (None if lat is None
                                  else round(float(lat), 9)),
            "trip_rate": round(float(self._trip_rate), 9),
            "save_cost_s": self._save_cost_mean(),
            "rollback_s": self._rollback_cost_mean(),
            "new_suspects": new_susp,
            "suspects_total": suspects,
            "clean_streak": self._clean,
            "active_jobs": len(active),
        }

    # -- the per-tick control pass ------------------------------------

    def tick(self, sched) -> dict:
        """One control pass at a scheduler tick boundary: gather
        inputs, run every tuning rule, apply the surviving knob
        values back onto the scheduler, export the live-knob gauges
        and (periodically) the status snapshot. Pure host-side
        arithmetic — no device work. Returns the gathered inputs
        (the tests' window into the observation path)."""
        self._tick = int(sched.ticks)
        inp = self.gather(sched)
        if not self._warmed:
            # journal-driven cross-run warm start: applied once, at
            # the first control pass, through a journaled rule like
            # every other knob move (no-op without recovered history)
            self._warmed = True
            self._warm_start_quantum(sched)
        self._tune_quantum(sched, inp)
        self._tune_audit(sched, inp)
        self._tune_shed(sched, inp)
        self._tune_retries(sched, inp)
        if self._tick % self.adjust_every == 0:
            self._tune_checkpoints(sched, inp)
        telemetry.set_gauge("dccrg_autopilot_quantum", self.quantum)
        telemetry.set_gauge("dccrg_autopilot_audit_every",
                            self.audit_every)
        if self._tick % self.status_every == 0:
            self.write_status(sched, inp)
        return inp

    def _warm_start_quantum(self, sched) -> None:
        before = max(1, int(sched.quantum))
        lo, hi = self.bounds["quantum"]
        q = self._apply(
            "quantum.warm_start", "quantum", before,
            {"learned_quantum": self.learned_quantum, "lo": lo,
             "hi": hi, "configured": self.quantum0})
        if q != before:
            self.quantum = q
            sched.quantum = q
            sched.slo.quantum = q

    def _tune_quantum(self, sched, inp) -> None:
        # the scheduler's live value is the source of truth: the
        # controller only ever moves it through a journaled rule —
        # an injected controller whose constructor defaults differ
        # from the configured knob must not silently stomp it
        self.quantum = max(1, int(sched.quantum))
        lo, hi = self.bounds["quantum"]
        slack = inp["slo_slack_min_s"]
        rate = inp["trip_rate"]
        short_evi = ((slack is not None and slack < 0.0)
                     or rate > self.trip_warm)
        self._q_short = self._q_short + 1 if short_evi else 0
        lat = inp["quantum_latency_s"]
        long_evi = (lat is not None and rate <= self.trip_cool
                    and (slack is None
                         or slack >= self.slack_factor * lat))
        self._q_long = self._q_long + 1 if long_evi else 0
        base = dict(inp, lo=lo, hi=hi, trip_warm=self.trip_warm,
                    trip_cool=self.trip_cool,
                    slack_factor=self.slack_factor)
        q = self._apply(
            "quantum.shorten", "quantum", self.quantum,
            dict(base, streak=self._q_short,
                 patience=self.shorten_patience))
        if q == self.quantum:
            q = self._apply(
                "quantum.lengthen", "quantum", self.quantum,
                dict(base, streak=self._q_long,
                     patience=self.lengthen_patience))
        if q != self.quantum:
            self._q_short = self._q_long = 0
            self.quantum = q
            # the scheduler budgets and the SLO projections both
            # follow the tuned quantum (written back ONLY on a
            # journaled decision)
            sched.quantum = self.quantum
            sched.slo.quantum = self.quantum

    def _tune_audit(self, sched, inp) -> None:
        self.audit_every = max(0, int(sched.audit_every))  # live truth
        lo, hi = self.bounds["audit_every"]
        base = dict(inp, lo=lo, hi=hi, baseline=self.audit0,
                    warm_start=8, relax_after=self.relax_after)
        a = self._apply("audit.tighten", "audit_every",
                        self.audit_every, base)
        if a == self.audit_every:
            a = self._apply("audit.relax", "audit_every",
                            self.audit_every, base)
        if a != self.audit_every:
            self.audit_every = a
            sched.audit_every = a

    def _tune_shed(self, sched, inp) -> None:
        # the PR-12 carried item: the shed cooldown rides the same
        # pure-rule machinery as every other knob — live value is the
        # truth, only a journaled firing writes back
        before = max(1, int(sched.slo.shed_cooldown))
        if self._shed0 is None:
            self._shed0 = before  # the configured baseline
        lo, hi = self.bounds["shed_cooldown"]
        new = self._apply(
            "shed.cooldown", "shed_cooldown", before,
            dict(inp, lo=lo, hi=hi, baseline=self._shed0,
                 relax_after=self.relax_after))
        if new != before:
            sched.slo.shed_cooldown = new

    def _tune_retries(self, sched, inp) -> None:
        # the PR-12 carried item: per-job retry budgets from each
        # job's OWN trip history, re-evaluated only when that history
        # moved (event-driven — no per-tick churn toward a bound)
        lo, hi = self.bounds["max_retries"]
        for _b, _s, job in sched.active_jobs():
            trips = len(job.trips)
            if self._retry_seen.get(job.name) == trips or trips == 0:
                continue
            self._retry_seen[job.name] = trips
            before = max(1, int(job.max_retries))
            # job.retries is the scheduler's consecutive same-step
            # streak (reset on progress); recovered = trips the job
            # progressed past
            new = self._apply(
                "retry.budget", f"max_retries[{job.name}]", before,
                {"repeat_trips": int(job.retries),
                 "recovered": max(0, trips - int(job.retries)),
                 "trips_total": trips, "lo": lo, "hi": hi})
            if new != before:
                job.max_retries = new

    def record_reclaim(self, dead_rank, jobs, lease_s) -> None:
        """An elastic-fleet reclaim happened on this rank: journal it
        through the ``fleet.reclaim`` rule so ``explain`` narrates who
        died and what was taken over, and ``replay`` re-derives the
        cumulative count."""
        jobs = sorted(str(j) for j in jobs)
        after = self._apply(
            "fleet.reclaim", "reclaims", int(self.reclaims),
            {"n": len(jobs), "jobs": jobs, "dead_rank": int(dead_rank),
             "lease_s": float(lease_s)})
        self.reclaims = int(after)

    # -- streaming-intake decisions (dccrg_tpu/intake.py) -------------

    def record_intake_gate(self, inputs: dict) -> int:
        """Evaluate the intake backpressure gate through the
        ``intake.backpressure`` rule (journaled on every flip) and
        return the new gate state (0 = open, 1 = closed). ``inputs``
        must already be JSON-faithful (rounded floats) — they are
        recorded verbatim and replay re-derives the flip from them
        alone."""
        after = self._apply("intake.backpressure", "intake_gate",
                            int(self.intake_gate), dict(inputs))
        self.intake_gate = int(after)
        return self.intake_gate

    def record_intake_shed(self, names, tenant, inputs: dict) -> None:
        """A graceful intake shed happened: journal it through the
        ``intake.shed`` rule so ``explain`` narrates what was shed
        and under which saturation numbers."""
        names = sorted(str(n) for n in names)
        after = self._apply(
            "intake.shed", "intake_sheds", int(self.intake_sheds),
            dict(inputs, n=len(names), names=names,
                 tenant=str(tenant)))
        self.intake_sheds = int(after)

    def record_intake_quarantine(self, name, reason: dict) -> None:
        """A poison job moved to quarantine: journal it through the
        ``intake.quarantine`` rule with the structured reason record
        (error type, attempts, tenant)."""
        after = self._apply(
            "intake.quarantine", "intake_quarantines",
            int(self.intake_quarantines),
            dict(reason, name=str(name)))
        self.intake_quarantines = int(after)

    # -- warm-start decisions (dccrg_tpu/warmstart.py) -----------------

    def record_warm(self, decision, kid, inputs: dict) -> None:
        """A warm-start cache decision happened (``warm``/``cold``/
        ``reject``/``quarantine``): journal it through the
        ``warmstart.cache`` rule so ``explain`` narrates every warm
        claim and every degradation-to-cold with its inputs."""
        after = self._apply(
            "warmstart.cache", "warm_events", int(self.warm_events),
            dict(inputs, decision=str(decision), key=str(kid)))
        self.warm_events = int(after)

    def record_warm_gc(self, pruned, inputs: dict) -> None:
        """An applied warm-cache retention GC pruned ``pruned``
        files: journal it through the ``warmstart.gc`` rule."""
        pruned = sorted(str(p) for p in pruned)
        after = self._apply(
            "warmstart.gc", "warm_gcs", int(self.warm_gcs),
            dict(inputs, n=len(pruned), pruned=pruned))
        self.warm_gcs = int(after)

    def _tune_checkpoints(self, sched, inp) -> None:
        lo, hi = self.bounds["checkpoint_every"]
        for b, _s, job in sched.active_jobs():
            before = int(job.checkpoint_every)
            if before <= 0 or job.steps_done < before:
                continue  # cadence disabled / not one period of data
            # step time from the job's OWN bucket latency (a
            # heterogeneous fleet's fast buckets must not be priced
            # by the slowest bucket's EWMA)
            lat = sched.slo.quantum_latency(b.key)
            step_s = (None if lat is None
                      else round(lat / max(1, self.quantum), 9))
            rate = round(len(job.trips) / max(1, job.steps_done), 9)
            new = self._apply(
                "checkpoint.retune", f"checkpoint_every[{job.name}]",
                before, dict(inp, lo=lo, hi=hi, step_seconds=step_s,
                             trip_rate=rate, deadband=0.25))
            if new != before:
                job.checkpoint_every = new

    # -- capacity history ---------------------------------------------

    def seed_capacity(self, bucket_key, default_cap: int,
                      min_capacity: int = 1) -> int:
        """The initial capacity for a NEW bucket of ``bucket_key``:
        the learned surviving capacity when the recorded OOM/shed
        history knows one smaller than ``default_cap``, else the
        default. ``min_capacity`` floors the seed (the scheduler
        passes the largest single job's slot demand, so a DMR job's
        shadow slot survives history learned from plain jobs)."""
        kid = key_id(bucket_key)
        self._default_seen[kid] = int(default_cap)
        if self.capacity.get(kid) is not None:
            self._seeded.add(kid)
        return self._apply(
            "capacity.seed", f"capacity[{kid}]", int(default_cap),
            {"learned_capacity": self.capacity.get(kid),
             "default_capacity": int(default_cap),
             "lo": max(1, int(min_capacity))})

    def _learn_capacity(self, bucket_key, surviving: int,
                        event: str) -> None:
        kid = key_id(bucket_key)
        self._shrunk.add(kid)
        before = self.capacity.get(kid)
        after = self._apply(
            "capacity.learn", f"capacity[{kid}]", before,
            {"observed_capacity": int(surviving), "event": event})
        if after is not None:
            self.capacity[kid] = int(after)

    def record_oom(self, bucket_key, surviving_capacity: int) -> None:
        """A real batch OOM forced a half-capacity rebuild that
        survived at ``surviving_capacity`` slots."""
        self._learn_capacity(bucket_key, surviving_capacity, "oom")

    def record_shed(self, bucket_key, surviving_capacity: int) -> None:
        """An SLO shed rebuilt the bucket at ``surviving_capacity``
        slots."""
        self._learn_capacity(bucket_key, surviving_capacity, "shed")

    def end_of_run(self) -> None:
        """The scheduler drained cleanly: every SEEDED bucket key
        that saw no OOM/shed this run earns a ``capacity.probe`` —
        the learned floor doubles back toward the configured default,
        so one transient spike never pins a key's capacity down
        across all future runs (the recovery is journaled and
        replayable like every other decision)."""
        for kid in sorted(self._seeded - self._shrunk):
            before = self.capacity.get(kid)
            if before is None:
                continue
            after = self._apply(
                "capacity.probe", f"capacity[{kid}]", int(before),
                {"clean_run": True,
                 "default_capacity": self._default_seen.get(kid)})
            if after != before:
                self.capacity[kid] = int(after)
        self._seeded.clear()
        self._shrunk.clear()
        # cross-run QUANTUM memory: journal the converged value when
        # it differs from what the next run would start at (the
        # previously learned value, else the configured default) —
        # a fresh controller sharing only the journal warm-starts
        # there (pinned by tests/test_autopilot.py)
        before_q = self.learned_quantum
        after_q = self._apply(
            "quantum.learn", "quantum.learned", before_q,
            {"final_quantum": int(self.quantum),
             "configured": self.quantum0})
        if after_q != before_q and after_q is not None:
            self.learned_quantum = int(after_q)

    # -- status snapshot ----------------------------------------------

    def status_text(self, sched, inp=None) -> str:
        """The human-readable operator snapshot: live knob values
        (with their hard bounds), per-bucket latency EWMAs and
        occupancy, per-lane suspect counters, per-job SLO slack and
        checkpoint cadence, and the tail of the decision ring."""
        lines = [
            f"dccrg autopilot status — tick {self._tick}, "
            f"{self.seq} decision(s)",
            f"knobs: quantum={self.quantum} "
            f"(bounds {self.bounds['quantum'][0]}.."
            f"{self.bounds['quantum'][1]}, configured {self.quantum0})"
            f" audit_every={self.audit_every} "
            f"(bounds {self.bounds['audit_every'][0]}.."
            f"{self.bounds['audit_every'][1]}, "
            f"configured {self.audit0})",
        ]
        if inp is not None:
            lines.append(
                "inputs: " + " ".join(
                    f"{k}={v}" for k, v in sorted(inp.items())))
        lines.append("buckets:")
        for key, insts in sched.buckets.items():
            kid = key_id(key)
            lat = sched.slo.quantum_latency(key)
            for b in insts:
                lines.append(
                    f"  {kid} cap={b.capacity} jobs={len(b.jobs)} "
                    f"ewma_s={'-' if lat is None else f'{lat:.6g}'}"
                    + (f" seeded<={self.capacity[kid]}"
                       if kid in self.capacity else ""))
        lines.append(
            "suspects: " + " ".join(
                f"lane{i}={n}" + ("(quarantined)"
                                  if i in sched.quarantined else "")
                for i, n in enumerate(sched.suspects)))
        lines.append("jobs:")
        for _b, _s, job in sched.active_jobs():
            slack = sched.slo.slack_s(job)
            lines.append(
                f"  {job.name} steps={job.steps_done}/{job.n_steps} "
                f"ckpt_every={job.checkpoint_every} "
                f"trips={len(job.trips)} slo_slack_s="
                + ("-" if slack is None else f"{slack:.6g}"))
        if self.decisions:
            lines.append("recent decisions:")
            for rec in list(self.decisions)[-5:]:
                lines.append("  " + explain_decision(rec))
        return "\n".join(lines) + "\n"

    def write_status(self, sched, inp=None) -> bool:
        """Best-effort (re)write of the status snapshot to
        ``DCCRG_STATUS_FILE``; no sink configured is a no-op."""
        path = self._status_file
        if path is None:
            return False
        return telemetry._best_effort_write(
            self._resolved(path), self.status_text(sched, inp),
            append=False)


# ---------------------------------------------------------------------
# journal reading, explain, replay (no controller needed)
# ---------------------------------------------------------------------

def read_journal(path: str) -> list:
    """Parse one JSONL decision journal — the trace-file reader with
    a dict filter (torn tail lines from a killed run are skipped)."""
    return [r for r in telemetry.read_trace(path)
            if isinstance(r, dict)]


def merge_journals(paths) -> list:
    """Merge per-rank journals into one ``(ts, rank, seq)``-ordered
    list — records already carry their rank tag, like trace
    events."""
    recs = []
    for p in paths:
        recs.extend(read_journal(p))
    recs.sort(key=lambda r: (r.get("ts", 0.0), r.get("rank", 0),
                             r.get("seq", 0)))
    return recs


def explain_decision(rec: dict) -> str:
    """One decision record as a human-readable line: when, which rule,
    what moved, every observed input it depended on, and the expected
    effect."""
    inputs = rec.get("inputs", {})
    shown = ", ".join(f"{k}={inputs[k]}" for k in sorted(inputs))
    return (f"[tick {rec.get('tick', '?')} seq {rec.get('seq', '?')} "
            f"rank {rec.get('rank', 0)}] {rec.get('rule', '?')}: "
            f"{rec.get('knob', '?')} {rec.get('before')} -> "
            f"{rec.get('after')} | observed: {shown} | expected: "
            f"{rec.get('expected', '')}")


def replay(records) -> list:
    """Re-derive every journaled action by feeding the RECORDED inputs
    back through the same pure rules the live controller used.
    Returns ``[(record, why)]`` divergences — an empty list means the
    journal fully explains the run; anything else is a bug (journal
    corruption, a nondeterministic input leak, or a rule edit that
    silently changed behavior)."""
    divergences = []
    for rec in records:
        rule = RULES.get(rec.get("rule"))
        if rule is None:
            divergences.append((rec, f"unknown rule {rec.get('rule')!r}"))
            continue
        try:
            got = rule(rec.get("before"), rec.get("inputs", {}))
        except Exception as e:  # noqa: BLE001 - a divergence, not a crash
            divergences.append((rec, f"rule raised {e!r}"))
            continue
        if got is None:
            divergences.append(
                (rec, "rule does not fire on the recorded inputs"))
        elif got != rec.get("after"):
            divergences.append(
                (rec, f"re-derived {got!r} != recorded "
                      f"{rec.get('after')!r}"))
    return divergences


# ---------------------------------------------------------------------
# CLI: python -m dccrg_tpu.autopilot explain|replay <journal>...
# ---------------------------------------------------------------------

def _main(argv=None) -> int:
    """``python -m dccrg_tpu.autopilot explain <journal.jsonl>...``
    prints every decision human-readably (rule, knob move, observed
    inputs, expected effect) from the journal alone; ``replay``
    re-derives each action from the recorded inputs through the same
    rules the live controller used and exits 1 on any divergence
    (replay divergence = bug). Per-rank journals of one run merge
    like traces. Needs no jax."""
    import argparse

    ap = argparse.ArgumentParser(prog="python -m dccrg_tpu.autopilot",
                                 description=_main.__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    e = sub.add_parser("explain", help="reconstruct every decision "
                                       "human-readably")
    e.add_argument("files", nargs="+")
    r = sub.add_parser("replay", help="re-derive every action from "
                                      "the recorded inputs; exit 1 "
                                      "on divergence")
    r.add_argument("files", nargs="+")
    args = ap.parse_args(argv)
    recs = merge_journals(args.files)
    if args.cmd == "explain":
        for rec in recs:
            print(explain_decision(rec))
        print(f"# {len(recs)} decision(s)")
        return 0
    div = replay(recs)
    for rec, why in div:
        print(f"DIVERGED seq {rec.get('seq', '?')} "
              f"({rec.get('rule', '?')}): {why}")
    print(json.dumps({"decisions": len(recs),
                      "divergences": len(div)}))
    return 1 if div else 0


if __name__ == "__main__":  # pragma: no cover - exercised via CLI
    import sys

    sys.exit(_main())
