"""ctypes loader for the native (C++) host runtime.

Compiles ``dccrg_native.cpp`` with g++ on first import (cached by
source hash next to the source), then exposes typed wrappers. If the
toolchain is unavailable or ``DCCRG_TPU_NATIVE=0`` is set, ``lib`` is
None and callers fall back to the NumPy implementations — the tests
exercise both paths and assert identical results.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
from pathlib import Path

import numpy as np

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "dccrg_native.cpp"

lib = None


def _load():
    if os.environ.get("DCCRG_TPU_NATIVE", "1") == "0":
        return None
    # cache tag covers source AND the build environment: -march=native
    # code from one machine must not be reused on another (SIGILL)
    import platform

    flags = "-O3 -march=native -std=c++17 -ffp-contract=off"
    try:
        gxx = subprocess.run(["g++", "--version"], capture_output=True,
                             text=True).stdout.splitlines()[0]
    except OSError:
        return None
    fingerprint = _SRC.read_bytes() + f"|{platform.machine()}|{gxx}|{flags}".encode()
    tag = hashlib.sha256(fingerprint).hexdigest()[:16]
    so = _HERE / f"_dccrg_native_{tag}.so"
    if not so.exists():
        for stale in _HERE.glob("_dccrg_native_*.so"):
            try:
                stale.unlink()
            except OSError:
                pass
        # build to a temp path, publish with an atomic rename so an
        # interrupted compile can never leave a half-written cache
        tmp = _HERE / f".build_{os.getpid()}_{tag}.so"
        cmd = [
            "g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
            # no FMA contraction: the geometry kernels promise
            # bit-identical results vs the NumPy fallbacks
            "-ffp-contract=off",
            "-fopenmp", "-o", str(tmp), str(_SRC),
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True)
        except (OSError, subprocess.CalledProcessError):
            # retry without OpenMP (serial build still beats NumPy)
            cmd.remove("-fopenmp")
            try:
                subprocess.run(cmd, check=True, capture_output=True)
            except (OSError, subprocess.CalledProcessError) as exc:
                print(f"dccrg_tpu: native build failed, using NumPy fallback: {exc}",
                      file=sys.stderr)
                tmp.unlink(missing_ok=True)
                return None
        os.replace(tmp, so)
    try:
        dll = ctypes.CDLL(str(so))
    except OSError:
        return None
    if dll.dn_abi_version() != 2:
        return None

    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    dll.dn_find_neighbors_of.restype = ctypes.c_int64
    dll.dn_find_neighbors_of.argtypes = [
        u64p, ctypes.c_int32, u8p,          # grid_length, max_lvl, periodic
        u64p, ctypes.c_int64,               # cells_sorted, n_cells
        u64p, ctypes.c_int64,               # query, n_query
        i64p, ctypes.c_int64,               # hood, n_hood
        i64p, u64p, i64p, i64p,             # out src/nbr/off/item
        ctypes.c_int64,                     # capacity
        u64p, i64p,                         # err_cell, err_item
    ]
    dll.dn_find_neighbors_to_subset.restype = ctypes.c_int64
    dll.dn_find_neighbors_to_subset.argtypes = [
        u64p, ctypes.c_int32, u8p,          # grid_length, max_lvl, periodic
        u64p, ctypes.c_int64,               # cells_sorted, n_cells
        u64p, ctypes.c_int64,               # query, n_query
        i64p, ctypes.c_int64,               # hood, n_hood
        i64p, u64p, i64p, i64p,             # out q/src/off/item
        ctypes.c_int64,                     # capacity
    ]
    dll.dn_morton_keys.restype = None
    dll.dn_morton_keys.argtypes = [u64p, ctypes.c_int64, ctypes.c_int32, u64p]
    dll.dn_hilbert_keys.restype = None
    dll.dn_hilbert_keys.argtypes = [u64p, ctypes.c_int64, ctypes.c_int32, u64p]
    dll.dn_refinement_levels.restype = None
    dll.dn_refinement_levels.argtypes = [u64p, ctypes.c_int32, u64p,
                                         ctypes.c_int64, i32p]
    dll.dn_cell_indices.restype = None
    dll.dn_cell_indices.argtypes = [u64p, ctypes.c_int32, u64p,
                                    ctypes.c_int64, u64p]
    f64p = ctypes.POINTER(ctypes.c_double)
    dll.dn_geometry_min_len.restype = None
    dll.dn_geometry_min_len.argtypes = [u64p, ctypes.c_int32,
                                        f64p, f64p, f64p,
                                        u64p, ctypes.c_int64, f64p, f64p]
    dll.dn_cell_lengths.restype = None
    dll.dn_cell_lengths.argtypes = [u64p, ctypes.c_int32, f64p,
                                    u64p, ctypes.c_int64, f64p]
    dll.dn_geometry_centers.restype = None
    dll.dn_geometry_centers.argtypes = [u64p, ctypes.c_int32,
                                        f64p, f64p, f64p,
                                        u64p, ctypes.c_int64, f64p]
    dll.dn_table_counts.restype = ctypes.c_int64
    dll.dn_table_counts.argtypes = [i32p, i32p, ctypes.c_int64,
                                    ctypes.c_int64, ctypes.c_int64, i64p]
    dll.dn_table_fill.restype = None
    dll.dn_table_fill.argtypes = [i32p, i32p, i32p, i64p, ctypes.c_int64,
                                  ctypes.c_int64, ctypes.c_int64,
                                  ctypes.c_int64, i64p, i32p, i32p, u8p]
    dll.dn_uniform_tables.restype = None
    dll.dn_uniform_tables.argtypes = [
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,   # nx, ny, nz
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,   # periodic
        i64p, ctypes.c_int64,                             # offs, k
        i32p, i32p,                                       # row_of_pos, owner
        ctypes.c_int32,                                   # pad_row
        i32p, u8p,                                        # rows_out, mask_out
    ]
    dll.dn_sorted_positions.restype = None
    dll.dn_sorted_positions.argtypes = [u64p, ctypes.c_int64,
                                        u64p, ctypes.c_int64, i64p]
    dll.dn_level_lookup.restype = None
    dll.dn_level_lookup.argtypes = [
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,   # nxl, nyl, nzl
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,   # periodic
        i64p, ctypes.c_int64, ctypes.c_int64,             # lin, m, a
        u64p, ctypes.c_int64, ctypes.c_uint64,            # cells, b, first
        i64p, ctypes.c_int64,                             # offs, kb
        i32p, ctypes.c_int64,                             # plat, n_lat
        i32p, u8p, u8p,                                   # pos, valid, exist
    ]
    dll.dn_far_tables.restype = ctypes.c_int64
    dll.dn_far_tables.argtypes = [
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,   # nx, ny, nz
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,   # periodic
        i64p, ctypes.c_int64,                             # offs, k
        i64p, ctypes.c_int64, i64p,                       # far_slots, nf, rowidx
        i32p, i32p,                                       # row_of_pos0, owner0
        ctypes.c_int32,                                   # pad_row
        i32p, u8p,                                        # rows_t, mask_t
        i64p, ctypes.c_int64,                             # fix_out, fix_cap
    ]
    dll.dn_easy_tables.restype = ctypes.c_int64
    dll.dn_easy_tables.argtypes = [
        i64p, ctypes.c_int64, i64p,                       # ei, E, ridx
        i64p, ctypes.c_int64,                             # sel, k
        i32p, u8p, ctypes.c_int64,                        # pos_all, valid_all, m
        i32p, i32p, i32p,                                 # row_of_pos, owner, edev
        ctypes.c_int32,                                   # pad_row
        i32p, u8p,                                        # rows_t, mask_t
        i64p, ctypes.c_int64,                             # fix_out, fix_cap
    ]
    dll.dn_hard_counts.restype = None
    dll.dn_hard_counts.argtypes = [i64p, ctypes.c_int64, i32p,
                                   ctypes.c_int64, i64p]
    dll.dn_hard_fill.restype = ctypes.c_int64
    dll.dn_hard_fill.argtypes = [
        i64p, i64p, i64p, ctypes.c_int64,                 # s_p, s_n, s_off, nE
        i32p, i32p,                                       # owner, row_of_pos
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,   # n_dev, Hmax, S
        ctypes.c_int32, ctypes.c_int32,                   # row_pad, nbr_pad
        i32p, i32p, i32p, u8p,                            # rows/nbr/offs/mask
        i64p, ctypes.c_int64,                             # fix_out, fix_cap
    ]
    dll.dn_stream_remap_merge.restype = ctypes.c_int64
    dll.dn_stream_remap_merge.argtypes = [
        i64p, u8p,                                        # old2new, reus_old
        i64p, i64p, i64p, i64p, ctypes.c_int64,           # prev s/n/off/item
        i64p, i64p, i64p, i64p, ctypes.c_int64,           # fresh s/n/off/item
        i64p, i64p, i64p, i64p, ctypes.c_int64,           # merged + capacity
    ]
    return dll


def _ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def _i32_ptr_or_null(arr):
    """int32 pointer, or a typed NULL when ``arr`` is None (optional
    owner/lattice parameters of the recommit kernels)."""
    if arr is None:
        return ctypes.cast(None, ctypes.POINTER(ctypes.c_int32))
    return _ptr(arr, ctypes.c_int32)


def _with_fixups(call, cap):
    """Run a table-writer kernel that appends cross-device fixup
    records into a caller-allocated buffer: retry with a bigger buffer
    until the count fits (the table writes themselves are idempotent).
    ``call(fix, cap)`` returns the total fixup count."""
    while True:
        fix = np.empty(cap, dtype=np.int64)
        n_fix = call(fix, cap)
        if n_fix <= cap:
            return fix[:n_fix]
        cap = int(n_fix)


def find_neighbors_of(mapping, topology, all_cells_sorted, query_cells,
                      neighborhood):
    """Native find_neighbors_of; same contract as
    neighbors.find_neighbors_of. Raises neighbors.StructureError /
    ValueError with the same messages on invalid structure."""
    from ..neighbors import StructureError

    cells = np.ascontiguousarray(all_cells_sorted, dtype=np.uint64)
    query = np.ascontiguousarray(query_cells, dtype=np.uint64)
    hood = np.ascontiguousarray(neighborhood, dtype=np.int64).reshape(-1, 3)
    length = np.ascontiguousarray(mapping.length.get(), dtype=np.uint64)
    periodic = np.array([topology.is_periodic(d) for d in range(3)],
                        dtype=np.uint8)
    n, k = len(query), len(hood)

    # headroom over the uniform-grid exact size (n*k) so the common
    # lightly-refined case doesn't pay a count-only pass plus a retry
    capacity = max(n * k + (n * k) // 4 + 64, 1)
    err_cell = np.zeros(1, dtype=np.uint64)
    err_item = np.zeros(1, dtype=np.int64)
    while True:
        src = np.empty(capacity, dtype=np.int64)
        nbr = np.empty(capacity, dtype=np.uint64)
        off = np.empty((capacity, 3), dtype=np.int64)
        item = np.empty(capacity, dtype=np.int64)
        total = lib.dn_find_neighbors_of(
            _ptr(length, ctypes.c_uint64), mapping.max_refinement_level,
            _ptr(periodic, ctypes.c_uint8),
            _ptr(cells, ctypes.c_uint64), len(cells),
            _ptr(query, ctypes.c_uint64), n,
            _ptr(hood, ctypes.c_int64), k,
            _ptr(src, ctypes.c_int64), _ptr(nbr, ctypes.c_uint64),
            _ptr(off, ctypes.c_int64), _ptr(item, ctypes.c_int64),
            capacity,
            _ptr(err_cell, ctypes.c_uint64), _ptr(err_item, ctypes.c_int64),
        )
        if total == -3:
            raise ValueError("invalid cell id in query")
        if total == -1:
            raise StructureError(
                f"no neighbor found for cell {err_cell[0]} at offset "
                f"{hood[err_item[0]]}: grid does not tile the domain"
            )
        if total == -2:
            lvl = mapping.get_refinement_level(err_cell[0])
            raise StructureError(
                f"cell {err_cell[0]} offset {hood[err_item[0]]}: window "
                f"neither tiled by level {lvl + 1} cells nor coarser "
                f"(2:1 balance violated or grid has gaps)"
            )
        if total <= capacity:
            return src[:total], nbr[:total], off[:total], item[:total]
        capacity = int(total)


def find_neighbors_to_subset_raw(mapping, topology, all_cells_sorted,
                                 query_cells, neighborhood):
    """Native raw to-subset enumeration: the candidate entries of
    neighbors.find_neighbors_to_subset's hard path, duplicates
    included (the caller dedups/orders exactly as the NumPy path).
    Returns (q_idx, src_id, off, item)."""
    cells = np.ascontiguousarray(all_cells_sorted, dtype=np.uint64)
    query = np.ascontiguousarray(query_cells, dtype=np.uint64)
    hood = np.ascontiguousarray(neighborhood, dtype=np.int64).reshape(-1, 3)
    length = np.ascontiguousarray(mapping.length.get(), dtype=np.uint64)
    periodic = np.array([topology.is_periodic(d) for d in range(3)],
                        dtype=np.uint8)
    n, k = len(query), len(hood)
    capacity = max(2 * n * k + 64, 1)
    while True:
        q = np.empty(capacity, dtype=np.int64)
        srcs = np.empty(capacity, dtype=np.uint64)
        off = np.empty((capacity, 3), dtype=np.int64)
        item = np.empty(capacity, dtype=np.int64)
        total = lib.dn_find_neighbors_to_subset(
            _ptr(length, ctypes.c_uint64), mapping.max_refinement_level,
            _ptr(periodic, ctypes.c_uint8),
            _ptr(cells, ctypes.c_uint64), len(cells),
            _ptr(query, ctypes.c_uint64), n,
            _ptr(hood, ctypes.c_int64), k,
            _ptr(q, ctypes.c_int64), _ptr(srcs, ctypes.c_uint64),
            _ptr(off, ctypes.c_int64), _ptr(item, ctypes.c_int64),
            capacity,
        )
        if total == -3:
            raise ValueError("invalid cell id in query")
        if total <= capacity:
            return q[:total], srcs[:total], off[:total], item[:total]
        capacity = int(total)


def refinement_levels(mapping, cells) -> np.ndarray:
    """Native bulk refinement-level query (-1 for invalid ids)."""
    cells = np.ascontiguousarray(cells, dtype=np.uint64)
    length = np.ascontiguousarray(mapping.length.get(), dtype=np.uint64)
    out = np.empty(len(cells), dtype=np.int32)
    lib.dn_refinement_levels(
        _ptr(length, ctypes.c_uint64), mapping.max_refinement_level,
        _ptr(cells, ctypes.c_uint64), len(cells), _ptr(out, ctypes.c_int32),
    )
    return out.astype(np.int64)


def cell_indices(mapping, cells) -> np.ndarray:
    """Native bulk (n,3) min-corner indices (all-ones for invalid)."""
    cells = np.ascontiguousarray(cells, dtype=np.uint64)
    length = np.ascontiguousarray(mapping.length.get(), dtype=np.uint64)
    out = np.empty((len(cells), 3), dtype=np.uint64)
    lib.dn_cell_indices(
        _ptr(length, ctypes.c_uint64), mapping.max_refinement_level,
        _ptr(cells, ctypes.c_uint64), len(cells), _ptr(out, ctypes.c_uint64),
    )
    return out


def build_stencil_table(entry_dev, src_rows, nbr_rows, offs, n_dev, L, pad_row):
    """Pad the ragged per-cell neighbor entry stream into
    ([n_dev, L, S] rows, [n_dev, L, S, 3] offsets, [n_dev, L, S] mask)
    preserving per-cell entry order."""
    entry_dev = np.ascontiguousarray(entry_dev, dtype=np.int32)
    src_rows = np.ascontiguousarray(src_rows, dtype=np.int32)
    nbr_rows = np.ascontiguousarray(nbr_rows, dtype=np.int32)
    offs = np.ascontiguousarray(offs, dtype=np.int64).reshape(-1, 3)
    n = len(entry_dev)
    counts = np.zeros(n_dev * L, dtype=np.int64)
    S = int(lib.dn_table_counts(
        _ptr(entry_dev, ctypes.c_int32), _ptr(src_rows, ctypes.c_int32),
        n, n_dev, L, _ptr(counts, ctypes.c_int64),
    ))
    S = max(1, S)
    rows = np.full(n_dev * L * S, pad_row, dtype=np.int32)
    out_offs = np.zeros(n_dev * L * S * 3, dtype=np.int32)
    mask = np.zeros(n_dev * L * S, dtype=np.uint8)
    slots = np.zeros(n_dev * L, dtype=np.int64)
    lib.dn_table_fill(
        _ptr(entry_dev, ctypes.c_int32), _ptr(src_rows, ctypes.c_int32),
        _ptr(nbr_rows, ctypes.c_int32), _ptr(offs, ctypes.c_int64),
        n, n_dev, L, S,
        _ptr(slots, ctypes.c_int64), _ptr(rows, ctypes.c_int32),
        _ptr(out_offs, ctypes.c_int32), _ptr(mask, ctypes.c_uint8),
    )
    return (
        rows.reshape(n_dev, L, S),
        out_offs.reshape(n_dev, L, S, 3),
        mask.reshape(n_dev, L, S).astype(bool),
    )


def uniform_tables(dims, periodic, offs, row_of_pos, owner, pad_row):
    """One-pass uniform (level-0-only) gather tables: rows [n0, k] and
    mask [n0, k] in grid-index order. Cross-device entries carry the
    sentinel ``-2 - neighbor_gidx`` (caller fixes up ghost rows);
    ``owner=None`` skips cross detection. Returns None when the native
    lib is unavailable."""
    if lib is None:
        return None
    nx, ny, nz = (int(v) for v in dims)
    k = len(offs)
    offs = np.ascontiguousarray(offs, dtype=np.int64)
    row_of_pos = np.ascontiguousarray(row_of_pos, dtype=np.int32)
    n0 = nx * ny * nz
    rows = np.empty((n0, k), dtype=np.int32)
    mask = np.empty((n0, k), dtype=bool)
    own_arr = (np.ascontiguousarray(owner, dtype=np.int32)
               if owner is not None else None)
    own_ptr = _i32_ptr_or_null(own_arr)
    lib.dn_uniform_tables(
        nx, ny, nz,
        int(bool(periodic[0])), int(bool(periodic[1])), int(bool(periodic[2])),
        _ptr(offs, ctypes.c_int64), k,
        _ptr(row_of_pos, ctypes.c_int32), own_ptr,
        np.int32(pad_row),
        _ptr(rows, ctypes.c_int32), _ptr(mask, ctypes.c_uint8),
    )
    return rows, mask


def geometry_min_len(mapping, boundaries, cells):
    """Native (min corner, edge length) lookup: ``boundaries`` is the
    per-dimension level-0 boundary coordinate arrays."""
    cells = np.ascontiguousarray(cells, dtype=np.uint64)
    length = np.ascontiguousarray(mapping.length.get(), dtype=np.uint64)
    bd = [np.ascontiguousarray(b, dtype=np.float64) for b in boundaries]
    n = len(cells)
    out_min = np.empty((n, 3), dtype=np.float64)
    out_len = np.empty((n, 3), dtype=np.float64)
    lib.dn_geometry_min_len(
        _ptr(length, ctypes.c_uint64), mapping.max_refinement_level,
        _ptr(bd[0], ctypes.c_double), _ptr(bd[1], ctypes.c_double),
        _ptr(bd[2], ctypes.c_double),
        _ptr(cells, ctypes.c_uint64), n,
        _ptr(out_min, ctypes.c_double), _ptr(out_len, ctypes.c_double),
    )
    return out_min, out_len


def geometry_centers(mapping, boundaries, cells) -> np.ndarray:
    """Native (n,3) cell center coordinates."""
    cells = np.ascontiguousarray(cells, dtype=np.uint64)
    length = np.ascontiguousarray(mapping.length.get(), dtype=np.uint64)
    bd = [np.ascontiguousarray(b, dtype=np.float64) for b in boundaries]
    out = np.empty((len(cells), 3), dtype=np.float64)
    lib.dn_geometry_centers(
        _ptr(length, ctypes.c_uint64), mapping.max_refinement_level,
        _ptr(bd[0], ctypes.c_double), _ptr(bd[1], ctypes.c_double),
        _ptr(bd[2], ctypes.c_double),
        _ptr(cells, ctypes.c_uint64), len(cells), _ptr(out, ctypes.c_double),
    )
    return out


def cell_lengths(mapping, length_table, cells) -> np.ndarray:
    """Native (n,3) edge lengths from the per-level length table."""
    cells = np.ascontiguousarray(cells, dtype=np.uint64)
    length = np.ascontiguousarray(mapping.length.get(), dtype=np.uint64)
    tbl = np.ascontiguousarray(length_table, dtype=np.float64)
    out = np.empty((len(cells), 3), dtype=np.float64)
    lib.dn_cell_lengths(
        _ptr(length, ctypes.c_uint64), mapping.max_refinement_level,
        _ptr(tbl, ctypes.c_double),
        _ptr(cells, ctypes.c_uint64), len(cells), _ptr(out, ctypes.c_double),
    )
    return out


def sorted_positions(haystack, needles):
    """``np.searchsorted(haystack, needles)`` for SORTED needles as one
    linear native sweep. Returns None when the native lib is absent."""
    if lib is None:
        return None
    hay = np.ascontiguousarray(haystack, dtype=np.uint64)
    nee = np.ascontiguousarray(needles, dtype=np.uint64)
    out = np.empty(len(nee), dtype=np.int64)
    lib.dn_sorted_positions(
        _ptr(hay, ctypes.c_uint64), len(hay),
        _ptr(nee, ctypes.c_uint64), len(nee), _ptr(out, ctypes.c_int64),
    )
    return out


def level_lookup(dims_l, periodic, lin, a, cells, b, first, offs,
                 plat, pos_out, valid_out, exist_out):
    """Batched level-block lookup (hybrid._LevelBlock): fill the
    caller's [kb, m] pos/valid/exist arrays for every offset at once.
    ``plat`` is the arena-held position-lattice scratch (int32,
    ``n_lat``) or None for the binary-search strategy. Returns False
    when the native lib is absent (caller falls back to numpy)."""
    if lib is None:
        return False
    nxl, nyl, nzl = (int(v) for v in dims_l)
    lin = np.ascontiguousarray(lin, dtype=np.int64)
    offs = np.ascontiguousarray(offs, dtype=np.int64).reshape(-1, 3)
    lib.dn_level_lookup(
        nxl, nyl, nzl,
        int(bool(periodic[0])), int(bool(periodic[1])), int(bool(periodic[2])),
        _ptr(lin, ctypes.c_int64), len(lin), int(a),
        _ptr(cells, ctypes.c_uint64), int(b), ctypes.c_uint64(int(first)),
        _ptr(offs, ctypes.c_int64), len(offs),
        _i32_ptr_or_null(plat), 0 if plat is None else len(plat),
        _ptr(pos_out, ctypes.c_int32), _ptr(valid_out, ctypes.c_uint8),
        _ptr(exist_out, ctypes.c_uint8),
    )
    return True


def far_tables(dims, periodic, offs, far_slots, far_rowidx, row_of_pos0,
               owner0, pad_row, rows_t, mask_t):
    """Far-row gather tables written straight into the caller's
    [n_rows, k] tables at ``far_rowidx`` (no [n0, k] intermediate).
    Returns the packed ``i * k + j`` cross-device fixup indices, or
    None when the native lib is absent."""
    if lib is None:
        return None
    nx, ny, nz = (int(v) for v in dims)
    offs = np.ascontiguousarray(offs, dtype=np.int64).reshape(-1, 3)
    far_slots = np.ascontiguousarray(far_slots, dtype=np.int64)
    far_rowidx = np.ascontiguousarray(far_rowidx, dtype=np.int64)
    return _with_fixups(
        lambda fix, cap: lib.dn_far_tables(
            nx, ny, nz,
            int(bool(periodic[0])), int(bool(periodic[1])),
            int(bool(periodic[2])),
            _ptr(offs, ctypes.c_int64), len(offs),
            _ptr(far_slots, ctypes.c_int64), len(far_slots),
            _ptr(far_rowidx, ctypes.c_int64),
            _ptr(row_of_pos0, ctypes.c_int32), _i32_ptr_or_null(owner0),
            np.int32(pad_row),
            _ptr(rows_t, ctypes.c_int32), _ptr(mask_t, ctypes.c_uint8),
            _ptr(fix, ctypes.c_int64), cap,
        ),
        1024 if owner0 is None else max(1024, len(far_slots) // 8))


def easy_tables(ei, ridx, sel, pos_all, valid_all, m, row_of_pos, owner,
                edev, pad_row, rows_t, mask_t):
    """Easy-row gather tables written straight into the caller's
    [n_rows, k] tables from the batched level-block lookup results.
    Returns the packed ``e * k + j`` cross-device fixup indices, or
    None when the native lib is absent."""
    if lib is None:
        return None
    ei = np.ascontiguousarray(ei, dtype=np.int64)
    ridx = np.ascontiguousarray(ridx, dtype=np.int64)
    sel = np.ascontiguousarray(sel, dtype=np.int64)
    return _with_fixups(
        lambda fix, cap: lib.dn_easy_tables(
            _ptr(ei, ctypes.c_int64), len(ei), _ptr(ridx, ctypes.c_int64),
            _ptr(sel, ctypes.c_int64), len(sel),
            _ptr(pos_all, ctypes.c_int32), _ptr(valid_all, ctypes.c_uint8),
            int(m),
            _ptr(row_of_pos, ctypes.c_int32), _i32_ptr_or_null(owner),
            _i32_ptr_or_null(edev),
            np.int32(pad_row),
            _ptr(rows_t, ctypes.c_int32), _ptr(mask_t, ctypes.c_uint8),
            _ptr(fix, ctypes.c_int64), cap,
        ),
        1024 if owner is None else max(1024, len(ei) // 4))


def hard_counts(s_p, owner, n_dev):
    """(n_groups, widest_group, per-device group counts) of the
    source-sorted hard entry stream, or None without the native lib."""
    if lib is None:
        return None
    s_p = np.ascontiguousarray(s_p, dtype=np.int64)
    out = np.zeros(2 + n_dev, dtype=np.int64)
    lib.dn_hard_counts(_ptr(s_p, ctypes.c_int64), len(s_p),
                       _i32_ptr_or_null(owner), int(n_dev),
                       _ptr(out, ctypes.c_int64))
    return int(out[0]), int(out[1]), out[2:]


def hard_fill(s_p, s_n, s_off, owner, row_of_pos, n_dev, Hmax, S, row_pad,
              nbr_pad, rows_dev, nbr_dev, offs_dev, mask_dev):
    """Fused hard-table writer (grouping + scatter + pad in one pass).
    Returns the packed flat-nbr-table fixup indices, or None without
    the native lib."""
    if lib is None:
        return None
    s_p = np.ascontiguousarray(s_p, dtype=np.int64)
    s_n = np.ascontiguousarray(s_n, dtype=np.int64)
    s_off = np.ascontiguousarray(s_off, dtype=np.int64)
    return _with_fixups(
        lambda fix, cap: lib.dn_hard_fill(
            _ptr(s_p, ctypes.c_int64), _ptr(s_n, ctypes.c_int64),
            _ptr(s_off, ctypes.c_int64), len(s_p),
            _i32_ptr_or_null(owner), _ptr(row_of_pos, ctypes.c_int32),
            int(n_dev), int(Hmax), int(S),
            np.int32(row_pad), np.int32(nbr_pad),
            _ptr(rows_dev, ctypes.c_int32), _ptr(nbr_dev, ctypes.c_int32),
            _ptr(offs_dev, ctypes.c_int32), _ptr(mask_dev, ctypes.c_uint8),
            _ptr(fix, ctypes.c_int64), cap,
        ),
        1024 if owner is None else max(1024, len(s_p) // 8))


def stream_remap_merge(old2new, reus_old, prev_stream, fresh_stream):
    """Reuse-branch stream merge: remap the kept previous-epoch
    entries through ``old2new`` and merge with the fresh entries in
    one linear pass. Returns (spos, npos, off, item) or None when the
    native lib is absent."""
    if lib is None:
        return None
    ps, pn, po, pi = prev_stream
    fs, fn_, fo, fi = fresh_stream
    old2new = np.ascontiguousarray(old2new, dtype=np.int64)
    reus_old = np.ascontiguousarray(reus_old.view(np.uint8))
    ps = np.ascontiguousarray(ps, dtype=np.int64)
    pn = np.ascontiguousarray(pn, dtype=np.int64)
    po = np.ascontiguousarray(po, dtype=np.int64)
    pi = np.ascontiguousarray(pi, dtype=np.int64)
    fs = np.ascontiguousarray(fs, dtype=np.int64)
    fn_ = np.ascontiguousarray(fn_, dtype=np.int64)
    fo = np.ascontiguousarray(fo, dtype=np.int64)
    fi = np.ascontiguousarray(fi, dtype=np.int64)
    cap = len(fs) + len(ps)
    ms = np.empty(cap, dtype=np.int64)
    mn = np.empty(cap, dtype=np.int64)
    mo = np.empty((cap, 3), dtype=np.int64)
    mi = np.empty(cap, dtype=np.int64)
    total = lib.dn_stream_remap_merge(
        _ptr(old2new, ctypes.c_int64), _ptr(reus_old, ctypes.c_uint8),
        _ptr(ps, ctypes.c_int64), _ptr(pn, ctypes.c_int64),
        _ptr(po, ctypes.c_int64), _ptr(pi, ctypes.c_int64), len(ps),
        _ptr(fs, ctypes.c_int64), _ptr(fn_, ctypes.c_int64),
        _ptr(fo, ctypes.c_int64), _ptr(fi, ctypes.c_int64), len(fs),
        _ptr(ms, ctypes.c_int64), _ptr(mn, ctypes.c_int64),
        _ptr(mo, ctypes.c_int64), _ptr(mi, ctypes.c_int64), cap,
    )
    assert total <= cap  # nb <= len(ps) by construction
    return ms[:total], mn[:total], mo[:total], mi[:total]


def sfc_keys(indices, bits, kind):
    """Morton or Hilbert keys from (n,3) min-corner indices."""
    idx = np.ascontiguousarray(indices, dtype=np.uint64).reshape(-1, 3)
    out = np.empty(len(idx), dtype=np.uint64)
    fn = lib.dn_morton_keys if kind == "morton" else lib.dn_hilbert_keys
    fn(_ptr(idx, ctypes.c_uint64), len(idx), int(bits),
       _ptr(out, ctypes.c_uint64))
    return out


lib = _load()
