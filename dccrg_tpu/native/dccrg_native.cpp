// Native host runtime for dccrg_tpu.
//
// C++ equivalents of the host-side structure code that the reference
// implements in C++ (dccrg is a header-only C++ library): the AMR cell
// addressing scheme (dccrg_mapping.hpp), the neighbor-table builder
// (dccrg.hpp:4236-4897 find_neighbors_of / find_neighbors_to), and the
// space-filling-curve keys used for partitioning (dccrg.hpp:8147-8220,
// sfc++ replacement).  These run at structure-change events (init,
// refine, balance) on the host; results are identical to the NumPy
// reference implementations in ../neighbors.py and ../partition.py,
// which remain as fallback and as the cross-check used by the tests.
//
// Exposed as a plain C ABI for ctypes (the image has no pybind11).
// All output buffers are caller-allocated; functions that emit ragged
// output take a capacity and return the required entry count so the
// caller can retry with a larger buffer (entries beyond capacity are
// counted, not written).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <limits>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// Mapping: 1-based, level-major cell ids (parity with dccrg_mapping.hpp).

// Division by a runtime-invariant u64 via 128-bit reciprocal multiply:
// recip = floor((2^64 - 1) / d) gives q0 = mulhi(n, recip) within 2 of
// floor(n / d) for any n; a tiny correction loop finishes the job.
// (Replaces the hardware divides in the per-cell index math — the hot
// op of the geometry/position lookups, tests/geometry README.)
struct DnDiv {
  uint64_t d;
  uint64_t recip;
};

static inline DnDiv dn_div_make(uint64_t d) {
  DnDiv v;
  v.d = d;
  v.recip = d ? ~(uint64_t)0 / d : 0;
  return v;
}

static inline uint64_t dn_div(uint64_t n, const DnDiv dv, uint64_t *rem) {
  uint64_t q = (uint64_t)(((__uint128_t)n * dv.recip) >> 64);
  uint64_t r = n - q * dv.d;
  while (r >= dv.d) {
    r -= dv.d;
    ++q;
  }
  *rem = r;
  return q;
}

struct DnMapping {
  uint64_t length[3];       // level-0 extents
  int32_t max_lvl;          // maximum refinement level
  uint64_t level_first[32]; // first cell id of each level (1-based)
  uint64_t last_cell;
  uint64_t index_length[3]; // extents in smallest-cell index units
  DnDiv div_lx[32];         // per-level reciprocal divisors for
  DnDiv div_ly[32];         // length[0] << lvl and length[1] << lvl
};

static void dn_mapping_init(DnMapping *m, const uint64_t length[3],
                            int32_t max_lvl) {
  m->length[0] = length[0];
  m->length[1] = length[1];
  m->length[2] = length[2];
  m->max_lvl = max_lvl;
  const uint64_t gl = length[0] * length[1] * length[2];
  uint64_t acc = 1, per = gl;
  for (int l = 0; l <= max_lvl; ++l) {
    m->level_first[l] = acc;
    acc += per;
    per *= 8;
    m->div_lx[l] = dn_div_make(length[0] << (uint64_t)l);
    m->div_ly[l] = dn_div_make(length[1] << (uint64_t)l);
  }
  m->last_cell = acc - 1;
  for (int d = 0; d < 3; ++d)
    m->index_length[d] = length[d] << (uint64_t)max_lvl;
}

static inline int32_t dn_level(const DnMapping *m, uint64_t cell) {
  if (cell == 0 || cell > m->last_cell)
    return -1;
  // branchless: level = (number of level-firsts <= cell) - 1; random
  // per-cell levels would mispredict an early-exit scan on every call
  int32_t lvl = -1;
  for (int32_t l = 0; l <= m->max_lvl; ++l)
    lvl += (int32_t)(cell >= m->level_first[l]);
  return lvl;
}

// indices (smallest-cell units) of a cell known to be valid at level lvl
static inline void dn_indices(const DnMapping *m, uint64_t cell, int32_t lvl,
                              uint64_t out[3]) {
  const uint64_t within = cell - m->level_first[lvl];
  const uint64_t shift = (uint64_t)(m->max_lvl - lvl);
  uint64_t ox, oy;
  const uint64_t rest = dn_div(within, m->div_lx[lvl], &ox);
  const uint64_t oz = dn_div(rest, m->div_ly[lvl], &oy);
  out[0] = ox << shift;
  out[1] = oy << shift;
  out[2] = oz << shift;
}

// cell id at given smallest-cell indices and refinement level
// (indices must be inside the grid, lvl in [0, max_lvl])
static inline uint64_t dn_cell_from_indices(const DnMapping *m,
                                            const uint64_t idx[3],
                                            int32_t lvl) {
  const uint64_t shift = (uint64_t)(m->max_lvl - lvl);
  const uint64_t ox = idx[0] >> shift, oy = idx[1] >> shift,
                 oz = idx[2] >> shift;
  const uint64_t lx = m->length[0] << (uint64_t)lvl;
  const uint64_t ly = m->length[1] << (uint64_t)lvl;
  return m->level_first[lvl] + ox + oy * lx + oz * lx * ly;
}

// ---------------------------------------------------------------------------
// Neighbor-table builder (semantics of dccrg.hpp:4375-4716; algorithm of
// ../neighbors.py::find_neighbors_of: binary search in the sorted
// replicated leaf-cell set instead of walking per-cell links).

static inline bool dn_exists(const uint64_t *cells, int64_t n, uint64_t id) {
  const uint64_t *p = std::lower_bound(cells, cells + n, id);
  return p != cells + n && *p == id;
}

// Per-(cell, neighborhood-item) resolution. Writes up to 8 entries into
// nbr/off (off is the neighbor's min-corner displacement in
// smallest-cell units, logical i.e. unwrapped across periodic faces).
// Returns entry count, or a negative error code:
//   -1 window not covered at max level (grid does not tile)
//   -2 window neither same-level, coarser, nor tiled by children
static inline int dn_resolve_window(
    const DnMapping *m, const uint8_t periodic[3], const uint64_t *cells,
    int64_t n_cells, const int64_t base[3], int64_t size, int32_t lvl,
    const int64_t hood[3], uint64_t nbr[8], int64_t off[8][3]) {
  int64_t win[3];
  uint64_t wrapped[3];
  for (int d = 0; d < 3; ++d) {
    win[d] = base[d] + hood[d] * size;
    const int64_t il = (int64_t)m->index_length[d];
    if (periodic[d]) {
      int64_t w = win[d] % il;
      if (w < 0)
        w += il;
      wrapped[d] = (uint64_t)w;
    } else {
      if (win[d] < 0 || win[d] >= il)
        return 0; // outside a non-periodic boundary: no neighbor
      wrapped[d] = (uint64_t)win[d];
    }
  }

  // same-level cell occupying the window
  const uint64_t slot = dn_cell_from_indices(m, wrapped, lvl);
  if (dn_exists(cells, n_cells, slot)) {
    nbr[0] = slot;
    for (int d = 0; d < 3; ++d)
      off[0][d] = hood[d] * size;
    return 1;
  }

  // coarser (level-1) cell containing the window
  if (lvl > 0) {
    const uint64_t coarse = dn_cell_from_indices(m, wrapped, lvl - 1);
    if (dn_exists(cells, n_cells, coarse)) {
      const uint64_t csize = 2 * (uint64_t)size;
      nbr[0] = coarse;
      for (int d = 0; d < 3; ++d) {
        const int64_t cmin = (int64_t)((wrapped[d] / csize) * csize);
        off[0][d] = hood[d] * size + (cmin - (int64_t)wrapped[d]);
      }
      return 1;
    }
  }

  // finer: the window's 8 child cells in z-order (x fastest)
  if (lvl >= m->max_lvl)
    return -1;
  const int64_t half = size / 2;
  for (int k = 0; k < 8; ++k) {
    const int64_t rel[3] = {(k & 1) * half, ((k >> 1) & 1) * half,
                            ((k >> 2) & 1) * half};
    uint64_t cidx[3];
    for (int d = 0; d < 3; ++d)
      cidx[d] = wrapped[d] + (uint64_t)rel[d];
    const uint64_t child = dn_cell_from_indices(m, cidx, lvl + 1);
    if (!dn_exists(cells, n_cells, child))
      return -2;
    nbr[k] = child;
    for (int d = 0; d < 3; ++d)
      off[k][d] = hood[d] * size + rel[d];
  }
  return 8;
}

// neighbors_of for query_cells against the complete sorted leaf-cell
// set.  Output entries are ordered (query position, neighborhood item,
// z-order child rank) — identical to the NumPy engine's lexsort order.
// Returns the total entry count (may exceed capacity; entries past
// capacity are not written), or negative on error with the offending
// (cell, item) in err_cell/err_item:
//   -1 tiling gap at max refinement level
//   -2 2:1 balance violation or gap
//   -3 invalid cell id in query
int64_t dn_find_neighbors_of(
    const uint64_t grid_length[3], int32_t max_lvl, const uint8_t periodic[3],
    const uint64_t *cells_sorted, int64_t n_cells, const uint64_t *query,
    int64_t n_query, const int64_t *hood, int64_t n_hood, int64_t *out_src,
    uint64_t *out_nbr, int64_t *out_off, int64_t *out_item, int64_t capacity,
    uint64_t *err_cell, int64_t *err_item) {
  DnMapping m;
  dn_mapping_init(&m, grid_length, max_lvl);

  // pass 1: per-query entry counts (parallel)
  std::vector<int64_t> counts((size_t)n_query, 0);
  int64_t err_flag = 0; // 0 ok, else -1/-2/-3
  int64_t err_q = -1, err_k = -1;

#pragma omp parallel for schedule(static)
  for (int64_t q = 0; q < n_query; ++q) {
    int64_t seen_err;
#pragma omp atomic read
    seen_err = err_flag;
    if (seen_err)
      continue;
    const uint64_t cell = query[q];
    const int32_t lvl = dn_level(&m, cell);
    if (lvl < 0) {
#pragma omp critical
      {
        if (!err_flag) {
          err_q = q;
          err_k = 0;
#pragma omp atomic write
          err_flag = -3;
        }
      }
      continue;
    }
    const int64_t size = (int64_t)1 << (uint64_t)(max_lvl - lvl);
    uint64_t bidx[3];
    dn_indices(&m, cell, lvl, bidx);
    const int64_t base[3] = {(int64_t)bidx[0], (int64_t)bidx[1],
                             (int64_t)bidx[2]};
    int64_t cnt = 0;
    uint64_t nbr[8];
    int64_t off[8][3];
    for (int64_t k = 0; k < n_hood; ++k) {
      const int r = dn_resolve_window(&m, periodic, cells_sorted, n_cells,
                                      base, size, lvl, &hood[3 * k], nbr, off);
      if (r < 0) {
#pragma omp critical
        {
          if (!err_flag) {
            err_q = q;
            err_k = k;
#pragma omp atomic write
            err_flag = r;
          }
        }
        break;
      }
      cnt += r;
    }
    counts[(size_t)q] = cnt;
  }
  if (err_flag) {
    if (err_cell)
      *err_cell = query[err_q];
    if (err_item)
      *err_item = err_k;
    return err_flag;
  }

  // prefix sum
  std::vector<int64_t> starts((size_t)n_query + 1);
  starts[0] = 0;
  for (int64_t q = 0; q < n_query; ++q)
    starts[(size_t)q + 1] = starts[(size_t)q] + counts[(size_t)q];
  const int64_t total = starts[(size_t)n_query];
  if (total > capacity)
    return total; // caller re-allocates and retries

  // pass 2: fill (parallel, deterministic via per-query offsets)
#pragma omp parallel for schedule(static)
  for (int64_t q = 0; q < n_query; ++q) {
    const uint64_t cell = query[q];
    const int32_t lvl = dn_level(&m, cell);
    const int64_t size = (int64_t)1 << (uint64_t)(max_lvl - lvl);
    uint64_t bidx[3];
    dn_indices(&m, cell, lvl, bidx);
    const int64_t base[3] = {(int64_t)bidx[0], (int64_t)bidx[1],
                             (int64_t)bidx[2]};
    int64_t w = starts[(size_t)q];
    uint64_t nbr[8];
    int64_t off[8][3];
    for (int64_t k = 0; k < n_hood; ++k) {
      const int r = dn_resolve_window(&m, periodic, cells_sorted, n_cells,
                                      base, size, lvl, &hood[3 * k], nbr, off);
      for (int j = 0; j < r; ++j, ++w) {
        out_src[w] = q;
        out_nbr[w] = nbr[j];
        out_off[3 * w + 0] = off[j][0];
        out_off[3 * w + 1] = off[j][1];
        out_off[3 * w + 2] = off[j][2];
        out_item[w] = k;
      }
    }
  }
  return total;
}

// ---------------------------------------------------------------------------
// Space-filling-curve keys over cell min-corner indices (sfc++ / HSFC
// replacement; parity with ../partition.py::morton_key / hilbert_key).

// Morton: bit-interleave (x lowest) at smallest-cell resolution.
void dn_morton_keys(const uint64_t *indices, int64_t n, int32_t bits,
                    uint64_t *out) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint64_t key = 0;
    for (int32_t b = 0; b < bits; ++b)
      for (int d = 0; d < 3; ++d)
        key |= ((indices[3 * i + d] >> (uint64_t)b) & 1u)
               << (uint64_t)(3 * b + d);
    out[i] = key;
  }
}

// Hilbert: Skilling's transpose algorithm (3-D).
void dn_hilbert_keys(const uint64_t *indices, int64_t n, int32_t bits,
                     uint64_t *out) {
  const uint64_t N = (uint64_t)1 << (uint64_t)bits;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint64_t x[3] = {indices[3 * i], indices[3 * i + 1], indices[3 * i + 2]};
    // Gray-decode: inverse undo excess work
    for (uint64_t q = N >> 1; q > 1; q >>= 1) {
      const uint64_t p = q - 1;
      for (int d = 0; d < 3; ++d) {
        if (x[d] & q) {
          x[0] ^= p;
        } else {
          const uint64_t t = (x[0] ^ x[d]) & p;
          x[0] ^= t;
          x[d] ^= t;
        }
      }
    }
    // Gray encode
    for (int d = 1; d < 3; ++d)
      x[d] ^= x[d - 1];
    uint64_t t = 0;
    for (uint64_t q = N >> 1; q > 1; q >>= 1)
      if (x[2] & q)
        t ^= q - 1;
    for (int d = 0; d < 3; ++d)
      x[d] ^= t;
    // interleave transpose form, MSB first, dim 0 highest per group
    uint64_t key = 0;
    for (int32_t b = bits - 1; b >= 0; --b)
      for (int d = 0; d < 3; ++d)
        key = (key << 1) | ((x[d] >> (uint64_t)b) & 1u);
    out[i] = key;
  }
}

// ---------------------------------------------------------------------------
// Vectorized mapping queries (host-side bulk id math).

// refinement level per cell (-1 for invalid ids)
void dn_refinement_levels(const uint64_t grid_length[3], int32_t max_lvl,
                          const uint64_t *cells, int64_t n, int32_t *out) {
  DnMapping m;
  dn_mapping_init(&m, grid_length, max_lvl);
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i)
    out[i] = dn_level(&m, cells[i]);
}

// (n,3) min-corner indices per cell; all-ones rows (~0) for invalid ids
void dn_cell_indices(const uint64_t grid_length[3], int32_t max_lvl,
                     const uint64_t *cells, int64_t n, uint64_t *out) {
  DnMapping m;
  dn_mapping_init(&m, grid_length, max_lvl);
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const int32_t lvl = dn_level(&m, cells[i]);
    if (lvl < 0) {
      out[3 * i] = out[3 * i + 1] = out[3 * i + 2] = ~(uint64_t)0;
    } else {
      dn_indices(&m, cells[i], lvl, &out[3 * i]);
    }
  }
}

// Per-cell geometry lookup: min corner and edge lengths from
// per-dimension level-0 boundary coordinate arrays (bd[d] has
// grid_length[d]+1 monotone values).  Covers all three geometries —
// the hot path of the reference's geometry micro-benchmarks
// (tests/geometry README).  NaN rows for invalid ids.
void dn_geometry_min_len(const uint64_t grid_length[3], int32_t max_lvl,
                         const double *bx, const double *by, const double *bz,
                         const uint64_t *cells, int64_t n, double *out_min,
                         double *out_len) {
  DnMapping m;
  dn_mapping_init(&m, grid_length, max_lvl);
  const double *bd[3] = {bx, by, bz};
  const double inv_scale = 1.0 / (double)((uint64_t)1 << max_lvl);
  const uint64_t mask = ((uint64_t)1 << max_lvl) - 1;
  const double nan = std::numeric_limits<double>::quiet_NaN();
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const int32_t lvl = dn_level(&m, cells[i]);
    if (lvl < 0) {
      for (int d = 0; d < 3; ++d) {
        out_min[3 * i + d] = nan;
        out_len[3 * i + d] = nan;
      }
      continue;
    }
    uint64_t idx[3];
    dn_indices(&m, cells[i], lvl, idx);
    const double extent = 1.0 / (double)((uint64_t)1 << lvl);
    for (int d = 0; d < 3; ++d) {
      const uint64_t l0 = idx[d] >> max_lvl;
      const double lo = bd[d][l0], hi = bd[d][l0 + 1];
      const double frac = (double)(idx[d] & mask) * inv_scale;
      out_min[3 * i + d] = lo + frac * (hi - lo);
      out_len[3 * i + d] = (hi - lo) * extent;
    }
  }
}

// Per-cell center coordinates in one pass (no separate min/len
// round-trip through the caller).
void dn_geometry_centers(const uint64_t grid_length[3], int32_t max_lvl,
                         const double *bx, const double *by, const double *bz,
                         const uint64_t *cells, int64_t n, double *out) {
  DnMapping m;
  dn_mapping_init(&m, grid_length, max_lvl);
  const double *bd[3] = {bx, by, bz};
  const double inv_scale = 1.0 / (double)((uint64_t)1 << max_lvl);
  const uint64_t mask = ((uint64_t)1 << max_lvl) - 1;
  const double nan = std::numeric_limits<double>::quiet_NaN();
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const int32_t lvl = dn_level(&m, cells[i]);
    if (lvl < 0) {
      out[3 * i] = out[3 * i + 1] = out[3 * i + 2] = nan;
      continue;
    }
    uint64_t idx[3];
    dn_indices(&m, cells[i], lvl, idx);
    const double half_extent = 0.5 / (double)((uint64_t)1 << lvl);
    for (int d = 0; d < 3; ++d) {
      const uint64_t l0 = idx[d] >> max_lvl;
      const double lo = bd[d][l0], hi = bd[d][l0 + 1];
      const double frac = (double)(idx[d] & mask) * inv_scale;
      out[3 * i + d] = lo + (frac + half_extent) * (hi - lo);
    }
  }
}

// Per-cell edge lengths only: level lookup + a copy from the
// (max_lvl+1, 3) per-level length table — no index math (the
// reference's "cell size" micro-benchmark, tests/geometry README).
void dn_cell_lengths(const uint64_t grid_length[3], int32_t max_lvl,
                     const double *len_table, const uint64_t *cells,
                     int64_t n, double *out) {
  DnMapping m;
  dn_mapping_init(&m, grid_length, max_lvl);
  const double nan = std::numeric_limits<double>::quiet_NaN();
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const int32_t lvl = dn_level(&m, cells[i]);
    const double *row = lvl < 0 ? nullptr : &len_table[3 * lvl];
    out[3 * i] = row ? row[0] : nan;
    out[3 * i + 1] = row ? row[1] : nan;
    out[3 * i + 2] = row ? row[2] : nan;
  }
}

// Stencil gather-table builder (the runtime's plan construction —
// reference update_cell_pointers, dccrg.hpp:11453-11767): pad the
// ragged per-cell neighbor entry stream into [n_dev, L, S] tables.
// Entries arrive ordered per cell; a sequential fill with per-(dev,
// row) slot counters preserves that order with no sort at all.
int64_t dn_table_counts(const int32_t *entry_dev, const int32_t *src_rows,
                        int64_t n, int64_t n_dev, int64_t L,
                        int64_t *counts /* [n_dev*L], zeroed */) {
  int64_t s_max = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t c = ++counts[(int64_t)entry_dev[i] * L + src_rows[i]];
    if (c > s_max)
      s_max = c;
  }
  return s_max;
}

void dn_table_fill(const int32_t *entry_dev, const int32_t *src_rows,
                   const int32_t *nbr_rows, const int64_t *offs, int64_t n,
                   int64_t n_dev, int64_t L, int64_t S, int64_t *slots
                   /* [n_dev*L], zeroed */, int32_t *rows_out
                   /* [n_dev*L*S], pre-filled with the pad row */,
                   int32_t *offs_out /* [n_dev*L*S*3], zeroed */,
                   uint8_t *mask_out /* [n_dev*L*S], zeroed */) {
  for (int64_t i = 0; i < n; ++i) {
    const int64_t cell = (int64_t)entry_dev[i] * L + src_rows[i];
    const int64_t at = cell * S + slots[cell]++;
    rows_out[at] = nbr_rows[i];
    offs_out[3 * at] = (int32_t)offs[3 * i];
    offs_out[3 * at + 1] = (int32_t)offs[3 * i + 1];
    offs_out[3 * at + 2] = (int32_t)offs[3 * i + 2];
    mask_out[at] = 1;
  }
}

// Uniform (all-level-0) gather tables in ONE pass (the fast path of
// plan construction, uniform.py): for every cell and neighborhood item
// write the neighbor's row on the reader's device into rows_out[i*k+j]
// and its existence into mask_out. Interior cells — the overwhelming
// majority — resolve through a precomputed flat-index delta per item;
// only boundary cells take the wrap/validity math. Cross-device
// neighbors are emitted as the sentinel ``-2 - neighbor_gidx`` for the
// (small) host-side ghost-row fixup. owner == NULL means one device
// (no cross edges possible).
void dn_uniform_tables(int64_t nx, int64_t ny, int64_t nz, int32_t px,
                       int32_t py, int32_t pz,
                       const int64_t *offs /* [k, 3] cell units */, int64_t k,
                       const int32_t *row_of_pos /* [n0] */,
                       const int32_t *owner /* [n0] or NULL */,
                       int32_t pad_row,
                       int32_t *rows_out /* [n0, k] */,
                       uint8_t *mask_out /* [n0, k] */) {
  const int64_t nxy = nx * ny;
  std::vector<int64_t> dflat(k), lo(3, 0), hi(3);
  hi[0] = nx;
  hi[1] = ny;
  hi[2] = nz;
  for (int64_t j = 0; j < k; ++j) {
    dflat[j] = offs[3 * j] + offs[3 * j + 1] * nx + offs[3 * j + 2] * nxy;
    // interior box: cells whose every neighbor is in-bounds unwrapped
    lo[0] = std::max(lo[0], -offs[3 * j]);
    hi[0] = std::min(hi[0], nx - offs[3 * j]);
    lo[1] = std::max(lo[1], -offs[3 * j + 1]);
    hi[1] = std::min(hi[1], ny - offs[3 * j + 1]);
    lo[2] = std::max(lo[2], -offs[3 * j + 2]);
    hi[2] = std::min(hi[2], nz - offs[3 * j + 2]);
  }
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int64_t z = 0; z < nz; ++z) {
    for (int64_t y = 0; y < ny; ++y) {
      const int64_t rowbase = y * nx + z * nxy;
      const bool yz_interior =
          y >= lo[1] && y < hi[1] && z >= lo[2] && z < hi[2];
      for (int64_t x = 0; x < nx; ++x) {
        const int64_t i = rowbase + x;
        int32_t *rout = rows_out + i * k;
        uint8_t *mout = mask_out + i * k;
        if (yz_interior && x >= lo[0] && x < hi[0]) {
          if (owner == nullptr) {
            for (int64_t j = 0; j < k; ++j) {
              rout[j] = row_of_pos[i + dflat[j]];
              mout[j] = 1;
            }
          } else {
            const int32_t own = owner[i];
            for (int64_t j = 0; j < k; ++j) {
              const int64_t ng = i + dflat[j];
              rout[j] = owner[ng] == own ? row_of_pos[ng]
                                         : (int32_t)(-2 - ng);
              mout[j] = 1;
            }
          }
          continue;
        }
        for (int64_t j = 0; j < k; ++j) {
          int64_t xx = x + offs[3 * j], yy = y + offs[3 * j + 1],
                  zz = z + offs[3 * j + 2];
          bool valid = true;
          if (xx < 0 || xx >= nx) {
            if (px)
              xx = ((xx % nx) + nx) % nx;
            else
              valid = false;
          }
          if (yy < 0 || yy >= ny) {
            if (py)
              yy = ((yy % ny) + ny) % ny;
            else
              valid = false;
          }
          if (zz < 0 || zz >= nz) {
            if (pz)
              zz = ((zz % nz) + nz) % nz;
            else
              valid = false;
          }
          if (!valid) {
            rout[j] = pad_row;
            mout[j] = 0;
            continue;
          }
          const int64_t ng = xx + yy * nx + zz * nxy;
          if (owner != nullptr && owner[ng] != owner[i])
            rout[j] = (int32_t)(-2 - ng);
          else
            rout[j] = row_of_pos[ng];
          mout[j] = 1;
        }
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Recommit fast-path kernels (../hybrid.py): the AMR plan re-commit's
// hot loops, moved out of numpy so a 192^3 rebuild stops paying
// multi-GB temporary materialization + page faults (ROADMAP "Hybrid
// re-commit cost at 192^3").  All functions are bitwise-equivalent to
// the numpy fallbacks at the level the plan consumes (gather tables,
// masks, merged streams) — pinned by tests/test_recommit.py.

// positions of sorted needles in a sorted haystack — np.searchsorted
// (side='left') lowered to one linear sweep, O(n + m) instead of
// O(m log n), since both inputs are sorted cell-id arrays.
void dn_sorted_positions(const uint64_t *hay, int64_t n,
                         const uint64_t *needles, int64_t m, int64_t *out) {
  int64_t i = 0;
  for (int64_t j = 0; j < m; ++j) {
    const uint64_t v = needles[j];
    while (i < n && hay[i] < v) ++i;
    out[j] = i;
  }
}

// Batched level-block neighbor-position lookup: for the contiguous
// block of level-l cells at positions [a, b) in the sorted cell list,
// resolve every (cell, offset) pair of the whole symmetrized offset
// set in one call (hybrid._LevelBlock.lookup's per-offset
// lattice/searchsorted loop).  `plat` is caller-provided scratch of
// n_lat int32 (the level-l position lattice, arena-reused across
// epochs); pass NULL to use per-item binary search instead (huge
// lattices).  Outputs are [kb, m]: position in the cell list (0 when
// the neighbor does not exist), in-grid validity, and existence as a
// level-l leaf.
void dn_level_lookup(int64_t nxl, int64_t nyl, int64_t nzl, int32_t px,
                     int32_t py, int32_t pz, const int64_t *lin, int64_t m,
                     int64_t a, const uint64_t *cells, int64_t b,
                     uint64_t first, const int64_t *offs, int64_t kb,
                     int32_t *plat, int64_t n_lat, int32_t *pos_out,
                     uint8_t *valid_out, uint8_t *exist_out) {
  std::vector<int32_t> xs((size_t)m), ys((size_t)m), zs((size_t)m);
  const int64_t nxy = nxl * nyl;
  for (int64_t i = 0; i < m; ++i) {
    const int64_t l = lin[i];
    xs[(size_t)i] = (int32_t)(l % nxl);
    ys[(size_t)i] = (int32_t)((l / nxl) % nyl);
    zs[(size_t)i] = (int32_t)(l / nxy);
  }
  if (plat != nullptr) {
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int64_t i = 0; i < n_lat; ++i)
      plat[i] = -1;
    for (int64_t i = 0; i < m; ++i)
      plat[lin[i]] = (int32_t)(a + i);
  }
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int64_t j = 0; j < kb; ++j) {
    const int64_t ox = offs[3 * j], oy = offs[3 * j + 1], oz = offs[3 * j + 2];
    int32_t *po = pos_out + j * m;
    uint8_t *vo = valid_out + j * m;
    uint8_t *eo = exist_out + j * m;
    for (int64_t i = 0; i < m; ++i) {
      int64_t x = xs[(size_t)i] + ox, y = ys[(size_t)i] + oy,
              z = zs[(size_t)i] + oz;
      bool valid = true;
      if (x < 0 || x >= nxl) {
        if (px)
          x = ((x % nxl) + nxl) % nxl;
        else
          valid = false;
      }
      if (y < 0 || y >= nyl) {
        if (py)
          y = ((y % nyl) + nyl) % nyl;
        else
          valid = false;
      }
      if (z < 0 || z >= nzl) {
        if (pz)
          z = ((z % nzl) + nzl) % nzl;
        else
          valid = false;
      }
      int32_t p = 0;
      bool exist = false;
      if (valid) {
        const int64_t lin_n = x + nxl * (y + nyl * z);
        if (plat != nullptr) {
          const int32_t q = plat[lin_n];
          if (q >= 0) {
            exist = true;
            p = q;
          }
        } else {
          const uint64_t nid = first + (uint64_t)lin_n;
          const uint64_t *lo = std::lower_bound(cells + a, cells + b, nid);
          if (lo != cells + b && *lo == nid) {
            exist = true;
            p = (int32_t)(lo - cells);
          }
        }
      }
      po[i] = p;
      vo[i] = (uint8_t)valid;
      eo[i] = (uint8_t)exist;
    }
  }
}

// Far-row gather tables written IN PLACE: the level-0 lattice rows of
// dn_uniform_tables restricted to the far slots and scattered straight
// into the (arena-reused) [n_rows, k] hybrid table at far_rowidx — no
// [n0, k] intermediate, no host-side gather + scatter passes.
// Cross-device entries carry the ``-2 - neighbor_slot`` sentinel and
// their (far index, item) pair is appended (packed i * k + j) to
// fix_out so the host fixes up ONLY the partition surface.  Returns
// the fixup count (may exceed fix_cap: caller re-calls with a larger
// buffer; table writes are idempotent).
int64_t dn_far_tables(int64_t nx, int64_t ny, int64_t nz, int32_t px,
                      int32_t py, int32_t pz, const int64_t *offs, int64_t k,
                      const int64_t *far_slots, int64_t nf,
                      const int64_t *far_rowidx, const int32_t *row_of_pos0,
                      const int32_t *owner0, int32_t pad_row, int32_t *rows_t,
                      uint8_t *mask_t, int64_t *fix_out, int64_t fix_cap) {
  const int64_t nxy = nx * ny;
  std::vector<int64_t> dflat((size_t)k), lo(3, 0), hi(3);
  hi[0] = nx;
  hi[1] = ny;
  hi[2] = nz;
  for (int64_t j = 0; j < k; ++j) {
    dflat[(size_t)j] = offs[3 * j] + offs[3 * j + 1] * nx + offs[3 * j + 2] * nxy;
    lo[0] = std::max(lo[0], -offs[3 * j]);
    hi[0] = std::min(hi[0], nx - offs[3 * j]);
    lo[1] = std::max(lo[1], -offs[3 * j + 1]);
    hi[1] = std::min(hi[1], ny - offs[3 * j + 1]);
    lo[2] = std::max(lo[2], -offs[3 * j + 2]);
    hi[2] = std::min(hi[2], nz - offs[3 * j + 2]);
  }
  int64_t n_fix = 0;
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < nf; ++i) {
    const int64_t g = far_slots[i];
    const int64_t x = g % nx, y = (g / nx) % ny, z = g / nxy;
    int32_t *rout = rows_t + far_rowidx[i] * k;
    uint8_t *mout = mask_t + far_rowidx[i] * k;
    const bool interior = x >= lo[0] && x < hi[0] && y >= lo[1] &&
                          y < hi[1] && z >= lo[2] && z < hi[2];
    const int32_t own = owner0 ? owner0[g] : 0;
    for (int64_t j = 0; j < k; ++j) {
      int64_t ng;
      if (interior) {
        ng = g + dflat[(size_t)j];
      } else {
        int64_t xx = x + offs[3 * j], yy = y + offs[3 * j + 1],
                zz = z + offs[3 * j + 2];
        bool valid = true;
        if (xx < 0 || xx >= nx) {
          if (px)
            xx = ((xx % nx) + nx) % nx;
          else
            valid = false;
        }
        if (yy < 0 || yy >= ny) {
          if (py)
            yy = ((yy % ny) + ny) % ny;
          else
            valid = false;
        }
        if (zz < 0 || zz >= nz) {
          if (pz)
            zz = ((zz % nz) + nz) % nz;
          else
            valid = false;
        }
        if (!valid) {
          rout[j] = pad_row;
          mout[j] = 0;
          continue;
        }
        ng = xx + yy * nx + zz * nxy;
      }
      if (owner0 != nullptr && owner0[ng] != own) {
        rout[j] = (int32_t)(-2 - ng);
        int64_t at;
#ifdef _OPENMP
#pragma omp atomic capture
#endif
        at = n_fix++;
        if (at < fix_cap)
          fix_out[at] = i * k + j;
      } else {
        rout[j] = row_of_pos0[ng];
      }
      mout[j] = 1;
    }
  }
  return n_fix;
}

// Easy-row gather tables written IN PLACE from the batched level-block
// lookup results: for every easy cell e and neighborhood item j, the
// same-level neighbor's row goes straight into the [n_rows, k] table
// at ridx[e] (hybrid.py's posm/validm staging + resolve_rows pass).
// `sel` maps each hood item to its row in the [kb, m] batch arrays.
// Cross-device entries get the ``-2 - neighbor_position`` sentinel +
// a packed (e * k + j) fixup record, as dn_far_tables.
int64_t dn_easy_tables(const int64_t *ei, int64_t E, const int64_t *ridx,
                       const int64_t *sel, int64_t k, const int32_t *pos_all,
                       const uint8_t *valid_all, int64_t m,
                       const int32_t *row_of_pos, const int32_t *owner,
                       const int32_t *edev, int32_t pad_row, int32_t *rows_t,
                       uint8_t *mask_t, int64_t *fix_out, int64_t fix_cap) {
  int64_t n_fix = 0;
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (int64_t e = 0; e < E; ++e) {
    const int64_t be = ei[e];
    int32_t *rout = rows_t + ridx[e] * k;
    uint8_t *mout = mask_t + ridx[e] * k;
    const int32_t dev = owner ? edev[e] : 0;
    for (int64_t j = 0; j < k; ++j) {
      const int64_t row = sel[j];
      const uint8_t v = valid_all[row * m + be];
      if (!v) {
        rout[j] = pad_row;
        mout[j] = 0;
        continue;
      }
      const int32_t p = pos_all[row * m + be];
      if (owner != nullptr && owner[p] != dev) {
        rout[j] = (int32_t)(-2 - p);
        int64_t at;
#ifdef _OPENMP
#pragma omp atomic capture
#endif
        at = n_fix++;
        if (at < fix_cap)
          fix_out[at] = e * k + j;
      } else {
        rout[j] = row_of_pos[p];
      }
      mout[j] = 1;
    }
  }
  return n_fix;
}

// Hard-table shape probe: one scan of the source-sorted entry stream
// yielding the per-device group (= hard cell) counts and the widest
// group — the quantities the sticky caps bucket into (Hmax, S_hard).
// out = [nG, S_needed, counts[0..n_dev)].
void dn_hard_counts(const int64_t *s_p, int64_t nE, const int32_t *owner,
                    int64_t n_dev, int64_t *out) {
  int64_t nG = 0, s_max = 0;
  for (int64_t d = 0; d < n_dev; ++d)
    out[2 + d] = 0;
  int64_t i = 0;
  while (i < nE) {
    const int64_t sp = s_p[i];
    int64_t cnt = 0;
    while (i < nE && s_p[i] == sp) {
      ++cnt;
      ++i;
    }
    ++nG;
    if (cnt > s_max)
      s_max = cnt;
    ++out[2 + (owner ? owner[sp] : 0)];
  }
  out[0] = nG;
  out[1] = s_max;
}

// Fused hard-table writer: grouping, dense per-device row assignment,
// entry scatter AND pad fill in ONE sequential pass — every byte of
// the four tables is written exactly once (the numpy path pays a full
// pad fill plus a fancy-indexed scatter; at 128^3+ the pad fill alone
// is GBs of cold writes).  Entries arrive source-sorted, so a
// device's rows fill consecutively (identical to the numpy stable
// argsort by device).  Cross-device neighbors get the
// ``-2 - position`` sentinel + a packed flat-table-index fixup, as
// the far/easy writers.  Returns the fixup count.
int64_t dn_hard_fill(const int64_t *s_p, const int64_t *s_n,
                     const int64_t *s_off, int64_t nE, const int32_t *owner,
                     const int32_t *row_of_pos, int64_t n_dev, int64_t Hmax,
                     int64_t S, int32_t row_pad, int32_t nbr_pad,
                     int32_t *rows_dev, int32_t *nbr_dev, int32_t *offs_dev,
                     uint8_t *mask_dev, int64_t *fix_out, int64_t fix_cap) {
  std::vector<int64_t> cursor((size_t)n_dev, 0);
  int64_t n_fix = 0, i = 0;
  while (i < nE) {
    const int64_t sp = s_p[i];
    const int32_t d = owner ? owner[sp] : 0;
    const int64_t r = cursor[(size_t)d]++;
    const int64_t cell = (int64_t)d * Hmax + r;
    rows_dev[cell] = row_of_pos[sp];
    int64_t slot = 0;
    for (; i < nE && s_p[i] == sp; ++i, ++slot) {
      const int64_t at = cell * S + slot;
      const int64_t np_ = s_n[i];
      if (owner != nullptr && owner[np_] != d) {
        nbr_dev[at] = (int32_t)(-2 - np_);
        if (n_fix < fix_cap)
          fix_out[n_fix] = at;
        ++n_fix;
      } else {
        nbr_dev[at] = row_of_pos[np_];
      }
      offs_dev[3 * at] = (int32_t)s_off[3 * i];
      offs_dev[3 * at + 1] = (int32_t)s_off[3 * i + 1];
      offs_dev[3 * at + 2] = (int32_t)s_off[3 * i + 2];
      mask_dev[at] = 1;
    }
    // slot tail of this row
    for (; slot < S; ++slot) {
      const int64_t at = cell * S + slot;
      nbr_dev[at] = nbr_pad;
      offs_dev[3 * at] = offs_dev[3 * at + 1] = offs_dev[3 * at + 2] = 0;
      mask_dev[at] = 0;
    }
  }
  // row tails of every device
  for (int64_t d = 0; d < n_dev; ++d) {
    for (int64_t r = cursor[(size_t)d]; r < Hmax; ++r) {
      const int64_t cell = d * Hmax + r;
      rows_dev[cell] = row_pad;
      for (int64_t slot = 0; slot < S; ++slot) {
        const int64_t at = cell * S + slot;
        nbr_dev[at] = nbr_pad;
        offs_dev[3 * at] = offs_dev[3 * at + 1] = offs_dev[3 * at + 2] = 0;
        mask_dev[at] = 0;
      }
    }
  }
  return n_fix;
}

// Epoch-to-epoch hard-stream reuse: remap the kept previous-epoch
// entries' positions through old2new and merge them with the freshly
// computed entries, both source-position-sorted, in one linear pass
// (hybrid.py's reuse-branch gather + double-searchsorted merge).  The
// two runs share no source cell (a cell is wholly fresh or wholly
// reused), so the merge is unambiguous; within-source entry order is
// preserved piecewise.  Returns the merged length (may exceed
// capacity: caller re-allocates and retries).
int64_t dn_stream_remap_merge(
    const int64_t *old2new, const uint8_t *reus_old, const int64_t *ps,
    const int64_t *pn, const int64_t *po, const int64_t *pi, int64_t n_prev,
    const int64_t *fs, const int64_t *fn, const int64_t *fo,
    const int64_t *fi, int64_t n_fresh, int64_t *ms, int64_t *mn, int64_t *mo,
    int64_t *mi, int64_t capacity) {
  int64_t nb = 0;
  for (int64_t i = 0; i < n_prev; ++i)
    nb += (int64_t)(reus_old[ps[i]] != 0);
  const int64_t total = n_fresh + nb;
  if (total > capacity)
    return total;
  int64_t ia = 0, ib = 0, w = 0;
  while (ib < n_prev && !reus_old[ps[ib]])
    ++ib;
  while (ia < n_fresh || ib < n_prev) {
    bool take_fresh;
    if (ib >= n_prev)
      take_fresh = true;
    else if (ia >= n_fresh)
      take_fresh = false;
    else
      take_fresh = fs[ia] <= old2new[ps[ib]];
    if (take_fresh) {
      ms[w] = fs[ia];
      mn[w] = fn[ia];
      mo[3 * w] = fo[3 * ia];
      mo[3 * w + 1] = fo[3 * ia + 1];
      mo[3 * w + 2] = fo[3 * ia + 2];
      mi[w] = fi[ia];
      ++ia;
    } else {
      ms[w] = old2new[ps[ib]];
      mn[w] = old2new[pn[ib]];
      mo[3 * w] = po[3 * ib];
      mo[3 * w + 1] = po[3 * ib + 1];
      mo[3 * w + 2] = po[3 * ib + 2];
      mi[w] = pi[ib];
      ++ib;
      while (ib < n_prev && !reus_old[ps[ib]])
        ++ib;
    }
    ++w;
  }
  return total;
}

int32_t dn_abi_version(void) { return 2; }


// ---------------------------------------------------------------------------
// Subset neighbors_to: for each query cell v, the cells c with v in
// their neighbors_of (semantics of ../neighbors.py::
// find_neighbors_to_subset's enumeration path, itself mirroring
// dccrg.hpp:4744-4897): candidate window bases are the <=3-per-
// dimension size_c-aligned positions overlapping v's box, enumerated
// per (item, source level); a candidate source counts iff it exists as
// a leaf. Raw entries (duplicates included — the caller dedups exactly
// like the NumPy path) are ordered by query index.

static inline int64_t dn_floordiv(int64_t a, int64_t b) {
  int64_t q = a / b, r = a % b;
  return (r != 0 && ((r < 0) != (b < 0))) ? q - 1 : q;
}

// Returns total entry count (entries past capacity are counted, not
// written), or -3 for an invalid query id.
int64_t dn_find_neighbors_to_subset(
    const uint64_t grid_length[3], int32_t max_lvl, const uint8_t periodic[3],
    const uint64_t *cells_sorted, int64_t n_cells, const uint64_t *query,
    int64_t n_query, const int64_t *hood, int64_t n_hood, int64_t *out_q,
    uint64_t *out_src, int64_t *out_off, int64_t *out_item,
    int64_t capacity) {
  DnMapping m;
  dn_mapping_init(&m, grid_length, max_lvl);
  int64_t total = 0;
  for (int64_t qi = 0; qi < n_query; ++qi) {
    const uint64_t v = query[qi];
    const int32_t lvl = dn_level(&m, v);
    if (lvl < 0)
      return -3;
    const int64_t sv = (int64_t)1 << (uint64_t)(m.max_lvl - lvl);
    uint64_t vb_u[3];
    dn_indices(&m, v, lvl, vb_u);
    const int64_t vb[3] = {(int64_t)vb_u[0], (int64_t)vb_u[1],
                           (int64_t)vb_u[2]};
    for (int64_t j = 0; j < n_hood; ++j) {
      const int64_t *o = hood + 3 * j;
      for (int32_t dlvl = -1; dlvl <= 1; ++dlvl) {
        const int32_t c_lvl = lvl + dlvl;
        if (c_lvl < 0 || c_lvl > m.max_lvl)
          continue;
        const int64_t sc = (int64_t)1 << (uint64_t)(m.max_lvl - c_lvl);
        // per-dim aligned window bases overlapping [vb, vb + sv)
        int64_t w_lo[3];
        int64_t cnt[3];
        for (int d = 0; d < 3; ++d) {
          w_lo[d] = -dn_floordiv(-(vb[d] - sc + 1), sc) * sc;  // ceil*sc
          cnt[d] = (vb[d] + sv - 1 - w_lo[d]) / sc + 1;
          if (cnt[d] < 0)
            cnt[d] = 0;
        }
        for (int64_t ix = 0; ix < cnt[0]; ++ix)
          for (int64_t iy = 0; iy < cnt[1]; ++iy)
            for (int64_t iz = 0; iz < cnt[2]; ++iz) {
              const int64_t w[3] = {w_lo[0] + ix * sc, w_lo[1] + iy * sc,
                                    w_lo[2] + iz * sc};
              bool ok = true;
              uint64_t cw[3];
              for (int d = 0; d < 3; ++d) {
                const int64_t il = (int64_t)m.index_length[d];
                const int64_t cb = w[d] - o[d] * sc;
                if (periodic[d]) {
                  int64_t r = cb % il;
                  if (r < 0)
                    r += il;
                  cw[d] = (uint64_t)r;
                } else {
                  // source cell fully inside, window min inside
                  if (cb < 0 || cb + sc > il || w[d] < 0 || w[d] >= il) {
                    ok = false;
                    break;
                  }
                  cw[d] = (uint64_t)cb;
                }
              }
              if (!ok)
                continue;
              const uint64_t cid = dn_cell_from_indices(&m, cw, c_lvl);
              if (!dn_exists(cells_sorted, n_cells, cid))
                continue;
              if (total < capacity) {
                out_q[total] = qi;
                out_src[total] = cid;
                // recorded to-offset = -(v.min - c.min in c's frame)
                //                    = w - vb - o*sc per dimension
                for (int d = 0; d < 3; ++d)
                  out_off[3 * total + d] = w[d] - vb[d] - o[d] * sc;
                out_item[total] = j;
              }
              ++total;
            }
      }
    }
  }
  return total;
}

} // extern "C"
