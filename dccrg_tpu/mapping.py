"""Cell id <-> (refinement level, indices) mapping.

Re-implements the reference's AMR addressing scheme (dccrg_mapping.hpp)
with bit-for-bit id parity, but vectorized over numpy arrays instead of
per-cell scalar calls:

- Cell ids are 1-based and enumerated level-by-level: all level-0 cells
  first (x-fastest over the level-0 index box), then ``8x`` as many
  level-1 slots, and so on (dccrg_mapping.hpp:154-209).
- Indices are measured in units of the *smallest possible* cell, i.e. a
  cell at refinement level ``l`` occupies ``2**(max_ref_lvl - l)``
  index units per dimension (dccrg_mapping.hpp:218-254).
- Children of a cell are enumerated in z-order with x fastest
  (dccrg_mapping.hpp:392-442).

Every query accepts scalars or arrays and broadcasts; invalid inputs map
to ERROR_CELL / ERROR_INDEX / level -1 rather than raising, matching the
reference's error-value convention.
"""

from __future__ import annotations

import numpy as np

from .length import GridLength
from .types import ERROR_CELL, ERROR_INDEX, as_cell_array, as_index_array

_U1 = np.uint64(1)


class Mapping:
    """Grid addressing: 64-bit cell ids under octree refinement.

    Parameters mirror the reference ``Mapping`` (dccrg_mapping.hpp:55):
    level-0 extents (``GridLength``) plus a maximum refinement level.
    """

    def __init__(self, length=(1, 1, 1), maximum_refinement_level: int = 0):
        self.length = GridLength(length)
        self.max_refinement_level = 0
        self._update_tables()
        if maximum_refinement_level != 0:
            if not self.set_maximum_refinement_level(maximum_refinement_level):
                raise ValueError(
                    f"maximum refinement level {maximum_refinement_level} not "
                    f"possible for grid of length {length}"
                )

    # ------------------------------------------------------------------
    # configuration

    def set_length(self, length) -> bool:
        old = tuple(int(v) for v in self.length.get())
        try:
            self.length.set(length)
        except (ValueError, OverflowError):
            return False
        # the current max refinement level must remain representable
        if self.max_refinement_level > self.get_maximum_possible_refinement_level():
            self.length.set(old)
            return False
        self._update_tables()
        return True

    def get_maximum_possible_refinement_level(self) -> int:
        """Largest max_ref_lvl whose cumulative id range fits uint64.

        Exact-integer version of dccrg_mapping.hpp:317-330.
        """
        gl = self.length.total_level0_cells
        level = 0
        total = 0
        while True:
            total += gl * 8**level
            if total > 2**64 - 1:
                return level - 1
            level += 1

    def set_maximum_refinement_level(self, level: int) -> bool:
        """Set max refinement level (0 = unrefined). Invalidates old ids."""
        if level < 0 or level > self.get_maximum_possible_refinement_level():
            return False
        self.max_refinement_level = int(level)
        self._update_tables()
        return True

    def get_maximum_refinement_level(self) -> int:
        return self.max_refinement_level

    def _update_tables(self) -> None:
        """Precompute per-level id offsets and index scales."""
        gl = self.length.total_level0_cells
        nlvl = self.max_refinement_level + 1
        # first id of each level, 1-based (exact Python ints; validated
        # to fit uint64 by get_maximum_possible_refinement_level)
        firsts, acc = [], 1
        for l in range(nlvl):
            firsts.append(acc)
            acc += gl * 8**l
        self._level_first = np.array(firsts, dtype=np.uint64)  # [nlvl]
        self.last_cell = np.uint64(acc - 1)
        # grid extents in units of smallest cells
        self._index_length = self.length.get() * (_U1 << np.uint64(self.max_refinement_level))

    # ------------------------------------------------------------------
    # queries (all vectorized; scalars in -> scalars out)

    def get_last_cell(self):
        return self.last_cell

    def get_index_length(self) -> np.ndarray:
        """Grid extents measured in smallest-cell index units."""
        return self._index_length.copy()

    def get_refinement_level(self, cells):
        """Refinement level of each cell; -1 for invalid ids.

        Vectorized replacement for the reference's linear scan over
        level ranges (dccrg_mapping.hpp:262-290).
        """
        scalar = np.isscalar(cells) or np.asarray(cells).ndim == 0
        cells = as_cell_array(cells)
        if cells.ndim == 1 and len(cells) >= 4096:
            from . import native

            if native.lib is not None:
                return native.refinement_levels(self, cells)
        # level = number of level-firsts <= cell, minus 1
        lvl = np.searchsorted(self._level_first, cells, side="right").astype(np.int64) - 1
        lvl[(cells == ERROR_CELL) | (cells > self.last_cell)] = -1
        return int(lvl[0]) if scalar else lvl

    def get_cell_from_indices(self, indices, refinement_level):
        """Cell id of given refinement level at given indices.

        Parity with dccrg_mapping.hpp:154-209; ERROR_CELL for any index
        outside the grid or invalid level.
        """
        indices = as_index_array(indices)
        scalar = indices.ndim == 1
        indices = np.atleast_2d(indices)
        lvl = np.broadcast_to(
            np.asarray(refinement_level, dtype=np.int64), indices.shape[:-1]
        ).copy()

        bad = (lvl < 0) | (lvl > self.max_refinement_level)
        bad |= np.any(indices >= self._index_length, axis=-1)
        lvl_safe = np.where(bad, 0, lvl)

        # indices at the cell's own refinement level
        shift = (self.max_refinement_level - lvl_safe).astype(np.uint64)
        own = indices >> shift[..., None]
        L = self.length.get()
        lx = L[0] << lvl_safe.astype(np.uint64)
        ly = L[1] << lvl_safe.astype(np.uint64)
        cell = (
            self._level_first[lvl_safe]
            + own[..., 0]
            + own[..., 1] * lx
            + own[..., 2] * lx * ly
        ).astype(np.uint64)
        cell[bad] = ERROR_CELL
        return np.uint64(cell[0]) if scalar else cell

    def get_indices(self, cells):
        """(..., 3) indices of each cell, in smallest-cell units.

        Parity with dccrg_mapping.hpp:218-254; ERROR_INDEX rows for
        invalid ids.
        """
        scalar = np.isscalar(cells) or np.asarray(cells).ndim == 0
        cells = as_cell_array(cells)
        if cells.ndim == 1 and len(cells) >= 4096:
            from . import native

            if native.lib is not None:
                return native.cell_indices(self, cells)
        lvl = np.atleast_1d(np.asarray(self.get_refinement_level(cells), dtype=np.int64))
        bad = lvl < 0
        lvl_safe = np.where(bad, 0, lvl)
        within = cells - self._level_first[lvl_safe]  # 0-based rank inside its level
        L = self.length.get()
        lx = (L[0] << lvl_safe.astype(np.uint64)).astype(np.uint64)
        ly = (L[1] << lvl_safe.astype(np.uint64)).astype(np.uint64)
        shift = (self.max_refinement_level - lvl_safe).astype(np.uint64)
        out = np.empty(cells.shape + (3,), dtype=np.uint64)
        out[..., 0] = (within % lx) << shift
        out[..., 1] = ((within // lx) % ly) << shift
        out[..., 2] = (within // (lx * ly)) << shift
        out[bad] = ERROR_INDEX
        return out[0] if scalar else out

    def get_cell_length_in_indices(self, cells):
        """Edge length of each cell in smallest-cell index units."""
        scalar = np.isscalar(cells) or np.asarray(cells).ndim == 0
        cells = as_cell_array(cells)
        lvl = np.atleast_1d(np.asarray(self.get_refinement_level(cells), dtype=np.int64))
        out = np.where(
            lvl < 0, ERROR_INDEX, _U1 << (self.max_refinement_level - np.where(lvl < 0, 0, lvl)).astype(np.uint64)
        ).astype(np.uint64)
        return np.uint64(out[0]) if scalar else out

    # ------------------------------------------------------------------
    # parent / child navigation (dccrg_mapping.hpp:339-496)

    def get_child(self, cells):
        """First (z-order) child; the cell itself at max level; ERROR_CELL if invalid."""
        scalar = np.isscalar(cells) or np.asarray(cells).ndim == 0
        cells = as_cell_array(cells)
        lvl = np.atleast_1d(np.asarray(self.get_refinement_level(cells), dtype=np.int64))
        out = np.where(lvl < 0, ERROR_CELL, cells).astype(np.uint64)
        can = (lvl >= 0) & (lvl < self.max_refinement_level)
        if np.any(can):
            idx = np.atleast_2d(self.get_indices(cells[can]))
            out[can] = np.atleast_1d(self.get_cell_from_indices(idx, lvl[can] + 1))
        return np.uint64(out[0]) if scalar else out

    def get_parent(self, cells):
        """Parent cell; the cell itself at level 0; ERROR_CELL if invalid."""
        scalar = np.isscalar(cells) or np.asarray(cells).ndim == 0
        cells = as_cell_array(cells)
        lvl = np.atleast_1d(np.asarray(self.get_refinement_level(cells), dtype=np.int64))
        out = np.where(lvl < 0, ERROR_CELL, cells).astype(np.uint64)
        has = lvl > 0
        if np.any(has):
            idx = np.atleast_2d(self.get_indices(cells[has]))
            out[has] = np.atleast_1d(self.get_cell_from_indices(idx, lvl[has] - 1))
        return np.uint64(out[0]) if scalar else out

    def get_level_0_parent(self, cells):
        scalar = np.isscalar(cells) or np.asarray(cells).ndim == 0
        cells = as_cell_array(cells)
        lvl = np.atleast_1d(np.asarray(self.get_refinement_level(cells), dtype=np.int64))
        out = np.where(lvl < 0, ERROR_CELL, cells).astype(np.uint64)
        has = lvl > 0
        if np.any(has):
            idx = np.atleast_2d(self.get_indices(cells[has]))
            out[has] = np.atleast_1d(self.get_cell_from_indices(idx, 0))
        return np.uint64(out[0]) if scalar else out

    def get_all_children(self, cells):
        """(..., 8) children in z-order (x fastest); ERROR_CELL rows when
        the cell is at max level or invalid (dccrg_mapping.hpp:392-442)."""
        scalar = np.isscalar(cells) or np.asarray(cells).ndim == 0
        cells = as_cell_array(cells)
        lvl = np.atleast_1d(np.asarray(self.get_refinement_level(cells), dtype=np.int64))
        out = np.full(cells.shape + (8,), ERROR_CELL, dtype=np.uint64)
        can = (lvl >= 0) & (lvl < self.max_refinement_level)
        if np.any(can):
            sub = cells[can]
            sub_lvl = lvl[can] + 1
            base = np.atleast_2d(self.get_indices(sub))  # [n, 3]
            off = (_U1 << (self.max_refinement_level - sub_lvl).astype(np.uint64)).astype(np.uint64)
            # z-order: child k has offsets (k&1, (k>>1)&1, (k>>2)&1)
            k = np.arange(8, dtype=np.uint64)
            dx = (k & _U1)[None, :] * off[:, None]
            dy = ((k >> _U1) & _U1)[None, :] * off[:, None]
            dz = ((k >> np.uint64(2)) & _U1)[None, :] * off[:, None]
            child_idx = np.stack(
                [base[:, 0:1] + dx, base[:, 1:2] + dy, base[:, 2:3] + dz], axis=-1
            )  # [n, 8, 3]
            out[can] = self.get_cell_from_indices(
                child_idx.reshape(-1, 3), np.repeat(sub_lvl, 8)
            ).reshape(-1, 8)
        return out[0] if scalar else out

    def get_siblings(self, cells):
        """(..., 8) the cell's sibling group (all children of its parent);
        for level-0 cells: [cell, ERROR_CELL x 7] (dccrg_mapping.hpp:450)."""
        scalar = np.isscalar(cells) or np.asarray(cells).ndim == 0
        cells = as_cell_array(cells)
        lvl = np.atleast_1d(np.asarray(self.get_refinement_level(cells), dtype=np.int64))
        out = np.full(cells.shape + (8,), ERROR_CELL, dtype=np.uint64)
        lvl0 = lvl == 0
        out[lvl0, 0] = cells[lvl0]
        deeper = lvl > 0
        if np.any(deeper):
            out[deeper] = self.get_all_children(self.get_parent(cells[deeper]))
        return out[0] if scalar else out

    # ------------------------------------------------------------------
    # file format (reference: dccrg_mapping.hpp:516-652)
    # Record: 3 x uint64 level-0 lengths + 1 x int32 max_ref_lvl.

    def data_size(self) -> int:
        return 3 * 8 + 4

    def to_bytes(self) -> bytes:
        return self.length.get().tobytes() + np.int32(self.max_refinement_level).tobytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "Mapping":
        if len(data) != 28:
            raise ValueError(f"mapping record must be 28 bytes, got {len(data)}")
        length = np.frombuffer(data[:24], dtype=np.uint64)
        max_lvl = int(np.frombuffer(data[24:], dtype=np.int32)[0])
        return cls(tuple(int(v) for v in length), max_lvl)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Mapping)
            and self.length == other.length
            and self.max_refinement_level == other.max_refinement_level
        )

    def __repr__(self) -> str:
        return (
            f"Mapping(length={tuple(int(v) for v in self.length.get())}, "
            f"max_refinement_level={self.max_refinement_level})"
        )
