"""Level-0 grid extents.

Equivalent of the reference's ``Grid_Length`` (dccrg_length.hpp:34):
holds the number of level-0 cells in each dimension, validating that the
total cell count over all refinement levels cannot overflow uint64.
"""

from __future__ import annotations

import numpy as np


class GridLength:
    """Number of level-0 cells in each dimension.

    Reference parity: dccrg_length.hpp:95-134 (``set`` with overflow
    check against the uint64 id space).
    """

    def __init__(self, length=(1, 1, 1)):
        self._length = np.array([1, 1, 1], dtype=np.uint64)
        self.set(length)

    def set(self, length) -> None:
        raw = np.asarray(length)
        if raw.shape != (3,):
            raise ValueError(f"grid length must be 3 values, got {raw!r}")
        if np.any(np.asarray(raw, dtype=object) < 0):
            raise ValueError(f"grid length must be > 0 in every dimension, got {raw}")
        try:
            arr = raw.astype(np.uint64)
        except OverflowError as e:
            raise ValueError(str(e))
        if raw.dtype == object and np.any(raw != arr):
            raise ValueError(f"grid length does not fit uint64: {raw}")
        if np.any(arr == 0):
            raise ValueError(f"grid length must be > 0 in every dimension, got {arr}")
        # Total level-0 cell count must fit uint64 (the per-level id
        # ranges are checked against max_refinement_level by Mapping).
        prod = int(arr[0]) * int(arr[1]) * int(arr[2])
        if prod >= 2**64:
            raise ValueError(f"grid of {arr} level-0 cells overflows the 64-bit id space")
        self._length = arr

    def get(self) -> np.ndarray:
        """The (3,) uint64 array of level-0 extents."""
        return self._length.copy()

    @property
    def total_level0_cells(self) -> int:
        return int(self._length[0]) * int(self._length[1]) * int(self._length[2])

    def __eq__(self, other) -> bool:
        return isinstance(other, GridLength) and bool(np.all(self._length == other._length))

    def __repr__(self) -> str:
        return f"GridLength({tuple(int(v) for v in self._length)})"
