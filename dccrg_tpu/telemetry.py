"""Telemetry subsystem: unified tracing + metrics for every layer.

The fleet story (millions of users, preemptible hardware, SDC-suspect
devices) is only operable when every run continuously answers "where
did the wall-clock go" and "how often did which defense fire". Until
now the sole observability primitive was one per-step latency
histogram in the supervision layer; this module is the process-wide
substrate everything else reports into:

**Metrics registry** — named counters, gauges and log-bucketed
histograms (:class:`LogHistogram`, the one histogram implementation in
the codebase — ``supervise.LatencyHistogram`` is a thin alias), with
optional ``{label: value}`` dimensions. Always on: an increment is a
dict update, cheap enough for every trip/rollback/audit/save to count
itself unconditionally. :func:`dump_prometheus` renders the standard
text exposition; ``DCCRG_METRICS_FILE`` (+ ``DCCRG_METRICS_EVERY``
seconds, default 10) exports it periodically from the run/scheduler
loops via :func:`maybe_export_metrics`.

**Span tracer** — :func:`span` is a context manager recording
``(name, wall start, monotonic duration, rank, nesting, tags)`` into a
bounded ring (``DCCRG_TRACE_RING`` events, default 65536; oldest
dropped). Tracing is OFF by default: ``DCCRG_TRACE=1`` (or
:func:`configure`) enables it, and when off ``span()`` returns one
shared no-op singleton — no event object, no dict, no ring append, so
the instrumented hot paths (``Grid.run_steps``, the halo exchange,
the fleet quantum) pay one truthiness check (pinned zero-allocation
by tests/test_telemetry.py). Every hot boundary the codebase owns is
instrumented: grid step / exchange start+wait, adapt/recommit epochs
and arena swaps, checkpoint save/load/delta/GC phases, runner
trips+rollbacks, integrity invariant checks and shadow audits, fleet
admission/dispatch/quantum/preemption, the elastic multi-host control
plane (``fleet.membership`` heartbeat+poll spans, ``fleet.reclaim``
spans with ``dccrg_fleet_reclaims_total`` /
``dccrg_fleet_reclaim_seconds``, the ``dccrg_fleet_membership{state}``
live/suspect/dead gauges, ``dccrg_fleet_ownership_lost_total`` fenced
zombies and ``dccrg_membership_poll_failures_total`` bounded-poll
expiries) — and the zero-stall overlap
machinery (background.py): ``recommit.bg`` wraps a background plan
build, ``grid.recommit.swap`` the step-boundary install, and
``ckpt.async`` an overlapped checkpoint write, with the *residual*
step-loop blockage recorded in the ``dccrg_recommit_stall_seconds``
(labeled ``where=swap``/unlabeled worker waits) and
``dccrg_ckpt_stall_seconds`` histograms — the serving-path stall a
sync epoch would have charged in full, so the sync-vs-background win
is one PromQL ratio (``bench/recommit_bench.py --overlap`` measures
the same quantity offline). The per-field ghost split counts its
outer re-pass row slots in ``dccrg_outer_repass_rows_total{mode}``
(vs ``dccrg_outer_repass_rows_full_total``, the full-re-pass
baseline), and the mixed-kernel lane SLO shed marks each parked
cohabitant in ``dccrg_fleet_lane_sheds_total{job}``. The warm-start
layer (warmstart.py) counts pool-served vs compiled first dispatches
in ``dccrg_warm_hits_total`` / ``dccrg_warm_misses_total`` (the
``where=aot_fallback`` series marks an AOT executable that declined
its arguments and fell back to the jit path), every journaled
warm/cold/reject/quarantine call in
``dccrg_warm_decisions_total{decision}``, convicted manifest records
in ``dccrg_warm_quarantined_total`` with typed degradations in
``dccrg_warm_cache_errors_total``, pre-compiled programs in
``dccrg_warm_prewarmed_total`` with per-key sweep latency in the
``dccrg_prewarm_seconds`` histogram (worker crashes in
``dccrg_prewarm_errors_total``), and the time from pool construction
to the first dispatch actually served in the
``dccrg_warm_first_dispatch_ready_seconds`` gauge — the rejoin
latency the mp harness's ``rejoin_warm`` scenario bounds.

**Trace export** — :func:`flush_trace` appends the ring as JSONL (one
event per line) to ``DCCRG_TRACE_FILE`` (auto-flushed at process
exit), each event tagged with the ``coord`` rank id, so per-rank files
of one multi-process run merge into a single coherent timeline with
:func:`merge_traces` / ``python -m dccrg_tpu.telemetry merge`` (events
carry wall-clock ``ts`` anchors for cross-rank ordering and monotonic
``dur`` for intervals; pinned by the mp harness ``trace_merge``
scenario against 2 REAL ranks).

**Strictly best-effort** — telemetry must never be the thing that
kills a run: every exporter write (trace and metrics) swallows I/O
failures, counts them in ``dccrg_telemetry_export_errors_total`` and
carries on. The ``telemetry.export`` :class:`~dccrg_tpu.faults
.FaultPlan` site (:meth:`~dccrg_tpu.faults.FaultPlan
.telemetry_io_error`) injects exactly that failure; the pinning test
runs a full supervised loop with EVERY export failing and asserts
zero trips/rollbacks.

The per-job quantum-latency story this module records is also a
control input: :class:`dccrg_tpu.scheduler.SLOPolicy` turns the
EWMA of measured fleet quantum latencies into latency-SLO admission
(per-job ``slo_ms`` deadlines) — see scheduler.py.
"""

from __future__ import annotations

import atexit
import collections
import json
import math
import os
import re
import threading
import time

from . import faults

logger = __import__("logging").getLogger("dccrg_tpu.telemetry")


# ---------------------------------------------------------------------
# env knobs
# ---------------------------------------------------------------------

def trace_enabled_default(default: bool = False) -> bool:
    """The ``DCCRG_TRACE`` env knob: ``1`` records spans into the
    trace ring (and, with ``DCCRG_TRACE_FILE``, to disk). Off
    (default) the span API is a shared no-op singleton — zero
    allocation on the step path."""
    v = os.environ.get("DCCRG_TRACE", "")
    if v == "":
        return default
    return v not in ("0", "off", "false", "no")


def trace_ring_default(default: int = 65536) -> int:
    """The ``DCCRG_TRACE_RING`` env knob: how many span events the
    in-memory trace ring holds before the oldest are dropped."""
    try:
        return max(16, int(os.environ.get("DCCRG_TRACE_RING", "")
                           or default))
    except ValueError:
        return default


def trace_file_default():
    """The ``DCCRG_TRACE_FILE`` env knob: JSONL file span events are
    appended to by :func:`flush_trace` (and at process exit). On
    multi-process meshes give each rank its own path (the events
    carry the rank id either way; a literal ``{rank}`` in the value
    is substituted with the coord rank id)."""
    return os.environ.get("DCCRG_TRACE_FILE") or None


def metrics_file_default():
    """The ``DCCRG_METRICS_FILE`` env knob: where
    :func:`maybe_export_metrics` periodically writes the Prometheus
    text exposition."""
    return os.environ.get("DCCRG_METRICS_FILE") or None


def metrics_every_default(default: float = 10.0) -> float:
    """The ``DCCRG_METRICS_EVERY`` env knob: minimum seconds between
    periodic metrics-file exports."""
    try:
        return float(os.environ.get("DCCRG_METRICS_EVERY", "") or default)
    except ValueError:
        return default


# ---------------------------------------------------------------------
# the one histogram implementation (supervise.LatencyHistogram aliases)
# ---------------------------------------------------------------------

class LogHistogram:
    """Fixed log-spaced latency buckets.

    Bucket 0 covers ``[0, BASE)`` seconds and bucket ``i >= 1`` covers
    ``[BASE * 2**(i-1), BASE * 2**i)`` (the last absorbs the upper
    tail), so the whole histogram is ~30 ints — cheap enough to update
    every step forever, yet wide enough (100 us .. ~15 hours) that a
    slowly degrading interconnect shows up as mass migrating to the
    right long before anything actually wedges."""

    BASE = 1e-4  # seconds; bucket 0 = anything below 100 us
    N_BUCKETS = 30

    def __init__(self):
        self.counts = [0] * self.N_BUCKETS
        self.total = 0
        self.max_seconds = 0.0
        self.sum_seconds = 0.0

    def record(self, seconds: float) -> None:
        seconds = max(float(seconds), 0.0)
        i = 0 if seconds < self.BASE else int(
            math.log2(seconds / self.BASE)) + 1
        self.counts[min(max(i, 0), self.N_BUCKETS - 1)] += 1
        self.total += 1
        self.sum_seconds += seconds
        self.max_seconds = max(self.max_seconds, seconds)

    def buckets(self) -> list:
        """``[(lo_seconds, hi_seconds, count)]`` for every bucket."""
        out = []
        for i, c in enumerate(self.counts):
            lo = 0.0 if i == 0 else self.BASE * (2.0 ** (i - 1))
            hi = self.BASE * (2.0 ** i)
            out.append((lo, hi, c))
        return out

    def quantile(self, q: float) -> float:
        """Upper edge of the bucket holding the q-quantile (0 when
        nothing was recorded)."""
        if self.total == 0:
            return 0.0
        target = max(1, math.ceil(q * self.total))
        seen = 0
        for lo, hi, c in self.buckets():
            seen += c
            if seen >= target:
                return hi
        return self.buckets()[-1][1]

    def summary(self) -> str:
        if self.total == 0:
            return "no steps recorded"
        return (f"{self.total} steps, p50<={self.quantile(0.5):.3g}s, "
                f"p95<={self.quantile(0.95):.3g}s, "
                f"max={self.max_seconds:.3g}s")


# ---------------------------------------------------------------------
# the metrics registry
# ---------------------------------------------------------------------

def _key(name: str, labels: dict):
    return (name, tuple(sorted(labels.items())))


class Registry:
    """Process-wide metrics store: ``{(name, labels): value}`` maps
    for counters/gauges plus :class:`LogHistogram` instances. Plain
    GIL-atomic dict updates — telemetry is best-effort by contract,
    and a lost increment under a race is preferable to a lock on the
    step path."""

    def __init__(self):
        self.counters: dict = {}
        self.gauges: dict = {}
        self.histograms: dict = {}

    def inc(self, name: str, n=1, **labels) -> None:
        k = _key(name, labels)
        self.counters[k] = self.counters.get(k, 0) + n

    def set_gauge(self, name: str, value, **labels) -> None:
        self.gauges[_key(name, labels)] = float(value)

    def observe(self, name: str, seconds, **labels) -> None:
        k = _key(name, labels)
        h = self.histograms.get(k)
        if h is None:
            h = self.histograms[k] = LogHistogram()
        h.record(seconds)

    def counter_value(self, name: str, **labels):
        return self.counters.get(_key(name, labels), 0)

    def counter_total(self, name: str, **labels) -> float:
        """Sum of every series of ``name`` whose labels include the
        given ones (e.g. all ``kind=...`` series of one job)."""
        want = set(labels.items())
        return sum(v for (n, lab), v in self.counters.items()
                   if n == name and want <= set(lab))

    def histogram(self, name: str, **labels) -> "LogHistogram | None":
        return self.histograms.get(_key(name, labels))

    def histogram_total(self, name: str, **labels) -> "LogHistogram | None":
        """Merge every series of ``name`` whose labels include the
        given ones into one :class:`LogHistogram` (the counter_total
        analogue — e.g. the p99 queue age across all per-tenant
        intake series), or None when no series matches."""
        want = set(labels.items())
        merged = None
        for (n, lab), h in list(self.histograms.items()):
            if n != name or not want <= set(lab):
                continue
            if merged is None:
                merged = LogHistogram()
            merged.counts = [a + b for a, b in
                             zip(merged.counts, h.counts)]
            merged.total += h.total
            merged.sum_seconds += h.sum_seconds
            merged.max_seconds = max(merged.max_seconds, h.max_seconds)
        return merged

    def reset(self) -> None:
        self.counters.clear()
        self.gauges.clear()
        self.histograms.clear()


_REGISTRY = Registry()


def registry() -> Registry:
    """The process-wide metrics registry."""
    return _REGISTRY


def inc(name: str, n=1, **labels) -> None:
    """Increment counter ``name`` (created on first use)."""
    _REGISTRY.inc(name, n, **labels)


def set_gauge(name: str, value, **labels) -> None:
    _REGISTRY.set_gauge(name, value, **labels)


def observe(name: str, seconds, **labels) -> None:
    """Record ``seconds`` into the log-bucketed histogram ``name``."""
    _REGISTRY.observe(name, seconds, **labels)


def _fmt_labels(lab) -> str:
    if not lab:
        return ""
    # label values are arbitrary user strings (job names): escape per
    # the exposition format or one odd name corrupts the whole file
    def esc(v):
        return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    inner = ",".join(f'{k}="{esc(v)}"' for k, v in lab)
    return "{" + inner + "}"


def dump_prometheus() -> str:
    """The registry as Prometheus text exposition: counters and gauges
    one sample per series, histograms in the standard
    ``_bucket{le=...}`` / ``_sum`` / ``_count`` cumulative form."""
    out = []
    seen = set()
    for (name, lab) in sorted(_REGISTRY.counters):
        if name not in seen:
            seen.add(name)
            out.append(f"# TYPE {name} counter")
        v = _REGISTRY.counters[(name, lab)]
        out.append(f"{name}{_fmt_labels(lab)} {v}")
    for (name, lab) in sorted(_REGISTRY.gauges):
        if name not in seen:
            seen.add(name)
            out.append(f"# TYPE {name} gauge")
        out.append(f"{name}{_fmt_labels(lab)} "
                   f"{_REGISTRY.gauges[(name, lab)]:g}")
    for (name, lab) in sorted(_REGISTRY.histograms):
        if name not in seen:
            seen.add(name)
            out.append(f"# TYPE {name} histogram")
        h = _REGISTRY.histograms[(name, lab)]
        cum = 0
        for _lo, hi, c in h.buckets():
            cum += c
            le = _fmt_labels(lab + (("le", f"{hi:g}"),))
            out.append(f"{name}_bucket{le} {cum}")
        le = _fmt_labels(lab + (("le", "+Inf"),))
        out.append(f"{name}_bucket{le} {h.total}")
        out.append(f"{name}_sum{_fmt_labels(lab)} {h.sum_seconds:.9g}")
        out.append(f"{name}_count{_fmt_labels(lab)} {h.total}")
    return "\n".join(out) + ("\n" if out else "")


# ---------------------------------------------------------------------
# the span tracer
# ---------------------------------------------------------------------

class _NullSpan:
    """The shared tracing-off no-op: entering/exiting records nothing
    and allocates nothing (one module-level instance, ever)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()

#: tracing state, mutable at runtime via :func:`configure`. A dict so
#: instrumented modules can ``from . import telemetry`` once and still
#: observe later reconfiguration.
_TRACE = {
    "on": trace_enabled_default(),
    "ring": collections.deque(maxlen=trace_ring_default()),
    "dropped": 0,
}

_TLS = threading.local()
_RANK_CACHE = [None]  # resolved lazily; None until jax can answer


def _rank() -> int:
    """The ``coord`` rank id events are tagged with — resolved lazily
    from jax.distributed's OWN state (never ``jax.process_index()``:
    that call side-effectfully initializes the local backend and
    answers 0 before ``jax.distributed.initialize`` has run, which
    would cache the wrong rank for the process lifetime) and cached
    once the distributed service has actually assigned one. Plain
    single-process runs stay uncached and report 0."""
    if _RANK_CACHE[0] is not None:
        return _RANK_CACHE[0]
    import sys

    if "jax" not in sys.modules:
        return 0
    try:
        from jax._src import distributed

        pid = distributed.global_state.process_id
    except Exception:  # noqa: BLE001 - private API may move
        return 0
    if pid is None:
        return 0  # not (yet) distributed: do not cache
    _RANK_CACHE[0] = int(pid)
    return _RANK_CACHE[0]


def _stack() -> list:
    s = getattr(_TLS, "stack", None)
    if s is None:
        s = _TLS.stack = []
    return s


def _ambient_tags() -> dict:
    t = getattr(_TLS, "tags", None)
    return t if t else {}


def _ring_append(ev) -> None:
    """Append one event; a full ring evicts its oldest event, and the
    eviction is COUNTED (``dccrg_trace_dropped_total`` + the
    flush-time log) so a truncated trace never reads as complete."""
    ring = _TRACE["ring"]
    if len(ring) == ring.maxlen:
        _TRACE["dropped"] += 1
        _REGISTRY.inc("dccrg_trace_dropped_total")
    ring.append(ev)


class _Span:
    __slots__ = ("name", "tags", "t_wall", "t0")

    def __init__(self, name, tags):
        self.name = name
        self.tags = tags

    def __enter__(self):
        _stack().append(self.name)
        self.t_wall = time.time()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self.t0
        stack = _stack()
        stack.pop()
        ev = {
            "name": self.name,
            "ts": self.t_wall,
            "dur": dur,
            "rank": _rank(),
            "depth": len(stack),
        }
        if stack:
            ev["parent"] = stack[-1]
        amb = _ambient_tags()
        if amb:
            ev.update(amb)
        if self.tags:
            ev.update(self.tags)
        _ring_append(ev)
        return False


def span(name: str, tags: "dict | None" = None):
    """A tracing span: ``with telemetry.span("grid.step"): ...``
    records one ring event (name, wall-clock anchor, monotonic
    duration, rank, nesting depth/parent, tags) on exit. With tracing
    off this returns the shared no-op singleton — the hot-path
    contract is ONE dict lookup and no allocation, so instrumented
    step paths cost nothing in production. ``tags`` is an optional
    plain dict (not kwargs, so the off path never builds one)."""
    if not _TRACE["on"]:
        return _NULL_SPAN
    return _Span(name, tags)


def record_span(name: str, seconds: float,
                tags: "dict | None" = None) -> None:
    """Record an already-measured interval as a span event (the
    after-the-fact form for callers that timed themselves, e.g. the
    hybrid plan builder's phase marks). No-op with tracing off."""
    if not _TRACE["on"]:
        return
    ev = {"name": name, "ts": time.time() - seconds, "dur": float(seconds),
          "rank": _rank(), "depth": len(_stack())}
    amb = _ambient_tags()
    if amb:
        ev.update(amb)
    if tags:
        ev.update(tags)
    _ring_append(ev)


class _TagScope:
    __slots__ = ("kv", "prev")

    def __init__(self, kv):
        self.kv = kv

    def __enter__(self):
        self.prev = getattr(_TLS, "tags", None)
        merged = dict(self.prev) if self.prev else {}
        merged.update(self.kv)
        _TLS.tags = merged
        return self

    def __exit__(self, *exc):
        _TLS.tags = self.prev
        return False


def traced(name: str, tags: "dict | None" = None,
           counter: "str | None" = None):
    """Decorator form of :func:`span` for whole-function boundaries
    (checkpoint save/load/GC phases). With tracing off the wrapper is
    one dict lookup and a tail call. ``counter`` additionally bumps a
    registry counter on every call, traced or not (the metrics side
    is always on)."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            if counter is not None:
                _REGISTRY.inc(counter)
            if not _TRACE["on"]:
                return fn(*a, **kw)
            with _Span(name, tags):
                return fn(*a, **kw)
        return wrapper
    return deco


def tags(**kv):
    """Thread-local ambient tags merged into every span recorded
    inside the context (the fleet layer tags checkpoint saves with the
    owning ``job=``). No-op singleton with tracing off."""
    if not _TRACE["on"]:
        return _NULL_SPAN
    return _TagScope(kv)


def trace_enabled() -> bool:
    return bool(_TRACE["on"])


def events() -> list:
    """Snapshot of the in-memory trace ring (oldest first)."""
    return list(_TRACE["ring"])


def clear_trace() -> None:
    _TRACE["ring"].clear()
    _TRACE["dropped"] = 0


def configure(trace=None, ring=None) -> None:
    """Runtime (re)configuration: ``trace=True/False`` toggles span
    recording; ``trace=None`` re-reads ``DCCRG_TRACE``. ``ring``
    resizes the event ring (dropping held events)."""
    if ring is not None:
        _TRACE["ring"] = collections.deque(_TRACE["ring"],
                                           maxlen=max(16, int(ring)))
    _TRACE["on"] = (trace_enabled_default() if trace is None
                    else bool(trace))


# ---------------------------------------------------------------------
# exporters — strictly best-effort, never raise
# ---------------------------------------------------------------------

def _best_effort_write(path: str, payload: str, append: bool) -> bool:
    """One exporter write. Failures (real I/O errors or the injected
    ``telemetry.export`` fault) are counted and swallowed: telemetry
    must NEVER trip, roll back or kill the run it observes."""
    try:
        faults.fire("telemetry.export", path=path)
        with open(path, "a" if append else "w") as f:
            f.write(payload)
        return True
    except Exception as e:  # noqa: BLE001 - best-effort by contract
        _REGISTRY.inc("dccrg_telemetry_export_errors_total")
        logger.debug("telemetry export to %s failed (%s); dropped",
                     path, e)
        return False


def flush_trace(path: "str | None" = None) -> int:
    """Append every ring event to ``path`` (default
    ``DCCRG_TRACE_FILE``, with ``{rank}`` substituted) as JSONL and
    clear the ring. Returns the number of events written (0 when no
    sink is configured or the write failed — the events are dropped
    either way, the ring must not grow into the run)."""
    if path is None:
        path = trace_file_default()
    ring = _TRACE["ring"]
    if not ring:
        return 0
    evs = list(ring)
    ring.clear()
    if _TRACE["dropped"]:
        logger.warning(
            "trace ring overflowed: %d span event(s) were dropped "
            "before this flush (raise DCCRG_TRACE_RING or flush more "
            "often)", _TRACE["dropped"])
        _TRACE["dropped"] = 0
    if path is None:
        return 0
    path = path.replace("{rank}", str(_rank()))
    payload = "".join(json.dumps(e, sort_keys=True) + "\n" for e in evs)
    return len(evs) if _best_effort_write(path, payload, append=True) \
        else 0


def read_trace(path: str) -> list:
    """Parse one JSONL trace file back into event dicts (lines that
    fail to parse — a torn tail from a killed run — are skipped)."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def merge_traces(paths) -> list:
    """Merge per-rank JSONL trace files into one timeline ordered by
    wall-clock ``ts`` (ties broken by rank then name — deterministic).
    The events already carry their rank tag, so the merged list IS the
    cross-rank story of one run."""
    evs = []
    for p in paths:
        evs.extend(read_trace(p))
    evs.sort(key=lambda e: (e.get("ts", 0.0), e.get("rank", 0),
                            e.get("name", "")))
    return evs


_METRICS_STATE = {"last": None}


def export_metrics(path: "str | None" = None) -> bool:
    """Write :func:`dump_prometheus` to ``path`` (default
    ``DCCRG_METRICS_FILE``). Best-effort; returns success."""
    if path is None:
        path = metrics_file_default()
    if path is None:
        return False
    return _best_effort_write(path, dump_prometheus(), append=False)


def maybe_export_metrics(now: "float | None" = None) -> bool:
    """Periodic metrics export: writes the exposition to
    ``DCCRG_METRICS_FILE`` at most every ``DCCRG_METRICS_EVERY``
    seconds (monotonic clock). The run/scheduler loops call this at
    their boundaries; without the env knob it is one None check."""
    path = metrics_file_default()
    if path is None:
        return False
    t = time.monotonic() if now is None else float(now)
    last = _METRICS_STATE["last"]
    if last is not None and t - last < metrics_every_default():
        return False
    _METRICS_STATE["last"] = t
    return export_metrics(path)


@atexit.register
def _flush_at_exit() -> None:  # pragma: no cover - process teardown
    try:
        if trace_file_default():
            flush_trace()
        if metrics_file_default():
            export_metrics()
    except Exception:  # noqa: BLE001 - never fail interpreter exit
        pass


# ---------------------------------------------------------------------
# trace analysis (shared by the CLI and the tests)
# ---------------------------------------------------------------------

def span_stats(evs) -> dict:
    """Per-span-name aggregates of a trace: ``{name: {count,
    total_s, p50_s, p99_s, max_s}}`` (log-bucket quantiles)."""
    hists: dict = {}
    for e in evs:
        h = hists.get(e.get("name"))
        if h is None:
            h = hists[e.get("name")] = LogHistogram()
        h.record(float(e.get("dur", 0.0)))
    return {n: {"count": h.total, "total_s": h.sum_seconds,
                "p50_s": h.quantile(0.5), "p99_s": h.quantile(0.99),
                "max_s": h.max_seconds}
            for n, h in sorted(hists.items())}


def root_coverage(evs, wall_s: float) -> float:
    """Fraction of ``wall_s`` accounted for by depth-0 spans — the
    "where did the step wall-clock go" acceptance metric (nested spans
    excluded so nothing double-counts)."""
    covered = sum(float(e.get("dur", 0.0)) for e in evs
                  if int(e.get("depth", 0)) == 0)
    return covered / wall_s if wall_s > 0 else 0.0


# ---------------------------------------------------------------------
# Prometheus exposition read-back: the histogram half of `summary`
# ---------------------------------------------------------------------

_PROM_LINE = re.compile(r"^([A-Za-z_:][A-Za-z0-9_:]*)"
                        r"(?:\{(.*)\})?\s(\S+)$")
_PROM_LABEL = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)='
                         r'"((?:[^"\\]|\\.)*)"')


def _unescape_label(v: str) -> str:
    # single pass: sequential str.replace would corrupt values like
    # a\n-after-backslash (the \\ must not feed the \n rule)
    return re.sub(r"\\(.)",
                  lambda m: "\n" if m.group(1) == "n" else m.group(1),
                  v)


def parse_prometheus_histograms(text: str) -> dict:
    """Parse the histogram series back out of a Prometheus text
    exposition (a ``DCCRG_METRICS_FILE``): ``{(name, labels):
    {"count", "sum", "buckets": [(le, cumulative)]}}`` with the
    ``le`` label lifted out of the labels and ``+Inf`` mapped to
    ``math.inf``. Counters/gauges are ignored (they read directly);
    this is the read-back path for the numbers the registry's
    :class:`LogHistogram` wrote out."""
    series: dict = {}

    def ent(name, labels):
        key = (name, tuple(sorted(labels.items())))
        return series.setdefault(
            key, {"count": 0, "sum": 0.0, "buckets": []})

    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if m is None:
            continue
        name, labstr, sval = m.groups()
        try:
            val = float(sval)
        except ValueError:
            continue
        labels = {k: _unescape_label(v)
                  for k, v in _PROM_LABEL.findall(labstr or "")}
        if name.endswith("_bucket") and "le" in labels:
            le = labels.pop("le")
            ent(name[:-len("_bucket")], labels)["buckets"].append(
                (math.inf if le in ("+Inf", "+inf", "inf") else
                 float(le), val))
        elif name.endswith("_sum"):
            ent(name[:-len("_sum")], labels)["sum"] = val
        elif name.endswith("_count"):
            ent(name[:-len("_count")], labels)["count"] = int(val)
    out = {}
    for key, s in series.items():
        if not s["buckets"]:
            continue  # a counter that merely ends in _sum/_count
        s["buckets"].sort(key=lambda b: b[0])
        out[key] = s
    return out


def merge_prometheus_histograms(into: dict, more: dict) -> dict:
    """Accumulate one :func:`parse_prometheus_histograms` result into
    another IN PLACE (and return it): same-keyed series SUM their
    counts, sums and per-``le`` cumulative bucket counts — the
    correct merge for per-rank metrics files of one run (a plain
    dict update would silently keep only the last rank's series)."""
    for key, s in more.items():
        have = into.get(key)
        if have is None:
            into[key] = {"count": s["count"], "sum": s["sum"],
                         "buckets": list(s["buckets"])}
            continue
        have["count"] += s["count"]
        have["sum"] += s["sum"]
        by_le = dict(have["buckets"])
        for le, cum in s["buckets"]:
            by_le[le] = by_le.get(le, 0.0) + cum
        have["buckets"] = sorted(by_le.items(), key=lambda b: b[0])
    return into


def _bucket_quantile(buckets, total: int, q: float):
    """Upper bucket edge holding the q-quantile of a cumulative
    ``[(le, cum)]`` list (the same convention as
    :meth:`LogHistogram.quantile`); None when empty/unbounded."""
    if total <= 0:
        return 0.0
    target = max(1, math.ceil(q * total))
    for le, cum in buckets:
        if cum >= target:
            return None if le == math.inf else le
    le = buckets[-1][0]
    return None if le == math.inf else le


def histogram_stats(hists=None) -> dict:
    """Per-histogram ``{series: {count, sum_s, p50_s, p99_s}}`` — the
    same numbers the autopilot controller acts on, readable by
    operators. ``hists=None`` aggregates the LIVE registry histograms;
    otherwise pass a :func:`parse_prometheus_histograms` result (the
    offline ``summary`` CLI path over a metrics file)."""
    out = {}
    if hists is None:
        for (name, lab), h in sorted(_REGISTRY.histograms.items()):
            out[name + _fmt_labels(lab)] = {
                "count": h.total, "sum_s": h.sum_seconds,
                "p50_s": h.quantile(0.5), "p99_s": h.quantile(0.99),
                "max_s": h.max_seconds}
        return out
    for (name, lab), s in sorted(hists.items()):
        out[name + _fmt_labels(lab)] = {
            "count": s["count"], "sum_s": s["sum"],
            "p50_s": _bucket_quantile(s["buckets"], s["count"], 0.5),
            "p99_s": _bucket_quantile(s["buckets"], s["count"], 0.99)}
    return out


# ---------------------------------------------------------------------
# CLI: python -m dccrg_tpu.telemetry merge|summary ...
# ---------------------------------------------------------------------

def _looks_like_prometheus(path: str) -> bool:
    """Sniff a summary input: a Prometheus exposition (a
    ``DCCRG_METRICS_FILE``) vs a JSONL trace. Traces are JSON object
    lines; expositions carry ``# TYPE`` comments / bare samples."""
    try:
        with open(path) as f:
            head = f.read(4096)
    except OSError:
        return False
    for line in head.splitlines():
        line = line.strip()
        if not line:
            continue
        return not line.startswith("{")
    return False


def _main(argv=None) -> int:
    """``python -m dccrg_tpu.telemetry merge <trace.jsonl>...`` prints
    the rank-merged timeline as JSONL; ``summary <file>...`` prints
    per-span aggregates (count, total, p50/p99/max) of JSONL traces
    AND per-histogram p50/p99 of Prometheus metrics files
    (``DCCRG_METRICS_FILE`` expositions — sniffed apart
    automatically), so operators can read the same latency numbers
    the autopilot controller acts on. Works on per-rank files of one
    run (the events carry rank tags) without importing jax."""
    import argparse

    ap = argparse.ArgumentParser(prog="python -m dccrg_tpu.telemetry",
                                 description=_main.__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    m = sub.add_parser("merge", help="merge per-rank JSONL traces into "
                                     "one ts-ordered timeline")
    m.add_argument("files", nargs="+")
    s = sub.add_parser("summary", help="per-span-name aggregates of "
                                       "traces and per-histogram "
                                       "p50/p99 of metrics files")
    s.add_argument("files", nargs="+")
    args = ap.parse_args(argv)
    if args.cmd == "merge":
        for e in merge_traces(args.files):
            print(json.dumps(e, sort_keys=True))
        return 0
    prom_files = [p for p in args.files if _looks_like_prometheus(p)]
    trace_files = [p for p in args.files if p not in prom_files]
    evs = merge_traces(trace_files)
    out = {"events": len(evs),
           "ranks": sorted({e.get("rank", 0) for e in evs}),
           "spans": span_stats(evs)}
    if prom_files:
        hists: dict = {}
        for p in prom_files:
            try:
                with open(p) as f:
                    merge_prometheus_histograms(
                        hists, parse_prometheus_histograms(f.read()))
            except OSError:
                continue
        out["histograms"] = histogram_stats(hists)
    print(json.dumps(out, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via CLI
    import sys

    sys.exit(_main())
