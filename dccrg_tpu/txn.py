"""Transactional grid mutations.

The reference guards every structural mutation (refinement commit,
induced 2:1 balancing, load balancing) with ``#ifdef DEBUG`` invariant
checkers because a half-applied mutation silently corrupts neighbor
lists, and every later halo exchange then moves garbage. This module
makes the mutation paths of :class:`~dccrg_tpu.grid.Grid` **atomic**:

    with grid_transaction(grid, op="stop_refining"):
        ... mutate cells / owners / plan / field arrays ...

- On entry the minimal mutable structural state is snapshotted: the
  plan reference (plans are replaced wholesale, never edited in
  place), the field-array dict (jax arrays are immutable, so the
  snapshot is a dict of references), the AMR request sets, the staged
  balance state, pins/weights (``resolve_adaptation`` mutates them in
  place for inheritance), capacity memos, and the hybrid builder's
  epoch-reuse cache (``build_hybrid_plan`` swaps its contents in
  place).
- Any exception — including injected :class:`~dccrg_tpu.faults`
  faults — restores every snapshotted attribute and re-raises as
  :class:`MutationAbortedError` with the original failure as
  ``__cause__``. The grid is then bitwise identical to its
  pre-mutation state (pinned by tests/test_txn.py via checkpoint-bytes
  comparison) and the same mutation can simply be retried: the
  request sets were part of the snapshot.
- On successful commit, when ``DCCRG_DEBUG=1`` (or
  ``validate=True``), ``verify_all`` runs against the NEW state; a
  broken invariant rolls back too and raises
  :class:`GridInvariantError` naming the offending cells — the
  runtime equivalent of XLA running HloVerifier after every transform.

Transactions are reentrant: the composite ``balance_load`` opens one
transaction and its three stages (each transactional on its own for
the staged multi-phase API) join it, so a fault anywhere inside rolls
back the whole balance.

Only HOST state is snapshotted, and only by reference or one-level
copy — no field payload is copied, so a transaction costs O(#cells
dict entries), not O(data). That relies on two properties the rest of
the codebase maintains: jax arrays are immutable (a "write" installs a
new array into ``grid.data``), and plan/numpy structure arrays are
rebuilt, never edited in place, by every mutation path.
"""

from __future__ import annotations

import os
import tempfile
from contextlib import contextmanager

from . import faults as faults_mod
from . import verify as verify_mod


class MutationError(RuntimeError):
    """Base of the mutation-boundary error hierarchy. ``cells`` names
    the offending cell ids when known (empty tuple otherwise)."""

    def __init__(self, msg: str, cells=()):
        self.cells = tuple(int(c) for c in cells)
        super().__init__(msg + verify_mod.format_cells(self.cells))


class MutationAbortedError(MutationError):
    """A structural mutation failed mid-flight and the grid was rolled
    back to its pre-mutation state. ``op`` names the mutation, the
    original failure is ``__cause__``; the pending requests survived
    the rollback, so the same mutation can be retried."""

    def __init__(self, op: str, cause: BaseException, cells=()):
        self.op = op
        super().__init__(
            f"{op} aborted, grid rolled back "
            f"({type(cause).__name__}: {cause})", cells=cells)


class GridInvariantError(MutationError):
    """Post-commit validation found a broken grid invariant; the
    commit was rolled back. The underlying
    :class:`~dccrg_tpu.verify.VerificationError` is ``__cause__``."""

    def __init__(self, op: str, cause: BaseException, cells=()):
        self.op = op
        super().__init__(
            f"{op} violated a grid invariant, commit rolled back "
            f"({cause})", cells=cells)


class CrossRankAbortedError(MutationAbortedError):
    """A DISTRIBUTED structural mutation aborted on this rank. The
    local half is the inherited contract: the grid — request sets
    included — is bitwise its pre-mutation state and the mutation can
    be retried. The distributed half already happened by the time this
    propagates: the abort was ANNOUNCED to every peer inside the same
    collective commit (the ``on_abort`` hook posts the abort marker
    their fenced barriers fast-abort on), so the whole fleet rolls
    back together instead of the survivors waiting out a timeout.
    ``rank`` names the aborting rank."""

    def __init__(self, op: str, cause: BaseException, rank: int = -1,
                 cells=()):
        self.rank = int(rank)
        super().__init__(op, cause, cells=cells)


@contextmanager
def cross_rank_transaction(grid, op: str = "distributed_mutation", *,
                           rank: int = -1, on_abort=None, validate=None):
    """:func:`grid_transaction` plus distributed rollback: any failure
    rolls this rank back bitwise (inherited) and then invokes
    ``on_abort(error)`` — the distributed-AMR commit posts its abort
    marker there, so peers blocked in the round's
    :func:`~dccrg_tpu.coord.kv_barrier` / proposal collects abort
    immediately instead of burning their deadline. Re-raises as
    :class:`CrossRankAbortedError`.

    Two failure classes deliberately bypass the announcement: an
    :class:`~dccrg_tpu.faults.InjectedRankDeath` (a kill -9 cannot
    post markers — peers must convict it by lease/timeout, which is
    the invariant under test) and ``BaseException`` (interpreter
    teardown)."""
    try:
        with grid_transaction(grid, op=op, validate=validate):
            yield
    except MutationError as e:
        if on_abort is not None:
            try:
                on_abort(e)
            except Exception:  # noqa: BLE001 - announcing is best-effort
                pass
        if isinstance(e, CrossRankAbortedError):
            raise
        cause = e.__cause__ if e.__cause__ is not None else e
        raise CrossRankAbortedError(
            op, cause, rank=rank, cells=e.cells) from cause


_MISSING = object()

# Attributes whose values are REPLACED wholesale by the mutation paths
# (restore = re-assign the old reference).
_REF_ATTRS = (
    "plan",
    "_pending_owner",
    "_pending_changed_cells",
    "_cells_epoch",
    "_ckpt_epoch",
    "_cut_edges",
    "_plan_gather_mode",
    "_removed_cells",
    "_new_cells",
    "_unrefined_parents",
)

# Dict attributes mutated in place — item assignment, or clear+update
# (``_hybrid_reuse``); snapshot = one-level copy. Values are never
# edited in place (jax arrays / rebuilt numpy arrays / fresh tuples).
_DICT_ATTRS = (
    "data",
    "_removed_data",
    "_staged_balance",
    "_pins",
    "_weights",
    "_cap_memo",
    "_balance_added",
    "_balance_removed",
    "_cell_item_values",
    "_neighbor_item_values",
    "_hybrid_reuse",
)

# Set attributes (the AMR request queues) cleared by the commit, plus
# the delta-checkpoint dirty-field set (mutated via .update; its None
# sentinel — everything dirty — passes through the isinstance guard).
_SET_ATTRS = ("_refines", "_unrefines", "_dont_refines",
              "_dont_unrefines", "_ckpt_dirty")


def snapshot_state(grid) -> dict:
    """Capture the minimal mutable structural state (see module
    docstring). O(host dict/set sizes); no device data is copied."""
    snap = {}
    for name in _REF_ATTRS:
        snap[name] = getattr(grid, name, _MISSING)
    for name in _DICT_ATTRS:
        val = getattr(grid, name, _MISSING)
        snap[name] = dict(val) if isinstance(val, dict) else val
    for name in _SET_ATTRS:
        val = getattr(grid, name, _MISSING)
        snap[name] = set(val) if isinstance(val, set) else val
    return snap


def restore_state(grid, snap: dict) -> None:
    """Reinstall a :func:`snapshot_state` capture. Dict/set attributes
    get fresh copies so a snapshot can restore more than once."""
    for name in _REF_ATTRS:
        _put(grid, name, snap[name])
    for name in _DICT_ATTRS:
        val = snap[name]
        _put(grid, name, dict(val) if isinstance(val, dict) else val)
    for name in _SET_ATTRS:
        val = snap[name]
        _put(grid, name, set(val) if isinstance(val, set) else val)


def _put(grid, name, val):
    if val is _MISSING:
        if hasattr(grid, name):
            delattr(grid, name)
    else:
        setattr(grid, name, val)


def _discard_bg(grid) -> None:
    """Rollback hook: drop a background plan build submitted INSIDE
    the aborted transaction (any build pending at entry was installed
    by the entry barrier). Waits for the worker to stop touching the
    arena; the orphaned generation's buffers are reclaimed by the next
    build's ``arena.begin`` — the live plan's and the snapshot's
    (restored) tables were protected the whole time, pinned by
    tests/test_bgrecommit.py."""
    if getattr(grid, "_bg_build", None) is not None:
        grid.bg_discard()


@contextmanager
def grid_transaction(grid, op: str = "mutation", validate=None):
    """Run a structural mutation atomically (see module docstring).

    ``validate=None`` validates post-commit iff the grid runs in
    DEBUG mode (``DCCRG_DEBUG=1``); ``True``/``False`` force it.
    Reentrant: a transaction opened while another is active on the
    same grid joins it — rollback and validation belong to the
    outermost one."""
    if getattr(grid, "_txn_depth", 0):
        grid._txn_depth += 1
        try:
            yield
        finally:
            grid._txn_depth -= 1
        return

    # background-recommit barrier (DCCRG_BG_RECOMMIT): a pending
    # background plan build installs BEFORE the snapshot — the mutation
    # must observe (and a rollback must restore) the final structure
    # epoch, and no worker may be writing arena tables while this
    # mutation rebuilds them. The install wraps itself in its own
    # (outermost, completed here) transaction.
    if getattr(grid, "_bg_build", None) is not None:
        grid.bg_install(wait=True)

    snap = snapshot_state(grid)
    grid._txn_depth = 1
    # the rollback target plan: the hybrid builder's PlanArena keeps
    # its table buffers protected for the transaction's duration, so a
    # failed rebuild can never scribble on tables a rollback restores
    _snap_plan = snap.get("plan")
    grid._txn_plan = None if _snap_plan is _MISSING else _snap_plan
    try:
        try:
            yield
        except Exception as e:
            _discard_bg(grid)
            restore_state(grid, snap)
            if isinstance(e, faults_mod.InjectedRankDeath):
                # a simulated kill -9: the process is about to die (the
                # mp harness hard-exits the OS process on it), so keep
                # the type — peers key their recovery on the DEATH, not
                # on an abort this corpse could never announce. The
                # rollback above still runs: a consistent grid costs
                # nothing and the in-process fakes assert against it.
                raise
            raise MutationAbortedError(
                op, e, cells=tuple(getattr(e, "cells", ()) or ())) from e
        except BaseException:
            # KeyboardInterrupt & co.: still leave a consistent grid,
            # but re-raise untouched
            _discard_bg(grid)
            restore_state(grid, snap)
            raise
        check = (getattr(grid, "_debug", False)
                 if validate is None else validate)
        if check:
            try:
                # pins are requests until a balance applies them; the
                # balance paths check placement in their own DEBUG hook
                verify_mod.verify_all(grid, check_pins=False)
            except Exception as e:
                # a VerificationError is a diagnosed invariant break; a
                # verifier CRASHING on malformed state is the same
                # verdict with less detail — either way the commit is
                # suspect, so all-or-nothing demands the rollback
                _discard_bg(grid)
                restore_state(grid, snap)
                raise GridInvariantError(
                    op, e, cells=getattr(e, "cells", ())) from e
    finally:
        grid._txn_depth = 0
        grid._txn_plan = None


def grid_state_bytes(grid, header: bytes = b"") -> bytes:
    """The grid's exact ``.dc`` checkpoint bytes (structure metadata +
    every field payload) — the canonical fingerprint the atomicity
    tests and the fuzzer compare to assert a rolled-back mutation left
    the grid bitwise identical to its pre-mutation state."""
    fd, path = tempfile.mkstemp(suffix=".dc", prefix="dccrg_txn_")
    os.close(fd)
    try:
        grid.save_grid_data(path, header)
        with open(path, "rb") as f:
            return f.read()
    finally:
        os.unlink(path)
