"""Preemption-aware run supervision: the layer that drives the
resilience stack across a run's whole lifecycle.

On preemptible accelerator fleets the dominant failure mode is not
corruption (the CRC sidecars catch that), torn mutations (txn.py) or a
rank dying mid-save (the two-phase commit) — it is **preemption**: the
scheduler SIGTERMs the job with a short grace window, and a hung
collective or a compiled step quietly eats that window. The reference
dccrg survives week-long Vlasiator campaigns only through its MPI-IO
checkpoint/restart; this module is that restart capability lifted to
preemptible hardware, wrapped around
:class:`~dccrg_tpu.resilience.ResilientRunner`:

**Preemption handling** — :class:`SupervisedRunner` installs
SIGTERM/SIGINT handlers that set a flag; the flag is polled at step
boundaries and put through the per-step trip consensus
(``resilience._TRIP_INTERRUPT``, outranked by any real trip), so on a
multi-process mesh EVERY rank observes the preemption together even
though only one received the signal. All ranks then take an
**emergency checkpoint** — the ordinary atomic save, routed through
the two-phase multi-process path when ``jax.process_count() > 1``,
with the ``coord.barrier`` timeout shortened to a quarter of the
grace window (``DCCRG_PREEMPT_GRACE``) so ONE dead peer cannot eat
all of it — verify its CRC, and surface :class:`PreemptedError`
carrying the distinct resumable exit code :data:`RESUMABLE_EXIT`
(``EX_TEMPFAIL``, 75: 'reschedule me').

**Step-hang watchdog** — with ``DCCRG_STEP_TIMEOUT`` (or
``step_timeout=``) set, each dispatched step runs under a deadline
thread (``jax.block_until_ready`` included, so async dispatch cannot
hide a wedged collective) and raises a typed
:class:`StepTimeoutError` naming the step instead of blocking
forever. Transient dispatch errors (the ``UNAVAILABLE`` /
``DEADLINE_EXCEEDED`` class, or injected
:class:`~dccrg_tpu.faults.InjectedDispatchError`) retry with bounded
exponential backoff WITHOUT tripping a rollback. Unset, the step path
is byte-for-byte today's (no thread, no extra sync).

**Incremental checkpoints + auto-resume + retention GC** — periodic
checkpoints land in a :class:`CheckpointStore` as one numbered file
per step: full keyframes (``ckpt_00000042.dc``) and dirty-field
DELTAS (``.dcd``) that save only the fields whose bytes changed since
the previous save, chained through sidecar parent links
(:meth:`CheckpointStore.save`; ``DCCRG_KEYFRAME_EVERY`` keyframe
cadence, ``DCCRG_DELTA=0`` opt-out; structural mutations force a
keyframe). :func:`resume_latest` scans such a directory and picks the
newest entry that passes verification — CHAIN-AWARE for deltas: the
whole keyframe+delta chain is verified and replayed, bitwise
identical to an uninterrupted run, with typed
:class:`~dccrg_tpu.resilience.DeltaChainError` fallback to the last
verifying prefix — falling back to older entries and — last — to a
salvage load of the newest salvageable file. :func:`gc_checkpoints`
applies a keep-last-K (``DCCRG_KEEP_LAST``) / keep-every-N retention
policy after each save, chain-aware: whole chains only, it can NEVER
orphan a delta nor delete the only verifying chain (and refuses to
prune at all when nothing verifies), and it sweeps stale
save/salvage/chain-scratch temp files of dead runs
(:func:`dccrg_tpu.checkpoint.stale_temp_files`).

Every path is pinned deterministically by fault injection
(:meth:`~dccrg_tpu.faults.FaultPlan.preempt_signal`,
:meth:`~dccrg_tpu.faults.FaultPlan.step_hang`,
:meth:`~dccrg_tpu.faults.FaultPlan.dispatch_error`;
tests/test_supervise.py), and by a REAL ``kill -TERM`` of one rank in
the multi-process harness (tests/mp_harness.py, scenario
``preempt``). See also ``examples/preemptible_run.py`` and
``python -m dccrg_tpu.resilience verify|gc``.
"""

from __future__ import annotations

import logging
import math
import os
import re
import signal
import threading
import time
from contextlib import contextmanager, nullcontext
from dataclasses import dataclass, field as dataclass_field

from . import background
from . import checkpoint as checkpoint_mod
from . import coord, faults, resilience, telemetry

logger = logging.getLogger("dccrg_tpu.supervise")

#: The distinct exit code of a preempted-but-resumable run —
#: EX_TEMPFAIL (75), the sysexits convention schedulers read as
#: "transient failure, reschedule me". A supervised job that exits
#: with it left a CRC-verified checkpoint behind; restart it and call
#: :func:`resume_latest`.
RESUMABLE_EXIT = 75


class StepTimeoutError(RuntimeError):
    """A supervised deadline expired: the dispatched step (or the
    emergency checkpoint — ``what`` says which) did not complete
    within its bound. The signature of a wedged collective or a dead
    accelerator tunnel mid-dispatch — the one failure that otherwise
    blocks forever and silently eats a preemption grace window.
    ``step`` names the step for step deadlines."""

    def __init__(self, what, timeout, step=None):
        super().__init__(
            f"{what} did not complete within {timeout:g}s (wedged "
            "collective, dead accelerator tunnel, or a stuck host "
            "callback); the worker thread is abandoned — this state "
            "is not recoverable in-process, only reportable")
        self.what = str(what)
        self.timeout = float(timeout)
        self.step = step


class PreemptedError(RuntimeError):
    """The supervised run stopped at a step boundary because a
    preemption signal arrived (or a faked
    :meth:`~dccrg_tpu.faults.FaultPlan.preempt_signal` fired).
    ``checkpoint`` is the CRC-verified emergency checkpoint — or,
    when the emergency save could not finish inside the grace window
    (``clean=False``), the last periodic one; either way the run is
    resumable from it via :func:`resume_latest`. ``exit_code`` is
    :data:`RESUMABLE_EXIT`."""

    exit_code = RESUMABLE_EXIT

    def __init__(self, step, checkpoint=None, clean=True):
        super().__init__(
            f"preempted at the boundary after step {step}; resumable "
            f"from {checkpoint or '<no checkpoint>'} (exit code "
            f"{RESUMABLE_EXIT})")
        self.step = int(step)
        self.checkpoint = checkpoint
        self.clean = bool(clean)


# ---------------------------------------------------------------------
# env knobs
# ---------------------------------------------------------------------

def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def step_timeout_default(default: float = 0.0) -> float:
    """The ``DCCRG_STEP_TIMEOUT`` env knob: seconds before a
    dispatched step is declared wedged (0 = watchdog off; the step
    path then has no thread and no extra device sync)."""
    return _env_float("DCCRG_STEP_TIMEOUT", default)


def ckpt_seconds_default(default: float = 0.0) -> float:
    """The ``DCCRG_CKPT_SECONDS`` env knob: wall-clock checkpoint
    cadence in seconds (monotonic clock, evaluated at step boundaries
    only — never mid-step), for runs whose step times are too uneven
    for a step-count cadence. 0 keeps the step-count cadence alone."""
    return _env_float("DCCRG_CKPT_SECONDS", default)


def preempt_grace(default: float = 30.0) -> float:
    """The ``DCCRG_PREEMPT_GRACE`` env knob: seconds the emergency
    checkpoint may spend after a preemption signal — set it below the
    scheduler's kill grace. Barriers inside the save get a quarter of
    it each, so one dead peer cannot eat the whole window."""
    return _env_float("DCCRG_PREEMPT_GRACE", default)


def keep_last_default(default: int = 3) -> int:
    """The ``DCCRG_KEEP_LAST`` env knob: how many newest checkpoints
    retention GC keeps (minimum 1)."""
    try:
        return max(1, int(os.environ.get("DCCRG_KEEP_LAST", "")
                          or default))
    except ValueError:
        return default


def delta_enabled(default: bool = True) -> bool:
    """The ``DCCRG_DELTA`` env knob: ``0`` opts out of incremental
    (dirty-field delta) periodic saves — every save is then a full
    keyframe, byte-for-byte the pre-delta behavior."""
    v = os.environ.get("DCCRG_DELTA", "")
    if v == "":
        return default
    return v != "0"


def keyframe_every_default(default: int = 8) -> int:
    """The ``DCCRG_KEYFRAME_EVERY`` env knob: every K-th periodic save
    is a full keyframe, so a delta chain holds at most K-1 deltas
    (minimum 1 = every save a keyframe). Long chains save bytes but
    lengthen resume (each link replays) and widen the blast radius of
    a lost link — the retention GC never splits a chain either way."""
    try:
        return max(1, int(os.environ.get("DCCRG_KEYFRAME_EVERY", "")
                          or default))
    except ValueError:
        return default


# ---------------------------------------------------------------------
# preemption flag + signal handlers
# ---------------------------------------------------------------------

_PREEMPT = threading.Event()
_sigint_count = 0


def preempt_requested() -> bool:
    """True when a preemption signal (real or programmatic) is
    pending; the supervised loop observes it at the next step
    boundary."""
    return _PREEMPT.is_set()


def request_preempt() -> None:
    """Set the preempt flag programmatically — exactly what the signal
    handler (and a consumed :meth:`~dccrg_tpu.faults.FaultPlan
    .preempt_signal`) does."""
    _PREEMPT.set()


def clear_preempt() -> None:
    _PREEMPT.clear()


def _signal_handler(signum, frame):  # noqa: ARG001 - signal API
    global _sigint_count
    if signum == getattr(signal, "SIGINT", None):
        _sigint_count += 1
        if _sigint_count > 1:
            # a second ctrl-C means "now": the graceful path already
            # had its chance
            raise KeyboardInterrupt
    _PREEMPT.set()
    try:
        name = signal.Signals(signum).name
    except ValueError:  # pragma: no cover - exotic signal number
        name = str(signum)
    logger.warning(
        "received %s: finishing the current step, then emergency "
        "checkpoint and resumable exit (%d)", name, RESUMABLE_EXIT)


@contextmanager
def preemption_handlers(signals=(signal.SIGTERM, signal.SIGINT)):
    """Install the preemption signal handlers for the duration of a
    supervised run; previous handlers are restored on exit and the
    preempt flag starts cleared (this context owns the run's
    lifecycle). Only the main thread may install handlers — elsewhere
    this degrades to a no-op and the flag can still be raised via
    :func:`request_preempt`. A second SIGINT escalates to
    ``KeyboardInterrupt`` (the graceful path already had its
    chance)."""
    global _sigint_count
    _sigint_count = 0
    clear_preempt()
    prev = {}
    for s in signals:
        try:
            prev[s] = signal.signal(s, _signal_handler)
        except (ValueError, OSError):  # non-main thread / unsupported
            pass
    try:
        yield
    finally:
        # the flag belongs to THIS run's lifecycle: a signal this run
        # already answered (emergency checkpoint + resumable exit)
        # must not leak into the next run in the same process
        clear_preempt()
        for s, h in prev.items():
            try:
                signal.signal(s, h)
            except (ValueError, OSError):  # pragma: no cover
                pass


# ---------------------------------------------------------------------
# deadline machinery
# ---------------------------------------------------------------------

def _under_deadline(fn, timeout, what, step=None):
    """Run ``fn()`` under :func:`dccrg_tpu.coord.run_with_deadline`
    (the shared watchdog-thread primitive). On expiry the worker is
    abandoned — a wedged collective cannot be cancelled, only
    reported — and :class:`StepTimeoutError` is raised; ``fn``'s own
    exception re-raises on the caller thread."""
    finished, result, err = coord.run_with_deadline(
        fn, timeout, f"deadline:{what}")
    if not finished:
        raise StepTimeoutError(what, timeout, step=step)
    if err is not None:
        raise err
    return result


@contextmanager
def _grace_env(grace: float):
    """Shorten ``DCCRG_BARRIER_TIMEOUT`` for the emergency save: the
    two-phase multi-process checkpoint crosses up to three barriers
    (prepare/commit/done), so each gets a quarter of the grace window
    — one dead peer can eat at most its barrier's share, never the
    whole of it. Never lengthens an already-shorter configured
    timeout; the caller's value is restored either way."""
    cut = min(coord.barrier_timeout(), max(1.0, float(grace) / 4.0))
    old = os.environ.get("DCCRG_BARRIER_TIMEOUT")
    os.environ["DCCRG_BARRIER_TIMEOUT"] = str(cut)
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("DCCRG_BARRIER_TIMEOUT", None)
        else:
            os.environ["DCCRG_BARRIER_TIMEOUT"] = old


#: The per-step latency histogram — a thin alias over THE histogram
#: implementation (:class:`dccrg_tpu.telemetry.LogHistogram`), kept
#: under its historical name so ``SupervisedRunner.latency_histogram``
#: callers and subclasses see the identical API (``record`` /
#: ``buckets`` / ``quantile`` / ``summary`` / ``counts`` / ``total`` /
#: ``max_seconds``, BASE=1e-4, 30 buckets). There is exactly one
#: histogram type in the codebase; the telemetry registry's
#: ``dccrg_step_seconds`` series is fed from the same measurements.
LatencyHistogram = telemetry.LogHistogram


# markers of the transient class of XLA runtime errors (a flaky
# host-accelerator link) that a re-dispatch can cure; RESOURCE_EXHAUSTED
# is excluded — the OOM fallback chain owns it
_TRANSIENT_MARKERS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED")


def _is_transient_dispatch(e: BaseException) -> bool:
    if isinstance(e, faults.InjectedDispatchError):
        return True
    if isinstance(e, (StepTimeoutError, resilience.NumericsError,
                      faults.SimulatedResourceExhausted)):
        return False
    s = str(e)
    if "RESOURCE_EXHAUSTED" in s:
        return False
    return any(m in s for m in _TRANSIENT_MARKERS)


# ---------------------------------------------------------------------
# the numbered checkpoint store + retention GC + auto-resume
# ---------------------------------------------------------------------

_CKPT_RE = re.compile(r"^(?P<stem>.+)_(?P<step>\d{1,12})\.(?P<ext>dcd?)$")


def _scan_checkpoints(dirpath: str) -> list:
    """``[(stem, step, path)]`` of every numbered checkpoint —
    keyframe (``.dc``) or delta (``.dcd``) — in ``dirpath``, in name
    order."""
    out = []
    try:
        names = os.listdir(dirpath)
    except OSError:
        return out
    for name in sorted(names):
        m = _CKPT_RE.match(name)
        if m is not None:
            out.append((m.group("stem"), int(m.group("step")),
                        os.path.join(dirpath, name)))
    return out


def list_checkpoints(dirpath: str, stem: str | None = None) -> list:
    """``[(step, path)]`` of the numbered checkpoints in ``dirpath``
    (``<stem>_<step>.dc`` keyframes and ``<stem>_<step>.dcd`` deltas),
    newest step first; a keyframe outranks a same-step delta (an
    emergency save landing on a delta's step). ``stem=None`` matches
    any stem."""
    out = [(s, p) for st, s, p in _scan_checkpoints(dirpath)
           if stem is None or st == stem]
    # ".dc" sorts before ".dcd" (prefix), so path order breaks the tie
    # toward the keyframe
    out.sort(key=lambda e: (-e[0], e[1]))
    return out


def retention_plan(steps, keep_last: int = 3, keep_every: int = 0):
    """The pure retention policy: which checkpoint steps to keep and
    which to drop. Keeps the newest ``keep_last`` steps (clamped to at
    least 1 — the policy alone can never empty a directory) plus, with
    ``keep_every > 0``, every step divisible by it (the coarse
    long-horizon trail, the reference's keep-every-Nth restart files).
    Returns ``(keep, drop)``, both newest first. Verification safety
    is :func:`gc_checkpoints`'s job, not this function's."""
    steps = sorted({int(s) for s in steps}, reverse=True)
    keep = set(steps[:max(1, int(keep_last))])
    if int(keep_every) > 0:
        keep.update(s for s in steps if s % int(keep_every) == 0)
    return ([s for s in steps if s in keep],
            [s for s in steps if s not in keep])


@dataclass
class GCReport:
    """What a retention sweep kept, dropped and refused. ``rescued``
    names a step kept beyond policy because it was the only one that
    passes verification; ``refused`` is non-None when nothing in the
    directory verifies and the GC declined to prune at all."""

    kept: list = dataclass_field(default_factory=list)      # [(step, path)]
    dropped: list = dataclass_field(default_factory=list)   # [(step, path)]
    stale_temps: list = dataclass_field(default_factory=list)
    rescued: int | None = None
    refused: str | None = None
    applied: bool = False


def _unlink(path: str) -> None:
    try:
        os.unlink(path)
    except FileNotFoundError:
        pass


def _chain_index(files) -> dict:
    """Chain structure of one stem's ``[(step, path)]`` (sorted): maps
    each chain's root path -> sorted member ``(step, path)`` list. A
    keyframe roots its own chain; each delta attaches to its sidecar's
    recorded parent file. A delta whose parent cannot be resolved
    (missing file, unreadable sidecar, self/cyclic link) roots an
    already-orphaned chain of its own — it can never verify, so the
    retention guards treat it like any other dead chain."""
    by_name = {os.path.basename(p): p for _s, p in files}
    parent: dict = {}
    for _s, p in files:
        if not p.endswith(resilience.DELTA_SUFFIX):
            continue
        pf = None
        try:
            rec = resilience.read_sidecar(p)
            d = rec.get("delta") if rec else None
            pf = d["parent"]["file"] if d else None
        except resilience.CheckpointCorruptionError:
            pf = None
        target = by_name.get(pf) if pf else None
        if target is not None and target != p:
            parent[p] = target
    root_of: dict = {}
    for _s, p in files:
        trail, seen, q = [], set(), p
        while q in parent and q not in root_of and q not in seen:
            seen.add(q)
            trail.append(q)
            q = parent[q]
        r = root_of.get(q, q)  # a cycle roots at its entry point
        for t in trail:
            root_of[t] = r
        root_of.setdefault(p, r)
    chains: dict = {}
    for s, p in files:
        chains.setdefault(root_of[p], []).append((s, p))
    for r in chains:
        chains[r].sort()
    return chains


def chain_report(dirpath: str, stem: str | None = None) -> list:
    """Every keyframe->delta chain in ``dirpath`` with per-link
    verification status: ``[(stem, [(step, path, kind, status)])]``,
    newest chain first per stem, links oldest-first. ``status`` is
    ``OK`` (the link's whole sub-chain verifies), ``CORRUPT`` (this
    link's own bytes/sidecar fail) or ``BROKEN(<link>)`` (an ancestor
    fails, naming it). The ``python -m dccrg_tpu.resilience chain``
    subcommand prints this."""
    groups: dict = {}
    for stem_name, step, path in _scan_checkpoints(dirpath):
        if stem is not None and stem_name != stem:
            continue
        groups.setdefault(stem_name, []).append((step, path))
    out = []
    for stem_name in sorted(groups):
        files = sorted(groups[stem_name])
        chains = _chain_index(files)
        memo: dict = {}
        for root in sorted(chains, key=lambda r: -chains[r][-1][0]):
            links = []
            for s, p in chains[root]:
                kind = ("delta" if p.endswith(resilience.DELTA_SUFFIX)
                        else "keyframe")
                try:
                    resilience.verify_chain(p, _memo=memo)
                    status = "OK"
                except resilience.DeltaChainError as e:
                    if e.link and os.path.abspath(e.link) == \
                            os.path.abspath(p):
                        status = "CORRUPT"
                    else:
                        status = ("BROKEN("
                                  + (os.path.basename(e.link)
                                     if e.link else "?") + ")")
                except resilience.CheckpointCorruptionError:
                    status = "CORRUPT"
                links.append((s, p, kind, status))
            out.append((stem_name, links))
    return out


@telemetry.traced("ckpt.gc")
def gc_checkpoints(dirpath: str, keep_last: int = 3, keep_every: int = 0,
                   stem: str | None = None, apply: bool = False,
                   assume_ok: int | None = None) -> GCReport:
    """Prune a checkpoint directory by the keep-last-K / keep-every-N
    retention policy (:func:`retention_plan`) — DRY-RUN unless
    ``apply`` — CHAIN-AWARE over keyframe+delta chains.

    Safety properties, regardless of policy (pinned by the fuzzed
    retention tests):

    - **Never orphan a delta.** Chains are pruned WHOLE or kept whole:
      a chain any of whose members the step policy keeps is kept
      entirely (a kept delta needs every ancestor down to its
      keyframe), and a dropped chain is deleted deltas-newest-first
      with the keyframe LAST, so a crash (or injected
      ``checkpoint.gc`` fault) mid-prune can only shorten a chain,
      never strand a delta without its keyframe.
    - **Never drop the only verifying chain.** A chain counts as
      verifying when any of its links' sub-chains verifies end to end
      (= something is strictly resumable from it). If no kept chain
      verifies, the newest verifying dropped chain is rescued whole;
      when NOTHING verifies the GC refuses to prune at all — a
      salvage load may still need any of those bytes.

    Checkpoint files are removed before their sidecars, so a crash
    mid-prune can only leave a harmless orphan sidecar. Stale
    save/salvage/chain-scratch temp files of dead runs are swept too
    (:func:`dccrg_tpu.checkpoint.stale_temp_files`).

    ``assume_ok`` lets the process that JUST saved (and sidecar-
    verified) a step vouch for that step's file AND, when that step
    heads a kept chain, for the chain it extended (the same process
    wrote and verified every link), so the per-save GC path stays
    zero-read in the common case; chains the vouching process did not
    just extend verify normally.

    With ``stem=None`` each stem in the directory is an INDEPENDENT
    checkpoint sequence: the retention policy and the only-verifiable
    guard run per stem, so one run's files can never shadow or doom
    another's."""
    groups: dict = {}
    for stem_name, step, path in _scan_checkpoints(dirpath):
        if stem is not None and stem_name != stem:
            continue
        groups.setdefault(stem_name, []).append((step, path))
    kept, dropped = [], []
    rescued = refused = None
    for stem_name in sorted(groups):
        files = sorted(groups[stem_name])
        chains = _chain_index(files)
        keep_steps, _drop_steps = retention_plan(
            {s for s, _p in files}, keep_last, keep_every)
        keep_set = set(keep_steps)
        heads = sorted(chains, key=lambda r: -chains[r][-1][0])
        kept_chains = [r for r in heads
                       if any(s in keep_set for s, _p in chains[r])]
        drop_chains = [r for r in heads if r not in kept_chains]
        if drop_chains:
            memo: dict = {}
            assume = {p for s, p in files
                      if assume_ok is not None and s == int(assume_ok)}

            def _chain_ok(root):
                # the process that JUST saved (and whose earlier saves
                # built the links the new one chains to) vouches for
                # the chain it extended — the zero-read common path:
                # in steady state every sweep drops an aged-out chain,
                # and re-reading the kept chain's multi-GB keyframe
                # each time is exactly the I/O delta saves exist to
                # avoid. Every OTHER chain still byte-verifies.
                if (assume_ok is not None
                        and chains[root][-1][0] == int(assume_ok)):
                    return True
                # resumable = some link's whole sub-chain verifies
                for _s, p in reversed(chains[root]):
                    try:
                        resilience.verify_chain(p, assume_ok=assume,
                                                _memo=memo)
                        return True
                    except resilience.CheckpointCorruptionError:
                        continue
                return False

            if not any(_chain_ok(r) for r in kept_chains):
                for r in drop_chains:  # newest chain first
                    if _chain_ok(r):
                        rescued = chains[r][-1][0]
                        drop_chains = [d for d in drop_chains if d != r]
                        kept_chains.append(r)
                        break
                else:
                    refused = (
                        f"no {stem_name!r} checkpoint chain passes "
                        "verification; refusing to prune that "
                        "sequence — a salvage load may still need "
                        "any of them")
                    kept_chains += drop_chains
                    drop_chains = []
        stem_kept = sorted((e for r in kept_chains for e in chains[r]),
                           key=lambda e: (-e[0], e[1]))
        kept.extend(stem_kept)
        # whole chains only, deltas first, keyframe last — in every
        # chain independently (report order = deletion order)
        for r in sorted(drop_chains, key=lambda r: -chains[r][-1][0]):
            dropped.extend(reversed(chains[r]))
    stale = checkpoint_mod.stale_temp_files(dirpath)
    if apply:
        for s, path in dropped:
            # fault-injection site: an I/O error (or crash) here may
            # shorten a chain but can never orphan a delta — its
            # ancestors, the keyframe included, are deleted after it
            faults.fire("checkpoint.gc", path=path, step=s)
            _unlink(path)  # the checkpoint first: a crash leaves only
            _unlink(resilience.sidecar_path(path))  # an orphan sidecar
        for path in stale:
            faults.fire("checkpoint.gc", path=path, step=None)
            _unlink(path)
        telemetry.inc("dccrg_gc_pruned_total",
                      len(dropped) + len(stale))
    return GCReport(kept=kept, dropped=dropped, stale_temps=stale,
                    rescued=rescued, refused=refused,
                    applied=bool(apply))


class CheckpointStore:
    """A directory of numbered checkpoints, one file per checkpointed
    step — ``<stem>_<step:08d>.dc`` keyframes and ``.dcd`` dirty-field
    deltas, each with a CRC sidecar: the disk layout retention GC and
    :func:`resume_latest` operate on.

    :meth:`save` implements the incremental-save policy: a periodic
    save becomes a delta (only the fields whose bytes changed since
    the last save, tracked by the grid) chained to the previous save,
    with a full keyframe forced every ``keyframe_every`` saves
    (``DCCRG_KEYFRAME_EVERY``), after any structural mutation or
    shape/partition change (deltas are only valid within one structure
    epoch), when ragged (variable-size) fields are dirty, and on
    ``DCCRG_DELTA=0`` (opt-out: every save a keyframe)."""

    def __init__(self, dirpath, stem: str = "ckpt",
                 keyframe_every: int | None = None):
        self.dir = str(dirpath)
        self.stem = str(stem)
        self.keyframe_every = (keyframe_every_default()
                               if keyframe_every is None
                               else max(1, int(keyframe_every)))
        # the last save THIS process made: the next delta's parent
        # (path, step, grid structure epoch, chain length so far)
        self._parent = None
        # async-save writer (DCCRG_ASYNC_SAVE): at most one write in
        # flight per store; drain() is the barrier every reader takes
        self._saver = background.AsyncSaver()
        os.makedirs(self.dir, exist_ok=True)

    def drain(self) -> None:
        """Async-save barrier: block until this stem's in-flight write
        (if any) is durable, re-raising its failure (see
        :class:`~dccrg_tpu.background.AsyncSaver`). Every reader of
        the store — rollback, resume, retention GC, digest comparisons
        — must pass through here first."""
        self._saver.drain()

    def pending(self) -> bool:
        """True while an async write of this stem is in flight."""
        return self._saver.pending()

    def path_for(self, step: int, delta: bool = False) -> str:
        ext = resilience.DELTA_SUFFIX if delta else ".dc"
        return os.path.join(self.dir, f"{self.stem}_{int(step):08d}{ext}")

    def _delta_fields(self, grid, variable, force_keyframe,
                      dirty_override=None):
        """The dirty-field list for a delta save, or None when this
        save must be a full keyframe. Every input is replicated state
        (dirty set, structure epoch, save counters), so multi-process
        ranks reach the identical decision without a collective."""
        if force_keyframe or not delta_enabled():
            return None
        last = self._parent
        if last is None:
            return None  # nothing to chain to in this process
        if getattr(grid, "_ckpt_epoch", 0) != last["epoch"]:
            return None  # structural mutation / repartition: new epoch
        if last["chain_len"] + 1 >= self.keyframe_every:
            return None  # periodic keyframe cadence
        dirty = (set(dirty_override) if dirty_override is not None
                 else getattr(grid, "_ckpt_dirty", None))
        if dirty is None:
            return None  # conservative: everything may have changed
        # ragged payloads resize with their counts: a dirty variable
        # field (or count field) moves the offset table, which only a
        # keyframe may capture
        var = variable or {}
        if dirty & (set(var) | set(var.values())):
            return None
        if set(dirty) >= set(grid.fields):
            return None  # a delta of everything is a keyframe + overhead
        return sorted(dirty)

    def save(self, grid, step: int, header: bytes = b"", variable=None,
             force_keyframe: bool = False, dirty_fields=None,
             post=None) -> str:
        """Periodic save at ``step``: a dirty-field delta chained to
        this process's previous save when safe (see class docstring),
        else a full keyframe. Atomic either way (two-phase on
        multi-process meshes); on success the grid's dirty tracking is
        re-baselined to this save. Returns the path written.
        ``dirty_fields`` overrides the grid's own dirty tracking — the
        fleet layer saves ONE batch slot through a shared scratch grid
        whose tracking reflects whatever slot passed through last, but
        it knows exactly which fields its step program writes.

        With ``DCCRG_ASYNC_SAVE=1`` the write runs on a background
        thread against a frozen snapshot (:func:`~dccrg_tpu.background
        .freeze_grid`; multi-process grids through
        :func:`~dccrg_tpu.background.freeze_grid_mp`, whose two-phase
        barriers rendezvous on the ranks' writer threads),
        overlapped with the next quantum's dispatch; the chain policy,
        the parent link and the dirty re-baseline are all resolved
        synchronously here, so the published bytes are bitwise
        identical to a synchronous save's. ``post`` (the retention-GC
        hook) runs after the write — on the writer thread when async,
        inline otherwise — so GC never races a publish."""
        # one write in flight per stem: an earlier failure surfaces at
        # this save boundary (its on_fail already forced the next save
        # to a keyframe and dropped the unpublishable parent link)
        self.drain()
        fields = self._delta_fields(grid, variable, force_keyframe,
                                    dirty_override=dirty_fields)
        if not background.async_save_enabled():
            if fields is not None:
                path = self.path_for(step, delta=True)
                try:
                    resilience.save_delta_checkpoint(
                        grid, path, parent_path=self._parent["path"],
                        parent_step=self._parent["step"], step=step,
                        fields=fields, header=header, variable=variable)
                except resilience.CheckpointCorruptionError as e:
                    # the parent's sidecar went bad under us (external
                    # damage): save a keyframe, don't fail the run
                    logger.warning(
                        "delta save at step %d fell back to a keyframe "
                        "(%s)", step, e)
                    fields = None
            if fields is None:
                path = self.path_for(step)
                resilience.save_checkpoint(grid, path, header=header,
                                           variable=variable)
            self._record_parent(grid, path, step, fields)
            if post is not None:
                post()
            return path

        # async: resolve the delta parent link NOW — the drain above
        # made the parent durable — then hand the frozen snapshot to
        # the writer thread
        extra = None
        if fields is not None:
            try:
                extra = resilience.delta_sidecar_extra(
                    self._parent["path"], parent_step=self._parent["step"],
                    step=step, fields=fields, variable=variable)
            except resilience.CheckpointCorruptionError as e:
                logger.warning("delta save at step %d fell back to a "
                               "keyframe (%s)", step, e)
                fields = None
        path = self.path_for(step, delta=fields is not None)
        # multi-process grids freeze through freeze_grid_mp: the
        # two-phase commit's barriers are writer-thread safe (gRPC),
        # and the snapshot removes the save path's device touch points
        # (host-copy shard reads, KV CRC exchange, frozen count pulls)
        frozen = (background.freeze_grid_mp(grid, fields=fields,
                                            variable=variable)
                  if grid._multiproc
                  else background.freeze_grid(grid, fields=fields))

        def _write(path=path, fields=fields, extra=extra):
            resilience.save_checkpoint(frozen, path, header=header,
                                       variable=variable, fields=fields,
                                       sidecar_extra=extra)
            if post is not None:
                post()

        def _on_fail(_err):
            # the write never published: nothing may chain to it, and
            # the dirty set can no longer prove a proper delta subset
            # relative to a durable parent — force the next save to a
            # full keyframe
            self._parent = None
            grid._ckpt_dirty = None

        self._saver.submit(_write, on_fail=_on_fail, label=path)
        self._record_parent(grid, path, step, fields)
        return path

    def _record_parent(self, grid, path, step, fields) -> None:
        self._parent = {
            "path": path, "step": int(step),
            "epoch": getattr(grid, "_ckpt_epoch", 0),
            "chain_len": (0 if fields is None
                          else self._parent["chain_len"] + 1),
        }
        # re-baseline the dirty tracking: subsequent changes are
        # relative to THIS save (the next delta's parent)
        grid._ckpt_dirty = set()

    def list(self) -> list:
        """``[(step, path)]``, newest first (keyframes and deltas)."""
        return list_checkpoints(self.dir, self.stem)

    def gc(self, keep_last: int = 3, keep_every: int = 0,
           apply: bool = True, assume_ok: int | None = None) -> GCReport:
        # drain barrier: GC must never race an in-flight publish (a
        # no-op on the writer thread itself, where post-save GC is
        # already ordered after the write)
        self.drain()
        return gc_checkpoints(self.dir, keep_last=keep_last,
                              keep_every=keep_every, stem=self.stem,
                              apply=apply, assume_ok=assume_ok)


@dataclass
class ResumeInfo:
    """What :func:`resume_latest` restored: the reconstructed grid,
    the user header, the completed-step count the checkpoint
    captured, and how trustworthy it is (``salvaged=True``: corrupt
    ranges were zeroed / no sidecar existed — ``report`` lists the
    damage)."""

    grid: object
    header: bytes
    step: int
    path: str
    report: "resilience.SalvageReport"
    salvaged: bool = False


def resume_latest(dirpath, cell_data, *, stem: str | None = None,
                  mesh=None, header_size: int = 0, variable=None,
                  salvage: bool = True, load_balancing_method=None):
    """Resume from the best checkpoint in ``dirpath``: the newest one
    that passes CRC verification, falling back to older verified ones,
    and — with ``salvage`` (default) — last to a salvage load
    (``strict=False``) of the newest salvageable file. Returns a
    :class:`ResumeInfo` (grid reconstructed from nothing but the
    file, via :func:`dccrg_tpu.resilience.load_checkpoint` /
    ``load_grid``) or None when the directory holds no usable
    checkpoint.

    CHAIN-AWARE: a delta entry loads by verifying and replaying its
    whole keyframe+delta chain, bitwise identical to an uninterrupted
    run's full save. A broken link surfaces as a typed
    :class:`~dccrg_tpu.resilience.DeltaChainError` naming the link;
    the walk then continues to OLDER entries — which IS the fall-back
    to the last verifying chain prefix (the delta just before the
    break) and ultimately the keyframe. Resume ordering is pinned by
    tests/test_supervise.py's and the chain tests'
    planted-corruption fixtures."""
    entries = list_checkpoints(dirpath, stem)
    skipped = []
    for step, path in entries:  # newest first: strict, CRC-verified
        try:
            grid, header, report = resilience.load_checkpoint(
                path, cell_data, mesh=mesh, header_size=header_size,
                variable=variable, strict=True,
                load_balancing_method=load_balancing_method)
        except resilience.CheckpointCorruptionError as e:
            skipped.append((path, str(e)))
            continue
        except Exception as e:  # noqa: BLE001 - fall back to older
            skipped.append((path, f"failed to load: {e}"))
            continue
        if skipped:
            logger.warning(
                "resume_latest: skipped %d newer checkpoint(s) that "
                "failed verification: %s", len(skipped),
                [p for p, _ in skipped])
        return ResumeInfo(grid, header, step, path, report)
    if salvage:
        for step, path in entries:  # newest first: salvage what loads
            try:
                grid, header, report = resilience.load_checkpoint(
                    path, cell_data, mesh=mesh, header_size=header_size,
                    variable=variable, strict=False,
                    load_balancing_method=load_balancing_method)
            except Exception as e:  # noqa: BLE001 - keep walking back
                skipped.append((path, f"salvage failed: {e}"))
                continue
            logger.warning(
                "resume_latest: NO checkpoint verifies; salvaged %s "
                "(%d corrupt cell(s) restored with defaults)", path,
                len(report.corrupt_cells))
            return ResumeInfo(grid, header, step, path, report,
                              salvaged=True)
    if entries:
        logger.error("resume_latest: no usable checkpoint in %s (%s)",
                     dirpath, skipped)
    return None


# ---------------------------------------------------------------------
# the supervised runner
# ---------------------------------------------------------------------

class _StoreRunner(resilience.ResilientRunner):
    """A :class:`~dccrg_tpu.resilience.ResilientRunner` whose periodic
    checkpoints land in the supervisor's :class:`CheckpointStore` as
    numbered per-step files — dirty-field DELTAS chained to periodic
    keyframes (:meth:`CheckpointStore.save`) — with rollback always
    targeting the newest save (chain-aware when it is a delta) and
    retention GC after each save."""

    def __init__(self, sup, grid, step_fn, **kw):
        self._sup = sup
        super().__init__(grid, step_fn, sup.store.path_for(0), **kw)

    def _write_checkpoint(self):
        # retention GC rides the save as its ``post`` hook: inline
        # after a synchronous save (the pre-async behavior), chained
        # onto the writer thread after an async one — either way GC
        # only ever sees a fully published store
        step = self.step
        return self._sup.store.save(
            self.grid, step, header=self.header, variable=self.variable,
            post=lambda: self._sup._after_save(step))

    def _active_saver(self, create: bool = False):
        return self._sup.store._saver


class SupervisedRunner:
    """Run a step loop that survives preemption, wedged steps and
    transient dispatch faults — :class:`~dccrg_tpu.resilience
    .ResilientRunner` (watchdog, rollback, trip consensus) wrapped
    with the run-lifecycle machinery the module docstring describes.

    ``step_fn(grid, step_index)`` is the user's step, exactly as for
    ``ResilientRunner``; periodic checkpoints land in
    ``checkpoint_dir`` as numbered files. On preemption (SIGTERM /
    SIGINT / :func:`request_preempt` / a faked
    ``FaultPlan.preempt_signal``) the run stops at the next step
    boundary — consensus-agreed on multi-process meshes, so all ranks
    stop together — takes a CRC-verified emergency checkpoint inside
    the ``grace`` window and raises :class:`PreemptedError` (exit
    code :data:`RESUMABLE_EXIT`). Restart the job and pick the run
    back up with :func:`resume_latest` + ``start_step=info.step``; a
    resumed run reconverges bitwise with an uninterrupted one (pinned
    by tests/test_supervise.py and the mp harness).

    Keyword knobs (None = the env default): ``step_timeout``
    (``DCCRG_STEP_TIMEOUT``; 0 disables the per-step deadline thread
    entirely), ``checkpoint_seconds`` (``DCCRG_CKPT_SECONDS``;
    wall-clock checkpoint cadence for uneven step times — monotonic
    clock, step boundaries only, 0 keeps the step-count cadence),
    ``grace`` (``DCCRG_PREEMPT_GRACE``), ``keep_last``
    (``DCCRG_KEEP_LAST``) / ``keep_every`` (retention),
    ``dispatch_retries`` / ``dispatch_backoff`` (transient-error
    retry). Remaining keyword arguments (``fields``, ``check_every``,
    ``checkpoint_every``, ``max_retries``, ``backoff``, ``header``,
    ``variable``, ``diagnostics_dir``) pass through to
    ``ResilientRunner``. Per-step wall times are recorded into
    :meth:`latency_histogram` log-spaced buckets."""

    def __init__(self, grid, step_fn, checkpoint_dir, *, stem="ckpt",
                 step_timeout=None, dispatch_retries=2,
                 dispatch_backoff=0.05, keep_last=None, keep_every=0,
                 grace=None, signals=None, install_signal_handlers=True,
                 start_step=0, checkpoint_seconds=None, **runner_kw):
        self.grid = grid
        self.step_fn = step_fn
        self.store = CheckpointStore(checkpoint_dir, stem=stem)
        self.step_timeout = (step_timeout_default() if step_timeout is None
                             else float(step_timeout))
        # wall-clock checkpoint cadence (DCCRG_CKPT_SECONDS): uneven
        # step times make a step-count cadence either too chatty or
        # too sparse; the runner checks the monotonic clock at step
        # boundaries only (never mid-step, consensus-agreed on
        # multi-process meshes — see ResilientRunner)
        runner_kw.setdefault(
            "checkpoint_seconds",
            ckpt_seconds_default() if checkpoint_seconds is None
            else float(checkpoint_seconds))
        self._latency = LatencyHistogram()
        self.dispatch_retries = int(dispatch_retries)
        self.dispatch_backoff = float(dispatch_backoff)
        self.keep_last = (keep_last_default() if keep_last is None
                          else max(1, int(keep_last)))
        self.keep_every = int(keep_every)
        self.grace = preempt_grace() if grace is None else float(grace)
        self.signals = (tuple(signals) if signals is not None
                        else (signal.SIGTERM, signal.SIGINT))
        self._install = bool(install_signal_handlers)
        runner_kw.setdefault("diagnostics_dir", self.store.dir)
        self._runner = _StoreRunner(self, grid, self._dispatch,
                                    interrupt_poll=self._poll,
                                    **runner_kw)
        self._runner.step = int(start_step)
        self.preempted = False
        self.emergency_checkpoint = None
        self.dispatch_retried = 0  # transient errors retried through

    # -- mirrors of the inner runner's story --------------------------

    @property
    def runner(self):
        return self._runner

    @property
    def step(self):
        return self._runner.step

    @property
    def trips(self):
        return self._runner.trips

    @property
    def rollbacks(self):
        return self._runner.rollbacks

    @property
    def checkpoints(self):
        return self._runner.checkpoints

    def latency_histogram(self) -> list:
        """Per-step wall-time distribution as ``[(lo_s, hi_s, count)]``
        log-spaced buckets (see :class:`LatencyHistogram`); a summary
        line is logged automatically when a step wedges into
        :class:`StepTimeoutError`, so the latency trend that preceded
        the wedge is on record."""
        return self._latency.buckets()

    # -- the lifecycle ------------------------------------------------

    def run(self, n_steps: int) -> "SupervisedRunner":
        """Advance to ``n_steps`` total steps under supervision.
        Raises :class:`PreemptedError` after the emergency checkpoint
        when preempted; :class:`StepTimeoutError` when a step wedges
        past the deadline; whatever ``ResilientRunner`` raises
        otherwise."""
        ctx = (preemption_handlers(self.signals) if self._install
               else nullcontext())
        with ctx:
            try:
                self._runner.run(n_steps)
            except resilience.RunInterrupted as e:
                path, clean = self._emergency_checkpoint(e.step)
                # the preemption has been honored (checkpoint taken):
                # consume the flag HERE, not only in the handler
                # context — with install_signal_handlers=False a stale
                # flag would otherwise re-preempt every later run in
                # this process at its first boundary
                clear_preempt()
                self.preempted = True
                self.emergency_checkpoint = path
                raise PreemptedError(e.step, checkpoint=path,
                                     clean=clean) from e
        return self

    # -- step dispatch: deadline + transient retry --------------------

    def _poll(self) -> bool:
        m = coord.get_membership()
        if m is not None:
            # elastic-fleet liveness at the supervision poll boundary
            # (throttled): a supervised run under a registered
            # membership keeps its heartbeat lease fresh even when
            # the inner runner loop is replaced/overridden
            m.heartbeat()
        if faults.take_preempt(self._runner.step):
            request_preempt()
        return _PREEMPT.is_set()

    def _dispatch(self, grid, i):
        # a real transient error (async dispatch) typically surfaces
        # at the block_until_ready AFTER step_fn reassigned grid.data,
        # so a blind re-dispatch would double-apply the step. The
        # arrays are immutable, so a dict-of-refs snapshot is enough
        # to rewind the data state before retrying. (Structural
        # mutations inside step_fn are transactional and never
        # classify as transient.)
        before = dict(grid.data)
        for attempt in range(self.dispatch_retries + 1):
            try:
                faults.fire("supervise.dispatch", step=i, attempt=attempt)
                self._timed_step(grid, i)
                return
            except Exception as e:  # noqa: BLE001 - filtered just below
                if (not _is_transient_dispatch(e)
                        or attempt >= self.dispatch_retries):
                    raise
                grid.data = dict(before)
                self.dispatch_retried += 1
                delay = self.dispatch_backoff * (2 ** attempt)
                logger.warning(
                    "transient dispatch error at step %d (%s); retry "
                    "%d/%d in %.2fs", i, e, attempt + 1,
                    self.dispatch_retries, delay)
                time.sleep(delay)

    def _timed_step(self, grid, i):
        t0 = time.perf_counter()
        try:
            with telemetry.span("step"):
                self._timed_step_inner(grid, i)
        except StepTimeoutError:
            self._record_latency(time.perf_counter() - t0)
            # the latency trend BEFORE the wedge is the diagnosis: a
            # slowly degrading interconnect shows as mass migrating
            # into the slow buckets over the preceding steps
            logger.warning("step %d wedged; latency so far: %s",
                           i, self._latency.summary())
            raise
        else:
            self._record_latency(time.perf_counter() - t0)

    def _record_latency(self, seconds: float) -> None:
        self._latency.record(seconds)
        # the same measurement feeds the process-wide registry, so
        # dump_prometheus carries the step-latency distribution too
        telemetry.observe("dccrg_step_seconds", seconds)

    def _timed_step_inner(self, grid, i):
        timeout = self.step_timeout
        hang = faults.take_step_hang(i)
        if timeout <= 0:
            if hang is not None and math.isinf(hang):
                raise RuntimeError(
                    "FaultPlan.step_hang fired but no step deadline is "
                    "configured (DCCRG_STEP_TIMEOUT / step_timeout): "
                    "the injected wedge would block forever")
            if hang:
                time.sleep(hang)
            self.step_fn(grid, i)  # zero-overhead path: no thread
            return

        def _one():
            if hang is not None:
                # the injected wedge replaces the dispatch inside the
                # worker thread (same discipline as barrier_hang), so
                # the deadline machinery itself is what gets
                # exercised; a finite hang below the deadline models
                # a slow-but-alive step that still completes
                time.sleep(min(hang, timeout + 30.0))
                if math.isinf(hang):
                    return
            self.step_fn(grid, i)
            # async dispatch hides a wedged collective until somebody
            # blocks; make the deadline cover the actual compute
            import jax

            jax.block_until_ready(list(grid.data.values()))

        _under_deadline(_one, timeout, f"step {i}", step=i)

    # -- preemption: the emergency checkpoint -------------------------

    def _emergency_checkpoint(self, step: int):
        """The whole emergency save — the ordinary atomic (two-phase
        on multi-process meshes) checkpoint plus its CRC verification
        — runs under the ``grace`` deadline with shortened barrier
        timeouts. If it cannot finish (a dead peer, a wedged device
        pull), the LAST PERIODIC checkpoint is the resume point: the
        grace window belongs to the exit, not to the save."""
        r = self._runner
        # drain the periodic writer first: the emergency save itself
        # stays SYNCHRONOUS (it is deadline-bounded and must be
        # durable before the resumable exit), and a failed in-flight
        # write re-points the fallback at the last durable checkpoint
        # (resumability outranks the report — swallow)
        r._drain_saves(swallow=True)
        path = self.store.path_for(step)

        def _save():
            resilience.save_checkpoint(self.grid, path, header=r.header,
                                       variable=r.variable)
            bad = resilience.verify_checkpoint(path)
            if bad:
                raise resilience.CheckpointCorruptionError(
                    f"emergency checkpoint {path} failed its own "
                    f"verification (chunks {bad})", bad_chunks=bad)

        try:
            t0 = time.perf_counter()
            with telemetry.span("ckpt.emergency"), _grace_env(self.grace):
                _under_deadline(_save, self.grace,
                                f"emergency checkpoint at step {step}",
                                step=step)
            # the deadline-bounded save+verify cost, distinct from the
            # periodic kinds: how much of the grace window a preempt
            # actually spends (a controller/operator input)
            telemetry.observe("dccrg_ckpt_save_seconds",
                              time.perf_counter() - t0,
                              kind="emergency")
        except Exception as e:  # noqa: BLE001 - resumability outranks it
            logger.error(
                "emergency checkpoint failed (%s); the last periodic "
                "checkpoint %s (step %s) is the resume point", e,
                r.checkpoint_path, r._ckpt_step)
            return r.checkpoint_path, False
        logger.warning(
            "preempted: emergency checkpoint %s (step %d) verified; "
            "exiting resumable (%d)", path, step, RESUMABLE_EXIT)
        return path, True

    # -- retention ----------------------------------------------------

    def _after_save(self, step: int) -> None:
        """Retention GC after every periodic save. Filesystem-only (no
        barriers), so only one rank prunes; ``keep_last >= 1`` plus
        the only-verifiable guard means the newest checkpoint — the
        one a peer may be rolling back to — is never touched."""
        if self.grid._multiproc and coord.process_rank(self.grid) != 0:
            return
        try:
            rep = self.store.gc(keep_last=self.keep_last,
                                keep_every=self.keep_every, apply=True,
                                assume_ok=step)
        except OSError as e:  # GC must never kill the run
            logger.warning("retention GC failed (%s); continuing", e)
            return
        if rep.dropped or rep.stale_temps:
            logger.info(
                "retention GC: pruned %d checkpoint(s) and %d stale "
                "temp file(s); %d kept", len(rep.dropped),
                len(rep.stale_temps), len(rep.kept))
        # save boundaries are the supervised loop's natural metrics
        # cadence (one None check without DCCRG_METRICS_FILE)
        telemetry.maybe_export_metrics()
