"""Fast-path structure plan for all-level-0 grids.

When every cell sits at refinement level 0 (fresh init, or an AMR grid
before any refinement), the generic plan builder's machinery — the flat
neighbor-entry stream (~26 entries/cell), window search, dedup, stable
sort and scatter (grid.py build_table) — is pure overhead: neighbor
resolution is closed-form index arithmetic. This module builds the same
[n_dev, L, S] gather tables, ghost sets and send/receive lists directly
with O(L·K) vector ops and bounded temporaries: the neighbor map for an
offset is ``np.roll`` of the 3-D identity-index array (a strided copy,
no per-cell arithmetic), validity is edge-slab masking, and per-device
ghost-row fix-ups touch only the cross-device edge sets. Single-device
grids go further: the plan is fully CLOSED-FORM (roll shifts, wrap
fixup sets and validity masks from index arithmetic; no tables at all
unless a host introspection path forces them), so a 256^3 grid plans
in ~0.3 s and 512^3 in milliseconds of plan work. The host-side entry
stream (NeighborLists, used only by query APIs) is produced lazily on
first access.

Semantics match the generic path (reference find_neighbors_of,
dccrg.hpp:4375-4716, restricted to the level-0 case): each neighborhood
item resolves to the same-level cell at ``ijk + offset`` with periodic
wrap, offsets are recorded in smallest-cell index units
(``offset * 2^max_refinement_level``), and neighbors_to is the inverse
relation with negated offsets. Slot layout differs only in padding:
the generic builder left-compacts each cell's valid entries while this
path keeps item ``j`` in slot ``j`` — kernels are mask-driven, so both
are valid paddings of the same neighbor multiset.
"""

from __future__ import annotations

import os

import numpy as np


def is_uniform(cells: np.ndarray, n0: int) -> bool:
    """True when ``cells`` is exactly the full level-0 cell set 1..n0."""
    return len(cells) == n0 and int(cells[-1]) == n0


class _NeighborMaps:
    """Per-offset neighbor maps over the full level-0 grid.

    ``shift(off)`` returns ``(ngidx, valid)`` flat views: the grid
    index of each cell's neighbor at cell-unit offset ``off`` (periodic
    wrap applied) and whether that neighbor exists. The map is a
    ``np.roll`` of the identity-index array — a plain strided copy.
    """

    def __init__(self, dims, periodic):
        self.dims = dims
        self.periodic = periodic
        nx, ny, nz = dims
        self.n0 = nx * ny * nz
        self._g3 = np.arange(self.n0, dtype=np.int32).reshape(nz, ny, nx)

    def shift(self, off):
        nx, ny, nz = self.dims
        ox, oy, oz = int(off[0]), int(off[1]), int(off[2])
        ng = np.roll(self._g3, shift=(-oz, -oy, -ox), axis=(0, 1, 2))
        valid = np.ones((nz, ny, nx), dtype=bool)
        for axis, (o, n, per) in enumerate(
            ((oz, nz, self.periodic[2]), (oy, ny, self.periodic[1]),
             (ox, nx, self.periodic[0]))
        ):
            if per or o == 0:
                continue
            sl = [slice(None)] * 3
            if abs(o) >= n:
                valid[:] = False
                continue
            sl[axis] = slice(n - o, None) if o > 0 else slice(None, -o)
            valid[tuple(sl)] = False
        return ng.reshape(-1), valid.reshape(-1)


def build_pair_tables(ghost_lists, n_dev, owner_of_key, send_row_of,
                      recv_row_of, cap):
    """COMPACT halo send/receive lists from per-receiver ghost lists —
    the shared lexsort-grouping construction (no n_dev^2 Python loop;
    the reference builds the equivalent per-peer lists at
    dccrg.hpp:8729-8891).

    ``ghost_lists[q]`` is the SORTED array of ghost keys device q
    reads (cell ids, lattice indices or positions — whatever the
    caller's row resolvers understand). ``owner_of_key(keys)`` maps
    keys to their owning (sending) device; ``send_row_of(p_s, keys)``
    and ``recv_row_of(q_s, keys, gpos)`` resolve sender rows and
    receiver ghost rows, where ``gpos`` is each key's position within
    its receiver's sorted list. Entries within one (sender, receiver)
    pair are ordered by key (the reference sorts by id for tag
    assignment).

    Returns a compact dict — O(total ghosts) memory, NOT the dense
    ``[n_dev, n_dev, M]`` arrays (those are quadratic in devices and
    only materialized lazily for the all_to_all fallback and host
    introspection; see grid._HoodPlan.send_rows):
      ``n_dev, M`` — device count and the capped max pair width;
      ``p, q, pos, srow, rrow`` — per-entry sender, receiver, slot
      within the pair, sender row, receiver ghost row, sorted by
      (sender, receiver, key)."""
    g_all = (np.concatenate(ghost_lists) if n_dev
             else np.empty(0, np.int64))
    q_all = np.repeat(np.arange(n_dev), [len(g) for g in ghost_lists])
    total = len(g_all)
    if total == 0:
        return empty_pair_compact(n_dev, cap(1))
    p_all = np.asarray(owner_of_key(g_all))
    order = np.lexsort((g_all, q_all, p_all))
    p_s, q_s, g_s = p_all[order], q_all[order], g_all[order]
    # position of each ghost within its (sender, receiver) group
    pq = p_s.astype(np.int64) * n_dev + q_s
    starts = np.r_[0, np.flatnonzero(np.diff(pq)) + 1]
    lens = np.diff(np.r_[starts, total])
    pos = np.arange(total, dtype=np.int64) - np.repeat(starts, lens)
    M = cap(max(1, int(lens.max())))
    # g_all concatenates the receivers' sorted lists, so each key's
    # in-list position is its index minus its list's start
    lens_q = np.array([len(g) for g in ghost_lists], dtype=np.int64)
    q_starts = np.cumsum(lens_q) - lens_q
    gpos = (np.arange(total, dtype=np.int64) - q_starts[q_all])[order]
    return {
        "n_dev": n_dev, "M": M,
        "p": p_s.astype(np.int64), "q": q_s.astype(np.int64), "pos": pos,
        "srow": np.asarray(send_row_of(p_s, g_s), dtype=np.int32),
        "rrow": np.asarray(recv_row_of(q_s, g_s, gpos), dtype=np.int32),
    }


def empty_pair_compact(n_dev, M):
    """A compact pair record with no entries (single-device plans and
    ghost-free meshes)."""
    e = np.empty(0, np.int64)
    return {"n_dev": n_dev, "M": M, "p": e, "q": e, "pos": e,
            "srow": np.empty(0, np.int32), "rrow": np.empty(0, np.int32)}


def dense_pair_tables(compact):
    """Materialize the dense ``[n_dev, n_dev, M]`` send/recv arrays
    from a compact pair record (all_to_all fallback + introspection;
    O(n_dev^2 M) memory — never built on the per-delta ppermute
    path)."""
    n_dev, M = compact["n_dev"], compact["M"]
    send_rows = np.full((n_dev, n_dev, M), -1, dtype=np.int32)
    recv_rows = np.full((n_dev, n_dev, M), -1, dtype=np.int32)
    p, q, pos = compact["p"], compact["q"], compact["pos"]
    send_rows[p, q, pos] = compact["srow"]
    recv_rows[q, p, pos] = compact["rrow"]
    return send_rows, recv_rows


def _wrap_band(dims, o):
    """Sorted grid indices of cells whose neighbor at cell offset ``o``
    crosses a grid boundary in some dimension — the only cells besides
    partition-boundary bands whose flat neighbor index differs from
    ``gidx + flat_delta``. Periodicity doesn't matter here: a periodic
    wrap changes the flat index and a non-periodic crossing must be
    masked, so both land in the band. ~O(surface) cells."""
    nx, ny, nz = dims
    bands = []
    for d, (ov, nd) in enumerate(((int(o[0]), nx), (int(o[1]), ny),
                                  (int(o[2]), nz))):
        if ov == 0:
            continue
        if ov > 0:
            lo, hi = max(nd - ov, 0), nd
        else:
            lo, hi = 0, min(-ov, nd)
        coord = np.arange(lo, hi, dtype=np.int64)
        other = [np.arange(dims[e], dtype=np.int64) for e in range(3)]
        other[d] = coord
        gx, gy, gz = np.meshgrid(other[0], other[1], other[2], indexing="ij")
        bands.append((gx + nx * (gy + ny * gz)).reshape(-1))
    if not bands:
        return np.empty(0, np.int64)
    return np.unique(np.concatenate(bands))


def _closed_form_hoods(hoods, dims, periodic, size, n_dev, owner,
                       local_ids, ghost_gidx, n_inner, L, R,
                       row_of_pos, pair_compact, cap, dense_tables,
                       maps, reader_rows, perm):
    """Closed-form hood data for a multi-device partition contiguous in
    cell-id order (block slabs, incl. weighted cuts).

    Rows are [inner|outer] per device, but for a contiguous partition
    the outer cells cluster in bands at the slab ends (plus wrap
    bands), so every cell OUTSIDE the candidate bands has an affine
    row: row(c) = c - slab_start - n_head_outer, and its same-slab
    unwrapped neighbor satisfies row(n) = row(c) + flat_delta. The
    roll decomposition (grid._make_nbr_gather) therefore only needs
    exact fixups for the candidate bands — computed here in
    O(bands * k), never materializing the [n_dev, L, S] tables the
    dense path builds (the validity mask is synthesized ON DEVICE from
    the row-id array, grid._synth_mask). Dense tables remain available
    as memoized thunks for host query paths."""
    nx, ny, nz = dims
    n0 = nx * ny * nz
    nxy = nx * ny
    a = np.searchsorted(owner, np.arange(n_dev)).astype(np.int64)
    b = np.append(a[1:], n0).astype(np.int64)
    # mid-region bounds from the ACTUAL outer sets: everything outside
    # [head_end, tail_start) is re-checked exactly, so a pathological
    # outer cell in the middle just widens the candidate set
    head_end, tail_start = a.copy(), b.copy()
    for d in range(n_dev):
        og = local_ids[d][n_inner[d]:].astype(np.int64) - 1
        if len(og):
            mid = (a[d] + b[d]) // 2
            h, t = og[og < mid], og[og >= mid]
            head_end[d] = (h.max() + 1) if len(h) else a[d]
            tail_start[d] = t.min() if len(t) else b[d]

    _memo = {}

    def dense_memo(hid, offs):
        if hid not in _memo:
            _memo[hid] = dense_tables(offs)
        return _memo[hid]

    hood_data = {}
    for hid, offs in hoods.items():
        k = len(offs)
        shifts = (offs[:, 0] + nx * (offs[:, 1] + ny * offs[:, 2])
                  ).astype(np.int64)
        maxD = int(np.abs(shifts).max()) if k else 0
        bands = [_wrap_band(dims, o) for o in offs]
        wrong_per = [[None] * k for _ in range(n_dev)]
        W = 1
        for d in range(n_dev):
            lo, hi = int(a[d]), int(b[d])
            he = min(int(head_end[d]) + maxD, hi)
            ts = max(int(tail_start[d]) - maxD, lo)
            endcands = np.concatenate([
                np.arange(lo, he, dtype=np.int64),
                np.arange(max(ts, he), hi, dtype=np.int64),
            ])
            for j, o in enumerate(offs):
                bj = bands[j]
                cand = np.unique(np.concatenate(
                    [endcands, bj[(bj >= lo) & (bj < hi)]]
                ))
                if len(cand) == 0:
                    wrong_per[d][j] = (np.empty(0, np.int32),
                                       np.empty(0, np.int32))
                    continue
                x = cand % nx
                y = (cand // nx) % ny
                z = cand // nxy
                tx, ty, tz = x + int(o[0]), y + int(o[1]), z + int(o[2])
                valid = np.ones(len(cand), dtype=bool)
                for coord, ndim, per in ((tx, nx, periodic[0]),
                                         (ty, ny, periodic[1]),
                                         (tz, nz, periodic[2])):
                    if per:
                        coord %= ndim
                    else:
                        valid &= (coord >= 0) & (coord < ndim)
                cv = cand[valid]
                ngi = (tx + nx * (ty + ny * tz))[valid]
                row_c = row_of_pos[cv].astype(np.int64)
                row_n = np.empty(len(ngi), dtype=np.int64)
                loc = owner[ngi] == d
                row_n[loc] = row_of_pos[ngi[loc]]
                if (~loc).any():
                    row_n[~loc] = L + np.searchsorted(
                        ghost_gidx[d], ngi[~loc]
                    )
                # ghost reads must always go through the fixup even if
                # the shift coincidentally matches (the roll never
                # reaches rows >= L)
                wrong = (row_n != row_c + shifts[j]) | (row_n >= L)
                wrong_per[d][j] = (row_c[wrong].astype(np.int32),
                                   row_n[wrong].astype(np.int32))
                W = max(W, int(wrong.sum()))
        Wc = cap(("rollW", hid), W)
        wrong_rows = np.full((n_dev, k, Wc), L, dtype=np.int32)
        wrong_src = np.zeros((n_dev, k, Wc), dtype=np.int32)
        for d in range(n_dev):
            for j in range(k):
                wr, ws = wrong_per[d][j]
                wrong_rows[d, j, : len(wr)] = wr
                wrong_src[d, j, : len(ws)] = ws
        offs_const = (offs * size).astype(np.int32)

        def tables_thunk(hid=hid, offs=offs, k=k):
            rows_t, mask_t = dense_memo(hid, offs)
            return rows_t.reshape(n_dev, L, k), mask_t.reshape(n_dev, L, k)

        def offs_thunk(hid=hid, offs=offs, k=k, offs_const=offs_const):
            _rows, mask_t = dense_memo(hid, offs)
            out = (mask_t.reshape(n_dev * L, k)[:, :, None]
                   * offs_const[None, :, :]).astype(np.int32)
            return out.reshape(n_dev, L, k, 3)

        def make_to_thunk(offs=offs):
            def thunk():
                return _build_to_tables(
                    maps, offs, size, owner, reader_rows, perm, n_dev, L, R
                )

            return thunk

        hood_data[hid] = {
            "closed_form": {"dims": dims, "periodic": periodic, "n0": n0,
                            "offsets": offs.copy(), "multi": True},
            "roll_plan": (shifts, wrong_rows, wrong_src),
            "tables_thunk": tables_thunk,
            "nbr_offs": offs_thunk,
            "offs_const": offs_const,
            "pair_compact": pair_compact,
            "to_thunk": make_to_thunk(),
        }
    return hood_data


def build_uniform_plan(mapping, topology, neighborhoods, cells, owner, n_dev,
                       cap=None):
    """All plan pieces for a level-0-only grid.

    Returns ``(layout, hood_data)`` where layout is a dict with
    local_ids / ghost_ids / n_local / n_inner / L / R / row_of_pos, and
    hood_data maps hood id -> dict with the gather tables, a lazy
    neighbors_to thunk, and send/receive lists.
    """
    from .grid import DEFAULT_NEIGHBORHOOD_ID

    dims = tuple(int(v) for v in mapping.length.get())
    n0 = dims[0] * dims[1] * dims[2]
    if n0 >= 2**31 - 2:
        # int32 grid indices throughout (native AND numpy builders):
        # callers must use the generic builder beyond 2^31 cells
        raise ValueError(f"uniform fast path limited to < 2^31 cells, got {n0}")
    size = 1 << mapping.max_refinement_level  # index units per cell
    periodic = tuple(topology.is_periodic(d) for d in range(3))
    owner = np.asarray(owner, dtype=np.int32)

    hoods = {hid: np.asarray(offs, dtype=np.int64).reshape(-1, 3)
             for hid, offs in neighborhoods.items()}

    if n_dev == 1 and os.environ.get("DCCRG_FORCE_TABLES") != "1":
        # closed-form: no lattice map, no tables (DCCRG_FORCE_TABLES=1
        # falls through to the dense builder — the bench's roll-vs-
        # table A/B leg and the cross-check path)
        return _build_single_device_plan(
            mapping, hoods, cells, dims, periodic, size, cap)

    maps = _NeighborMaps(dims, periodic)

    # -- phase 1: boundary classification + ghost edges -------------
    outer_flag = np.zeros(n0, dtype=bool)
    ghost_src_dev = []  # device that reads
    ghost_nbr = []  # gidx read remotely
    for hid, offs in hoods.items():
        seen = set()
        for o in offs:
            for sign in (1, -1):  # of-reads and to-reads (inverse offsets)
                key = (sign * int(o[0]), sign * int(o[1]), sign * int(o[2]))
                if key in seen:
                    continue
                seen.add(key)
                if n_dev == 1:
                    continue
                ng, valid = maps.shift(key)
                cross = valid & (owner[ng] != owner)
                if hid == DEFAULT_NEIGHBORHOOD_ID:
                    outer_flag |= cross
                if cross.any():
                    ghost_src_dev.append(owner[cross])
                    ghost_nbr.append(ng[cross])

    if ghost_nbr:
        gdev = np.concatenate(ghost_src_dev)
        gnbr = np.concatenate(ghost_nbr)
    else:
        gdev = np.empty(0, np.int32)
        gnbr = np.empty(0, np.int32)

    local_ids, ghost_ids, ghost_gidx = [], [], []
    n_inner = np.zeros(n_dev, np.int64)
    for d in range(n_dev):
        mine = owner == d
        inner = cells[mine & ~outer_flag]
        outer = cells[mine & outer_flag]
        local_ids.append(np.concatenate([inner, outer]))
        n_inner[d] = len(inner)
        gg = np.unique(gnbr[gdev == d]) if n_dev > 1 else np.empty(0, np.int32)
        ghost_gidx.append(gg.astype(np.int64))
        ghost_ids.append((gg.astype(np.uint64) + 1))

    from .grid import bucket_capacity

    if cap is None:
        cap = lambda name, needed: bucket_capacity(needed)
    n_local = np.array([len(x) for x in local_ids], dtype=np.int64)
    n_ghost = np.array([len(x) for x in ghost_ids], dtype=np.int64)
    L = cap("L", max(1, int(n_local.max())))
    G = int(n_ghost.max()) if n_dev > 1 else 0
    G = cap("G", G) if G else 0
    R = L + G + 1  # final row = permanent zero pad

    row_of_pos = np.full(n0, -1, dtype=np.int32)
    local_gidx = []
    for d in range(n_dev):
        lg = local_ids[d].astype(np.int64) - 1
        local_gidx.append(lg)
        row_of_pos[lg] = np.arange(len(lg), dtype=np.int32)

    # row of each cell's neighbor ON THE READER'S device: start from the
    # owner-device row (valid when reader == owner) and fix up the
    # cross-device entries with ghost rows, per reading device
    def reader_rows(ng, valid):
        rows = np.where(valid, row_of_pos[ng], R - 1).astype(np.int32)
        cross = valid & (owner[ng] != owner)
        ci = np.nonzero(cross)[0]
        if len(ci):
            cd = owner[ci]
            cn = ng[ci].astype(np.int64)
            for d in np.unique(cd):
                m = cd == d
                gpos = np.searchsorted(ghost_gidx[d], cn[m])
                rows[ci[m]] = (L + gpos).astype(np.int32)
        return rows

    # scatter permutation: flat table slot of cell c = owner*L + row
    perm = owner.astype(np.int64) * L + row_of_pos

    # pair lists for halo exchange (same construction as the generic
    # path: receive every ghost, sender = owner, sorted by id) — one
    # lexsort-grouping over the concatenated ghosts, no n_dev^2 loop
    pair_compact = build_pair_tables(
        ghost_gidx, n_dev,
        lambda keys: owner[keys],
        lambda p_s, keys: row_of_pos[keys],
        lambda q_s, keys, gpos: (L + gpos).astype(np.int32),
        lambda needed: cap(("M", "uniform"), needed),
    )

    # pad rows (beyond each device's local count) need explicit init
    # since the permutation pass only covers real cells
    pad_rows = np.concatenate([
        d * L + np.arange(n_local[d], L, dtype=np.int64) for d in range(n_dev)
    ]) if n_dev * L > n0 else np.empty(0, np.int64)
    identity_perm = n_dev == 1  # single device: rows are gidx order

    def to_row_order(glob):
        """[k, n0] (contiguous per offset) -> [n_dev*L, k] row order.
        Cache-blocked transpose; the permutation pass is skipped when
        rows are already in grid order."""
        k = glob.shape[0]
        out = np.empty((n_dev * L, k), dtype=glob.dtype)
        tgt = out if identity_perm else np.empty((n0, k), dtype=glob.dtype)
        B = 1 << 20
        for i in range(0, n0, B):
            end = min(i + B, n0)  # L may exceed n0 (bucketed capacity)
            tgt[i:end] = glob[:, i:end].T
        if not identity_perm:
            out[perm] = tgt
        return out

    def fixup_sentinels(rows):
        """Replace the native path's cross-device sentinels
        (-2 - neighbor_gidx) with ghost rows on the reader device.
        ``rows`` is in grid-index order, so the reader of entry
        (i, j) is owner[i]."""
        ci, cj = np.nonzero(rows < -1)
        if len(ci) == 0:
            return rows
        cn = (-2 - rows[ci, cj]).astype(np.int64)
        cd = owner[ci]
        for d in np.unique(cd):
            m = cd == d
            rows[ci[m], cj[m]] = (
                L + np.searchsorted(ghost_gidx[d], cn[m])
            ).astype(np.int32)
        return rows

    # -- phase 2: gather tables ------------------------------------
    from . import native

    def dense_tables(offs):
        """[n_dev*L, k] (rows, mask) in row order — the dense build."""
        k = len(offs)
        nat = (native.uniform_tables(
            dims, periodic, offs, row_of_pos,
            owner if n_dev > 1 else None, R - 1,
        ) if n0 < 2**31 - 2 else None)
        if nat is not None:
            grows, gmask = nat  # [n0, k] grid-index order
            if n_dev > 1:  # single device emits no cross sentinels
                grows = fixup_sentinels(grows)
            if identity_perm:
                # rows are gidx order, but L may exceed n0 (bucketed
                # capacity): place the lattice block, pad the rest
                rows_t = np.full((n_dev * L, k), R - 1, dtype=np.int32)
                mask_t = np.zeros((n_dev * L, k), dtype=bool)
                rows_t[:n0] = grows
                mask_t[:n0] = gmask
                del grows, gmask
            else:
                rows_t = np.empty((n_dev * L, k), dtype=np.int32)
                mask_t = np.empty((n_dev * L, k), dtype=bool)
                rows_t[perm] = grows
                mask_t[perm] = gmask
                del grows, gmask
        else:
            glob_rows = np.empty((k, n0), dtype=np.int32)
            glob_mask = np.empty((k, n0), dtype=bool)
            for j, o in enumerate(offs):
                ng, valid = maps.shift(o)
                glob_rows[j] = reader_rows(ng, valid)
                glob_mask[j] = valid
            rows_t = to_row_order(glob_rows)
            mask_t = to_row_order(glob_mask)
            del glob_rows, glob_mask
        if len(pad_rows):
            rows_t[pad_rows] = R - 1
            mask_t[pad_rows] = False
        return rows_t, mask_t

    # a partition contiguous in cell-id order (block, incl. weighted)
    # takes the closed-form path: rows are piecewise-affine in the grid
    # index, so roll shifts + fixup sets come from candidate bands and
    # NO [n_dev, L, S] table is materialized (VERDICT r3 item 4)
    contiguous = bool(np.all(owner[1:] >= owner[:-1])) if len(owner) else True
    if contiguous and os.environ.get("DCCRG_FORCE_TABLES") != "1":
        hood_data = _closed_form_hoods(
            hoods, dims, periodic, size, n_dev, owner,
            local_ids, ghost_gidx, n_inner, L, R,
            row_of_pos, pair_compact, cap, dense_tables,
            maps, reader_rows, perm,
        )
        layout = dict(
            local_ids=local_ids, ghost_ids=ghost_ids, n_local=n_local,
            n_inner=n_inner, L=L, R=R, row_of_pos=row_of_pos,
        )
        return layout, hood_data

    hood_data = {}
    for hid, offs in hoods.items():
        k = len(offs)
        rows_t, mask_t = dense_tables(offs)
        # offsets are per-slot constants (offset * cell size in index
        # units): stencils synthesize them on device from the mask, so
        # no [n_dev, L, k, 3] array is built here (offs_thunk serves
        # host-side queries/tests)
        offs_const = (offs * size).astype(np.int32)  # [k, 3]

        def offs_thunk(mask_t=mask_t, offs_const=offs_const, k=k):
            out = np.empty((n_dev * L, k, 3), dtype=np.int32)
            for j in range(k):
                np.multiply(
                    mask_t[:, j, None], offs_const[j][None, :], out=out[:, j, :]
                )
            return out.reshape(n_dev, L, k, 3)

        hood_data[hid] = {
            "nbr_rows": rows_t.reshape(n_dev, L, k),
            "nbr_offs": offs_thunk,
            "offs_const": offs_const,
            "nbr_mask": mask_t.reshape(n_dev, L, k),
            "pair_compact": pair_compact,
        }

    def make_to_thunk(offs):
        def thunk():
            return _build_to_tables(
                maps, offs, size, owner, reader_rows, perm, n_dev, L, R
            )

        return thunk

    for hid, offs in hoods.items():
        hood_data[hid]["to_thunk"] = make_to_thunk(offs)

    layout = dict(
        local_ids=local_ids, ghost_ids=ghost_ids, n_local=n_local,
        n_inner=n_inner, L=L, R=R, row_of_pos=row_of_pos,
    )
    return layout, hood_data


def _build_to_tables(maps, offs, size, owner, reader_rows, perm, n_dev, L, R):
    """neighbors_to gather tables: cell v is a to-neighbor of c when
    c = v + offset, i.e. the inverse relation at offset -o with the
    offset recorded negated (build_neighbor_lists, neighbors.py). Slot
    order within a row is (neighbor gidx, item) — any mask-consistent
    padding is equivalent for kernels."""
    k = len(offs)
    n0 = maps.n0
    ng_all = np.empty((n0, k), dtype=np.int32)
    valid_all = np.empty((n0, k), dtype=bool)
    for j, o in enumerate(offs):
        ng, valid = maps.shift((-int(o[0]), -int(o[1]), -int(o[2])))
        ng_all[:, j] = ng
        valid_all[:, j] = valid
    # order slots by (neighbor gidx, item), invalid entries last —
    # matches the generic stream's (source-sorted, stable) layout
    key = np.where(valid_all, ng_all.astype(np.int64) * k,
                   np.iinfo(np.int64).max - k)
    key = key + np.arange(k, dtype=np.int64)[None, :]
    order = np.argsort(key, axis=1, kind="stable")
    ng_s = np.take_along_axis(ng_all, order, axis=1)
    valid_s = np.take_along_axis(valid_all, order, axis=1)
    to_rows = np.full((n_dev * L, k), R - 1, dtype=np.int32)
    to_mask = np.zeros((n_dev * L, k), dtype=bool)
    for j in range(k):
        to_rows[perm, j] = reader_rows(ng_s[:, j], valid_s[:, j])
        to_mask[perm, j] = valid_s[:, j]
    o_arr = (-np.asarray(offs, dtype=np.int64) * size).astype(np.int32)  # [k,3]
    offs_s = o_arr[order]  # [n0, k, 3]
    to_offs = np.zeros((n_dev * L, k, 3), dtype=np.int32)
    to_offs[perm] = np.where(valid_s[..., None], offs_s, 0)
    return (
        to_rows.reshape(n_dev, L, k),
        to_offs.reshape(n_dev, L, k, 3),
        to_mask.reshape(n_dev, L, k),
    )


def _build_single_device_plan(mapping, hoods, cells, dims, periodic, size, cap):
    """Closed-form plan for a single-device uniform grid: NO gather
    tables are materialized. Rows are grid order; neighbor gathers
    lower to rolls whose shifts and wrap-fixup sets are computed
    arithmetically (the stencil paths read them via
    _HoodPlan.roll_plan), and the validity mask is synthesized on
    device from the row index (closed_form metadata). The full tables
    and the neighbors_to tables exist as lazy thunks for host query /
    introspection paths — a 512^3 grid plans in milliseconds instead
    of building multi-GB tables."""
    from .grid import bucket_capacity

    if cap is None:
        cap = lambda name, needed: bucket_capacity(needed)
    nx, ny, nz = dims
    n0 = nx * ny * nz
    L = cap("L", n0)
    R = L + 1
    row_of_pos = np.arange(n0, dtype=np.int32)
    _lazy = {}

    def get_maps():
        # the n0-sized lattice map exists only if an introspection
        # thunk actually fires
        if "maps" not in _lazy:
            _lazy["maps"] = _NeighborMaps(dims, periodic)
        return _lazy["maps"]

    def band_rows(o):
        """(wrong rows, true src rows) for one offset: the rows whose
        flat roll crosses a periodic wrap (non-periodic edges are
        masked invalid instead)."""
        ox, oy, oz = int(o[0]), int(o[1]), int(o[2])
        bands = []
        for d, (ov, nd) in enumerate(((ox, nx), (oy, ny), (oz, nz))):
            if ov == 0:
                continue
            # rows whose dim-d coordinate steps outside [0, nd); with
            # |offset| >= nd every row wraps (tiny periodic dims)
            if ov > 0:
                lo, hi = max(nd - ov, 0), nd
            else:
                lo, hi = 0, min(-ov, nd)
            coord = np.arange(lo, hi, dtype=np.int64)
            other = [np.arange(dims[e], dtype=np.int64) for e in range(3)]
            other[d] = coord
            gx, gy, gz = np.meshgrid(other[0], other[1], other[2],
                                     indexing="ij")
            bands.append((gx + nx * (gy + ny * gz)).reshape(-1))
        if not bands:
            return (np.empty(0, np.int64),) * 2
        rows = np.unique(np.concatenate(bands))
        # validity: non-periodic crossings are masked, not fixed up
        x = rows % nx
        y = (rows // nx) % ny
        z = rows // (nx * ny)
        tx, valid = x + ox, np.ones(len(rows), dtype=bool)
        ty, tz = y + oy, z + oz
        for coord, nd, per in ((tx, nx, periodic[0]), (ty, ny, periodic[1]),
                               (tz, nz, periodic[2])):
            if per:
                coord %= nd
            else:
                valid &= (coord >= 0) & (coord < nd)
        rows, tx, ty, tz = rows[valid], tx[valid], ty[valid], tz[valid]
        true_flat = tx + nx * (ty + ny * tz)
        # only rows where the plain roll would be wrong need fixing
        roll_val = (rows + (ox + nx * (oy + ny * oz))) % L
        wrong = roll_val != true_flat
        return rows[wrong], true_flat[wrong]

    hood_data = {}
    for hid, offs in hoods.items():
        k = len(offs)
        shifts = (offs[:, 0] + nx * (offs[:, 1] + ny * offs[:, 2])).astype(np.int64)
        wrongs = [band_rows(o) for o in offs]
        W = cap(("rollW", hid), max(1, max(len(w) for w, _ in wrongs)))
        wrong_rows = np.full((1, k, W), L, dtype=np.int32)
        wrong_src = np.zeros((1, k, W), dtype=np.int32)
        for j, (w, s) in enumerate(wrongs):
            wrong_rows[0, j, : len(w)] = w
            wrong_src[0, j, : len(w)] = s
        pair_compact = empty_pair_compact(1, 16)

        def tables_thunk(offs=offs, k=k, hid=hid):
            """Materialize the dense [1, L, k] tables on demand (host
            query / introspection paths only); memoized so nbr_rows,
            nbr_mask and nbr_offs consumers share one build."""
            key = ("tables", hid)
            if key in _lazy:
                return _lazy[key]
            rows_t = np.full((L, k), R - 1, dtype=np.int32)
            mask_t = np.zeros((L, k), dtype=bool)
            for j, o in enumerate(offs):
                ng, valid = get_maps().shift(o)
                rows_t[:n0, j] = np.where(valid, ng, R - 1)
                mask_t[:n0, j] = valid
            _lazy[key] = (rows_t.reshape(1, L, k), mask_t.reshape(1, L, k))
            return _lazy[key]

        offs_const = (offs * size).astype(np.int32)

        def offs_thunk(thunk=tables_thunk, offs_const=offs_const, k=k):
            _rows, mask_t = thunk()
            out = (mask_t.reshape(L, k)[:, :, None]
                   * offs_const[None, :, :]).astype(np.int32)
            return out.reshape(1, L, k, 3)

        def reader_rows(ng, valid):
            return np.where(valid, ng.astype(np.int32), R - 1).astype(np.int32)

        def make_to_thunk(offs=offs):
            def thunk():
                owner = np.zeros(n0, dtype=np.int32)
                perm = row_of_pos.astype(np.int64)
                return _build_to_tables(
                    get_maps(), offs, size, owner, reader_rows, perm, 1, L, R
                )

            return thunk

        hood_data[hid] = {
            "closed_form": {"dims": dims, "periodic": periodic, "n0": n0,
                            "offsets": offs.copy()},
            "roll_plan": (shifts, wrong_rows, wrong_src),
            "tables_thunk": tables_thunk,
            "nbr_offs": offs_thunk,
            "offs_const": offs_const,
            "pair_compact": pair_compact,
            "to_thunk": make_to_thunk(),
        }

    layout = dict(
        local_ids=[cells], ghost_ids=[np.empty(0, np.uint64)],
        n_local=np.array([n0], dtype=np.int64),
        n_inner=np.array([n0], dtype=np.int64),
        L=L, R=R, row_of_pos=row_of_pos,
    )
    return layout, hood_data
