"""Resilience layer: checkpoint integrity, numerics watchdog with
auto-rollback, and OOM-aware degradation.

dccrg is the grid layer of week-long production plasma runs (Vlasiator
survives node loss only through checkpoint/restart), so the framework
must detect, degrade and recover without a human watching. Four
pillars, each exercised end to end by the fault-injection suite
(tests/test_resilience.py, tests/test_checkpoint_integrity.py, driven
by :mod:`dccrg_tpu.faults`):

**Checkpoint integrity** — :func:`save_checkpoint` writes the pinned
``.dc`` byte format (unchanged — golden-file tests still pass)
*atomically*: temp file in the same directory, fsync, rename, with
bounded retries on transient I/O errors; a crash mid-save can never
destroy the previous checkpoint. A sidecar ``<file>.crc`` records a
CRC32 per fixed-size chunk of the final bytes; :func:`load_checkpoint`
verifies it and raises :class:`CheckpointCorruptionError` naming the
bad chunk, or — with ``strict=False`` — salvages every intact chunk
(corrupt cells come back zeroed and are listed in the
:class:`SalvageReport`).

**Numerics watchdog** — :func:`check_finite` runs a device-side
``isfinite`` reduction over the watched fields (one scalar crosses to
the host, a psum-style min via :mod:`dccrg_tpu.comm`);
:func:`assert_finite` turns a trip into a :class:`NumericsError`
naming the offending fields and cells (located host-side by
:func:`dccrg_tpu.verify.find_nonfinite_cells`). ``DCCRG_WATCHDOG=N``
makes ``Grid.run_steps`` self-check every ~N steps.

**Auto-rollback** — :class:`ResilientRunner` wraps a step loop:
checkpoint every C steps, watchdog-check every K; on a trip it dumps a
diagnostic bundle (step, fields, cell ids), rolls back to the last
good checkpoint and resumes, with bounded retries and exponential
backoff before surfacing :class:`ResilienceExhaustedError`.

**OOM degradation** — :func:`guarded_step` dispatches
``Grid.run_steps`` and, on XLA ``RESOURCE_EXHAUSTED`` (real or
injected), walks the fallback chain *current gather mode -> slot-wise
roll -> dense tables*, logging each downgrade; :func:`safe_devices`
probes the backend in a killable subprocess with retries/backoff so a
dead accelerator tunnel can never hang a bench or example script
(``python -m dccrg_tpu.resilience`` is the CLI probe the poller
scripts use).
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import time
import zlib
from contextlib import contextmanager
from dataclasses import dataclass, field as dataclass_field

import numpy as np

from . import background
from . import checkpoint as checkpoint_mod
from . import faults, telemetry

logger = logging.getLogger("dccrg_tpu.resilience")

CRC_CHUNK = 1 << 20  # bytes per sidecar checksum chunk
SIDECAR_FORMAT = "dccrg-dc-crc-v1"
SIDECAR_SUFFIX = ".crc"
#: Incremental (delta) checkpoints: a ``.dcd`` file is a valid ``.dc``
#: of the dirty-field sub-schema, chained to a parent save through its
#: sidecar's ``delta`` record (parent file + step + content digest).
DELTA_SUFFIX = ".dcd"
_MAX_CHAIN = 4096  # delta-chain depth bound (cycle backstop)


class CheckpointCorruptionError(ValueError):
    """A checkpoint failed integrity verification. ``bad_chunks`` holds
    the failing sidecar chunk indices (empty when the sidecar itself is
    missing/unreadable)."""

    def __init__(self, msg, bad_chunks=()):
        super().__init__(msg)
        self.bad_chunks = list(bad_chunks)


class DeltaChainError(CheckpointCorruptionError):
    """A delta checkpoint's keyframe+delta chain cannot be restored end
    to end. ``link`` names the broken file; ``chain`` lists the link
    paths resolved so far (keyframe first, when known). The typed
    salvage contract: :func:`dccrg_tpu.supervise.resume_latest` catches
    this and falls back to the last verifying prefix (an older delta or
    the keyframe) instead of failing the resume."""

    def __init__(self, msg, link=None, chain=()):
        super().__init__(msg)
        self.link = link
        self.chain = list(chain)


class NumericsError(RuntimeError):
    """The watchdog found non-finite values. ``details`` maps field
    name -> offending cell ids."""

    def __init__(self, msg, details=None):
        super().__init__(msg)
        self.details = details or {}


class ResilienceExhaustedError(RuntimeError):
    """Every bounded recovery attempt failed; the error is surfaced."""


class DeviceProbeError(RuntimeError):
    """The device backend did not answer within the probe budget."""


class RunInterrupted(RuntimeError):
    """The step loop stopped cleanly at a step boundary because the
    runner's ``interrupt_poll`` requested it — consensus-agreed across
    ranks, so EVERY rank raises this at the same boundary with the
    grid holding exactly ``step`` completed steps. Raised for the
    supervision layer (:mod:`dccrg_tpu.supervise`), which turns it
    into an emergency checkpoint plus a resumable exit."""

    def __init__(self, step: int):
        super().__init__(
            f"run interrupted at the boundary after step {step} "
            "(preemption requested; state is consistent on every rank)")
        self.step = int(step)


# ---------------------------------------------------------------------
# checkpoint integrity: CRC sidecar + atomic save + verifying load
# ---------------------------------------------------------------------

def sidecar_path(filename: str) -> str:
    return filename + SIDECAR_SUFFIX


def _chunk_ranges(payload_start, file_bytes, chunk_bytes, n=None):
    """Byte ranges of the sidecar chunks: chunk 0 is exactly the
    metadata block [0, payload_start) — mapping / geometry / offset
    table, whose corruption is never salvageable — and chunks >= 1 tile
    the payload in ``chunk_bytes`` pieces, so a bad payload chunk maps
    onto a bounded set of cells."""
    ranges = [(0, payload_start)]
    pos = payload_start
    while pos < file_bytes or (n is not None and len(ranges) < n):
        ranges.append((pos, min(pos + chunk_bytes, file_bytes)))
        pos += chunk_bytes
    return ranges


def _range_crcs(path: str, ranges, block: int = CRC_CHUNK) -> list:
    """CRC32 of each ``[lo, hi)`` byte range of ``path``, streamed
    ``block`` bytes at a time — ``zlib.crc32`` is incremental, so no
    range ever materializes in host RAM (at 512^3 the checkpoint is
    multi-GB and the save path already streams precisely to bound host
    memory; the checksum passes must too). A range truncated away
    checksums only the bytes that exist, so it mismatches — exactly
    what the caller needs it to do."""
    out = []
    with open(path, "rb") as f:
        for lo, hi in ranges:
            f.seek(int(lo))
            crc, left = 0, int(hi) - int(lo)
            while left > 0:
                buf = f.read(min(block, left))
                if not buf:
                    break
                crc = zlib.crc32(buf, crc)
                left -= len(buf)
            out.append(crc & 0xFFFFFFFF)
    return out


def _stream_crcs(path: str, chunk_ranges, spans, block: int = CRC_CHUNK):
    """ONE sequential streamed pass computing CRC32s of both the chunk
    tiling (``chunk_ranges``: contiguous, in order) and an overlay of
    ``spans`` (sorted by start, non-overlapping — the two-phase save's
    per-rank slice runs). Returns ``(chunk_crcs, span_crcs)``. The
    commit rank needs both layouts over the same bytes; reading the
    (multi-GB at 512^3) temp file once instead of twice halves the
    publish-path disk traffic."""
    chunk_crcs = []
    span_crcs = [0] * len(spans)
    si = 0
    with open(path, "rb") as f:
        for lo, hi in chunk_ranges:
            f.seek(int(lo))
            crc, pos, left = 0, int(lo), int(hi) - int(lo)
            while left > 0:
                buf = f.read(min(block, left))
                if not buf:
                    break
                crc = zlib.crc32(buf, crc)
                blo, bhi = pos, pos + len(buf)
                while si < len(spans) and spans[si][1] <= blo:
                    si += 1  # spans fully behind this block are done
                j = si
                while j < len(spans) and spans[j][0] < bhi:
                    s = max(int(spans[j][0]), blo)
                    e = min(int(spans[j][1]), bhi)
                    if s < e:
                        span_crcs[j] = zlib.crc32(buf[s - blo:e - blo],
                                                  span_crcs[j])
                    j += 1
                pos = bhi
                left -= len(buf)
            chunk_crcs.append(crc & 0xFFFFFFFF)
    return chunk_crcs, [c & 0xFFFFFFFF for c in span_crcs]


def _sidecar_record(path: str, header_size: int = 0,
                    chunk_bytes: int = CRC_CHUNK) -> dict:
    """The sidecar record for ``path``'s current bytes, checksummed in
    ``chunk_bytes`` streams (the metadata parse pages in only the head
    of a memory map — the payload never crosses to host RAM whole)."""
    file_bytes = os.path.getsize(path)
    raw = np.memmap(path, dtype=np.uint8, mode="r")
    payload_start = checkpoint_mod.parse_metadata(raw, header_size)[6]
    del raw
    ranges = _chunk_ranges(payload_start, file_bytes, chunk_bytes)
    crcs = _range_crcs(path, ranges, chunk_bytes)
    return {"format": SIDECAR_FORMAT, "chunk_bytes": chunk_bytes,
            "file_bytes": file_bytes, "payload_start": payload_start,
            "header_size": header_size, "crc32": crcs}


def _write_sidecar_record(side: str, rec: dict) -> None:
    tmp = side + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(rec, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, side)


def write_sidecar(filename: str, header_size: int = 0,
                  chunk_bytes: int = CRC_CHUNK) -> str:
    """Checksum ``filename`` into its ``.crc`` sidecar: CRC32 of the
    metadata block (chunk 0), then one CRC32 per ``chunk_bytes`` of
    payload. The ``.dc`` file itself is untouched (the golden byte
    format stays pinned)."""
    side = sidecar_path(filename)
    _write_sidecar_record(side, _sidecar_record(filename, header_size,
                                                chunk_bytes))
    return side


def read_sidecar(filename: str):
    """The parsed sidecar record, or None when none exists. An
    unparseable sidecar raises CheckpointCorruptionError (corruption
    hit the sidecar itself — the checkpoint cannot be trusted)."""
    side = sidecar_path(filename)
    if not os.path.exists(side):
        return None
    try:
        with open(side) as f:
            rec = json.load(f)
        if rec.get("format") != SIDECAR_FORMAT:
            raise ValueError(f"unknown sidecar format {rec.get('format')!r}")
        # a sidecar corrupted at rest can still parse as JSON — reject
        # implausible geometry here rather than hanging or crashing
        # the chunk-range math downstream
        cb = int(rec["chunk_bytes"])
        fb = int(rec["file_bytes"])
        ps = int(rec["payload_start"])
        crcs = rec["crc32"]
        if (cb <= 0 or fb < 0 or not 0 <= ps <= fb
                or not isinstance(crcs, list)
                or not all(isinstance(c, int) for c in crcs)):
            raise ValueError("implausible sidecar geometry")
        # the crc list must cover the whole recorded file: a sidecar
        # whose tail entries were lost (still valid JSON) would
        # otherwise leave trailing payload chunks silently unverified
        # (_bad_chunks zips against the shorter list)
        want_chunks = 1 + max(0, -(-(fb - ps) // cb))
        if len(crcs) != want_chunks:
            raise ValueError(
                f"sidecar records {len(crcs)} chunk crc(s), geometry "
                f"implies {want_chunks}")
        # two-phase multi-process saves extend the record with a
        # per-rank slice table [dev, rank, lo, hi, crc]; reject a
        # mangled one here like the rest of the geometry
        sl = rec.get("slices")
        if sl is not None and not (
                isinstance(sl, list)
                and all(isinstance(s, list) and len(s) == 5
                        and all(isinstance(v, int) for v in s)
                        and 0 <= s[2] <= s[3] <= fb
                        for s in sl)):
            raise ValueError("implausible per-rank slice table")
        # incremental saves extend the record with a delta subrecord
        # (dirty-field list + parent link); reject a mangled one here
        # so the chain walk never dereferences garbage
        d = rec.get("delta")
        if d is not None:
            p = d.get("parent") if isinstance(d, dict) else None
            if not (isinstance(d, dict)
                    and isinstance(d.get("fields"), list)
                    and all(isinstance(f, str) for f in d["fields"])
                    and isinstance(d.get("step"), int)
                    and isinstance(p, dict)
                    and isinstance(p.get("file"), str) and p["file"]
                    and os.path.basename(p["file"]) == p["file"]
                    and isinstance(p.get("step"), int)
                    and isinstance(p.get("digest"), int)):
                raise ValueError("implausible delta record")
        # SDC-audit saves extend the record with a payload fingerprint
        # ({field: [s1, s2, nbytes]}, see resilience.audit_checkpoint);
        # reject a mangled one like the rest of the geometry
        integ = rec.get("integrity")
        if integ is not None and not (
                isinstance(integ, dict)
                and all(isinstance(k, str) and isinstance(v, list)
                        and len(v) == 3
                        and all(isinstance(x, int) for x in v)
                        and v[2] > 0
                        for k, v in integ.items())):
            raise ValueError("implausible integrity record")
        return rec
    except (ValueError, KeyError, TypeError) as e:
        raise CheckpointCorruptionError(
            f"unreadable checksum sidecar {side}: {e}") from e


def _rec_ranges(rec) -> list:
    return _chunk_ranges(int(rec["payload_start"]), int(rec["file_bytes"]),
                         int(rec["chunk_bytes"]), n=len(rec["crc32"]))


def _chunk_name(i: int, ranges) -> str:
    if i >= len(ranges):  # the trailing-garbage sentinel
        return "trailing bytes past the recorded file size"
    lo, hi = ranges[i]
    what = "metadata block" if i == 0 else f"payload chunk {i}"
    return f"{what} (bytes {lo}-{max(lo, hi - 1)})"


def _bad_chunks(filename: str, rec) -> list:
    """Indices of sidecar chunks whose CRC32 no longer matches,
    streamed ``chunk_bytes`` at a time (never the whole file in RAM).
    Chunks truncated away count as bad; garbage appended past the
    recorded size is reported as the sentinel index one past the last
    chunk — the recorded range may still be fully intact, so salvage
    just trims the tail instead of zeroing good cells."""
    want = rec["crc32"]
    got = _range_crcs(filename, _rec_ranges(rec), int(rec["chunk_bytes"]))
    bad = [i for i, (g, w) in enumerate(zip(got, want))
           if g != (w & 0xFFFFFFFF)]
    if os.path.getsize(filename) > int(rec["file_bytes"]):
        bad.append(len(want))
    return bad


def _bad_slices(filename: str, rec) -> list:
    """Indices of per-rank slice entries — two-phase multi-process
    saves record ``[dev, rank, lo, hi, crc]`` per written run — whose
    bytes no longer match. The attribution layer over the chunk CRCs:
    a bad chunk says WHERE the corruption is, a bad slice says WHOSE
    write it was (the dead/torn rank a salvage report names)."""
    sl = rec.get("slices") or []
    if not sl:
        return []
    got = _range_crcs(filename, [(int(s[2]), int(s[3])) for s in sl])
    return [i for i, s in enumerate(sl)
            if got[i] != (int(s[4]) & 0xFFFFFFFF)]


def verify_checkpoint(filename: str, require_sidecar: bool = True) -> list:
    """Verify ``filename`` against its sidecar. Returns the bad chunk
    indices (empty = intact). Raises CheckpointCorruptionError when the
    sidecar is missing and ``require_sidecar``."""
    rec = read_sidecar(filename)
    if rec is None:
        if require_sidecar:
            raise CheckpointCorruptionError(
                f"{filename}: no checksum sidecar ({sidecar_path(filename)}); "
                "wrote with a pre-resilience save, or the sidecar was lost. "
                "Load with strict=False to proceed unverified."
            )
        return []
    return _bad_chunks(filename, rec)


# ---------------------------------------------------------------------
# incremental (delta) checkpoints: dirty-field saves chained to a
# keyframe through sidecar parent links
# ---------------------------------------------------------------------

def record_digest(rec) -> int:
    """Content digest of a sidecar record — CRC32 over the per-chunk
    CRC list + file size, chained with the parent's digest for delta
    records. Derived (never stored), so a tampered sidecar changes the
    digest and breaks its children's recorded parent links; together
    with per-link byte verification this pins a chain to the exact
    saves that produced it: a parent *replaced* by a different save
    under the same name is detected even though its own CRCs verify."""
    import struct

    crcs = np.asarray([int(c) & 0xFFFFFFFF for c in rec["crc32"]],
                      dtype=np.uint32)
    d = zlib.crc32(crcs.tobytes(),
                   zlib.crc32(struct.pack("<Q", int(rec["file_bytes"]))))
    delta = rec.get("delta")
    if delta:
        d = zlib.crc32(
            struct.pack("<I", int(delta["parent"]["digest"]) & 0xFFFFFFFF),
            d)
    return d & 0xFFFFFFFF


def is_delta_checkpoint(filename: str, rec=None) -> bool:
    """True when ``filename`` is an incremental (delta) save — by its
    ``.dcd`` suffix or its sidecar's delta record."""
    if filename.endswith(DELTA_SUFFIX):
        return True
    if rec is None:
        try:
            rec = read_sidecar(filename)
        except CheckpointCorruptionError:
            return False
    return bool(rec and rec.get("delta"))


def chain_links(filename: str) -> list:
    """Resolve ``filename``'s keyframe+delta chain from sidecar parent
    links: ``[(path, record)]`` KEYFRAME FIRST (a plain full
    checkpoint is its own one-link chain). Structural resolution only
    — byte verification is :func:`verify_chain`'s job — but every
    parent's recorded content digest is checked against the child's
    link here, so a replaced ancestor is named. Raises
    :class:`DeltaChainError` naming the broken link on a missing
    file/sidecar, a digest mismatch, or a cycle."""
    links, seen = [], set()
    cur = os.path.abspath(filename)
    dirpath = os.path.dirname(cur)
    expect = None  # the child's recorded parent digest
    while True:
        done = [p for p, _r in reversed(links)]
        if cur in seen or len(links) >= _MAX_CHAIN:
            raise DeltaChainError(
                f"{filename}: delta parent links form a cycle at {cur}",
                link=cur, chain=done)
        seen.add(cur)
        if not os.path.exists(cur):
            raise DeltaChainError(
                f"{filename}: chain link {cur} is missing (its keyframe "
                "or an intermediate delta was deleted)", link=cur,
                chain=done)
        try:
            rec = read_sidecar(cur)
        except CheckpointCorruptionError as e:
            raise DeltaChainError(
                f"{filename}: chain link {cur} has an unreadable "
                f"sidecar ({e})", link=cur, chain=done) from e
        if rec is None:
            raise DeltaChainError(
                f"{filename}: chain link {cur} has no sidecar — a delta "
                "chain cannot be interpreted without one (the "
                "dirty-field list and parent link live there)",
                link=cur, chain=done)
        if expect is not None and record_digest(rec) != expect:
            raise DeltaChainError(
                f"{filename}: chain link {cur} does not match its "
                f"child's recorded parent digest {expect:#010x} — the "
                "parent was overwritten by a different save", link=cur,
                chain=done)
        links.append((cur, rec))
        delta = rec.get("delta")
        if not delta:
            break
        expect = int(delta["parent"]["digest"]) & 0xFFFFFFFF
        cur = os.path.join(dirpath, delta["parent"]["file"])
    links.reverse()
    return links


def verify_chain(filename: str, assume_ok=(), _memo=None) -> list:
    """Verify every link of ``filename``'s chain — bytes against each
    sidecar's chunk CRCs plus the parent digest links — and return the
    link paths, keyframe first. Raises :class:`DeltaChainError` naming
    the FIRST broken link in chain order (a broken ancestor
    invalidates every later delta). ``assume_ok`` paths skip the byte
    pass (the process that just saved and verified them can vouch);
    ``_memo`` caches per-file results across calls in one sweep."""
    links = chain_links(filename)
    memo = _memo if _memo is not None else {}
    vouched = {os.path.abspath(p) for p in assume_ok}
    for path, rec in links:
        if path in vouched:
            continue
        bad = memo.get(path)
        if bad is None:
            bad = memo[path] = _bad_chunks(path, rec)
        if bad:
            names = ", ".join(_chunk_name(i, _rec_ranges(rec))
                              for i in bad)
            raise DeltaChainError(
                f"{filename}: chain link {path} fails verification "
                f"({names})", link=path, chain=[p for p, _r in links])
    return [p for p, _r in links]


def _chain_scratch(path: str) -> str:
    """Writable scratch path for a chain materialization: next to the
    checkpoint when its directory is writable (same filesystem — a
    multi-GB reconstruction never lands on a small tmpfs — and an
    orphan is swept by ``checkpoint.stale_temp_files``), else the
    system temp dir: a READ-ONLY checkpoint directory (archived
    snapshot, RO-mounted shared volume) must stay resumable, exactly
    like full ``.dc`` saves which load in place."""
    dirpath = os.path.dirname(os.path.abspath(path))
    if os.access(dirpath, os.W_OK):
        return path + f".chain.{os.getpid()}"
    import tempfile

    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".chain.")
    os.close(fd)
    return tmp


def _cell_data_fields(cell_data) -> dict:
    """Normalize a user ``cell_data`` spec (or ``Grid.fields``) into
    ``{name: (shape tuple, np.dtype)}`` — the serialization contract
    the chain materializer computes field column offsets from."""
    out = {}
    for name, spec in cell_data.items():
        if isinstance(spec, tuple):
            shape, dtype = spec
        else:
            shape, dtype = (), spec
        out[name] = (tuple(shape), np.dtype(dtype))
    return out


def materialize_chain(filename: str, out_path: str, cell_data,
                      variable=None, verify: bool = True,
                      _memo=None) -> list:
    """Reconstruct the full checkpoint bytes of delta ``filename`` into
    ``out_path``: copy the keyframe, then overlay each delta's
    dirty-field columns in chain order (each cell's fixed-field block
    lives at its offset-table position, so the overlay is a strided
    byte scatter — vectorized, chunked, never the whole payload in
    RAM). The result is bitwise identical to the full save an
    uninterrupted run would have written at the delta's step (pinned by
    the chain tests and the fuzz oracle). ``cell_data`` is the caller's
    field schema (``Grid.fields`` works too); returns the chain's link
    paths. On multi-process meshes every rank reconstructs its own
    scratch copy (``out_path`` must be per-process, e.g. pid-suffixed)
    and the collective load barrier downstream keeps them aligned."""
    import shutil

    links = chain_links(filename)
    if verify:
        verify_chain(filename, _memo=_memo)
    key_path, key_rec = links[0]
    fields = _cell_data_fields(cell_data)
    fixed_spec, fixed_bytes, _var = checkpoint_mod._payload_spec_of(
        fields, variable)
    col_of = {}
    col = 0
    for name, _shape, _dtype, nbytes in fixed_spec:
        col_of[name] = col
        col += nbytes

    shutil.copyfile(key_path, out_path)
    header_size = int(key_rec.get("header_size", 0))
    raw_out = np.memmap(out_path, dtype=np.uint8, mode="r+")
    try:
        meta = checkpoint_mod.parse_metadata(raw_out, header_size)
        cells_full, offs_full = meta[4], meta[5].astype(np.int64)
        for dpath, drec in links[1:]:
            dnames = list(drec["delta"]["fields"])
            if not dnames:
                continue
            raw_d = np.memmap(dpath, dtype=np.uint8, mode="r")
            dmeta = checkpoint_mod.parse_metadata(
                raw_d, int(drec.get("header_size", 0)))
            dcells, doffs = dmeta[4], dmeta[5].astype(np.int64)
            if not np.array_equal(dcells, cells_full):
                raise DeltaChainError(
                    f"{filename}: delta {dpath} records a different "
                    "cell list than its keyframe (a structural change "
                    "without a keyframe — the chain is inconsistent)",
                    link=dpath, chain=[p for p, _r in links])
            try:
                dspec, _db, _dv = checkpoint_mod._payload_spec_of(
                    {n: fields[n] for n in dnames}, None)
            except KeyError as e:
                raise DeltaChainError(
                    f"{filename}: delta {dpath} stores field {e} not in "
                    "the caller's schema", link=dpath,
                    chain=[p for p, _r in links]) from e
            src_col = 0
            for name, _shape, _dtype, nbytes in dspec:
                dst = offs_full + col_of[name]
                src = doffs + src_col
                span = np.arange(nbytes, dtype=np.int64)[None, :]
                blk = max(1, (8 << 20) // max(nbytes, 1))
                for s in range(0, len(cells_full), blk):
                    e = min(s + blk, len(cells_full))
                    raw_out[dst[s:e, None] + span] = \
                        raw_d[src[s:e, None] + span]
                src_col += nbytes
            del raw_d
        raw_out.flush()
    finally:
        del raw_out
    return [p for p, _r in links]


@telemetry.traced("ckpt.save")
def save_checkpoint(grid, filename: str, header: bytes = b"",
                    variable=None, sidecar: bool = True, retries: int = 2,
                    backoff: float = 0.1, chunk_bytes: int = CRC_CHUNK,
                    *, fields=None, sidecar_extra=None) -> str:
    """Atomic checkpoint save: the pinned ``.dc`` bytes stream into a
    temp file in the target directory, fsync, then one rename — a crash
    at any point leaves either the old or the new checkpoint complete,
    never a torn file under the final name. Transient I/O errors retry
    with exponential backoff. With ``sidecar`` (default) the per-chunk
    CRC32 sidecar is written after the rename.

    ``fields`` restricts the save to a field subset and
    ``sidecar_extra`` merges extra keys (the delta parent link) into
    the sidecar record — the incremental-save plumbing; use
    :func:`save_delta_checkpoint` rather than passing them directly."""
    kind = ("delta" if sidecar_extra and "delta" in sidecar_extra
            else "keyframe")
    telemetry.inc("dccrg_saves_total", kind=kind)
    # measured save cost is a first-class controller input
    # (dccrg_ckpt_save_seconds{kind}): the autopilot prices checkpoint
    # cadence with it, and operators read the same histogram
    t_save = time.perf_counter()
    if grid._multiproc:
        # multi-process meshes take the TWO-PHASE-COMMIT save
        # (checkpoint._save_process_slice): every rank streams its
        # slice runs into <file>.mp-tmp, a timeout-guarded commit
        # barrier collects per-run CRC32s across ranks, and the
        # committing rank verifies every slice before the atomic
        # rename — with the sidecar (extended by the per-rank slice
        # table) written by that rank. No retry loop here: replaying
        # the save on ONE rank would desynchronize the ranks' barrier
        # sequence, so transient-I/O retry on this path belongs to the
        # caller (who can re-enter collectively on every rank).
        faults.fire("checkpoint.write", path=filename, attempt=0)
        checkpoint_mod.save_grid_data(
            grid, filename, header=header, variable=variable,
            sidecar=sidecar, sidecar_chunk_bytes=chunk_bytes,
            fields=fields, sidecar_extra=sidecar_extra)
        faults.corrupt_file(filename)
        telemetry.observe("dccrg_ckpt_save_seconds",
                          time.perf_counter() - t_save, kind=kind)
        return filename

    tmp = filename + f".tmp.{os.getpid()}"
    side = sidecar_path(filename)
    rec = None
    for attempt in range(retries + 1):
        try:
            checkpoint_mod.save_grid_data(grid, tmp, header=header,
                                          variable=variable, fields=fields)
            faults.fire("checkpoint.write", path=filename, attempt=attempt)
            with open(tmp, "rb+") as f:
                f.flush()
                os.fsync(f.fileno())
            if sidecar:
                # checksum the TEMP bytes so the record always matches
                # the file the rename publishes
                rec = _sidecar_record(tmp, header_size=len(header),
                                      chunk_bytes=chunk_bytes)
                if sidecar_extra:
                    rec.update(sidecar_extra)
                integ = _integrity_record(grid, fields, variable)
                if integ:
                    rec["integrity"] = integ
            # drop any previous sidecar BEFORE the rename: a crash in
            # this window leaves the new file with no sidecar — which
            # strict load refuses conservatively — never a new file
            # paired with a stale record (which would reject or
            # destructively 'salvage' an intact checkpoint). Keep the
            # old record's bytes: if the rename itself fails, the OLD
            # checkpoint is still the intact one under the final name
            # and must stay verifiable for rollback.
            old_side = None
            if os.path.exists(side):
                with open(side, "rb") as f:
                    old_side = f.read()
                os.unlink(side)
            try:
                os.replace(tmp, filename)
            except OSError:
                _restore_sidecar(side, old_side)
                raise
            _fsync_dir(os.path.dirname(os.path.abspath(filename)))
            break
        except OSError as e:
            if os.path.exists(tmp):
                os.unlink(tmp)
            if attempt >= retries:
                raise
            delay = backoff * (2 ** attempt)
            logger.warning(
                "checkpoint save of %s failed (%s); retry %d/%d in %.2fs",
                filename, e, attempt + 1, retries, delay)
            time.sleep(delay)
    if rec is not None:
        _write_sidecar_record(side, rec)
    # post-write corruption injection happens AFTER the sidecar records
    # the good bytes — exactly the at-rest corruption CRCs exist for
    faults.corrupt_file(filename)
    telemetry.observe("dccrg_ckpt_save_seconds",
                      time.perf_counter() - t_save, kind=kind)
    return filename


@telemetry.traced("ckpt.delta")
def save_delta_checkpoint(grid, filename: str, *, parent_path: str,
                          parent_step: int, step: int, fields,
                          header: bytes = b"", variable=None,
                          retries: int = 2, backoff: float = 0.1,
                          chunk_bytes: int = CRC_CHUNK) -> str:
    """Incremental checkpoint: save only ``fields`` (the dirty set
    since ``parent_path``) as a ``.dcd`` file — a valid ``.dc`` of the
    sub-schema, same atomic temp+fsync+rename (or two-phase
    multi-process commit) discipline as a full save — whose sidecar
    records the parent link ``{file, step, digest}``. The chain is only
    valid within one structure epoch and with fixed-size fields (the
    caller — :meth:`dccrg_tpu.supervise.CheckpointStore.save` — forces
    a keyframe otherwise). Restore via the chain-aware
    :func:`load_checkpoint` / ``resume_latest``; the reconstruction is
    bitwise identical to an uninterrupted full save."""
    extra = delta_sidecar_extra(parent_path, parent_step=parent_step,
                                step=step, fields=fields,
                                variable=variable)
    return save_checkpoint(grid, filename, header=header,
                           variable=variable, retries=retries,
                           backoff=backoff, chunk_bytes=chunk_bytes,
                           fields=extra["delta"]["fields"],
                           sidecar_extra=extra)


def delta_sidecar_extra(parent_path: str, *, parent_step: int, step: int,
                        fields, variable=None) -> dict:
    """The delta save's ``sidecar_extra`` record: the sorted dirty
    field list plus the parent link ``{file, step, digest}`` (digest
    derived from the parent's CURRENT sidecar, so a replaced parent is
    detected at load). Split out of :func:`save_delta_checkpoint` so
    the async-save path (``DCCRG_ASYNC_SAVE``) can resolve the link
    synchronously — while the drained parent is provably durable —
    before handing the write to the background thread. Raises
    :class:`CheckpointCorruptionError` when the parent has no sidecar
    (the caller falls back to a keyframe)."""
    fields = sorted(fields)
    var = variable or {}
    ragged = set(var) | set(var.values())
    if ragged & set(fields):
        raise ValueError(
            f"delta fields {sorted(ragged & set(fields))} are ragged "
            "(or ragged counts): their per-cell byte sizes move the "
            "offset table — only a full keyframe may capture that")
    parent_rec = read_sidecar(parent_path)
    if parent_rec is None:
        raise CheckpointCorruptionError(
            f"{parent_path}: delta parent has no sidecar; save a "
            "keyframe instead")
    digest = record_digest(parent_rec)
    if faults.take_delta_parent_corrupt():
        digest ^= 0x5A5A5A5A  # injected parent-link corruption
    return {"delta": {
        "fields": fields, "step": int(step),
        "parent": {"file": os.path.basename(parent_path),
                   "step": int(parent_step),
                   "digest": int(digest)}}}


def _integrity_record(grid, fields, variable) -> dict:
    """The sidecar ``integrity`` record: a payload fingerprint
    ``{field: [s1, s2, nbytes]}`` computed from the grid's LIVE
    device state (not the written bytes) via
    :func:`dccrg_tpu.integrity.grid_fingerprint`. Because the
    fingerprint is order-independent and exact, ``audit_checkpoint``
    can later re-derive it from the file's payload columns alone:
    bytes that rotted between device memory and the published file —
    or at rest afterwards, even under a plausible-looking CRC epoch —
    no longer match. Ragged (variable) fields are excluded (the file
    stores them truncated to their counts; the live rows differ).
    Empty when ``DCCRG_INTEGRITY=0`` or on multi-process grids (the
    two-phase commit path owns those sidecars)."""
    from . import integrity

    if not integrity.integrity_enabled():
        return {}
    var = variable or {}
    names = [n for n in sorted(fields if fields is not None
                               else grid.fields) if n not in var]
    if not names:
        return {}
    out = {}
    fp = integrity.grid_fingerprint(grid, names)
    for n in names:
        shape, dtype = grid.fields[n]
        nbytes = int(np.prod(shape, dtype=np.int64) or 1) * \
            np.dtype(dtype).itemsize
        out[n] = [int(fp[n][0]), int(fp[n][1]), nbytes]
    return out


def audit_checkpoint(filename: str) -> "dict | None":
    """Offline at-rest SDC audit: re-derive the payload fingerprint of
    ``filename`` from its bytes and compare against the ``integrity``
    record its sidecar captured from live device state at save time.
    Returns ``{field: (ok, got_pair, want_pair)}``, or None when the
    sidecar carries no integrity record (pre-SDC save, or
    ``DCCRG_INTEGRITY=0``). Complements the CRC chunk pass: CRCs
    verify the file matches what was WRITTEN; the fingerprint verifies
    what was written matches what the simulation actually HELD —
    corruption on the serialization path, or bit rot under a
    regenerated/intact-looking CRC epoch, fails here and only here.
    The ``python -m dccrg_tpu.resilience audit`` subcommand prints
    this."""
    from . import checkpoint as checkpoint_mod
    from . import integrity

    rec = read_sidecar(filename)
    if rec is None:
        raise CheckpointCorruptionError(
            f"{filename}: no checksum sidecar; nothing to audit "
            "against")
    integ = rec.get("integrity")
    if not integ:
        return None
    # synthesize a bytes-only schema: the column walk needs each
    # fixed field's serialized width and the sorted-name order, both
    # of which the record carries — the audit needs no grid schema
    fields = {n: ((int(v[2]),), np.uint8) for n, v in integ.items()}
    raw = np.memmap(filename, dtype=np.uint8, mode="r")
    try:
        meta = checkpoint_mod.parse_metadata(
            raw, int(rec.get("header_size", 0)))
        cols = checkpoint_mod.payload_columns(raw, meta, fields)
        out = {}
        for n, v in integ.items():
            got = integrity.fingerprint_rows(cols[n])
            want = (int(v[0]) & 0xFFFFFFFF, int(v[1]) & 0xFFFFFFFF)
            out[n] = (got == want, got, want)
        return out
    finally:
        del raw


def _restore_sidecar(side: str, old_side) -> None:
    """Put a displaced sidecar's bytes back after a failed rename —
    atomic (same tmp+fsync+rename discipline as _write_sidecar_record)
    and best effort: a torn restore must not shadow the original
    failure, and a missing sidecar is the conservative state. Shared by
    the single-controller save and the multi-process commit rank."""
    if old_side is None:
        return
    try:
        rtmp = side + f".tmp.{os.getpid()}"
        with open(rtmp, "wb") as f:
            f.write(old_side)
            f.flush()
            os.fsync(f.fileno())
        os.replace(rtmp, side)
    except OSError:  # pragma: no cover - double fault
        pass


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover - exotic filesystems
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


@dataclass
class SalvageReport:
    """What a non-strict load had to work around. ``bad_slices`` /
    ``dead_ranks`` attribute the damage when the sidecar carries a
    two-phase multi-process slice table: which writer ranks' slices
    fail their CRC (the dead rank whose cells came back zeroed)."""

    bad_chunks: list = dataclass_field(default_factory=list)
    corrupt_cells: np.ndarray = dataclass_field(
        default_factory=lambda: np.empty(0, np.uint64))
    sidecar_missing: bool = False
    bad_slices: list = dataclass_field(default_factory=list)
    dead_ranks: list = dataclass_field(default_factory=list)
    # the keyframe+delta link paths a chain-aware load replayed
    # (keyframe first; empty for plain full checkpoints)
    chain: list = dataclass_field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.bad_chunks and not self.sidecar_missing


@telemetry.traced("ckpt.load", counter="dccrg_loads_total")
def load_checkpoint_into(grid, filename: str, *, header_size: int = 0,
                         variable=None, verify: bool = True) -> None:
    """Load a checkpoint's exact bytes into an ALREADY-CONSTRUCTED
    grid of matching structure — the rollback/per-slot-restore
    primitive shared by :class:`ResilientRunner` and the fleet layer
    (:mod:`dccrg_tpu.fleet`, which restores ONE batch member into a
    scratch grid). CHAIN-AWARE: a delta checkpoint verifies and
    materializes its whole keyframe+delta chain into a scratch file
    first (a broken chain raises :class:`DeltaChainError`); a full
    checkpoint is CRC-verified against its sidecar (``verify=False``
    skips that for bytes the caller just wrote and verified). Ghost
    copies are refreshed afterwards, so static never-re-exchanged
    fields read exactly the checkpointed state."""
    if is_delta_checkpoint(filename):
        tmp = _chain_scratch(filename)
        try:
            materialize_chain(filename, tmp, grid.fields,
                              variable=variable, verify=verify)
            checkpoint_mod.load_grid_data(grid, tmp,
                                          header_size=header_size,
                                          variable=variable)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    else:
        if verify:
            bad = verify_checkpoint(filename)
            if bad:
                raise CheckpointCorruptionError(
                    f"rollback target {filename} is itself "
                    f"corrupt (chunks {bad})", bad_chunks=bad)
        checkpoint_mod.load_grid_data(grid, filename,
                                      header_size=header_size,
                                      variable=variable)
    # the load scatters LOCAL rows only; ghost copies of fields the
    # step loop treats as static (never re-exchanged) would stay
    # zero — refresh every field's ghosts so the resumed run sees
    # exactly the checkpointed state
    grid.update_copies_of_remote_neighbors()


@telemetry.traced("ckpt.load", counter="dccrg_loads_total")
def load_checkpoint(filename: str, cell_data, mesh=None,
                    header_size: int = 0, variable=None, strict: bool = True,
                    load_balancing_method=None):
    """Restart from a checkpoint with integrity verification.

    Returns ``(grid, header, report)``. With ``strict`` (default) any
    checksum mismatch — or a missing sidecar — raises
    :class:`CheckpointCorruptionError` naming the bad chunk. With
    ``strict=False`` intact chunks are salvaged: corrupt byte ranges
    are zeroed before the load, so affected cells come back with
    default (zero) values — variable-size fields read a zero count —
    and are listed in ``report.corrupt_cells``. Corruption inside the
    metadata block (mapping/geometry/offset table) is never salvageable
    and raises in both modes.

    An incremental (delta) checkpoint loads CHAIN-AWARE: the whole
    keyframe+delta chain is verified, materialized into a scratch file
    (``<file>.chain.<pid>`` next to it, or in the system temp dir
    when the checkpoint directory is read-only; removed afterwards)
    and loaded — bitwise
    identical to the full save an uninterrupted run would have
    written. A broken chain raises :class:`DeltaChainError` naming the
    broken link in BOTH modes (zero-salvage cannot repair a missing
    ancestor); the fallback to the last verifying prefix is
    ``resume_latest``'s job, which walks to older entries."""
    rec = read_sidecar(filename)
    if is_delta_checkpoint(filename, rec):
        if rec is None:
            raise DeltaChainError(
                f"{filename}: a delta checkpoint without its sidecar "
                "cannot be interpreted (the dirty-field list and parent "
                "link live there); resume from an older link instead",
                link=filename)
        tmp = _chain_scratch(filename)
        try:
            chain = materialize_chain(filename, tmp, cell_data,
                                      variable=variable)
            grid, header = checkpoint_mod.load_grid(
                tmp, cell_data, mesh=mesh, header_size=header_size,
                variable=variable,
                load_balancing_method=load_balancing_method)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return grid, header, SalvageReport(chain=chain)
    if rec is None:
        if strict:
            raise CheckpointCorruptionError(
                f"{filename}: no checksum sidecar; load with strict=False "
                "to proceed unverified")
        logger.warning("%s: loading without checksum verification "
                       "(sidecar missing)", filename)
        grid, header = checkpoint_mod.load_grid(
            filename, cell_data, mesh=mesh, header_size=header_size,
            variable=variable, load_balancing_method=load_balancing_method)
        return grid, header, SalvageReport(sidecar_missing=True)

    bad = _bad_chunks(filename, rec)
    if not bad:
        # chunk CRCs tile every recorded byte, so clean chunks imply
        # clean per-rank slices — no second verification pass needed
        grid, header = checkpoint_mod.load_grid(
            filename, cell_data, mesh=mesh, header_size=header_size,
            variable=variable, load_balancing_method=load_balancing_method)
        return grid, header, SalvageReport()

    # attribution: which ranks' two-phase slices cover the damage
    bad_sl = _bad_slices(filename, rec)
    dead = sorted({int(rec["slices"][i][1]) for i in bad_sl})
    all_ranges = _rec_ranges(rec)
    names = ", ".join(_chunk_name(i, all_ranges) for i in bad)
    if dead:
        names += (f"; slice(s) written by rank(s) {dead} fail their "
                  "CRC32")
    if strict:
        raise CheckpointCorruptionError(
            f"{filename}: checksum mismatch in {names}", bad_chunks=bad)

    # -- salvage: zero the corrupt ranges, load, report the cells -----
    if 0 in bad:
        raise CheckpointCorruptionError(
            f"{filename}: corruption in the {names}; the metadata block "
            "(mapping/geometry/offset table) cannot be trusted — not "
            "salvageable", bad_chunks=bad)
    file_bytes = int(rec["file_bytes"])
    with open(filename, "rb") as f:
        raw = bytearray(f.read())
    # a truncated file is padded back to the recorded size with zeros
    # (the missing tail is inside a corrupt range anyway)
    if len(raw) < file_bytes:
        raw += bytes(file_bytes - len(raw))
    del raw[file_bytes:]

    # the trailing-garbage sentinel has no in-range bytes to zero —
    # `del raw[file_bytes:]` below already trims it
    ranges = [all_ranges[i] for i in bad if i < len(all_ranges)]
    try:
        meta = checkpoint_mod.parse_metadata(bytes(raw), header_size)
    except Exception as e:  # metadata CRC passed but parse still failed
        raise CheckpointCorruptionError(
            f"{filename}: metadata unreadable ({e}); corruption in {names} "
            "is not salvageable", bad_chunks=bad) from e
    cells, offsets = meta[4], meta[5]

    for lo, hi in ranges:
        raw[lo:hi] = bytes(hi - lo)

    # per-cell payload extents from the (intact) offset table
    offs = offsets.astype(np.int64)
    ends = np.empty_like(offs)
    ends[:-1] = offs[1:]
    if len(ends):
        ends[-1] = file_bytes
    hit = np.zeros(len(cells), dtype=bool)
    for lo, hi in ranges:
        hit |= (offs < hi) & (ends > lo)
    corrupt_cells = cells[hit].copy()

    tmp = filename + f".salvage.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(bytes(raw))
        grid, header = checkpoint_mod.load_grid(
            tmp, cell_data, mesh=mesh, header_size=header_size,
            variable=variable, load_balancing_method=load_balancing_method)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    logger.warning(
        "%s: salvaged around %s — %d cell(s) restored with default "
        "values: %s", filename, names, len(corrupt_cells),
        corrupt_cells[:16].tolist())
    return grid, header, SalvageReport(bad_chunks=bad,
                                       corrupt_cells=corrupt_cells,
                                       bad_slices=bad_sl,
                                       dead_ranks=dead)


# ---------------------------------------------------------------------
# numerics watchdog
# ---------------------------------------------------------------------

def _inexact_fields(grid, fields=None):
    import jax.numpy as jnp

    names = list(fields) if fields is not None else list(grid.fields)
    return [n for n in names
            if jnp.issubdtype(grid.fields[n][1], jnp.inexact)]


def check_finite(grid, fields=None) -> bool:
    """Device-side watchdog probe: every element of the watched fields
    isfinite, reduced to ONE scalar crossing to the host (per-device
    ``all`` then a psum-style min over the mesh via comm.py). Cheap
    enough to run every few steps; locate the offenders with
    :func:`assert_finite` / verify.find_nonfinite_cells only on a
    trip."""
    import jax
    from jax.sharding import PartitionSpec as P

    from . import comm
    from .compat import shard_map

    names = _inexact_fields(grid, fields)
    if not names:
        return True
    key = ("finite", tuple(names),
           tuple(tuple(grid.fields[n][0]) for n in names))
    fn = grid._program_cache.get(key)
    if fn is None:
        axis, mesh = grid.axis, grid.mesh

        def body(*arrs):
            return comm.all_finite([a[0] for a in arrs], axis)[None]

        mapped = shard_map(
            body, mesh=mesh, in_specs=(P(axis),) * len(names),
            out_specs=P(axis), check_vma=False)
        fn = jax.jit(mapped)
        grid._program_cache[key] = fn
    out = fn(*(grid.data[n] for n in names))
    # the min all-reduce leaves identical rows on every device; pull
    # through comm so real multi-process meshes (where row 0 may not
    # be addressable) read their local shard instead
    return bool(int(comm.pull_replicated(out).ravel()[0]))


def assert_finite(grid, fields=None, step=None) -> None:
    """Raise :class:`NumericsError` (naming fields and cell ids, found
    host-side via verify.py) when the watchdog probe trips."""
    if check_finite(grid, fields):
        return
    from . import verify

    details = verify.find_nonfinite_cells(grid, fields)
    where = "" if step is None else f" at step {step}"
    names = {n: ids[:8].tolist() for n, ids in details.items()}
    raise NumericsError(
        f"non-finite values{where} in {names or 'ghost/pad rows only'}",
        details=details)


# ---------------------------------------------------------------------
# OOM-aware step dispatch: the gather-mode fallback chain
# ---------------------------------------------------------------------

_GATHER_ENV = ("DCCRG_ROLL_STENCIL", "DCCRG_FORCE_TABLES", "DCCRG_BULK")
FALLBACK_CHAIN = ("current", "roll", "tables")


def _is_resource_exhausted(e: BaseException) -> bool:
    return ("RESOURCE_EXHAUSTED" in str(e)
            or isinstance(e, faults.SimulatedResourceExhausted))


# the env each forced gather mode pins (None = unset). DCCRG_FORCE_TABLES
# is read at PLAN BUILD time (uniform.py), DCCRG_ROLL_STENCIL at program
# build — forcing a mode therefore needs a plan rebuild. Both fallback
# modes also drop out of the DCCRG_BULK=pallas executor: an OOM under
# the bulk program (its VMEM windows + epilogue tables cost more than
# the bare roll path) degrades to plain XLA gathers before dense
# tables are tried.
_MODE_ENV = {
    "roll": {"DCCRG_FORCE_TABLES": None, "DCCRG_ROLL_STENCIL": "1",
             "DCCRG_BULK": None},
    "tables": {"DCCRG_FORCE_TABLES": "1", "DCCRG_ROLL_STENCIL": "0",
               "DCCRG_BULK": None},
}


def _apply_mode(grid, mode: str) -> None:
    """Pin the gather env for ``mode`` and rebuild the plan if it was
    last built under a different forced mode. Cells/owners (and the
    sticky capacity memo) are unchanged by the rebuild, so the row
    layout — and with it every field array — stays valid."""
    if mode == "current":
        return
    for v, val in _MODE_ENV[mode].items():
        if val is None:
            os.environ.pop(v, None)
        else:
            os.environ[v] = val
    # _build_plan clears the marker, so any external rebuild (AMR
    # commit, load balance) correctly invalidates it
    if getattr(grid, "_plan_gather_mode", None) != mode:
        grid._build_plan(grid.plan.cells, grid.plan.owner)
        grid._plan_gather_mode = mode


@contextmanager
def _restore_env():
    saved = {v: os.environ.get(v) for v in _GATHER_ENV}
    try:
        yield saved
    finally:
        for v, val in saved.items():
            if val is None:
                os.environ.pop(v, None)
            else:
                os.environ[v] = val


def guarded_step(grid, kernel, fields_in, fields_out, n_steps=1, *,
                 exchange_fields=None, neighborhood_id=None,
                 extra_args=()) -> str:
    """Dispatch ``Grid.run_steps`` with graceful OOM degradation.

    On XLA ``RESOURCE_EXHAUSTED`` (real, or injected through
    faults.resource_exhausted) the dispatch walks the fallback chain
    *current mode -> slot-wise roll -> dense tables*, logging each
    downgrade, and returns the mode that completed. Fallback entries
    whose forced env equals the caller's current env are skipped
    (retrying the identical configuration would just re-OOM), and a
    successful downgrade is remembered on the grid: later guarded
    dispatches start from the working mode even after a structural
    rebuild reverted the plan. When every mode exhausts HBM,
    :class:`ResilienceExhaustedError` surfaces with the last error
    chained. The caller's env vars are restored either way."""
    from .grid import DEFAULT_NEIGHBORHOOD_ID

    hood = (DEFAULT_NEIGHBORHOOD_ID if neighborhood_id is None
            else neighborhood_id)
    failed = []
    with _restore_env() as saved:
        sticky = getattr(grid, "_sticky_gather_mode", None)
        if sticky is not None:
            chain = [m for m in FALLBACK_CHAIN[1:]
                     if FALLBACK_CHAIN.index(m) >= FALLBACK_CHAIN.index(sticky)]
        else:
            chain = ["current"] + [m for m in FALLBACK_CHAIN[1:]
                                   if _MODE_ENV[m] != saved]
        for mode in chain:
            try:
                _apply_mode(grid, mode)
                faults.fire("step.dispatch", mode=mode)
                grid.run_steps(kernel, fields_in, fields_out, n_steps,
                               exchange_fields=exchange_fields,
                               neighborhood_id=hood, extra_args=extra_args)
                if mode != "current":
                    grid._sticky_gather_mode = mode
                if failed:
                    logger.warning(
                        "step completed in fallback gather mode %r "
                        "(exhausted: %s); the downgrade sticks for "
                        "later guarded dispatches", mode,
                        [m for m, _ in failed])
                return mode
            except Exception as e:  # noqa: BLE001 - filtered just below
                if not _is_resource_exhausted(e):
                    raise
                logger.warning(
                    "RESOURCE_EXHAUSTED dispatching step in gather mode "
                    "%r; falling back (%s)", mode, e)
                failed.append((mode, e))
    raise ResilienceExhaustedError(
        f"every gather mode in {[m for m, _ in failed]} exhausted device "
        "memory") from failed[-1][1]


# ---------------------------------------------------------------------
# the resilient step loop: watchdog + checkpoint + rollback
# ---------------------------------------------------------------------

# trip codes the per-step consensus all-reduces (max wins), ordered by
# priority: _TRIP_INTERRUPT is a consensus-agreed step-boundary
# interrupt (a preemption signal observed by dccrg_tpu.supervise) that
# any REAL trip outranks — a rank that tripped rolls everyone back
# first and the still-set preempt flag is re-polled at the next
# boundary; _TRIP_ROLLBACK.._TRIP_OOM are recoverable (mutation /
# numerics / silent corruption / OOM -> every rank rolls back
# together; _TRIP_CORRUPT is an integrity-invariant verdict, see
# dccrg_tpu.integrity — finite wrong bits the numerics code cannot
# see); >= _TRIP_FATAL means a rank hit a non-recoverable error and
# every OTHER rank raises in sync instead of hanging in the dead
# rank's abandoned collectives
_TRIP_INTERRUPT = 1
_TRIP_ROLLBACK = 2   # MutationAbortedError
_TRIP_NUMERICS = 3
_TRIP_CORRUPT = 4    # integrity invariant (SDC) verdict
_TRIP_OOM = 5
_TRIP_FATAL = 6


def watchdog_interval(default: int = 0) -> int:
    """The DCCRG_WATCHDOG env knob: check every ~N steps (0 = off)."""
    try:
        return int(os.environ.get("DCCRG_WATCHDOG", "") or default)
    except ValueError:
        return default


class ResilientRunner:
    """Run a step loop that survives numerical blow-ups.

    ``step_fn(grid, step_index)`` advances the simulation by one step
    (typically a ``run_steps``/:func:`guarded_step` call). Every
    ``checkpoint_every`` steps the state is checkpointed atomically
    (CRC sidecar included); every ``check_every`` steps the watchdog
    probes for non-finite values. On a trip the runner

    1. dumps a diagnostic bundle (step, offending fields, cell ids)
       into ``diagnostics_dir``,
    2. rolls the grid back to the last *verified* checkpoint,
    3. backs off exponentially and resumes.

    Retries are bounded: ``max_retries`` consecutive trips without
    passing the previous trip point raise
    :class:`ResilienceExhaustedError`. Because the checkpoint holds
    exact field bytes and the step programs are deterministic, a
    recovered run reconverges to the bitwise-identical state of an
    undisturbed one (pinned by tests/test_resilience.py).
    """

    def __init__(self, grid, step_fn, checkpoint_path, *, fields=None,
                 check_every=None, checkpoint_every=10,
                 checkpoint_seconds=0.0, max_retries=3,
                 backoff=0.05, header=b"", variable=None,
                 diagnostics_dir=None, interrupt_poll=None,
                 conserved_fields=None):
        self.grid = grid
        self.step_fn = step_fn
        # SDC defense (dccrg_tpu.integrity): fields whose global sum
        # the caller's step kernel provably conserves. At every
        # watchdog boundary the runner recomputes the device-side
        # collective sums and compares them against the values
        # recorded at the last checkpoint; a drift beyond
        # integrity.sum_tolerance — finite, plausible bits the
        # numerics watchdog cannot see — is a _TRIP_CORRUPT verdict
        # put through coord.trip_consensus so EVERY rank rolls back
        # together. Off (None/empty, or DCCRG_INTEGRITY=0): zero
        # overhead, no extra program.
        self.conserved_fields = tuple(conserved_fields or ())
        self._integrity_base = None  # sums at the rollback target
        # optional step-boundary interrupt hook (the supervision
        # layer's preemption poll): truthy -> the _TRIP_INTERRUPT code
        # joins this step's trip consensus, and when it wins on every
        # rank the loop raises RunInterrupted instead of stepping on
        self.interrupt_poll = interrupt_poll
        self.checkpoint_path = checkpoint_path
        self.fields = fields
        self.check_every = (check_every if check_every is not None
                            else (watchdog_interval(0) or 1))
        self.checkpoint_every = checkpoint_every
        # wall-clock cadence (monotonic clock, evaluated only at step
        # boundaries — a save can never land mid-step): a checkpoint
        # becomes due once this many seconds passed since the last
        # one, whatever the step count. 0 disables; step-count cadence
        # may be disabled independently with checkpoint_every=0. On
        # multi-process meshes the per-rank clocks drift, so due-ness
        # goes through an any-rank consensus before acting — every
        # rank enters the collective save together.
        self.checkpoint_seconds = float(checkpoint_seconds or 0.0)
        self._last_save_t = None
        self.max_retries = max_retries
        self.backoff = backoff
        self.header = header
        self.variable = variable
        self.diagnostics_dir = (diagnostics_dir
                                or os.path.dirname(os.path.abspath(
                                    checkpoint_path)))
        self.step = 0
        self.trips = []  # diagnostic bundles, newest last
        self.rollbacks = 0
        self.checkpoints = 0
        self._ckpt_step = None
        self._retry_streak = 0
        self._streak_step = -1

    # -- checkpoint plumbing ------------------------------------------

    def _write_checkpoint(self) -> str:
        """Write the periodic checkpoint; returns the path written.
        The supervision layer's store-backed runner overrides this to
        route through :meth:`dccrg_tpu.supervise.CheckpointStore.save`
        (numbered files, dirty-field delta saves).

        With ``DCCRG_ASYNC_SAVE=1`` the write runs on a background
        thread against a :func:`dccrg_tpu.background.freeze_grid`
        snapshot (multi-process meshes through
        :func:`dccrg_tpu.background.freeze_grid_mp`, whose two-phase
        barriers rendezvous on the ranks' writer threads), overlapped
        with the following steps' dispatch — bitwise identical bytes,
        published atomically; :meth:`_drain_saves` is the barrier every
        store reader (rollback, run end) takes first."""
        if background.async_save_enabled():
            saver = self._active_saver(create=True)
            saver.drain()  # one in flight; an earlier failure raises here
            frozen = (background.freeze_grid_mp(self.grid,
                                                variable=self.variable)
                      if self.grid._multiproc
                      else background.freeze_grid(self.grid))
            path = self.checkpoint_path
            saver.submit(
                lambda: save_checkpoint(frozen, path, header=self.header,
                                        variable=self.variable),
                label=path)
            return path
        save_checkpoint(self.grid, self.checkpoint_path,
                        header=self.header, variable=self.variable)
        return self.checkpoint_path

    def _active_saver(self, create: bool = False):
        """The :class:`~dccrg_tpu.background.AsyncSaver` carrying this
        runner's in-flight periodic write, or None. The store-backed
        runner overrides this with its store's saver."""
        if create and getattr(self, "_saver", None) is None:
            self._saver = background.AsyncSaver()
        return getattr(self, "_saver", None)

    def _drain_saves(self, swallow: bool = False) -> None:
        """Async-save barrier: block until no periodic write is in
        flight. ``swallow=True`` (the rollback/emergency paths, where
        resumability outranks the report) logs a writer failure
        instead of raising — its ``on_fail`` hooks have already
        re-pointed the rollback target at the last durable save."""
        saver = self._active_saver()
        if saver is None:
            return
        try:
            saver.drain()
        except Exception as e:  # noqa: BLE001 - policy filter below
            if not swallow:
                raise
            logger.error("async checkpoint write failed (%s); the last "
                         "durable checkpoint is the rollback target", e)

    def _save(self) -> None:
        prev = (self.checkpoint_path, self._ckpt_step, self._last_save_t,
                self._integrity_base)
        self.checkpoint_path = self._write_checkpoint()
        self._ckpt_step = self.step
        self._last_save_t = time.monotonic()
        self.checkpoints += 1
        if self._integrity_on():
            # the conservation baseline the boundary drift check
            # compares against — recorded at the rollback target, so
            # a corrupt verdict always rolls back to state whose
            # invariants were verified clean
            self._integrity_base = self._conservation_sums()
        saver = self._active_saver()
        if saver is not None and saver.pending():
            # the bookkeeping above is speculative while the write is
            # in flight: a writer failure reverts the rollback target
            # to the last DURABLE checkpoint at the drain barrier
            def _restore(_err, prev=prev):
                (self.checkpoint_path, self._ckpt_step,
                 self._last_save_t, self._integrity_base) = prev
                self.checkpoints -= 1

            saver.add_on_fail(_restore)

    def _integrity_on(self) -> bool:
        from . import integrity

        return bool(self.conserved_fields) and integrity.integrity_enabled()

    def _conservation_sums(self):
        from . import integrity

        return integrity.conservation_sums(self.grid,
                                           self.conserved_fields)

    def _integrity_drift(self):
        """The boundary SDC check: None when clean, else a details
        dict naming each conserved field whose device-side global sum
        drifted beyond tolerance since the last checkpoint. The sums
        are a replicated collective (comm.field_sums), so every rank
        computes the identical verdict."""
        from . import integrity

        if not self._integrity_on() or self._integrity_base is None:
            return None
        telemetry.inc("dccrg_integrity_checks_total", where="runner")
        with telemetry.span("integrity.check"):
            now = self._conservation_sums()
        steps = max(1, self.step - (self._ckpt_step or 0))
        details = {}
        for i, name in enumerate(self.conserved_fields):
            shape, _dt = self.grid.fields[name]
            n_el = len(self.grid.plan.cells) * int(
                np.prod(shape, dtype=int) or 1)
            tol = integrity.sum_tolerance(self._integrity_base[i],
                                          n_el, steps)
            drift = abs(float(now[i]) - float(self._integrity_base[i]))
            if drift > tol:
                details[name] = np.empty(0, np.uint64)
                logger.warning(
                    "integrity drift in %r: conservation sum moved "
                    "%g (tolerance %g) since the step-%s checkpoint "
                    "— silent corruption", name, drift, tol,
                    self._ckpt_step)
        return details or None

    def _rollback(self) -> None:
        # chain-aware when the target is a delta: the shared primitive
        # verifies + materializes the keyframe+delta chain (a broken
        # chain surfaces as DeltaChainError — a corrupt rollback
        # target either way)
        t0 = time.perf_counter()
        # drain barrier: never read a store an async write is still
        # publishing into (a failed write re-points checkpoint_path at
        # the last durable save before the load below)
        self._drain_saves(swallow=True)
        with telemetry.span("runner.rollback"):
            load_checkpoint_into(self.grid, self.checkpoint_path,
                                 header_size=len(self.header),
                                 variable=self.variable)
        self.step = self._ckpt_step
        self.rollbacks += 1
        telemetry.inc("dccrg_rollbacks_total")
        # rollback cost is a controller input (with the trip rate it
        # prices the replay window a checkpoint cadence implies)
        telemetry.observe("dccrg_rollback_seconds",
                          time.perf_counter() - t0)

    # -- trip handling ------------------------------------------------

    def _dump_diagnostics(self, details) -> dict:
        bundle = {
            "step": self.step,
            "rollback_to": self._ckpt_step,
            "retry": self._retry_streak,
            "fields": {n: ids[:64].tolist() for n, ids in details.items()},
            "checkpoint": self.checkpoint_path,
            "wall_time": time.time(),
        }
        path = os.path.join(
            self.diagnostics_dir,
            f"dccrg_diag_step{self.step}_try{self._retry_streak}.json")
        try:
            with open(path, "w") as f:
                json.dump(bundle, f, indent=1)
            bundle["path"] = path
        except OSError as e:  # diagnostics must never kill recovery
            logger.warning("could not write diagnostic bundle: %s", e)
        self.trips.append(bundle)
        return bundle

    def _trip(self, details=None, kind="numerics") -> None:
        from . import verify

        if details is None:
            details = verify.find_nonfinite_cells(self.grid, self.fields)
        if self.step > self._streak_step:
            self._retry_streak = 0  # progress since the last trip
        self._streak_step = self.step
        self._retry_streak += 1
        telemetry.inc("dccrg_trips_total", kind=kind)
        bundle = self._dump_diagnostics(details)
        logger.warning(
            "watchdog trip (%s) at step %d (fields %s); rolling back "
            "to step %s (retry %d/%d)", kind, self.step,
            list(details) or "<ghost rows>", self._ckpt_step,
            self._retry_streak, self.max_retries)
        if self._retry_streak > self.max_retries:
            msg = (f"watchdog tripped {self._retry_streak} times at "
                   f"step {self.step} without progress; diagnostics: "
                   f"{bundle.get('path', '<unwritten>')}")
            if kind == "corrupt":
                # persistent SDC: the typed subclass names the class
                # of failure (likely a defective device, not a
                # transient upset) while generic handlers catching
                # ResilienceExhaustedError keep working
                from . import integrity

                raise integrity.IntegrityError(
                    "integrity invariants failed on every retry — "
                    "persistent silent corruption; " + msg,
                    details={n: "invariant drift" for n in details})
            raise ResilienceExhaustedError(msg)
        if self.backoff:
            time.sleep(self.backoff * (2 ** (self._retry_streak - 1)))
        self._rollback()

    # -- the loop -----------------------------------------------------

    def run(self, n_steps: int) -> "ResilientRunner":
        """Advance to ``n_steps`` total steps, recovering as needed.
        Returns self (``.step``, ``.trips``, ``.rollbacks``,
        ``.checkpoints`` carry the story).

        On multi-process meshes every trip decision is put through
        :func:`dccrg_tpu.coord.trip_consensus` (a max all-reduce of a
        per-rank trip code) BEFORE acting on it: a
        ``MutationAbortedError``, an OOM, or a watchdog-hook
        ``NumericsError`` raised host-side on ONE rank makes EVERY
        rank roll back to the same checkpoint together, instead of the
        tripped rank abandoning a barrier its peers then hang in. The
        device-side ``check_finite`` probe is a global collective and
        agrees by construction."""
        from . import coord
        from .txn import MutationAbortedError

        if self._ckpt_step is None:
            self._save()  # rollback target always exists
        membership = coord.get_membership()
        while self.step < n_steps:
            if membership is not None:
                # elastic-fleet liveness: renew this rank's heartbeat
                # lease at step boundaries (throttled to the heartbeat
                # cadence), so peers classify a healthy-but-busy rank
                # live instead of reclaiming its work — and a rank
                # that stops beating surfaces to THEM as a typed
                # PeerDeadError naming it, not a barrier-tag timeout
                membership.heartbeat()
            code, details = 0, None
            try:
                self.step_fn(self.grid, self.step)
            except MutationAbortedError as e:
                # a structural mutation inside the step (adapt /
                # balance) failed and already rolled itself back;
                # recover like a watchdog trip: diagnostics, rollback
                # to the last checkpoint, bounded retry
                logger.warning("step %d: %s", self.step, e)
                code, details = _TRIP_ROLLBACK, {"mutation": np.asarray(
                    e.cells, dtype=np.uint64)}
            except NumericsError as e:
                # the DCCRG_WATCHDOG hook inside run_steps tripped
                # mid-step: same recovery as the runner's own check
                # (it already names the offending fields and cells)
                logger.warning("step %d: %s", self.step, e)
                code, details = _TRIP_NUMERICS, (e.details if e.details
                                                 else None)
            except Exception as e:  # noqa: BLE001 - filtered just below
                if not _is_resource_exhausted(e):
                    # non-recoverable: tell the peers before dying —
                    # they are (or soon will be) blocked in this
                    # step's consensus reduce, which unlike
                    # coord.barrier has no timeout of its own; a
                    # FATAL code makes every rank raise in sync
                    # instead of N-1 ranks hanging in a collective.
                    # Deadline-bounded: the mesh may be the very thing
                    # that broke (a wedged collective is what
                    # StepTimeoutError reports), and telling the peers
                    # must never keep the dying rank alive.
                    coord.broadcast_fatal(self.grid, _TRIP_FATAL)
                    raise
                # a device OOM that escaped the step (no guarded_step
                # in the loop, or an injected one): recover like a
                # trip — rollback frees the live buffers and the
                # bounded retry surfaces a persistent OOM as
                # ResilienceExhaustedError
                logger.warning("step %d: %s", self.step, e)
                code, details = _TRIP_OOM, {"resource_exhausted":
                                            np.empty(0, np.uint64)}
            if (code == 0 and self.interrupt_poll is not None
                    and self.interrupt_poll()):
                # the step completed cleanly but an interrupt (a
                # preemption signal) is pending on this rank; offer
                # the LOWEST-priority code so a real trip elsewhere
                # still wins (the flag stays set — the next boundary
                # re-polls it after the collective rollback)
                code = _TRIP_INTERRUPT
            agreed = coord.trip_consensus(self.grid, code)
            if agreed >= _TRIP_FATAL:
                raise ResilienceExhaustedError(
                    f"a peer rank failed fatally at step {self.step} "
                    "(non-recoverable exception on another rank; see "
                    "its log) — stopping in sync instead of hanging "
                    "in its abandoned collectives")
            if agreed >= _TRIP_ROLLBACK:
                if code in (0, _TRIP_INTERRUPT):
                    # another rank tripped; this one rolls back with it
                    details = {"remote_rank_trip": np.empty(0, np.uint64)}
                self._trip(details=details)
                continue
            if agreed == _TRIP_INTERRUPT:
                # every rank completed this step cleanly and agreed to
                # stop: the grid holds step+1 completed steps on all of
                # them — exactly the state the supervision layer's
                # emergency checkpoint captures
                self.step += 1
                if not check_finite(self.grid, self.fields):
                    # the rollback-target invariant holds for the
                    # emergency checkpoint too: NEVER hand poisoned
                    # state to a save (CRCs cannot see NaNs). Recover
                    # first — check_finite is a global collective, so
                    # every rank takes this branch together — and the
                    # still-pending interrupt stops the run at the
                    # first clean boundary after the rollback.
                    self._trip()
                    continue
                raise RunInterrupted(self.step)
            self.step += 1
            faults.poison_step(self.grid, self.step)
            faults.flip_step(self.grid, self.step)
            ckpt_due = (bool(self.checkpoint_every)
                        and self.step % self.checkpoint_every == 0)
            if not ckpt_due and self.checkpoint_seconds > 0:
                due = (self._last_save_t is not None
                       and time.monotonic() - self._last_save_t
                       >= self.checkpoint_seconds)
                # clocks drift across ranks: agree (any rank due ->
                # all save) before entering the collective save path
                ckpt_due = bool(coord.trip_consensus(self.grid, int(due)))
            # a checkpoint step ALWAYS checks first — the rollback
            # target must never capture unverified (poisoned OR
            # silently corrupted) state, whatever the check/checkpoint
            # cadence ratio
            if (ckpt_due or self.step % self.check_every == 0
                    or self.step == n_steps):
                if not check_finite(self.grid, self.fields):
                    self._trip()
                    continue
                # SDC boundary check (conserved_fields opt-in): the
                # drift verdict is computed from a replicated
                # collective, but the trip still goes through the
                # consensus all-reduce — any rank's CORRUPT verdict
                # (however asymmetric a future detector might be)
                # rolls every rank back together, and the mp harness
                # pins that all ranks agree on the verdict
                drift = self._integrity_drift()
                if self._integrity_on() and int(coord.trip_consensus(
                        self.grid,
                        _TRIP_CORRUPT if drift else 0)) >= _TRIP_CORRUPT:
                    self._trip(details=drift or {
                        "remote_rank_corrupt": np.empty(0, np.uint64)},
                        kind="corrupt")
                    continue
            if ckpt_due:
                self._save()
        # a write still in flight when the loop finishes must be
        # durable before the caller reads the store (resume, digest
        # comparisons); a failure surfaces here like a sync save's
        self._drain_saves()
        return self


# ---------------------------------------------------------------------
# device probing that cannot hang
# ---------------------------------------------------------------------

def safe_devices(timeout: float = 90.0, retries: int = 2,
                 backoff: float = 2.0, platform=None):
    """``jax.devices()`` that cannot hang the caller: the backend is
    probed first in a SUBPROCESS (killed hard on timeout — the axon
    client is known to survive SIGTERM) with bounded retries and
    exponential backoff; only a successful probe lets the in-process
    call proceed. Raises :class:`DeviceProbeError` when the budget is
    spent. ``platform`` routes both the probe and the in-process jax
    through ``jax.config.update('jax_platforms', ...)`` (env vars are
    too late once the image's site hook has imported jax)."""
    code = "import jax; "
    if platform:
        code += f"jax.config.update('jax_platforms', {platform!r}); "
    code += "print(len(jax.devices()))"
    last = "no probe attempted"
    for attempt in range(retries + 1):
        try:
            faults.fire("device.probe", attempt=attempt)
            out = subprocess.run(
                [sys.executable, "-c", code], timeout=timeout,
                capture_output=True, text=True)
            if out.returncode == 0:
                import jax

                if platform:
                    jax.config.update("jax_platforms", platform)
                return jax.devices()
            last = (out.stderr or out.stdout).strip()[-200:]
        except (subprocess.TimeoutExpired, faults.InjectedProbeHang) as e:
            last = f"probe timed out after {timeout}s ({type(e).__name__})"
        if attempt < retries:
            delay = backoff * (2 ** attempt)
            logger.warning("device probe failed (%s); retry %d/%d in %.1fs",
                           last, attempt + 1, retries, delay)
            time.sleep(delay)
    raise DeviceProbeError(
        f"device backend unreachable after {retries + 1} probe(s): {last}")


_PROBED_DEVICES: dict = {}


def probed_devices(timeout: float = 120.0, retries: int = 1,
                   backoff: float = 2.0, platform=None) -> list:
    """Memoized :func:`safe_devices`: ONE hang-proof subprocess probe
    per process AND requested platform, however many grids/fuzzers/
    benches ask (the ROUND6 gotcha: a raw ``jax.devices()`` into a
    wedged accelerator tunnel blocks forever and survives SIGTERM —
    and even a successful probe costs a subprocess spawn nobody wants
    per construction). The cache is keyed by ``platform`` — it
    changes what the result MEANS, unlike the budget parameters,
    where the first caller's values win."""
    if platform not in _PROBED_DEVICES:
        _PROBED_DEVICES[platform] = list(safe_devices(
            timeout=timeout, retries=retries, backoff=backoff,
            platform=platform))
    return _PROBED_DEVICES[platform]


def _tool_main(argv) -> int:
    """Checkpoint maintenance subcommands, callable without a live
    accelerator: ``verify <file>`` re-checksums one checkpoint against
    its sidecar; ``gc <dir> --keep-last K --keep-every N`` applies the
    supervision layer's retention policy (DRY-RUN by default —
    ``--apply`` actually prunes; the GC can never delete the only
    checkpoint that passes verification)."""
    import argparse

    ap = argparse.ArgumentParser(prog="python -m dccrg_tpu.resilience",
                                 description=_tool_main.__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    v = sub.add_parser("verify", help="verify a checkpoint's CRC "
                                      "sidecar (a delta checkpoint "
                                      "verifies its WHOLE chain)")
    v.add_argument("file")
    c = sub.add_parser("chain", help="print every keyframe->delta "
                                     "chain in a checkpoint directory "
                                     "with per-link verification "
                                     "status")
    c.add_argument("dir")
    c.add_argument("--stem", default=None,
                   help="only checkpoints named <stem>_<step>.dc[d]")
    a = sub.add_parser("audit", help="at-rest SDC audit: recompute a "
                                     "checkpoint's payload integrity "
                                     "fingerprint and compare against "
                                     "the record its sidecar captured "
                                     "from live device state at save "
                                     "time (catches corruption the "
                                     "CRC pass cannot: serialization-"
                                     "path damage, rot under an "
                                     "intact-looking CRC epoch)")
    a.add_argument("file")
    g = sub.add_parser("gc", help="prune a checkpoint directory by the "
                                  "keep-last-K / keep-every-N retention "
                                  "policy — chain-aware: whole chains "
                                  "only, never orphans a delta "
                                  "(dry-run unless --apply)")
    g.add_argument("dir")
    g.add_argument("--keep-last", type=int, default=3)
    g.add_argument("--keep-every", type=int, default=0)
    g.add_argument("--stem", default=None,
                   help="only checkpoints named <stem>_<step>.dc[d]")
    g.add_argument("--apply", action="store_true",
                   help="actually delete (default: report only)")
    args = ap.parse_args(argv)

    if args.cmd == "audit":
        # CRC pass first: a file that fails its chunk CRCs is plain
        # detectable corruption, not the silent class
        try:
            bad = verify_checkpoint(args.file)
        except CheckpointCorruptionError as e:
            print(f"CORRUPT {args.file}: {e}")
            return 1
        if bad:
            print(f"CORRUPT {args.file}: chunk CRC mismatch "
                  f"(chunks {bad}) — detectable corruption, not SDC")
            return 1
        try:
            rep = audit_checkpoint(args.file)
        except CheckpointCorruptionError as e:
            print(f"CORRUPT {args.file}: {e}")
            return 1
        if rep is None:
            print(f"NO-RECORD {args.file}: sidecar carries no "
                  "integrity fingerprint (pre-SDC save or "
                  "DCCRG_INTEGRITY=0)")
            return 2
        rc = 0
        for name in sorted(rep):
            ok, got, want = rep[name]
            if ok:
                print(f"OK {args.file}: field {name} fingerprint "
                      f"({got[0]:#010x}, {got[1]:#010x})")
            else:
                rc = 1
                print(f"SDC {args.file}: field {name} payload "
                      f"fingerprint ({got[0]:#010x}, {got[1]:#010x}) "
                      f"!= device-state record ({want[0]:#010x}, "
                      f"{want[1]:#010x}) — the CRCs sealed corrupted "
                      "bytes")
        return rc

    if args.cmd == "verify":
        if is_delta_checkpoint(args.file):
            # a delta is only as good as its chain: verify every link
            try:
                links = verify_chain(args.file)
            except CheckpointCorruptionError as e:
                print(f"CORRUPT {args.file}: {e}")
                return 1
            print(f"OK {args.file} (chain of {len(links)}: "
                  + " -> ".join(os.path.basename(p) for p in links) + ")")
            return 0
        try:
            bad = verify_checkpoint(args.file)
        except CheckpointCorruptionError as e:
            print(f"CORRUPT {args.file}: {e}")
            return 1
        if bad:
            rec = read_sidecar(args.file)
            ranges = _rec_ranges(rec)
            names = ", ".join(_chunk_name(i, ranges) for i in bad)
            print(f"CORRUPT {args.file}: checksum mismatch in {names}")
            return 1
        print(f"OK {args.file}")
        return 0

    from . import supervise  # lazy: resilience must import standalone

    if args.cmd == "chain":
        chains = supervise.chain_report(args.dir, stem=args.stem)
        bad = 0
        for stem_name, links in chains:
            head = links[-1][0]
            print(f"chain {stem_name} @ step {head} "
                  f"({len(links)} link(s)):")
            for step, path, kind, status in links:
                if status != "OK":
                    bad += 1
                print(f"  {kind:<8} step {step:>8}  {status:<12} "
                      f"{os.path.basename(path)}")
        if not chains:
            print(f"no numbered checkpoints in {args.dir}")
        return 1 if bad else 0

    rep = supervise.gc_checkpoints(
        args.dir, keep_last=args.keep_last, keep_every=args.keep_every,
        stem=args.stem, apply=args.apply)
    verb = "pruned" if args.apply else "would prune"
    for step, path in rep.dropped:
        print(f"{verb} step {step}: {path}")
    for path in rep.stale_temps:
        print(f"{verb} stale temp file: {path}")
    if rep.rescued is not None:
        print(f"kept step {rep.rescued} beyond policy: it is the only "
              "checkpoint that passes verification")
    if rep.refused:
        print(f"REFUSED: {rep.refused}")
    print(f"{'applied' if rep.applied else 'dry-run'}: "
          f"{len(rep.kept)} kept, {len(rep.dropped)} "
          f"{'pruned' if rep.applied else 'prunable'}, "
          f"{len(rep.stale_temps)} stale temp file(s)"
          + ("" if args.apply else " — pass --apply to prune"))
    return 0


def _main(argv=None) -> int:
    """CLI probe for shell scripts: ``python -m dccrg_tpu.resilience
    [--timeout S] [--retries N] [--platform P]`` exits 0 and prints the
    devices when the backend answers, 1 otherwise — never hangs. The
    checkpoint-maintenance subcommands ``verify <file>``, ``audit
    <file>`` (at-rest SDC fingerprint audit), ``chain <dir>`` and
    ``gc <dir> [--keep-last K] [--keep-every N] [--apply]`` run
    without touching the accelerator at all (see
    :func:`_tool_main`)."""
    import argparse

    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("verify", "gc", "chain", "audit"):
        return _tool_main(argv)
    ap = argparse.ArgumentParser(description=_main.__doc__)
    ap.add_argument("--timeout", type=float, default=90.0)
    ap.add_argument("--retries", type=int, default=0)
    ap.add_argument("--backoff", type=float, default=2.0)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args(argv)
    try:
        devs = safe_devices(timeout=args.timeout, retries=args.retries,
                            backoff=args.backoff, platform=args.platform)
        print("OK", devs)
        return 0
    except DeviceProbeError as e:
        print("DOWN", e)
        return 1


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(_main())
