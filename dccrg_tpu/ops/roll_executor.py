"""Roll-plan-driven Pallas bulk executor.

The roll decomposition (`_HoodPlan.roll_plan`, grid.py) reduces any
rectangular stencil on a closed-form uniform plan to S flat axis
shifts plus a sparse set of wrong rows — exactly the shape a tiled,
double-buffered, temporally-blocked Pallas kernel wants. This module
promotes the hand-written 512^3 benchmark kernel's structure
(ops/advection_kernel.py: manual HBM->VMEM DMAs with slot-parity
double buffering, in-VMEM shifted views, scalar-prefetched step
parameters) into a *generic* executor compiled from any grid's roll
plan + SlotwiseKernel flux function:

- every field's flat row array ``[L]`` (L a multiple of 1024) is
  viewed as ``[G, 8, 128]`` register-tile groups; tiles span ``TG``
  groups plus wrap-around halo groups sized by the shift reach, so
  every DMA slice is group-granular on the *major* (untiled) axis —
  always alignment-legal, mirroring the advection kernel's trick;
- inside the kernel each flat shift ``s = 128*q + r`` becomes a row
  slice (``q``) plus a lane rotate (``r``: a concat of two row-shifted
  views) of the VMEM window — no gather ops ever touch HBM;
- the slot validity mask is synthesized from the global flat index
  (the same arithmetic as grid._synth_col), so no [L, S] mask array
  exists on device;
- ``steps_per_pass`` > 1 applies the flux update that many times per
  HBM pass over a shrinking in-VMEM region (temporal blocking),
  dividing HBM traffic per cell-update accordingly;
- the sparse wrong rows (periodic wraps, capacity-padding reads) are
  repaired by a **fused scatter epilogue** in the same jitted program:
  a host-precomputed cascade of dilated row sets is re-run through the
  reference XLA slot loop with exact gathered neighbors, so fixup rows
  are bitwise identical to the XLA roll path at every step.

`compile_bulk_step_loop` plugs this into ``Grid.run_steps`` behind the
``DCCRG_BULK=pallas`` mode switch (grid.compile_step_loop consults it;
with DCCRG_BULK unset the pre-executor XLA program is compiled
bit-identically — the negative pin). `make_fleet_bulk_step` builds the
batched variant (an extra leading Pallas grid dimension over fleet
slots) that GridBatch buckets select through the fleet's bulk kernel
registry.

Eligibility (anything else falls back to the XLA roll path): a
single-device closed-form plan, scalar cell fields, a SlotwiseKernel,
``L % 1024 == 0``, and halos that fit the tiling. On CPU backends the
kernel runs under Pallas TPU interpret mode (CI's parity suite,
tests/test_bulk_executor.py); lane rotates (minor-dim concats) and
in-kernel integer div/mod are Mosaic-supported but unmeasured on chip
until bench/chip_session.sh's executor A/B runs.
"""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..compat import pallas_compiler_params, pallas_interpret_mode

_LANES = 128
_SUBLANES = 8
_GROUP = _LANES * _SUBLANES  # flat cells per (8, 128) register tile


def bulk_mode() -> str:
    """The DCCRG_BULK mode switch: '' / 'xla' (default — the XLA roll
    path, bitwise the pre-executor program), 'pallas' (bulk executor
    where eligible, XLA fallback otherwise)."""
    return os.environ.get("DCCRG_BULK", "").strip().lower()


def bulk_steps_per_pass() -> int:
    """DCCRG_BULK_SPP: temporal blocking depth of the Pallas pass
    (sub-steps per HBM pass), clamped to 1..8 like the benchmark
    kernel's steps_per_pass."""
    try:
        k = int(os.environ.get("DCCRG_BULK_SPP", "1"))
    except ValueError:
        k = 1
    return max(1, min(k, 8))


# ---------------------------------------------------------------------
# static pass geometry
# ---------------------------------------------------------------------

class RollPassSpec:
    """Static geometry of one bulk pass, derived from the roll plan's
    flat shifts: the [G, 8, 128] group view, tile/halo extents and the
    per-sub-step shrinking compute regions."""

    def __init__(self, shifts, dims, periodic, offs_cells, n0, L, k,
                 tile_groups=None):
        self.shifts = tuple(int(s) for s in shifts)
        self.dims = tuple(int(d) for d in dims)
        self.periodic = tuple(bool(p) for p in periodic)
        self.offs_cells = tuple(tuple(int(v) for v in o)
                                for o in offs_cells)
        self.n0 = int(n0)
        self.L = int(L)
        self.k = int(k)
        if self.L % _GROUP:
            raise ValueError(f"L={L} not a multiple of {_GROUP}")
        self.G = self.L // _GROUP
        self.M = self.L // _LANES  # rows of 128 lanes
        # per slot: row shift q (floor) and lane rotate r in [0, 128)
        self.qr = [(s // _LANES, s % _LANES) for s in self.shifts]
        # per-sub-step row margins: slot j needs prev-region rows
        # [q_j, q_j + (r_j > 0)]
        self.a_r = max(0, max((-q for q, _r in self.qr), default=0))
        self.b_r = max(0, max((q + (1 if r else 0)
                               for q, r in self.qr), default=0))
        hm_rows, hp_rows = self.k * self.a_r, self.k * self.b_r
        self.Hm_g = -(-hm_rows // _SUBLANES)
        self.Hp_g = -(-hp_rows // _SUBLANES)
        if max(self.Hm_g, self.Hp_g) > self.G:
            raise ValueError("halo exceeds the grid (grid too small "
                             "for this steps_per_pass)")
        if tile_groups is None:
            env = os.environ.get("DCCRG_BULK_TILE_G")
            tile_groups = int(env) if env else None
        lo = max(self.Hm_g, self.Hp_g, 1)
        if tile_groups is not None:
            if (self.G % tile_groups) or tile_groups < lo:
                raise ValueError(
                    f"tile_groups={tile_groups} must divide G={self.G} "
                    f"and be >= {lo}")
            self.TG = int(tile_groups)
        else:
            self.TG = next(d for d in range(lo, self.G + 1)
                           if self.G % d == 0)
        self.n_tiles = self.G // self.TG
        self.WG = self.TG + self.Hm_g + self.Hp_g  # window groups
        self.WR = self.WG * _SUBLANES  # window rows

    def region(self, t):
        """Row bounds [lo, hi) of sub-step ``t``'s compute region
        within the window (t = 0 is the full input window)."""
        return t * self.a_r, self.WR - t * self.b_r


# ---------------------------------------------------------------------
# in-kernel helpers
# ---------------------------------------------------------------------

def _shifted_view(arr, base, length, q, r):
    """View of ``arr`` rows [base+q, ...) lane-rotated by ``r``: the
    flat-index shift ``128*q + r`` over the row-major [rows, 128]
    window — pure slices and one minor-dim concat."""
    a = arr[base + q: base + q + length]
    if r == 0:
        return a
    b = arr[base + q + 1: base + q + 1 + length]
    return jnp.concatenate([a[:, r:], b[:, :r]], axis=1)


def _mask_col(spec, i, base_valid, j):
    """Slot ``j`` validity over global flat indices ``i`` — the same
    closed-form arithmetic as grid._synth_col, evaluated per tile
    inside the kernel instead of per [L] column."""
    nx, ny, nz = spec.dims
    x = i % nx
    y = (i // nx) % ny
    z = i // (nx * ny)
    ox, oy, oz = spec.offs_cells[j]
    v = base_valid
    for coord, o, nd, per in ((x, ox, nx, spec.periodic[0]),
                              (y, oy, ny, spec.periodic[1]),
                              (z, oz, nz, spec.periodic[2])):
        if o != 0 and not per:
            t = coord + o
            v = v & (t >= 0) & (t < nd)
    return v


# ---------------------------------------------------------------------
# the bulk Pallas pass
# ---------------------------------------------------------------------

def make_bulk_pass(spec, kernel, fields_in, fields_out, dtypes,
                   offs_np, extra_dtypes, interpret, batch=None):
    """Compile one bulk pass: ``fn(extras_arr, *in_groups) -> outs``.

    ``in_groups`` are the fields_in arrays viewed as [G, 8, 128]
    ([B, G, 8, 128] when ``batch`` is an int — the fleet's slot axis
    becomes a leading Pallas grid dimension), ``extras_arr`` is the
    float32-packed per-pass scalars ([E] / [B, E]). Outputs are the
    fields_out group views after ``spec.k`` flux sub-steps, with wrap
    rows still un-fixed (the scatter epilogue repairs them)."""
    F = len(fields_in)
    n_out = len(fields_out)
    TG, WG, Hm_g, Hp_g, G = spec.TG, spec.WG, spec.Hm_g, spec.Hp_g, spec.G
    n_tiles, WR, M = spec.n_tiles, spec.WR, spec.M
    a_r, k = spec.a_r, spec.k
    carried = [f for f in fields_in if f in fields_out]

    def body(ex_ref, *refs):
        ins = refs[:F]
        outs = refs[F:F + n_out]
        bodies = refs[F + n_out:F + n_out + F]
        sems = refs[-1]
        if batch is None:
            b = None
            n = pl.program_id(0)
            lin = n
            total = n_tiles
        else:
            b = pl.program_id(0)
            n = pl.program_id(1)
            lin = b * n_tiles + n
            total = batch * n_tiles
        two = jnp.int32(2)  # keep int32 under jax_enable_x64
        slot = jax.lax.rem(lin, two)
        nxt = jax.lax.rem(lin + jnp.int32(1), two)

        def dmas(sl, li):
            if batch is None:
                bi, ni = None, li
            else:
                bi = li // jnp.int32(n_tiles)
                ni = li - bi * jnp.int32(n_tiles)
            t0 = pl.multiple_of(ni * TG, TG)
            cps = []
            for fi in range(F):
                src = ins[fi]

                def at(g0, cnt):
                    if batch is None:
                        return src.at[pl.ds(g0, cnt)]
                    return src.at[bi, pl.ds(g0, cnt)]

                cps.append(pltpu.make_async_copy(
                    at(t0, TG),
                    bodies[fi].at[sl, pl.ds(Hm_g, TG)],
                    sems.at[sl, 3 * fi],
                ))
                if Hm_g:
                    glo = jax.lax.rem(t0 - jnp.int32(Hm_g) + jnp.int32(G),
                                      jnp.int32(G))
                    cps.append(pltpu.make_async_copy(
                        at(glo, Hm_g),
                        bodies[fi].at[sl, pl.ds(0, Hm_g)],
                        sems.at[sl, 3 * fi + 1],
                    ))
                if Hp_g:
                    ghi = jax.lax.rem(t0 + jnp.int32(TG), jnp.int32(G))
                    cps.append(pltpu.make_async_copy(
                        at(ghi, Hp_g),
                        bodies[fi].at[sl, pl.ds(Hm_g + TG, Hp_g)],
                        sems.at[sl, 3 * fi + 2],
                    ))
            return cps

        @pl.when(lin == 0)
        def _():
            for c in dmas(jnp.int32(0), jnp.int32(0)):
                c.start()

        @pl.when(lin + 1 < total)
        def _():
            for c in dmas(nxt, lin + jnp.int32(1)):
                c.start()

        for c in dmas(slot, lin):
            c.wait()

        windows = {f: bodies[fi][slot].reshape(WR, _LANES)
                   for fi, f in enumerate(fields_in)}
        extras = tuple(
            (ex_ref[i] if batch is None else ex_ref[b, i]).astype(dt)
            for i, dt in enumerate(extra_dtypes))
        # global row index of window row 0 (mod M: the flat roll wraps
        # mod L, and L = M * 128 keeps the lane structure intact)
        row0 = (n * jnp.int32(TG) - jnp.int32(Hm_g)) * jnp.int32(_SUBLANES)

        carry = {}
        for t in range(1, k + 1):
            lo, hi = spec.region(t)
            length = hi - lo
            m_io = jax.lax.broadcasted_iota(jnp.int32, (length, _LANES), 0)
            c_io = jax.lax.broadcasted_iota(jnp.int32, (length, _LANES), 1)
            gr = jnp.remainder(row0 + jnp.int32(lo) + m_io, jnp.int32(M))
            i = gr * jnp.int32(_LANES) + c_io
            base_valid = i < spec.n0

            def src(f):
                # carried fields read sub-step t-1 values; statics read
                # the window — both with the region-local base offset
                if t > 1 and f in carried:
                    return carry[f], a_r
                return windows[f], lo

            cell = {}
            for f in fields_in:
                arr, base = src(f)
                cell[f] = arr[base: base + length]
            acc = kernel.init(cell, *extras)
            for j, (q, r) in enumerate(spec.qr):
                mj = _mask_col(spec, i, base_valid, j)
                nbr = {}
                for f in fields_in:
                    arr, base = src(f)
                    v = _shifted_view(arr, base, length, q, r)
                    nbr[f] = jnp.where(mj, v, jnp.zeros((), v.dtype))
                acc = kernel.slot(acc, cell, nbr, offs_np[j], mj, *extras)
            res = kernel.finish(acc, cell, *extras)
            carry = {f: res[f].astype(dtypes[f]) for f in fields_out}

        body_lo = Hm_g * _SUBLANES - spec.region(k)[0]
        for oi, f in enumerate(fields_out):
            out = carry[f][body_lo: body_lo + TG * _SUBLANES]
            out = out.reshape(TG, _SUBLANES, _LANES)
            if batch is None:
                outs[oi][...] = out
            else:
                outs[oi][0] = out

    if batch is None:
        grid = (n_tiles,)
        out_block = ((TG, _SUBLANES, _LANES),
                     lambda n, _ex: (n, 0, 0))
        out_shapes = [jax.ShapeDtypeStruct((G, _SUBLANES, _LANES),
                                           dtypes[f]) for f in fields_out]
    else:
        grid = (batch, n_tiles)
        out_block = ((1, TG, _SUBLANES, _LANES),
                     lambda b, n, _ex: (b, n, 0, 0))
        out_shapes = [jax.ShapeDtypeStruct((batch, G, _SUBLANES, _LANES),
                                           dtypes[f]) for f in fields_out]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * F,
        out_specs=[pl.BlockSpec(out_block[0], out_block[1],
                                memory_space=pltpu.VMEM)
                   for _ in fields_out],
        scratch_shapes=[pltpu.VMEM((2, WG, _SUBLANES, _LANES),
                                   dtypes[f]) for f in fields_in]
        + [pltpu.SemaphoreType.DMA((2, 3 * F))],
    )

    cells = spec.L * (batch or 1)
    call = pl.pallas_call(
        body,
        grid_spec=grid_spec,
        interpret=pallas_interpret_mode(interpret),
        out_shape=out_shapes,
        compiler_params=pallas_compiler_params(
            vmem_limit_bytes=96 * 1024 * 1024,
        ),
        cost_estimate=pl.CostEstimate(
            10 * len(spec.shifts) * k * cells,
            bytes_accessed=2 * sum(jnp.dtype(dtypes[f]).itemsize
                                   for f in fields_in) * cells,
            transcendentals=0,
        ),
    )

    def fn(extras_arr, *in_groups):
        out = call(extras_arr, *in_groups)
        return out if isinstance(out, (list, tuple)) else (out,)

    return fn


# ---------------------------------------------------------------------
# the fixup scatter epilogue
# ---------------------------------------------------------------------

def _flat_coords(rows, dims):
    nx, ny, _nz = dims
    return rows % nx, (rows // nx) % ny, rows // (nx * ny)


def _apply_offset(rows, off, dims, periodic, n0):
    """(valid, flat target) of stepping ``rows`` by cell offset
    ``off`` under the grid's periodicity — host-side mirror of the
    device mask/neighbor arithmetic."""
    rows = np.asarray(rows, dtype=np.int64)
    nx, ny, nz = dims
    x, y, z = _flat_coords(rows, dims)
    t = [x + off[0], y + off[1], z + off[2]]
    valid = rows < n0
    for d, nd in enumerate((nx, ny, nz)):
        if periodic[d]:
            t[d] = t[d] % nd
        else:
            valid = valid & (t[d] >= 0) & (t[d] < nd)
    tgt = t[0] + nx * (t[1] + ny * t[2])
    return valid, np.where(valid, tgt, 0)


def build_epilogue_sets(spec, wrong_rows_host):
    """Host tables of the fixup cascade for a ``spec.k``-deep pass.

    ``W`` = rows whose flat roll is wrong for some slot. After ``k``
    in-kernel sub-steps the wrongness has spread ``k-1`` stencil hops,
    and repairing it needs pass-input values ``k`` hops further out:
    ``need_k = W ∪ D(W) ∪ ... ∪ D^{k-1}(W)`` (D = inverse-neighbor
    dilation) re-run for k sub-steps over the nested supersets
    ``need_{t-1} = need_t ∪ N(need_t)`` (N = true neighbors), all
    gathers reading exact neighbor rows. Returns ``[(rows_t [Nt],
    nbr_rows_t [Nt, S], mask_t [Nt, S])]`` for t = 1..k (unpadded)."""
    L, k = spec.L, spec.k
    dims, periodic, n0 = spec.dims, spec.periodic, spec.n0
    offs = spec.offs_cells
    W = np.unique(np.asarray(wrong_rows_host, dtype=np.int64).ravel())
    W = W[W < L]

    def dilate_inverse(rows):
        parts = [rows]
        for o in offs:
            inv = (-o[0], -o[1], -o[2])
            valid, tgt = _apply_offset(rows, inv, dims, periodic, n0)
            # r' depends on rows via slot j iff r' + o_j lands on them
            # with a VALID mask at r'
            parts.append(tgt[valid])
        return np.unique(np.concatenate(parts))

    def dilate_forward(rows):
        parts = [rows]
        for o in offs:
            valid, tgt = _apply_offset(rows, o, dims, periodic, n0)
            parts.append(tgt[valid])
        return np.unique(np.concatenate(parts))

    wrong = W
    for _ in range(k - 1):
        wrong = np.union1d(W, dilate_inverse(wrong))
    need = [None] * (k + 1)
    need[k] = wrong
    for t in range(k - 1, 0, -1):
        need[t] = dilate_forward(need[t + 1])

    tables = []
    for t in range(1, k + 1):
        rows = need[t].astype(np.int64)
        S = len(offs)
        nbr = np.zeros((len(rows), S), dtype=np.int32)
        mask = np.zeros((len(rows), S), dtype=bool)
        for j, o in enumerate(offs):
            valid, tgt = _apply_offset(rows, o, dims, periodic, n0)
            nbr[:, j] = tgt.astype(np.int32)
            mask[:, j] = valid
        tables.append((rows.astype(np.int32), nbr, mask))
    return tables


def pad_epilogue_tables(tables, caps, L):
    """Pad the cascade tables to sticky row capacities (rows pad with
    ``L`` — gathers clamp, scatters drop) so the compiled program
    survives bucketed structure epochs."""
    out = []
    for (rows, nbr, mask), cap in zip(tables, caps):
        n = len(rows)
        rows_p = np.full(cap, L, dtype=np.int32)
        nbr_p = np.zeros((cap, nbr.shape[1]), dtype=np.int32)
        mask_p = np.zeros((cap, nbr.shape[1]), dtype=bool)
        rows_p[:n] = rows
        nbr_p[:n] = nbr
        mask_p[:n] = mask
        out.append((rows_p, nbr_p, mask_p))
    return out


def make_epilogue(kernel, fields_in, fields_out, dtypes, offs_const, L,
                  n_tables):
    """``fn(cur, tables_flat, extras) -> cur`` — the in-program fixup
    cascade: for each sub-step t, re-run the reference slot loop over
    the padded row set with exact gathered neighbors and scatter the
    results back, leaving fixup rows bitwise equal to the XLA roll
    path. ``cur`` maps every involved field to its [L] view. The slot
    loop is inlined (without the dense adapter's optimization_barrier
    — a scheduling hint with no effect on values, and vmap has no
    batching rule for it) so the fleet can vmap this over slots."""
    offs_dev = jnp.asarray(offs_const)
    S = len(offs_const)

    def fn(cur, tables_flat, extras):
        cur = dict(cur)
        for t in range(n_tables):
            rows, nbr, mask = tables_flat[3 * t: 3 * t + 3]
            rc = jnp.minimum(rows, L - 1)
            nc = jnp.minimum(nbr, L - 1)
            cell = {f: cur[f][rc] for f in fields_in}
            nbrv = {}
            for f in fields_in:
                g = cur[f][nc]
                nbrv[f] = jnp.where(
                    mask.reshape(mask.shape + (1,) * (g.ndim - 2)),
                    g, jnp.zeros((), g.dtype))
            offs = mask[..., None] * offs_dev[None, :, :]
            acc = kernel.init(cell, *extras)
            for j in range(S):
                nbr_j = {f: nbrv[f][:, j] for f in fields_in}
                acc = kernel.slot(acc, cell, nbr_j, offs[:, j],
                                  mask[:, j], *extras)
            res = kernel.finish(acc, cell, *extras)
            for f in fields_out:
                cur[f] = cur[f].at[rows].set(
                    res[f].astype(dtypes[f]), mode="drop")
        return cur

    return fn


# ---------------------------------------------------------------------
# Grid.run_steps integration
# ---------------------------------------------------------------------

def _grid_spec_for(grid, hood, k, neighborhood_id):
    """RollPassSpec for a grid's hood, or None when the bulk executor
    cannot express the plan (the caller falls back to XLA)."""
    cf = hood.closed_form
    if cf is None or cf.get("multi") or grid.n_dev != 1:
        return None
    roll = hood.roll_plan(grid.plan.L)
    if roll is None:
        return None
    L = int(grid.plan.L)
    if L % _GROUP:
        return None
    try:
        return RollPassSpec(roll[0], cf["dims"], cf["periodic"],
                            cf["offsets"], cf["n0"], L, k)
    except ValueError:
        return None


def _eligible_fields(grid, kernel, fields_in, fields_out):
    from ..grid import SlotwiseKernel

    if not isinstance(kernel, SlotwiseKernel):
        return False
    for f in set(fields_in) | set(fields_out):
        shape, _dt = grid.fields[f]
        if shape != ():
            return False
    return True


def compile_bulk_step_loop(grid, kernel, fields_in, fields_out,
                           exchange_fields, neighborhood_id, n_extra):
    """The DCCRG_BULK=pallas replacement for Grid.compile_step_loop on
    an eligible single-device closed-form plan: one jitted program
    running ``n_steps`` steps as temporally-blocked Pallas bulk passes
    with fused fixup epilogues. Same ``(fn, tables, static_in)``
    contract; returns None when ineligible (caller falls back to the
    XLA roll path)."""
    fields_in = tuple(fields_in)
    fields_out = tuple(fields_out)
    if not _eligible_fields(grid, kernel, fields_in, fields_out):
        return None
    hood = grid.plan.hoods[neighborhood_id]
    if hood.hard_nbr_rows is not None or hood.offs_const is None:
        return None
    k = bulk_steps_per_pass()
    spec_k = _grid_spec_for(grid, hood, k, neighborhood_id)
    if spec_k is None:
        return None
    spec_1 = spec_k if k == 1 else _grid_spec_for(
        grid, hood, 1, neighborhood_id)
    if spec_1 is None:
        return None
    L, R = grid.plan.L, grid.plan.R
    roll = hood.roll_plan(L)
    dtypes = {f: grid.fields[f][1] for f in set(fields_in) | set(fields_out)}
    offs_const = np.asarray(hood.offs_const)
    offs_np = [np.asarray(offs_const[j]) for j in range(len(offs_const))]
    static_in = tuple(f for f in fields_in if f not in fields_out)
    interpret = not grid._on_accelerator()
    if os.environ.get("DCCRG_BULK_INTERPRET") in ("0", "1"):
        interpret = os.environ.get("DCCRG_BULK_INTERPRET") == "1"

    # epilogue cascade tables (host, padded to sticky caps) for the
    # k-deep pass and — when k > 1 — the 1-deep remainder pass. The
    # numpy dilation cascade is O(wrong-set * S * k) — surface-sized
    # but ~10^6 rows at 512^3 — so it is memoized on the hood (one
    # structure epoch), like the roll plan itself; steady-state
    # run_steps calls only look up the cached program + tables.
    memo = getattr(hood, "_bulk_epilogue", None)
    if memo is None:
        memo = hood._bulk_epilogue = {}

    def padded(spec, tag):
        hit = memo.get(tag)
        if hit is not None:
            return hit
        raw = build_epilogue_sets(spec, roll[1])
        caps = [grid._sticky_cap(("bulkN", neighborhood_id, tag, t),
                                 max(1, len(r[0])))
                for t, r in enumerate(raw)]
        hit = (pad_epilogue_tables(raw, caps, L), tuple(caps))
        memo[tag] = hit
        return hit

    tab_k, caps_k = padded(spec_k, k)
    tab_1, caps_1 = (tab_k, caps_k) if k == 1 else padded(spec_1, 1)

    tables = []
    for t, (rows, nbr, mask) in enumerate(tab_k):
        cap = len(rows)
        tables.append(hood.dev(("bulk_rows", neighborhood_id, k, t, cap),
                               rows))
        tables.append(hood.dev(("bulk_nbr", neighborhood_id, k, t, cap),
                               nbr))
        tables.append(hood.dev(("bulk_mask", neighborhood_id, k, t, cap),
                               mask))
    n_tab_k = len(tab_k)
    if k > 1:
        for t, (rows, nbr, mask) in enumerate(tab_1):
            cap = len(rows)
            tables.append(hood.dev(
                ("bulk_rows", neighborhood_id, 1, t, cap), rows))
            tables.append(hood.dev(
                ("bulk_nbr", neighborhood_id, 1, t, cap), nbr))
            tables.append(hood.dev(
                ("bulk_mask", neighborhood_id, 1, t, cap), mask))
    n_tab_1 = len(tab_1)

    synth = (spec_k.dims, spec_k.periodic, spec_k.n0)
    key = ("bulksteploop", kernel, fields_in, fields_out, n_extra, L, R,
           spec_k.shifts, synth, k, spec_k.TG, spec_1.TG, caps_k, caps_1,
           interpret)
    fn = grid._program_cache.get(key)
    if fn is not None:
        return fn, tables, static_in

    n_static, n_out = len(static_in), len(fields_out)
    n_tabs_total = 3 * (n_tab_k + (n_tab_1 if k > 1 else 0))
    epi_k = make_epilogue(kernel, fields_in, fields_out, dtypes,
                          offs_const, L, n_tab_k)
    epi_1 = epi_k if k == 1 else make_epilogue(
        kernel, fields_in, fields_out, dtypes, offs_const, L, n_tab_1)
    f32 = jnp.float32

    def body(n_steps, *args):
        tabs = args[:n_tabs_total]
        tabs_k = tabs[: 3 * n_tab_k]
        tabs_1 = tabs_k if k == 1 else tabs[3 * n_tab_k:]
        args = args[n_tabs_total:]
        statics = {f: a[0][:L] for f, a in zip(static_in, args[:n_static])}
        outs_full = args[n_static: n_static + n_out]
        extra_dtypes = tuple(jnp.asarray(e).dtype
                             for e in args[n_static + n_out:])
        # extras ride the Pallas scalar-prefetch as float32; the
        # epilogue must see the SAME post-roundtrip values (a float64
        # extra under x64 would otherwise step fixup rows with more
        # dt bits than the bulk rows — a growing seam along the
        # wrong-row set)
        extras = tuple(
            jnp.asarray(e).astype(f32).astype(dt)
            for e, dt in zip(args[n_static + n_out:], extra_dtypes))
        ex_arr = (jnp.stack([e.astype(f32) for e in extras])
                  if extras else jnp.zeros((1,), f32))
        pass_k = make_bulk_pass(spec_k, kernel, fields_in, fields_out,
                                dtypes, offs_np, extra_dtypes, interpret)
        pass_1 = pass_k if k == 1 else make_bulk_pass(
            spec_1, kernel, fields_in, fields_out, dtypes, offs_np,
            extra_dtypes, interpret)

        def one_pass(state, pallas_fn, epi, tabs_t):
            full = dict(statics)
            full.update(zip(fields_out, state))
            ins = [full[f].reshape(spec_k.G, _SUBLANES, _LANES)
                   for f in fields_in]
            bulk_out = pallas_fn(ex_arr, *ins)
            bulk = {f: o.reshape(L)
                    for f, o in zip(fields_out, bulk_out)}
            cur = {f: full[f] for f in set(fields_in) | set(fields_out)}
            cur = epi(cur, tabs_t, extras)
            rows_last = tabs_t[-3]
            merged = []
            for f in fields_out:
                fixed = cur[f][jnp.minimum(rows_last, L - 1)]
                merged.append(bulk[f].at[rows_last].set(
                    fixed, mode="drop"))
            return tuple(merged)

        state0 = tuple(a[0][:L] for a in outs_full)
        kk = jnp.int32(k)
        passes = n_steps // kk
        state = jax.lax.fori_loop(
            0, passes,
            lambda _i, s: one_pass(s, pass_k, epi_k, tabs_k), state0)
        if k > 1:
            rem = n_steps - passes * kk
            state = jax.lax.fori_loop(
                0, rem,
                lambda _i, s: one_pass(s, pass_1, epi_1, tabs_1), state)
        return tuple(a.at[0, :L].set(s)
                     for a, s in zip(outs_full, state))

    fn = jax.jit(body)
    grid._program_cache[key] = fn
    return fn, tables, static_in


# ---------------------------------------------------------------------
# fleet (GridBatch) integration
# ---------------------------------------------------------------------

def make_fleet_bulk_step(grid, kernel, fields_in, fields_out, n_extra,
                         capacity):
    """Batched bulk step for a fleet bucket: ``step(state, extras)``
    over ``{field: [capacity, R, ...]}`` state with per-slot float32
    extras ``[capacity, E]`` — the Pallas grid gains a leading slot
    dimension and the fixup epilogue is vmapped. Returns None when the
    bucket's template grid or schema is ineligible (the caller keeps
    the table-gather vstep)."""
    fields_in = tuple(fields_in)
    fields_out = tuple(fields_out)
    if kernel is None:
        return None
    if not _eligible_fields(grid, kernel, fields_in, fields_out):
        return None
    from .. import grid as grid_mod

    hood = grid.plan.hoods[grid_mod.DEFAULT_NEIGHBORHOOD_ID]
    if hood.hard_nbr_rows is not None or hood.offs_const is None:
        return None
    spec = _grid_spec_for(grid, hood, 1,
                          grid_mod.DEFAULT_NEIGHBORHOOD_ID)
    if spec is None:
        return None
    L = int(grid.plan.L)
    roll = hood.roll_plan(L)
    dtypes = {f: grid.fields[f][1] for f in set(fields_in) | set(fields_out)}
    offs_const = np.asarray(hood.offs_const)
    offs_np = [np.asarray(offs_const[j]) for j in range(len(offs_const))]
    interpret = not grid._on_accelerator()
    raw = build_epilogue_sets(spec, roll[1])
    tabs = pad_epilogue_tables(
        raw, [max(1, len(r[0])) for r in raw], L)
    tabs_dev = []
    for rows, nbr, mask in tabs:
        tabs_dev.extend([jnp.asarray(rows), jnp.asarray(nbr),
                         jnp.asarray(mask)])
    epi = make_epilogue(kernel, fields_in, fields_out, dtypes,
                        offs_const, L, len(tabs))
    f32 = jnp.float32
    extra_dtypes = (f32,) * n_extra
    pallas_fn = make_bulk_pass(spec, kernel, fields_in, fields_out,
                               dtypes, offs_np, extra_dtypes, interpret,
                               batch=capacity)
    rows_last = tabs_dev[-3]

    def fix_one(bulk_row, full_row, ex_row):
        extras = tuple(ex_row[i] for i in range(n_extra))
        cur = epi(full_row, tabs_dev, extras)
        merged = {}
        for f in fields_out:
            fixed = cur[f][jnp.minimum(rows_last, L - 1)]
            merged[f] = bulk_row[f].at[rows_last].set(fixed, mode="drop")
        return merged

    def step(state, extras):
        full = {f: state[f][:, :L]
                for f in set(fields_in) | set(fields_out)}
        ins = [full[f].reshape(capacity, spec.G, _SUBLANES, _LANES)
               for f in fields_in]
        ex_arr = (extras.astype(f32) if n_extra
                  else jnp.zeros((capacity, 1), f32))
        bulk_out = pallas_fn(ex_arr, *ins)
        bulk = {f: o.reshape(capacity, L)
                for f, o in zip(fields_out, bulk_out)}
        merged = jax.vmap(fix_one)(bulk, full, extras)
        new = dict(state)
        for f in fields_out:
            new[f] = state[f].at[:, :L].set(
                merged[f].astype(state[f].dtype))
        return new

    return step
