"""Pallas TPU kernels for the hot per-cell stencil loops."""
