"""Pallas TPU kernel for the advection benchmark hot loop.

The reference's per-cell flux loop (tests/advection/solve.hpp:44-266)
iterates cells and face neighbors through pointer-chasing neighbor
lists. Here the uniform-grid hot path is a tiled VMEM stencil:

- density lives unpadded in HBM; tiles span the full y extent and a
  (tx, Y, tz) brick of x/z, so the only halos needed are two x rows —
  and x is the *untiled* dimension of the (8, 128)-tiled memrefs, so
  their DMA slices are always alignment-legal. Periodic wraparound is
  applied to the DMA source indices; no padded copy of the state is
  ever materialized.
- y is the sublane dimension: the y-shifted operands come from in-VMEM
  concatenation (a VPU shuffle over data already on chip, with the
  periodic wrap falling out of the concat order) instead of HBM halos;
- input tiles are double-buffered (slot = tile parity) so the next
  tile's DMA overlaps the current tile's compute;
- the rotation velocity field of the benchmark is separable
  (vx depends only on y, vy only on x — solve.hpp:339-346), so face
  velocities enter as two 1-D arrays: ~zero HBM traffic beyond one
  density read + one write per step.

The result is an HBM-bandwidth-limited step: one read + one write of
the density per time step. The general variable-velocity variant lives
in models/advection.py (dense path) and pays three extra field reads.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..compat import pallas_compiler_params, pallas_interpret_mode


def make_rotation_step(
    shape, dtype=jnp.float32, tile=(8, 128), cell_length=None, steps_per_pass=1,
    interpret=False,
):
    """Compile the 512^3-class benchmark step.

    shape: (X, Y, Z) interior extents; ``tile`` = (tx, tz) brick sizes
    for x and z (each tile covers the full y extent). X % tx == 0,
    Z % tz == 0, tz a multiple of 128 (or the full Z).
    Periodic in x and y (the 2d.cpp:237 configuration); vz == 0 so the
    z direction contributes no flux (and needs no halo).

    ``steps_per_pass``: temporal blocking depth — apply the upwind
    update that many times per HBM pass with a correspondingly wider x
    halo, dividing the HBM traffic per cell-update by the same factor.

    Returns ``step(rho, vx_face, vy_face, dt) -> rho'`` where
    ``vx_face`` is [1, Y] (vx at cell rows, constant along x) and
    ``vy_face`` is [X + 16, 1]: vy at cells (x - 8) % X, i.e. the cell
    values pre-extended by an 8-row wrap margin on each side so every
    dynamic slice offset stays sublane-aligned.

    ``interpret=True`` runs the kernel under Pallas's TPU interpret
    mode (pltpu.InterpretParams) so the DMA/semaphore logic and flux
    math execute on CPU — used by CI, which has no TPU.
    """
    X, Y, Z = shape
    tx, tz = tile
    tz = min(tz, Z)
    sp = int(steps_per_pass)
    if sp < 1 or sp > 8:
        raise ValueError("steps_per_pass must be in 1..8")
    if Z % 128:
        raise ValueError(
            f"pallas fast path needs Z a multiple of 128 (got {Z}); "
            "use the dense-path AdvectionSolver for small grids"
        )
    if X % tx or Z % tz:
        raise ValueError(f"shape {shape} not divisible by tile {(tx, tz)}")
    if tx % 8:
        raise ValueError("tile x extent must be a multiple of 8")
    gx, gz = X // tx, Z // tz
    n_tiles = gx * gz
    if cell_length is None:
        cell_length = (1.0 / X, 1.0 / Y, 1.0 / Z)
    # plain Python floats stay weakly typed so the flux arithmetic
    # keeps the kernel dtype (bfloat16 included) instead of promoting
    rdx = float(1.0 / cell_length[0])
    rdy = float(1.0 / cell_length[1])
    H = sp  # x-halo width on each side

    def tile_indices(n):
        return (n // gz) * tx, (n % gz) * tz

    def dmas(rho_hbm, body, sems, slot, n):
        """Body + two x-halo bands (x = untiled dim: always aligned).

        The wrapped halo band indices are contiguous because x0 is a
        multiple of tx >= H, so (x0 - H) mod X never splits a band."""
        x0, z0 = tile_indices(n)
        xm = (x0 - H + X) % X
        xp = (x0 + tx) % X
        zs = pl.ds(z0, tz)
        return [
            pltpu.make_async_copy(
                rho_hbm.at[pl.ds(x0, tx), :, zs],
                body.at[slot, pl.ds(H, tx), :, :],
                sems.at[slot, 0],
            ),
            pltpu.make_async_copy(
                rho_hbm.at[pl.ds(xm, H), :, zs],
                body.at[slot, pl.ds(0, H), :, :],
                sems.at[slot, 1],
            ),
            pltpu.make_async_copy(
                rho_hbm.at[pl.ds(xp, H), :, zs],
                body.at[slot, pl.ds(tx + H, H), :, :],
                sems.at[slot, 2],
            ),
        ]

    def upwind(s, cx, cy_col):
        """One upwind update: input s of R rows -> output of R - 2 rows
        (the interior), with cy_col/cy_sign (R - 2 rows) aligned to the
        output.

        Because the benchmark's velocity field is separable (vx depends
        only on y, vy only on x — solve.hpp:339-346) the hi and lo face
        velocities of a cell are EQUAL, so the two per-face fluxes
        collapse algebraically:

            flux_lo - flux_hi = v * (up_lo - up_hi)
                              = v * where(v >= 0, r_m - rc, rc - r_p)

        and both one-sided differences along a dimension are slices of
        ONE difference array.  ``cx``/``cy_col`` carry ``v * dt / dlen``
        pre-folded (computed once per pass on [1,Y]/[tx+16,1] vectors;
        dt > 0 so their signs still select the upwind donor), so the
        inner loop is ~10 full-array VPU ops per sub-step instead of
        the naive 16."""
        R = s.shape[0]
        rc = s[1 : R - 1]
        # one-sided differences along x: both sides slice one array
        d_x = s[0 : R - 1] - s[1:R]  # d_x[i] = s[i] - s[i+1]
        dxt = cx * jnp.where(cx >= 0, d_x[0 : R - 2], d_x[1 : R - 1])
        # y: d_y[j] = rc[j] - rc[(j+1) % Y]; the lo-side difference is
        # its +1 roll (periodic wrap falls out of the concat order)
        r_yp = jnp.concatenate([rc[:, 1:, :], rc[:, :1, :]], axis=1)
        d_y = rc - r_yp
        d_ym = jnp.concatenate([d_y[:, Y - 1 :, :], d_y[:, : Y - 1, :]], axis=1)
        dyt = cy_col * jnp.where(cy_col >= 0, d_ym, d_y)
        return rc + dxt + dyt

    def kernel(dt_ref, rho_hbm, vxf_ref, vyf_ref, out_ref, body, sems):
        n = pl.program_id(0)
        two = jnp.int32(2)  # keep int32 under jax_enable_x64
        slot = jax.lax.rem(n, two)
        nxt = jax.lax.rem(n + jnp.int32(1), two)

        @pl.when(n == 0)
        def _():
            for c in dmas(rho_hbm, body, sems, 0, 0):
                c.start()

        @pl.when(n + 1 < n_tiles)
        def _():
            for c in dmas(rho_hbm, body, sems, nxt, n + 1):
                c.start()

        for c in dmas(rho_hbm, body, sems, slot, n):
            c.wait()

        x0, _z0 = tile_indices(n)
        x0 = pl.multiple_of(x0, tx)
        dt = dt_ref[0]
        # fold dt/dlen into the 1-D velocity vectors once per pass;
        # the minor-dim-inserting reshapes run in float32 (Mosaic only
        # supports them for 32-bit types) and cast straight back, so
        # everything downstream stays in the storage dtype
        f32 = jnp.float32
        cx = (vxf_ref[0, :].astype(f32).reshape(1, Y, 1)
              * (dt.astype(f32) * rdx)).astype(dtype)
        # extended vy: index i of vyf_ref holds vy[(i - 8) % X], so the
        # slice at x0 (sublane-aligned) covers global rows x0-8..x0+tx+7
        cy_wide = (vyf_ref[pl.ds(x0, tx + 16), 0].astype(f32)
                   .reshape(tx + 16, 1, 1)
                   * (dt.astype(f32) * rdy)).astype(dtype)

        s = body[slot]  # rows cover global [x0 - H, x0 + tx + H)
        for k in range(sp):
            g = H - k - 1  # halo width remaining after this sub-step
            s = upwind(s, cx, cy_wide[8 - g : 8 - g + tx + 2 * g])
        out_ref[:] = s

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # rho stays in HBM
            pl.BlockSpec(memory_space=pltpu.VMEM),  # vx_face [1, Y]
            pl.BlockSpec(memory_space=pltpu.VMEM),  # vy_face [X, 1]
        ],
        out_specs=pl.BlockSpec(
            # (n, scalar_prefetch_ref) -> block indices
            (tx, Y, tz),
            lambda n, _dt: (n // gz, 0, n % gz),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, tx + 2 * H, Y, tz), dtype),  # body incl. x halos
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
    )

    flops_per_cell = 10 * sp
    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        interpret=pallas_interpret_mode(interpret),
        out_shape=jax.ShapeDtypeStruct((X, Y, Z), dtype),
        compiler_params=pallas_compiler_params(
            # deep temporal blocking holds several flux temporaries live;
            # let Mosaic use more than the 16 MiB default scoped VMEM
            vmem_limit_bytes=96 * 1024 * 1024,
        ),
        cost_estimate=pl.CostEstimate(
            flops_per_cell * X * Y * Z,
            bytes_accessed=2 * jnp.dtype(dtype).itemsize * X * Y * Z,
            transcendentals=0,
        ),
    )

    @jax.jit
    def step(rho, vx_face, vy_face, dt):
        return call(
            jnp.asarray([dt], dtype=dtype),
            rho.astype(dtype),
            vx_face.astype(dtype),
            vy_face.astype(dtype),
        )

    return step
