"""Pallas TPU kernel for the Poisson benchmark hot loop.

The reference's Poisson test spends its time in the per-iteration
matrix-vector product — a 7-point Laplacian applied through
pointer-chasing neighbor lists (tests/poisson/poisson_solve.hpp, the
``Solve`` class's per-cell neighbor loops). BASELINE.json names this
stencil loop as a Pallas target alongside the advection one.

Uniform-grid hot path, same structure as ops/advection_kernel.py:

- the operand lives unpadded in HBM; tiles span the full y AND z
  extents and a ``tx`` brick of x, so the only halos needed are two
  single x rows — and x is the *untiled* dimension of the
  (8, 128)-tiled memrefs, so their DMA slices are always
  alignment-legal. Periodic wraparound is applied to the DMA source
  indices.
- y and z neighbor terms come from in-VMEM concatenation (VPU
  shuffles over data already on chip, with the periodic wrap falling
  out of the concat order) — no y/z halos ever touch HBM;
- input tiles are double-buffered (slot = tile parity) so the next
  tile's DMA overlaps the current tile's compute;
- non-periodic boundaries drop the missing-neighbor terms
  (homogeneous Neumann), matching the masked stencil of
  models/poisson.py's general path and DensePoissonSolver.lap_kernel.

The result is an HBM-bandwidth-limited matvec: one read of the
operand + one write of the product per call — the memory-traffic
floor for one CG iteration's A·p.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..compat import pallas_compiler_params, pallas_interpret_mode


def make_laplacian_matvec(shape, cell_length=None, periodic=(True, True, True),
                          dtype=jnp.float32, tx=8, interpret=False):
    """Compile the 7-point Laplacian matvec ``p -> A p``.

    shape: (X, Y, Z) extents; tiles are (tx, Y, Z) bricks, so Z must be
    a multiple of 128 (the lane tiling) and X a multiple of ``tx``. The
    sign convention matches DensePoissonSolver.lap_kernel: ``A p``
    sums ``rdd2 * (p[neighbor] - p[center])`` over present neighbors.

    ``interpret=True`` runs under Pallas's TPU interpret mode (CI has
    no TPU); the kernel logic is identical.
    """
    X, Y, Z = (int(v) for v in shape)
    if Z % 128:
        raise ValueError(
            f"pallas poisson path needs Z a multiple of 128 (got {Z}); "
            "use DensePoissonSolver for small grids"
        )
    if X % tx or tx % 8:
        raise ValueError(f"X {X} must divide into x tiles of {tx} (mult. of 8)")
    if cell_length is None:
        cell_length = (1.0 / X, 1.0 / Y, 1.0 / Z)
    rdx2 = float(1.0 / cell_length[0] ** 2)
    rdy2 = float(1.0 / cell_length[1] ** 2)
    rdz2 = float(1.0 / cell_length[2] ** 2)
    px, py, pz = (bool(b) for b in periodic)
    gx = X // tx
    H = 1  # one-cell halo in x

    def dmas(p_hbm, body, sems, slot, n):
        x0 = n * tx
        xm = (x0 - H + X) % X
        xp = (x0 + tx) % X
        return [
            pltpu.make_async_copy(
                p_hbm.at[pl.ds(x0, tx)], body.at[slot, pl.ds(H, tx)],
                sems.at[slot, 0],
            ),
            pltpu.make_async_copy(
                p_hbm.at[pl.ds(xm, H)], body.at[slot, pl.ds(0, H)],
                sems.at[slot, 1],
            ),
            pltpu.make_async_copy(
                p_hbm.at[pl.ds(xp, H)], body.at[slot, pl.ds(tx + H, H)],
                sems.at[slot, 2],
            ),
        ]

    def kernel(p_hbm, out_ref, body, sems):
        n = pl.program_id(0)
        two = jnp.int32(2)
        slot = jax.lax.rem(n, two)
        nxt = jax.lax.rem(n + jnp.int32(1), two)

        @pl.when(n == 0)
        def _():
            for c in dmas(p_hbm, body, sems, 0, 0):
                c.start()

        @pl.when(n + 1 < gx)
        def _():
            for c in dmas(p_hbm, body, sems, nxt, n + 1):
                c.start()

        for c in dmas(p_hbm, body, sems, slot, n):
            c.wait()

        s = body[slot]  # rows cover global [x0 - 1, x0 + tx + 1)
        rc = s[1 : tx + 1]
        acc = jnp.zeros_like(rc)

        # x: halo rows from the DMA (wrapped indices); non-periodic
        # edges mask by the global row index
        t_lo = s[0:tx] - rc
        t_hi = s[2 : tx + 2] - rc
        if not px:
            x0 = pl.program_id(0) * tx
            gxr = x0 + jax.lax.broadcasted_iota(jnp.int32, rc.shape, 0)
            t_lo = jnp.where(gxr > 0, t_lo, 0.0)
            t_hi = jnp.where(gxr < X - 1, t_hi, 0.0)
        acc += rdx2 * (t_lo + t_hi)

        # y: in-VMEM concat rolls (wrap falls out of the concat order)
        y_hi = jnp.concatenate([rc[:, 1:, :], rc[:, :1, :]], axis=1)
        y_lo = jnp.concatenate([rc[:, Y - 1 :, :], rc[:, : Y - 1, :]], axis=1)
        t_lo = y_lo - rc
        t_hi = y_hi - rc
        if not py:
            gy = jax.lax.broadcasted_iota(jnp.int32, rc.shape, 1)
            t_lo = jnp.where(gy > 0, t_lo, 0.0)
            t_hi = jnp.where(gy < Y - 1, t_hi, 0.0)
        acc += rdy2 * (t_lo + t_hi)

        # z: same trick on the lane dimension
        z_hi = jnp.concatenate([rc[:, :, 1:], rc[:, :, :1]], axis=2)
        z_lo = jnp.concatenate([rc[:, :, Z - 1 :], rc[:, :, : Z - 1]], axis=2)
        t_lo = z_lo - rc
        t_hi = z_hi - rc
        if not pz:
            gz = jax.lax.broadcasted_iota(jnp.int32, rc.shape, 2)
            t_lo = jnp.where(gz > 0, t_lo, 0.0)
            t_hi = jnp.where(gz < Z - 1, t_hi, 0.0)
        acc += rdz2 * (t_lo + t_hi)

        out_ref[:] = acc

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(gx,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],  # p stays in HBM
        out_specs=pl.BlockSpec(
            (tx, Y, Z), lambda n: (n, 0, 0), memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, tx + 2 * H, Y, Z), jnp.dtype(dtype)),
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
    )

    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        interpret=pallas_interpret_mode(interpret),
        out_shape=jax.ShapeDtypeStruct((X, Y, Z), jnp.dtype(dtype)),
        compiler_params=pallas_compiler_params(
            vmem_limit_bytes=96 * 1024 * 1024,
        ),
        cost_estimate=pl.CostEstimate(
            12 * X * Y * Z,
            bytes_accessed=2 * 4 * X * Y * Z,
            transcendentals=0,
        ),
    )

    def matvec(p):
        return call(jnp.asarray(p, dtype=dtype))

    return jax.jit(matvec)


class PallasPoissonSolver:
    """CG on the Pallas matvec: the single-chip fast path of the
    Poisson benchmark (uniform grids; cross-checked against
    DensePoissonSolver in tests under interpret mode). The CG vector
    updates run as fused XLA ops; the matvec — the HBM-bound op — is
    the kernel above."""

    def __init__(self, length, periodic=(True, True, True),
                 dtype=jnp.float32, tx=8, interpret=False):
        self.length = tuple(int(v) for v in length)
        self.periodic = tuple(bool(b) for b in periodic)
        self.dtype = jnp.dtype(dtype)
        self._matvec = make_laplacian_matvec(
            self.length, cell_length=tuple(1.0 / v for v in self.length),
            periodic=self.periodic, dtype=dtype, tx=tx, interpret=interpret,
        )

    def solve(self, rhs, rtol=1e-5, max_iterations=1000):
        from ..models.poisson import cg_solve

        return cg_solve(self._matvec, rhs, singular=all(self.periodic),
                        dtype=self.dtype, rtol=rtol,
                        max_iterations=max_iterations)
