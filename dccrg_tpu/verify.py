"""DEBUG-style invariant verifiers.

Equivalents of the reference's ``#ifdef DEBUG`` checkers
(dccrg.hpp:12454-13036): each function recomputes a piece of derived
grid structure from first principles and compares it with what the
``Grid`` is actually using, raising ``VerificationError`` on the first
mismatch. They are pure host-side checks — safe to call at any point
between operations:

- ``is_consistent``       — replicated structure sanity (dccrg.hpp:12454-12510)
- ``verify_neighbors``    — recompute-and-compare neighbor lists, incl.
                            the <=1 refinement-level-difference invariant
                            (dccrg.hpp:12516-12750)
- ``verify_remote_neighbor_info`` — boundary classification and halo
                            send/receive list symmetry (dccrg.hpp:12759-12978)
- ``verify_user_data``    — field storage layout (dccrg.hpp:12984-13011)
- ``pin_requests_succeeded`` — pinned cells sit on their device (dccrg.hpp:13017-13035)
- ``verify_refinement_balance`` — the 2:1 invariant recomputed over
                            FACE adjacency only, independently of the
                            stored neighbor lists
- ``verify_neighbor_symmetry`` — of/to mutual consistency recomputed
                            with two independent engines (forward
                            of-engine vs direct to-subset query)
- ``verify_partition_coverage`` — every cell owned exactly once across
                            the per-device row sets
- ``verify_all``          — everything above
- ``find_nonfinite_cells`` — locate NaN/Inf per field (the resilience
                            watchdog's diagnostic pass: the cheap
                            device-side probe in resilience.py only
                            says *that* something blew up; this names
                            the field and cells for the bundle)

Every failure raises :class:`VerificationError`, whose ``cells``
attribute names the offending cell ids when the check can identify
them (the transactional layer in txn.py propagates them into its
:class:`~dccrg_tpu.txn.GridInvariantError`).

Setting ``DCCRG_DEBUG=1`` makes ``Grid`` run the verifiers after every
structure rebuild (init, AMR commit, load balance) AND ``verify_all``
at every transactional mutation boundary (txn.py) — the reference's
DEBUG builds do the same continuous self-checking.
"""

from __future__ import annotations

import numpy as np

from .neighbors import (_dedup_entries, _find_neighbors_of_numpy,
                        find_neighbors_to_subset, verify_tiling)

# parity with grid.DEFAULT_NEIGHBORHOOD_ID (import would be circular)
_DEFAULT_HOOD = -0xDCC


def format_cells(cells, limit: int = 8) -> str:
    """``" [cells a, b, ..., +n more]"`` suffix for error messages
    (shared by VerificationError, txn.MutationError, fuzz.FuzzFailure);
    empty string when no cells are named."""
    cells = tuple(cells)
    if not cells:
        return ""
    shown = ", ".join(str(c) for c in cells[:limit])
    more = "" if len(cells) <= limit else f", +{len(cells) - limit} more"
    return f" [cells {shown}{more}]"


class VerificationError(AssertionError):
    """A grid invariant does not hold. ``cells`` carries the offending
    cell ids when the failed check can name them (empty otherwise)."""

    def __init__(self, msg: str, cells=()):
        if np.size(cells):
            self.cells = tuple(
                int(c) for c in np.atleast_1d(np.asarray(cells, dtype=np.uint64))
            )
        else:
            self.cells = ()
        super().__init__(msg + format_cells(self.cells))


def _fail(msg: str, cells=()):
    raise VerificationError(msg, cells=cells)


def is_consistent(grid) -> None:
    """Replicated structure sanity: sorted unique leaf cells that tile
    the grid, owners in range, and the device row layout matching the
    replicated cell->owner map."""
    plan = grid.plan
    cells, owner = plan.cells, plan.owner
    # comparison, not np.diff: uint64 differences wrap, so a swapped
    # (decreasing) pair would yield a huge positive and slip through
    ordered = cells[1:] > cells[:-1]
    if len(cells) > 1 and not np.all(ordered):
        _fail("cell list is not strictly sorted", cells=cells[:-1][~ordered])
    verify_tiling(grid.mapping, cells)
    if len(owner) != len(cells):
        _fail("owner array length mismatch")
    if np.any((owner < 0) | (owner >= plan.n_dev)):
        _fail("cell owner out of device range",
              cells=cells[(owner < 0) | (owner >= plan.n_dev)])

    # row layout: each device's local rows hold exactly its cells
    for d in range(plan.n_dev):
        mine = np.sort(cells[owner == d])
        rows = np.sort(plan.local_ids[d])
        if not np.array_equal(mine, rows):
            _fail(f"device {d}: local row ids do not match owned cells",
                  cells=np.setxor1d(mine, rows))
        if plan.n_local[d] != len(plan.local_ids[d]):
            _fail(f"device {d}: n_local does not match row count")
        if len(plan.local_ids[d]) > plan.L:
            _fail(f"device {d}: local rows exceed capacity L")
        # ghost rows hold only existing, remote cells
        gids = plan.ghost_ids[d]
        pos = np.searchsorted(cells, gids)
        if len(gids) and (
            np.any(pos >= len(cells)) or np.any(cells[pos] != gids)
        ):
            missing = gids[(pos >= len(cells))
                           | (cells[np.minimum(pos, len(cells) - 1)] != gids)]
            _fail(f"device {d}: ghost id not an existing cell",
                  cells=missing)
        if len(gids) and np.any(owner[pos] == d):
            _fail(f"device {d}: ghost row holds a locally-owned cell",
                  cells=gids[owner[pos] == d])
        # row lookup agrees with the row arrays
        lpos = np.searchsorted(cells, plan.local_ids[d])
        if len(lpos) and not np.array_equal(
            plan.row_of_pos[lpos], np.arange(len(lpos), dtype=plan.row_of_pos.dtype)
        ):
            _fail(f"device {d}: row lookup mismatch in local rows")


def _recompute_of_streams(grid) -> dict:
    """{hood id: dedup'd (src, nbr, off, item)} recomputed from scratch
    with the NumPy reference engine — the shared input of
    verify_neighbors and verify_neighbor_symmetry (verify_all computes
    it once; standalone calls recompute)."""
    cells = grid.plan.cells
    return {
        hid: _dedup_entries(grid.mapping, cells, *_find_neighbors_of_numpy(
            grid.mapping, grid.topology, cells, cells, offsets
        ))
        for hid, offsets in grid.neighborhoods.items()
    }


def verify_neighbors(grid, of_streams: dict | None = None) -> None:
    """Recompute every neighborhood's neighbors_of/neighbors_to with the
    NumPy reference engine and compare with the lists the plan was built
    from; check the <=1 refinement-level-difference invariant."""
    plan = grid.plan
    cells = plan.cells
    if of_streams is None:
        of_streams = _recompute_of_streams(grid)
    for hid in grid.neighborhoods:
        nl = plan.hoods[hid].lists
        src, nbr, off, item = of_streams[hid]
        if not (
            np.array_equal(src, nl.of_source)
            and np.array_equal(nbr, nl.of_neighbor)
            and np.array_equal(off, nl.of_offset)
            and np.array_equal(item, nl.of_item)
        ):
            # name the sources whose entries diverge (comparable only
            # when the streams kept the same length)
            bad = np.empty(0, np.uint64)
            if len(src) == len(nl.of_source):
                m = ((src != nl.of_source) | (nbr != nl.of_neighbor)
                     | np.any(off != nl.of_offset, axis=1)
                     | (item != nl.of_item))
                bad = np.unique(cells[src[m]])
            _fail(f"neighborhood {hid}: stored neighbors_of != recomputed",
                  cells=bad)
        # inversion consistency: to-lists are exactly the inverse relation
        inv = np.lexsort((np.arange(len(src)), np.searchsorted(cells, nbr)))
        if not (
            np.array_equal(np.searchsorted(cells, nbr)[inv], nl.to_source)
            and np.array_equal(cells[src][inv], nl.to_neighbor)
            and np.array_equal(-off[inv], nl.to_offset)
        ):
            _fail(f"neighborhood {hid}: neighbors_to is not the inverse of neighbors_of")
        # refinement-level jumps (dccrg.hpp:12729-12747)
        lvl_src = grid.mapping.get_refinement_level(cells[src])
        lvl_nbr = grid.mapping.get_refinement_level(nbr)
        if np.any(np.abs(lvl_src - lvl_nbr) > 1):
            bad = np.argmax(np.abs(lvl_src - lvl_nbr) > 1)
            _fail(
                f"neighborhood {hid}: cells {cells[src[bad]]} and {nbr[bad]} "
                f"differ by more than one refinement level",
                cells=(cells[src[bad]], nbr[bad]),
            )


def verify_remote_neighbor_info(grid) -> None:
    """Boundary (inner/outer) classification and halo-exchange list
    symmetry: device p's send list to q names the same cells, in the
    same order, as q's receive list from p; ghost rows are exactly the
    cells some local cell reads remotely."""
    plan = grid.plan
    cells, owner = plan.cells, plan.owner
    nl = plan.hoods[_DEFAULT_HOOD].lists

    # recompute outer flags: a local cell is outer iff it has a remote
    # neighbor in its of- or to-lists (dccrg.hpp:9377-9409)
    nbr_owner = owner[np.searchsorted(cells, nl.of_neighbor)]
    to_owner = owner[np.searchsorted(cells, nl.to_neighbor)]
    outer = np.zeros(len(cells), dtype=bool)
    np.add.at(outer, nl.of_source[owner[nl.of_source] != nbr_owner], True)
    np.add.at(outer, nl.to_source[owner[nl.to_source] != to_owner], True)

    for d in range(plan.n_dev):
        n_inner = int(plan.hoods[_DEFAULT_HOOD].n_inner[d])
        ids = plan.local_ids[d]
        pos = np.searchsorted(cells, ids)
        got_outer = outer[pos]
        if np.any(got_outer[:n_inner]):
            _fail(f"device {d}: an inner row has a remote neighbor",
                  cells=ids[:n_inner][got_outer[:n_inner]])
        if np.any(~got_outer[n_inner:len(ids)]):
            _fail(f"device {d}: an outer row has no remote neighbor",
                  cells=ids[n_inner:][~got_outer[n_inner:len(ids)]])

    # send/receive symmetry per neighborhood
    for hid, hp in plan.hoods.items():
        for p in range(plan.n_dev):
            for q in range(plan.n_dev):
                srows = hp.send_rows[p, q]
                rrows = hp.recv_rows[q, p]
                if np.sum(srows >= 0) != np.sum(rrows >= 0):
                    _fail(f"hood {hid}: send/recv count mismatch {p}->{q}")
                for j in range(len(srows)):
                    if (srows[j] >= 0) != (rrows[j] >= 0):
                        _fail(f"hood {hid}: send/recv padding mismatch {p}->{q}@{j}")
                    if srows[j] < 0:
                        continue
                    sid = plan.local_ids[p][srows[j]]
                    rid = plan.ghost_ids[q][rrows[j] - plan.L]
                    if sid != rid:
                        _fail(
                            f"hood {hid}: transfer {p}->{q} slot {j} sends cell "
                            f"{sid} into ghost row of cell {rid}",
                            cells=(sid, rid),
                        )


def verify_user_data(grid) -> None:
    """Field arrays have the planned sharded layout and the permanent
    zero pad row really is zero (stencil gathers rely on it)."""
    plan = grid.plan
    for name, (shape, dtype) in grid.fields.items():
        arr = grid.data.get(name)
        if arr is None:
            _fail(f"field {name!r} missing from grid.data")
        want = (plan.n_dev, plan.R) + shape
        if tuple(arr.shape) != want:
            _fail(f"field {name!r}: shape {tuple(arr.shape)} != planned {want}")
        if arr.dtype != dtype:
            _fail(f"field {name!r}: dtype {arr.dtype} != declared {dtype}")
        pad = np.asarray(arr[:, plan.R - 1])
        if np.any(pad != 0):
            _fail(f"field {name!r}: zero pad row has been written to")


def pin_requests_succeeded(grid) -> None:
    """Every granted pin request placed its cell (dccrg.hpp:13017)."""
    plan = grid.plan
    for cid, dev in grid._pins.items():
        pos = np.searchsorted(plan.cells, np.uint64(cid))
        if pos >= len(plan.cells) or plan.cells[pos] != np.uint64(cid):
            continue  # pinned cell no longer exists (refined away)
        if plan.owner[pos] != dev:
            _fail(f"pinned cell {cid} is on device {plan.owner[pos]}, "
                  f"not {dev}", cells=(cid,))


def verify_refinement_balance(grid) -> None:
    """The 2:1 invariant recomputed over FACE adjacency from pure
    index arithmetic — no neighbor engine involved (the engines assume
    <=1-level jumps and cannot even resolve a violating grid), no
    stored lists trusted. For every cell, probe one smallest-index
    unit across each of its 6 faces at the cell's min corner: the leaf
    containing that probe face-touches the cell, and — because aligned
    boxes >=4x larger fully cover a smaller face they touch — every
    violating coarse/fine face pair is seen from its fine side's
    corner probe. |level difference| > 1 fails, naming both cells
    (dccrg.hpp:9730-9906 guarantees the invariant after every
    commit)."""
    mapping = grid.mapping
    cells = grid.plan.cells
    n = len(cells)
    if n == 0:
        return
    idx = mapping.get_indices(cells).astype(np.int64)  # [n, 3] min corner
    lvl = mapping.get_refinement_level(cells).astype(np.int64)
    size = (1 << (mapping.max_refinement_level - lvl)).astype(np.int64)
    ilen = mapping.get_index_length().astype(np.int64)
    periodic = np.array([grid.topology.is_periodic(d) for d in range(3)])

    for d in range(3):
        for sign in (-1, 1):
            probe = idx.copy()
            probe[:, d] = idx[:, d] + (size if sign > 0 else -1)
            if periodic[d]:
                probe[:, d] %= ilen[d]
                valid = np.ones(n, dtype=bool)
            else:
                valid = (probe[:, d] >= 0) & (probe[:, d] < ilen[d])
            if not valid.any():
                continue
            # finest-first descent: the leaf containing each probe
            nbr_id = np.zeros(n, dtype=np.uint64)
            nbr_lvl = np.full(n, -1, dtype=np.int64)
            todo = valid.copy()
            for L in range(mapping.max_refinement_level, -1, -1):
                if not todo.any():
                    break
                cand = np.asarray(mapping.get_cell_from_indices(
                    probe[todo], L))
                pos = np.minimum(np.searchsorted(cells, cand), n - 1)
                hit = cells[pos] == cand
                ti = np.nonzero(todo)[0][hit]
                nbr_id[ti] = cand[hit]
                nbr_lvl[ti] = L
                todo[ti] = False
            found = valid & (nbr_lvl >= 0)
            bad = found & (np.abs(lvl - nbr_lvl) > 1)
            if bad.any():
                offenders = np.unique(np.concatenate(
                    [cells[bad], nbr_id[bad]]))
                _fail(
                    f"2:1 refinement balance violated across "
                    f"{int(bad.sum())} face pair(s) (direction "
                    f"{'+-'[sign < 0]}{'xyz'[d]})", cells=offenders,
                )


def verify_neighbor_symmetry(grid, of_streams: dict | None = None) -> None:
    """of/to mutual consistency, recomputed with two INDEPENDENT
    engines: the forward of-engine (window resolution per source) and
    the direct to-subset query (candidate-source enumeration per
    target) must describe the exact same relation — if B is in A's
    neighbors_of, then A must be reported as a to-neighbor of B, and
    vice versa. A divergence means one engine resolved an edge the
    other missed (the bug class the reference's DEBUG builds catch by
    comparing both directions, dccrg.hpp:12516-12750)."""
    cells = grid.plan.cells
    n = len(cells)
    if of_streams is None:
        of_streams = _recompute_of_streams(grid)
    for hid, offsets in grid.neighborhoods.items():
        src, nbr, _off, _item = of_streams[hid]
        qi, to_src, _off2 = find_neighbors_to_subset(
            grid.mapping, grid.topology, cells, cells, offsets
        )
        fwd = np.unique(src.astype(np.int64) * n
                        + np.searchsorted(cells, nbr))
        rev = np.unique(np.searchsorted(cells, to_src) * n
                        + qi.astype(np.int64))
        if not np.array_equal(fwd, rev):
            odd = np.setxor1d(fwd, rev)
            offenders = np.unique(np.concatenate(
                [cells[odd // n], cells[odd % n]]
            ))
            _fail(
                f"neighborhood {hid}: forward and inverse neighbor "
                f"engines disagree on {len(odd)} edge(s)", cells=offenders,
            )


def verify_partition_coverage(grid) -> None:
    """Every cell is owned exactly once: the per-device local row sets
    are pairwise disjoint and their union is exactly the cell list —
    the global complement of is_consistent's per-device checks (a cell
    silently dropped from every device, or claimed by two, is caught
    here by the totals)."""
    plan = grid.plan
    all_local = (np.concatenate(plan.local_ids) if plan.n_dev
                 else np.empty(0, np.uint64))
    s = np.sort(all_local)
    dup = np.unique(s[:-1][s[:-1] == s[1:]]) if len(s) > 1 else s[:0]
    if len(dup):
        _fail("cells owned by more than one device", cells=dup)
    missing = np.setdiff1d(plan.cells, s, assume_unique=False)
    if len(missing):
        _fail("cells owned by no device", cells=missing)
    extra = np.setdiff1d(s, plan.cells, assume_unique=False)
    if len(extra):
        _fail("device rows hold ids outside the cell list", cells=extra)


def find_nonfinite_cells(grid, fields=None) -> dict:
    """Locate non-finite values: ``{field: cell ids}`` for every
    watched inexact field holding a NaN/Inf in a LOCAL row (ghost
    copies mirror some other device's local row, so local rows cover
    every real offender). Host-side and O(grid) — run it only after
    the cheap device-side probe (resilience.check_finite) has tripped,
    to name the offenders in the diagnostic bundle."""
    out = {}
    cells = grid.get_cells()
    names = list(fields) if fields is not None else list(grid.fields)
    for name in names:
        if not np.issubdtype(np.dtype(grid.fields[name][1]), np.inexact):
            continue
        vals = np.asarray(grid.get(name, cells))
        bad = ~np.isfinite(vals)
        while bad.ndim > 1:
            bad = bad.any(axis=-1)
        if bad.any():
            out[name] = np.asarray(cells)[bad]
    return out


def verify_all(grid, check_pins: bool = True) -> None:
    """Every invariant above. ``check_pins=False`` skips
    pin_requests_succeeded — a pin is a REQUEST until the next
    balance_load applies it, so mutation boundaries that don't apply
    pins (adapt commits) legitimately hold unplaced pins."""
    is_consistent(grid)
    verify_partition_coverage(grid)
    # one forward-engine recompute feeds both neighbor checks; the
    # symmetry check's independence comes from the to-subset engine
    of_streams = _recompute_of_streams(grid)
    verify_neighbors(grid, of_streams)
    verify_neighbor_symmetry(grid, of_streams)
    verify_refinement_balance(grid)
    verify_remote_neighbor_info(grid)
    verify_user_data(grid)
    if check_pins:
        pin_requests_succeeded(grid)
