"""DEBUG-style invariant verifiers.

Equivalents of the reference's ``#ifdef DEBUG`` checkers
(dccrg.hpp:12454-13036): each function recomputes a piece of derived
grid structure from first principles and compares it with what the
``Grid`` is actually using, raising ``VerificationError`` on the first
mismatch. They are pure host-side checks — safe to call at any point
between operations:

- ``is_consistent``       — replicated structure sanity (dccrg.hpp:12454-12510)
- ``verify_neighbors``    — recompute-and-compare neighbor lists, incl.
                            the <=1 refinement-level-difference invariant
                            (dccrg.hpp:12516-12750)
- ``verify_remote_neighbor_info`` — boundary classification and halo
                            send/receive list symmetry (dccrg.hpp:12759-12978)
- ``verify_user_data``    — field storage layout (dccrg.hpp:12984-13011)
- ``pin_requests_succeeded`` — pinned cells sit on their device (dccrg.hpp:13017-13035)
- ``verify_all``          — everything above
- ``find_nonfinite_cells`` — locate NaN/Inf per field (the resilience
                            watchdog's diagnostic pass: the cheap
                            device-side probe in resilience.py only
                            says *that* something blew up; this names
                            the field and cells for the bundle)

Setting ``DCCRG_DEBUG=1`` makes ``Grid`` run ``verify_all`` after every
structure rebuild (init, AMR commit, load balance) — the reference's
DEBUG builds do the same continuous self-checking.
"""

from __future__ import annotations

import numpy as np

from .neighbors import _dedup_entries, _find_neighbors_of_numpy, verify_tiling

# parity with grid.DEFAULT_NEIGHBORHOOD_ID (import would be circular)
_DEFAULT_HOOD = -0xDCC


class VerificationError(AssertionError):
    """A grid invariant does not hold."""


def _fail(msg: str):
    raise VerificationError(msg)


def is_consistent(grid) -> None:
    """Replicated structure sanity: sorted unique leaf cells that tile
    the grid, owners in range, and the device row layout matching the
    replicated cell->owner map."""
    plan = grid.plan
    cells, owner = plan.cells, plan.owner
    if not np.all(np.diff(cells.astype(np.uint64)) > 0):
        _fail("cell list is not strictly sorted")
    verify_tiling(grid.mapping, cells)
    if len(owner) != len(cells):
        _fail("owner array length mismatch")
    if np.any((owner < 0) | (owner >= plan.n_dev)):
        _fail("cell owner out of device range")

    # row layout: each device's local rows hold exactly its cells
    for d in range(plan.n_dev):
        mine = np.sort(cells[owner == d])
        rows = np.sort(plan.local_ids[d])
        if not np.array_equal(mine, rows):
            _fail(f"device {d}: local row ids do not match owned cells")
        if plan.n_local[d] != len(plan.local_ids[d]):
            _fail(f"device {d}: n_local does not match row count")
        if len(plan.local_ids[d]) > plan.L:
            _fail(f"device {d}: local rows exceed capacity L")
        # ghost rows hold only existing, remote cells
        gids = plan.ghost_ids[d]
        pos = np.searchsorted(cells, gids)
        if len(gids) and (
            np.any(pos >= len(cells)) or np.any(cells[pos] != gids)
        ):
            _fail(f"device {d}: ghost id not an existing cell")
        if len(gids) and np.any(owner[pos] == d):
            _fail(f"device {d}: ghost row holds a locally-owned cell")
        # row lookup agrees with the row arrays
        lpos = np.searchsorted(cells, plan.local_ids[d])
        if len(lpos) and not np.array_equal(
            plan.row_of_pos[lpos], np.arange(len(lpos), dtype=plan.row_of_pos.dtype)
        ):
            _fail(f"device {d}: row lookup mismatch in local rows")


def verify_neighbors(grid) -> None:
    """Recompute every neighborhood's neighbors_of/neighbors_to with the
    NumPy reference engine and compare with the lists the plan was built
    from; check the <=1 refinement-level-difference invariant."""
    plan = grid.plan
    cells = plan.cells
    for hid, offsets in grid.neighborhoods.items():
        nl = plan.hoods[hid].lists
        src, nbr, off, item = _dedup_entries(grid.mapping, cells, *_find_neighbors_of_numpy(
            grid.mapping, grid.topology, cells, cells, offsets
        ))
        if not (
            np.array_equal(src, nl.of_source)
            and np.array_equal(nbr, nl.of_neighbor)
            and np.array_equal(off, nl.of_offset)
            and np.array_equal(item, nl.of_item)
        ):
            _fail(f"neighborhood {hid}: stored neighbors_of != recomputed")
        # inversion consistency: to-lists are exactly the inverse relation
        inv = np.lexsort((np.arange(len(src)), np.searchsorted(cells, nbr)))
        if not (
            np.array_equal(np.searchsorted(cells, nbr)[inv], nl.to_source)
            and np.array_equal(cells[src][inv], nl.to_neighbor)
            and np.array_equal(-off[inv], nl.to_offset)
        ):
            _fail(f"neighborhood {hid}: neighbors_to is not the inverse of neighbors_of")
        # refinement-level jumps (dccrg.hpp:12729-12747)
        lvl_src = grid.mapping.get_refinement_level(cells[src])
        lvl_nbr = grid.mapping.get_refinement_level(nbr)
        if np.any(np.abs(lvl_src - lvl_nbr) > 1):
            bad = np.argmax(np.abs(lvl_src - lvl_nbr) > 1)
            _fail(
                f"neighborhood {hid}: cells {cells[src[bad]]} and {nbr[bad]} "
                f"differ by more than one refinement level"
            )


def verify_remote_neighbor_info(grid) -> None:
    """Boundary (inner/outer) classification and halo-exchange list
    symmetry: device p's send list to q names the same cells, in the
    same order, as q's receive list from p; ghost rows are exactly the
    cells some local cell reads remotely."""
    plan = grid.plan
    cells, owner = plan.cells, plan.owner
    nl = plan.hoods[_DEFAULT_HOOD].lists

    # recompute outer flags: a local cell is outer iff it has a remote
    # neighbor in its of- or to-lists (dccrg.hpp:9377-9409)
    nbr_owner = owner[np.searchsorted(cells, nl.of_neighbor)]
    to_owner = owner[np.searchsorted(cells, nl.to_neighbor)]
    outer = np.zeros(len(cells), dtype=bool)
    np.add.at(outer, nl.of_source[owner[nl.of_source] != nbr_owner], True)
    np.add.at(outer, nl.to_source[owner[nl.to_source] != to_owner], True)

    for d in range(plan.n_dev):
        n_inner = int(plan.hoods[_DEFAULT_HOOD].n_inner[d])
        ids = plan.local_ids[d]
        pos = np.searchsorted(cells, ids)
        got_outer = outer[pos]
        if np.any(got_outer[:n_inner]):
            _fail(f"device {d}: an inner row has a remote neighbor")
        if np.any(~got_outer[n_inner:len(ids)]):
            _fail(f"device {d}: an outer row has no remote neighbor")

    # send/receive symmetry per neighborhood
    for hid, hp in plan.hoods.items():
        for p in range(plan.n_dev):
            for q in range(plan.n_dev):
                srows = hp.send_rows[p, q]
                rrows = hp.recv_rows[q, p]
                if np.sum(srows >= 0) != np.sum(rrows >= 0):
                    _fail(f"hood {hid}: send/recv count mismatch {p}->{q}")
                for j in range(len(srows)):
                    if (srows[j] >= 0) != (rrows[j] >= 0):
                        _fail(f"hood {hid}: send/recv padding mismatch {p}->{q}@{j}")
                    if srows[j] < 0:
                        continue
                    sid = plan.local_ids[p][srows[j]]
                    rid = plan.ghost_ids[q][rrows[j] - plan.L]
                    if sid != rid:
                        _fail(
                            f"hood {hid}: transfer {p}->{q} slot {j} sends cell "
                            f"{sid} into ghost row of cell {rid}"
                        )


def verify_user_data(grid) -> None:
    """Field arrays have the planned sharded layout and the permanent
    zero pad row really is zero (stencil gathers rely on it)."""
    plan = grid.plan
    for name, (shape, dtype) in grid.fields.items():
        arr = grid.data.get(name)
        if arr is None:
            _fail(f"field {name!r} missing from grid.data")
        want = (plan.n_dev, plan.R) + shape
        if tuple(arr.shape) != want:
            _fail(f"field {name!r}: shape {tuple(arr.shape)} != planned {want}")
        if arr.dtype != dtype:
            _fail(f"field {name!r}: dtype {arr.dtype} != declared {dtype}")
        pad = np.asarray(arr[:, plan.R - 1])
        if np.any(pad != 0):
            _fail(f"field {name!r}: zero pad row has been written to")


def pin_requests_succeeded(grid) -> None:
    """Every granted pin request placed its cell (dccrg.hpp:13017)."""
    plan = grid.plan
    for cid, dev in grid._pins.items():
        pos = np.searchsorted(plan.cells, np.uint64(cid))
        if pos >= len(plan.cells) or plan.cells[pos] != np.uint64(cid):
            continue  # pinned cell no longer exists (refined away)
        if plan.owner[pos] != dev:
            _fail(f"pinned cell {cid} is on device {plan.owner[pos]}, not {dev}")


def find_nonfinite_cells(grid, fields=None) -> dict:
    """Locate non-finite values: ``{field: cell ids}`` for every
    watched inexact field holding a NaN/Inf in a LOCAL row (ghost
    copies mirror some other device's local row, so local rows cover
    every real offender). Host-side and O(grid) — run it only after
    the cheap device-side probe (resilience.check_finite) has tripped,
    to name the offenders in the diagnostic bundle."""
    out = {}
    cells = grid.get_cells()
    names = list(fields) if fields is not None else list(grid.fields)
    for name in names:
        if not np.issubdtype(np.dtype(grid.fields[name][1]), np.inexact):
            continue
        vals = np.asarray(grid.get(name, cells))
        bad = ~np.isfinite(vals)
        while bad.ndim > 1:
            bad = bad.any(axis=-1)
        if bad.any():
            out[name] = np.asarray(cells)[bad]
    return out


def verify_all(grid) -> None:
    is_consistent(grid)
    verify_neighbors(grid)
    verify_remote_neighbor_info(grid)
    verify_user_data(grid)
    pin_requests_succeeded(grid)
