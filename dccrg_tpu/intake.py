"""Durable streaming intake: the crash-consistent job front door.

The fleet historically drained a static job list handed to one
process — there was no way for work to ARRIVE. This module is the
streaming front door: jobs land as spec files in a **spool
directory** (atomic rename-in — visibility IS the rename), any live
rank may tail the spool, claim a record, and feed it to
``FleetScheduler.add`` with every step of that journey
crash-consistent:

- **Exactly-once admission.** A spool record is claimed through a
  coordination-KV CAS lease (:class:`~dccrg_tpu.scheduler.JobLeases`
  under the ``dccrg/intake`` prefix — the PR-14 lease/epoch
  machinery, unchanged): the KV's first-writer-wins ``create`` means
  exactly one rank owns an admission at a time. The claimant writes
  a sealed **journal record** (the validated payload) BEFORE adding
  the job, and keeps renewing its intake lease until the fleet shows
  durable evidence of the job (its ``dccrg/job`` lease or done
  marker; locally-held jobs in the single-host case). A rank killed
  between claim and add — or between add and fleet takeover — leaves
  an expiring lease a survivor reclaims with the epoch-fenced
  ``try_reclaim`` CAS and **re-admits from the journal record**
  (falling back to the spool file, which is only archived at
  finalize). Duplicate submissions are rejected by content **nonce**
  (a CAS-created ``nonce/`` key) and by the terminal ``done/``
  marker. Proven with real OS process kills in tests/mp_harness.py
  (``intake_kill``).

- **Typed retry/backoff envelope + poison-job quarantine.**
  Transient faults (torn spool reads convicted by the sealed-record
  CRC frame, injected I/O and KV faults from
  :class:`~dccrg_tpu.faults.FaultPlan`) retry with jittered
  exponential backoff (deterministically seeded, capped); a record
  that fails ``K`` times — or permanently
  (:class:`~dccrg_tpu.fleet.JobSpecError`,
  :class:`~dccrg_tpu.fleet.UnknownKernelError`, a torn frame that
  can never heal) — moves to ``spool/quarantine/`` with a structured
  ``<name>.reason.json`` record instead of wedging the stream.

- **Overload backpressure with hysteresis.** Arrival-rate and
  drain-rate EWMAs drive an admission gate evaluated once per EWMA
  window: it closes when arrivals outrun drain (ratio >= ``hi``) or
  the oldest waiting record ages past the bound, and reopens only
  below the strictly lower ``lo`` — the hysteresis band plus the
  windowed cadence keep it from flapping (<= 1 transition per
  window by construction). A closed gate pauses NEW admissions; the
  spool is the durable buffer. When the backlog implies an unbounded
  queue age even at full drain, the newest records of the
  most-backlogged tenant are **gracefully shed** (journaled, moved
  to ``spool/shed/`` — re-submittable, never silently dropped).
  Per-tenant token buckets (``DCCRG_TENANT_RATE``) and weighted
  virtual-time fairness (``DCCRG_TENANT_WEIGHT``) order admissions
  across tenants; within the scheduler the existing ``SLOPolicy``
  admission keys take over.

- **Control-plane integration.** Every backpressure flip, shed and
  quarantine is a structured autopilot decision record
  (``intake.backpressure`` / ``intake.shed`` /
  ``intake.quarantine`` rules) that ``python -m dccrg_tpu.autopilot
  explain|replay`` reconstructs divergence-free; telemetry grows
  queue-age histograms (``dccrg_intake_queue_age_seconds``,
  per-tenant), per-tenant admit/shed counters and an intake-lag
  gauge (``dccrg_intake_lag``).

Spool layout (all under one directory, shared by every rank)::

    spool/<name>.json            # sealed spec record (rename-in)
    spool/.tmp/                  # submit staging (never scanned)
    spool/admitted/<name>.json   # archived at finalize
    spool/quarantine/<name>.json + <name>.reason.json
    spool/shed/<name>.json       # graceful-shed victims

KV layout (``dccrg/intake`` prefix, riding ``JobLeases``)::

    dccrg/intake/<name>          # admission lease "rank:epoch:beat"
    dccrg/intake/<name>@<epoch>  # the reclaim claim (CAS)
    dccrg/intake/journal/<name>  # sealed validated payload
    dccrg/intake/nonce/<nonce>   # content-dedupe key (CAS) -> name
    dccrg/intake/done/<name>     # terminal marker "admitted:rank"

OFF by default: ``FleetScheduler`` constructs an intake only under
``DCCRG_INTAKE=1`` (spool from ``DCCRG_INTAKE_SPOOL``) or when one is
injected — otherwise ``sched.intake`` is None and the serving loop
takes zero new branches (the negative pin in tests/test_intake.py).
"""

from __future__ import annotations

import json
import logging
import os
import random
import time

from . import coord, faults, fleet, telemetry
from . import autopilot as autopilot_mod
from .scheduler import JobLeases, OwnershipLostError

logger = logging.getLogger(__name__)

#: spool subdirectories (never scanned for intake records)
TMP_DIR = ".tmp"
ADMITTED_DIR = "admitted"
QUARANTINE_DIR = "quarantine"
SHED_DIR = "shed"
_SUBDIRS = (TMP_DIR, ADMITTED_DIR, QUARANTINE_DIR, SHED_DIR)

#: the KV prefix intake admission leases/journals/nonces live under
#: (disjoint from the fleet's ``dccrg/job`` serving leases)
PREFIX = "dccrg/intake"


# ---------------------------------------------------------------------
# env knobs (all read at construction; features off by default)
# ---------------------------------------------------------------------

def intake_enabled_default(default: bool = False) -> bool:
    """The ``DCCRG_INTAKE`` env knob: ``1`` makes ``FleetScheduler``
    construct a :class:`StreamIntake` over ``DCCRG_INTAKE_SPOOL`` and
    pump it every tick. Off (default): no intake object exists and
    the serving loop is unchanged."""
    v = os.environ.get("DCCRG_INTAKE", "")
    if v == "":
        return default
    return v not in ("0", "off", "false", "no")


def spool_default():
    """The ``DCCRG_INTAKE_SPOOL`` env knob: the spool directory jobs
    arrive in (created on first use)."""
    return os.environ.get("DCCRG_INTAKE_SPOOL") or None


def retries_default(default: int = 4) -> int:
    """The ``DCCRG_INTAKE_RETRIES`` env knob: transient admission
    attempts before a record is quarantined as poison (K)."""
    try:
        return max(1, int(os.environ.get("DCCRG_INTAKE_RETRIES", "")
                          or default))
    except ValueError:
        return default


def backoff_default(default: float = 0.05) -> float:
    """The ``DCCRG_INTAKE_BACKOFF_S`` env knob: base of the jittered
    exponential retry backoff (seconds; attempt ``i`` waits
    ``base * 2**(i-1)`` +- jitter, capped)."""
    try:
        return max(0.0, float(
            os.environ.get("DCCRG_INTAKE_BACKOFF_S", "") or default))
    except ValueError:
        return default


def backoff_cap_default(default: float = 2.0) -> float:
    """The ``DCCRG_INTAKE_BACKOFF_CAP_S`` env knob: upper bound on a
    single retry delay (seconds)."""
    try:
        return max(0.0, float(
            os.environ.get("DCCRG_INTAKE_BACKOFF_CAP_S", "")
            or default))
    except ValueError:
        return default


def age_bound_default(default: float = 30.0) -> float:
    """The ``DCCRG_INTAKE_AGE_S`` env knob: the bounded-queue-age
    target (seconds) the backpressure gate and the graceful shed
    enforce."""
    try:
        return max(0.1, float(
            os.environ.get("DCCRG_INTAKE_AGE_S", "") or default))
    except ValueError:
        return default


def _parse_tenant_map(raw: str, cast=float):
    """``"5"`` (every tenant), ``"a=2,b=5,*=1"`` (named + default)
    -> ``{tenant: value}`` with ``"*"`` as the fallback key; None for
    empty/unparseable."""
    raw = (raw or "").strip()
    if not raw:
        return None
    out = {}
    try:
        if "=" not in raw:
            return {"*": cast(raw)}
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            k, v = part.split("=", 1)
            out[k.strip()] = cast(v)
    except ValueError:
        return None
    return out or None


def tenant_rate_default():
    """The ``DCCRG_TENANT_RATE`` env knob: per-tenant token-bucket
    admission rate in jobs/second — ``"5"`` for every tenant or
    ``"tenantA=2,tenantB=5,*=1"``. Unset: no rate limit."""
    return _parse_tenant_map(os.environ.get("DCCRG_TENANT_RATE", ""))


def tenant_weight_default():
    """The ``DCCRG_TENANT_WEIGHT`` env knob: weighted-fairness shares
    (same syntax as ``DCCRG_TENANT_RATE``; default weight 1)."""
    return _parse_tenant_map(os.environ.get("DCCRG_TENANT_WEIGHT", ""))


def tenant_burst_default(default: float = 4.0) -> float:
    """The ``DCCRG_TENANT_BURST`` env knob: token-bucket burst depth
    (jobs a briefly idle tenant may admit back-to-back)."""
    try:
        return max(1.0, float(
            os.environ.get("DCCRG_TENANT_BURST", "") or default))
    except ValueError:
        return default


# ---------------------------------------------------------------------
# typed errors
# ---------------------------------------------------------------------

class IntakeError(Exception):
    """Base class of intake front-door failures."""


class IntakeRetryExhausted(IntakeError):
    """A spool record burned its K transient-retry budget — the
    poison-job verdict that moves it to quarantine."""

    def __init__(self, name: str, attempts: int, last_error):
        self.name = str(name)
        self.attempts = int(attempts)
        self.last_error = last_error
        super().__init__(
            f"intake record {name!r}: {attempts} admission attempts "
            f"exhausted (last: {type(last_error).__name__}: "
            f"{last_error})")


#: admission faults that can NEVER heal by retrying — straight to
#: quarantine with the typed reason (the satellite contract:
#: unknown-kernel specs are a typed quarantine reason, not a raw
#: KeyError)
PERMANENT_FAULTS = (fleet.JobSpecError, fleet.UnknownKernelError,
                    json.JSONDecodeError)


# ---------------------------------------------------------------------
# producer side: durable spool submission
# ---------------------------------------------------------------------

def record_nonce(row: dict, tenant: str) -> str:
    """The content nonce a duplicate submission is rejected by: a
    CRC of the canonical JSON of (tenant, job row). Two submissions
    of the SAME spec dedupe; a different spec under a reused name is
    a conflict the admission path surfaces."""
    import zlib

    canon = json.dumps({"job": row, "tenant": tenant}, sort_keys=True)
    return f"{zlib.crc32(canon.encode('utf-8')) & 0xFFFFFFFF:08x}"


def ensure_spool(spool: str) -> str:
    """Create the spool directory tree (idempotent)."""
    spool = str(spool)
    os.makedirs(spool, exist_ok=True)
    for d in _SUBDIRS:
        os.makedirs(os.path.join(spool, d), exist_ok=True)
    return spool


def submit(spool: str, row: dict, *, tenant: str = "default",
           nonce=None) -> str:
    """Durably submit one job record to the spool: write the sealed
    spec to ``spool/.tmp/`` and atomically rename it in — a crashed
    submitter leaves either a complete visible record or an invisible
    temp file, never a half-visible one (fault injection lands both
    torn halves deliberately: :meth:`~dccrg_tpu.faults.FaultPlan.
    spool_torn_write` tears the payload AT the final name so the
    reader's CRC conviction is exercised;
    :meth:`~dccrg_tpu.faults.FaultPlan.spool_torn_rename` drops the
    rename). Returns the final spool path. ``row`` is a fleet job-row
    dict (see :func:`dccrg_tpu.fleet.job_from_row`); ``name`` is
    required and is the admission/checkpoint identity."""
    if "name" not in row:
        raise fleet.JobSpecError(f"job row without a name: {row}")
    name = str(row["name"])
    if os.sep in name or name.startswith("."):
        raise fleet.JobSpecError(f"unsafe job name {name!r}")
    ensure_spool(spool)
    payload = {"job": dict(row), "tenant": str(tenant),
               "nonce": str(nonce) if nonce is not None
               else record_nonce(row, str(tenant))}
    sealed = coord.seal_record(json.dumps(payload, sort_keys=True))
    if faults.take_spool_torn(job=name):
        # a submitter death mid-write: a truncated frame LANDS
        sealed = sealed[:max(1, len(sealed) // 2)]
    tmp = os.path.join(spool, TMP_DIR, f"{name}.json")
    with open(tmp, "w") as f:
        f.write(sealed)
        f.flush()
        os.fsync(f.fileno())
    final = os.path.join(spool, f"{name}.json")
    if faults.take_spool_torn_rename(job=name):
        # a submitter death between write and rename: the record
        # never becomes visible (the durable-spool contract)
        return final
    os.replace(tmp, final)
    return final


# ---------------------------------------------------------------------
# rate estimation + per-tenant admission shaping
# ---------------------------------------------------------------------

class _Ewma:
    """Rate EWMA over irregular samples: ``update(count, dt)`` folds
    ``count/dt`` in with weight ``1 - exp(-dt/tau)`` (so the smoothing
    horizon is ``tau`` SECONDS regardless of pump cadence — fake-clock
    and real-clock tests share the numbers)."""

    def __init__(self, tau_s: float):
        self.tau_s = float(tau_s)
        self.value = None

    def update(self, count: float, dt: float) -> float:
        import math

        if dt <= 0:
            return self.value if self.value is not None else 0.0
        rate = float(count) / dt
        if self.value is None:
            self.value = rate
        else:
            a = 1.0 - math.exp(-dt / self.tau_s)
            self.value += a * (rate - self.value)
        return self.value


class _TokenBucket:
    """Per-tenant admission rate limit: ``rate`` tokens/second up to
    ``burst``; an admission spends one token."""

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self.t = float(now)

    def take(self, now: float) -> bool:
        self.tokens = min(self.burst,
                          self.tokens + (now - self.t) * self.rate)
        self.t = float(now)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class _Retry:
    __slots__ = ("attempts", "next_t", "last_error")

    def __init__(self):
        self.attempts = 0
        self.next_t = 0.0
        self.last_error = None


# ---------------------------------------------------------------------
# the consumer: spool tail -> claim -> admit, crash-consistently
# ---------------------------------------------------------------------

class StreamIntake:
    """Tail a spool directory and feed a ``FleetScheduler``
    crash-consistently (see the module docstring for the protocol).

    ``kv``/``rank``/``clock`` default to the scheduler's membership
    when attached (real multi-host fleets share the coordination
    service KV); standalone construction takes
    :func:`~dccrg_tpu.coord.default_kv` and rank 0. All control knobs
    (retries, backoff, age bound, tenant rates/weights) default to
    their env readers; tests inject a fake clock plus explicit
    numbers. ``autopilot=None`` journals nothing — the same
    negative-pin discipline as the scheduler's controller hook."""

    def __init__(self, spool, *, kv=None, rank=None, clock=None,
                 membership=None, autopilot=None, lease_s=None,
                 retries=None, backoff_s=None, backoff_cap_s=None,
                 age_bound_s=None, hi_ratio=1.2, lo_ratio=0.9,
                 window_s=2.0, ewma_tau_s=None, rates=None,
                 weights=None, burst=None, max_admit=8, seed=0,
                 poll_s=0.02):
        self.spool = ensure_spool(spool)
        self.membership = membership
        if kv is None:
            kv = (membership.kv if membership is not None
                  else coord.default_kv())
        if rank is None:
            rank = membership.rank if membership is not None else 0
        if clock is None:
            clock = (membership.clock if membership is not None
                     else time.monotonic)
        self.rank = int(rank)
        self.clock = clock
        self.leases = JobLeases(kv, self.rank, lease_s=lease_s,
                                clock=clock, prefix=PREFIX)
        self.kv = kv
        self.autopilot = autopilot
        self.retries = (retries_default() if retries is None
                        else max(1, int(retries)))
        self.backoff_s = (backoff_default() if backoff_s is None
                          else float(backoff_s))
        self.backoff_cap_s = (backoff_cap_default()
                              if backoff_cap_s is None
                              else float(backoff_cap_s))
        self.age_bound_s = (age_bound_default() if age_bound_s is None
                            else float(age_bound_s))
        self.hi_ratio = float(hi_ratio)
        self.lo_ratio = float(lo_ratio)
        self.window_s = float(window_s)
        self.ewma_tau_s = (self.window_s if ewma_tau_s is None
                           else float(ewma_tau_s))
        self.rates = tenant_rate_default() if rates is None else rates
        self.weights = (tenant_weight_default() if weights is None
                        else weights)
        self.burst = (tenant_burst_default() if burst is None
                      else float(burst))
        self.max_admit = max(1, int(max_admit))
        self.poll_s = float(poll_s)
        self._rng = random.Random(int(seed) * 9176 + self.rank)
        self.sched = None
        # gate state: 0 = open, 1 = closed; transitions counted for
        # the flap bound the bench asserts
        self.gate = 0
        self.gate_transitions = 0
        self._gate_eval_t = None
        self.arrival = _Ewma(self.ewma_tau_s)
        self.drain = _Ewma(self.ewma_tau_s)
        self._last_pump_t = None
        self._arrived_since = 0
        self._done_seen = 0
        # observer-clock arrival tracking: name -> first-seen clock
        # (the queue-age signal; no cross-host clock comparison)
        self._seen: dict = {}
        self._waiting: list = []  # [(name, path)] from the last scan
        self._retry: dict = {}    # name -> _Retry
        self._buckets: dict = {}  # tenant -> _TokenBucket
        self._vtime: dict = {}    # tenant -> virtual time (fairness)
        self._meta: dict = {}     # owned name -> {"tenant": ...}
        self.admitted = 0
        self.deduped = 0
        self.quarantined = 0
        self.shed = 0
        self.reclaimed = 0

    # -- wiring --------------------------------------------------------

    @classmethod
    def from_env(cls, sched):
        """The ``DCCRG_INTAKE=1`` construction path: spool from
        ``DCCRG_INTAKE_SPOOL`` (required), everything else from the
        env readers and the scheduler's own membership/autopilot."""
        spool = spool_default()
        if not spool:
            raise IntakeError(
                "DCCRG_INTAKE=1 needs DCCRG_INTAKE_SPOOL=<dir>")
        intake = cls(spool, membership=sched.membership,
                     autopilot=sched.autopilot)
        return intake

    def attach(self, sched) -> None:
        """Bind to the scheduler whose ``add`` this intake feeds;
        adopts its autopilot when none was injected (one journal)."""
        self.sched = sched
        if self.autopilot is None:
            self.autopilot = sched.autopilot

    # -- spool scanning ------------------------------------------------

    def _scan(self, now: float) -> list:
        """List the waiting spool records (sorted — deterministic
        admission order), tracking first-seen clocks for the queue-age
        signal. Honors the delayed-visibility fault: one scan hides
        the newest not-yet-tracked entry."""
        try:
            names = sorted(os.listdir(self.spool))
        except OSError:
            return self._waiting
        entries = [n[:-5] for n in names
                   if n.endswith(".json") and not n.startswith(".")]
        if entries and faults.take_spool_delay(rank=self.rank):
            fresh = [n for n in entries if n not in self._seen]
            if fresh:
                entries = [n for n in entries if n != fresh[-1]]
        for n in entries:
            if n not in self._seen:
                self._seen[n] = now
                self._arrived_since += 1
        gone = [n for n in self._seen if n not in entries]
        for n in gone:
            # admitted/archived/shed elsewhere: stop aging it
            if n not in self.leases.owned:
                self._seen.pop(n, None)
        self._waiting = [(n, os.path.join(self.spool, f"{n}.json"))
                         for n in entries
                         if n not in self.leases.owned]
        return self._waiting

    def backlog(self) -> int:
        """Waiting spool records as of the last pump (the intake-lag
        gauge's source)."""
        return len(self._waiting)

    def idle(self) -> bool:
        """True when nothing is in flight: no waiting spool records
        and no admission lease still being watched to finalize."""
        return not self._waiting and not self.leases.owned

    def oldest_age(self, now: float) -> float:
        """Age of the oldest WAITING record by this observer's clock
        (0.0 with an empty spool) — the gate's bounded-queue-age
        signal."""
        ages = [now - self._seen[n] for n, _p in self._waiting
                if n in self._seen]
        return max(ages) if ages else 0.0

    # -- record loading (the retry envelope's protected region) --------

    def _load(self, name: str, path: str) -> dict:
        faults.fire("intake.spool.read", job=name, rank=self.rank)
        with open(path) as f:
            raw = f.read()
        payload = coord.unseal_record(raw, key=f"spool/{name}")
        d = json.loads(payload)
        if not isinstance(d, dict) or "job" not in d:
            raise fleet.JobSpecError(
                f"spool record {name!r}: no job row")
        return d

    # -- quarantine ----------------------------------------------------

    def _quarantine(self, name: str, path: str, err, attempts: int,
                    tenant: str = "?") -> None:
        """Move a poison record to ``spool/quarantine/`` with a
        structured reason file; journal the decision; the stream
        continues draining behind it."""
        qdir = os.path.join(self.spool, QUARANTINE_DIR)
        try:
            if os.path.exists(path):
                os.replace(path, os.path.join(qdir, f"{name}.json"))
        except OSError:
            pass
        reason = {
            "name": name, "tenant": tenant,
            "attempts": int(attempts),
            "error_type": type(err).__name__,
            "error": str(err),
            "rank": self.rank,
            "t": round(float(self.clock()), 6),
        }
        tmp = os.path.join(qdir, f".{name}.reason.tmp")
        try:
            with open(tmp, "w") as f:
                json.dump(reason, f, sort_keys=True, indent=1)
            os.replace(tmp, os.path.join(qdir,
                                         f"{name}.reason.json"))
        except OSError:
            logger.warning("intake: quarantine reason for %s did not "
                           "land", name)
        self.quarantined += 1
        self._retry.pop(name, None)
        self._seen.pop(name, None)
        self.leases.release(name)
        telemetry.inc("dccrg_intake_quarantined_total", tenant=tenant)
        if self.autopilot is not None:
            self.autopilot.record_intake_quarantine(
                name, {"tenant": tenant, "attempts": int(attempts),
                       "error_type": type(err).__name__,
                       "error": str(err)[:200]})
        logger.warning("intake: record %s quarantined after %d "
                       "attempt(s): %s", name, attempts, err)

    def _transient_failed(self, name: str, path: str, err,
                          now: float, tenant: str = "?") -> None:
        """One transient admission failure: jittered exponential
        backoff, quarantine at the K-th."""
        st = self._retry.setdefault(name, _Retry())
        st.attempts += 1
        st.last_error = err
        telemetry.inc("dccrg_intake_retries_total")
        if st.attempts >= self.retries:
            self._quarantine(
                name, path,
                IntakeRetryExhausted(name, st.attempts, err),
                st.attempts, tenant)
            return
        delay = min(self.backoff_cap_s,
                    self.backoff_s * (2.0 ** (st.attempts - 1)))
        delay *= 1.0 + 0.25 * self._rng.random()  # decorrelate ranks
        st.next_t = now + delay
        logger.info("intake: record %s attempt %d failed (%s); "
                    "retry in %.3gs", name, st.attempts, err, delay)

    # -- exactly-once admission ---------------------------------------

    def _journal_key(self, name: str) -> str:
        return f"{PREFIX}/journal/{name}"

    def _done_key(self, name: str) -> str:
        return f"{PREFIX}/done/{name}"

    def _archive(self, name: str) -> None:
        src = os.path.join(self.spool, f"{name}.json")
        try:
            if os.path.exists(src):
                os.replace(src, os.path.join(
                    self.spool, ADMITTED_DIR, f"{name}.json"))
        except OSError:
            pass

    def _admit_payload(self, name: str, payload: dict,
                       now: float) -> bool:
        """The claim->journal->add critical section (intake lease
        already held). Returns True when the job entered the
        scheduler queue."""
        tenant = str(payload.get("tenant", "default"))
        # the journal record is what a survivor re-admits from after
        # a kill between this write and the scheduler add
        self.kv.set(self._journal_key(name), coord.seal_record(
            json.dumps(payload, sort_keys=True)))
        # the exactly-once admission window the mp harness kills in
        faults.fire("intake.claim", rank=self.rank, job=name)
        job = fleet.job_from_row(payload["job"], validate_kernel=True)
        self.leases.check(name)  # the fencing gate before the add
        if self.sched is None:
            raise IntakeError("intake not attached to a scheduler")
        if name in self.sched._by_name:
            # already in this scheduler (a reclaim raced a requeue):
            # nothing to add; fall through to finalize-watching
            pass
        else:
            self.sched.add(job)
        warm = getattr(self.sched, "warm", None)
        if warm is not None:
            # the stream knows what is about to dispatch: bump this
            # bucket key to the front of the prewarm queue
            warm.note_incoming(job.bucket_key())
        self._meta[name] = {"tenant": tenant}
        self._retry.pop(name, None)
        age = now - self._seen.get(name, now)
        telemetry.observe("dccrg_intake_queue_age_seconds", age,
                          tenant=tenant)
        telemetry.inc("dccrg_intake_admitted_total", tenant=tenant)
        self.admitted += 1
        vt = self._vtime.get(tenant, 0.0)
        self._vtime[tenant] = vt + 1.0 / self._weight(tenant)
        return True

    def _try_admit(self, name: str, path: str, payload: dict,
                   now: float) -> str:
        """Admit one waiting record (spool payload already loaded by
        the caller, ONCE, under the same envelope); returns a
        disposition tag (for tests): ``admitted``, ``dedup``,
        ``foreign``, ``inflight``, ``failed``, ``quarantined``."""
        if name in self.leases.owned:
            return "inflight"  # this pump already (re-)admitted it
        tenant = str(payload.get("tenant", "default"))
        try:
            # terminal marker: already admitted (and finalized) by
            # someone — a late duplicate file
            if self.kv.get(self._done_key(name)) is not None:
                self._archive(name)
                self._seen.pop(name, None)
                self.deduped += 1
                telemetry.inc("dccrg_intake_dedupe_total",
                              tenant=tenant)
                return "dedup"
            # content-nonce dedupe: the CAS key maps nonce -> name;
            # losing the CAS to a DIFFERENT name means this content
            # was already submitted under another identity
            nonce = str(payload.get("nonce", ""))
            if nonce:
                key = f"{PREFIX}/nonce/{nonce}"
                if not self.kv.create(key, name):
                    owner = self.kv.get(key)
                    if owner is not None and str(owner) != name:
                        self._archive(name)
                        self._seen.pop(name, None)
                        self.deduped += 1
                        telemetry.inc("dccrg_intake_dedupe_total",
                                      tenant=tenant)
                        logger.info(
                            "intake: record %s deduped (nonce held "
                            "by %s)", name, owner)
                        return "dedup"
            try:
                self.leases.acquire(name)
            except OwnershipLostError:
                return "foreign"  # another live rank is admitting it
            self._admit_payload(name, payload, now)
            return "admitted"
        except PERMANENT_FAULTS as e:
            st = self._retry.get(name)
            self._quarantine(name, path, e,
                             (st.attempts if st else 0) + 1, tenant)
            return "quarantined"
        except OwnershipLostError:
            return "foreign"
        except coord.TornRecordError as e:
            # a torn spool frame MAY be a submitter still mid-crash
            # landing; retry K times, then it is poison
            self._transient_failed(name, path, e, now, tenant)
            return "failed"
        except (OSError, faults.InjectedIOError) as e:
            self._transient_failed(name, path, e, now, tenant)
            return "failed"

    # -- crash recovery: reclaim + half-admitted re-admission ---------

    def _reclaim_pass(self, census, now: float) -> None:
        """Scan the intake-lease census for records whose claimant
        died mid-admission: lease expired (observer-aged) — and the
        holder DEAD by membership when one is attached — then the
        epoch-fenced CAS takeover, and re-admission from the journal
        record (the spool file as fallback)."""
        if census is None:
            return
        base = PREFIX + "/"
        for key, _raw in sorted(census.items()):
            tail = key[len(base):]
            if "/" in tail or "@" in tail or not tail:
                continue  # claim keys / journal / nonce / done
            name = tail
            if name in self.leases.owned:
                continue
            if census.get(self._done_key(name)) is not None:
                continue
            dead = self.leases.expired_holder(name, census)
            if dead is None:
                continue
            if (self.membership is not None
                    and self.membership.state(dead)
                    != coord.Membership.DEAD):
                continue  # a live rank stalled mid-admission keeps it
            epoch = self.leases.try_reclaim(name)
            if epoch is None:
                continue  # another survivor won
            self.reclaimed += 1
            telemetry.inc("dccrg_intake_reclaims_total")
            logger.warning(
                "intake: admission lease of rank %d on %s expired "
                "(>= %gs); reclaimed at epoch %d — re-admitting",
                dead, name, self.leases.lease_s, epoch)
            self._readmit(name, now)

    def _readmit(self, name: str, now: float) -> None:
        """Re-admit a reclaimed half-admitted record from its journal
        (falling back to the still-unarchived spool file)."""
        path = os.path.join(self.spool, f"{name}.json")
        payload = None
        raw = self.kv.get(self._journal_key(name))
        if raw is not None:
            try:
                payload = json.loads(
                    coord.unseal_record(raw, key=f"journal/{name}"))
            except (coord.TornRecordError, ValueError):
                payload = None  # torn journal: the spool file decides
        try:
            if payload is None:
                payload = self._load(name, path)
            self._seen.setdefault(name, now)
            self._admit_payload(name, payload, now)
        except PERMANENT_FAULTS as e:
            self._quarantine(name, path, e, 1,
                             str((payload or {}).get("tenant", "?")))
        except OwnershipLostError:
            pass  # fenced while re-admitting: the new owner has it
        except (OSError, faults.InjectedIOError,
                coord.TornRecordError) as e:
            # transient: keep the lease, the retry envelope resumes
            # on the next pump via the normal waiting path
            self._transient_failed(name, path, e, now)

    def _fleet_evidence(self, name: str) -> bool:
        """Durable evidence the fleet took the job over (the intake
        lease may stop renewing): the scheduler's own serving lease
        or done marker in rank-aware mode, plain local presence
        otherwise (single-host: the KV dies with the process)."""
        sched = self.sched
        if sched is None:
            return False
        if sched.leases is None:
            return name in sched._by_name or name in sched.report
        if name in sched.report:
            return True
        jk = f"{sched.leases.prefix}/{name}"
        if self.kv.get(jk) is not None:
            return True
        return self.kv.get(
            f"{sched.leases.prefix}/done/{name}") is not None

    def _watch_owned(self, census) -> None:
        """Renew every admission lease still covering an in-flight
        admission; FINALIZE (terminal done marker, spool archive,
        journal GC, lease release) once the fleet shows durable
        evidence of the job."""
        for name, err in self.leases.renew_owned(census=census):
            # a reclaimer fenced us while paused: it owns the
            # re-admission; ours stays only in OUR scheduler, whose
            # job-level lease fencing arbitrates serving
            logger.warning("intake: admission lease on %s fenced: %s",
                           name, err)
            self._meta.pop(name, None)
        for name in sorted(self.leases.owned):
            if not self._fleet_evidence(name):
                continue
            self.kv.create(self._done_key(name),
                           f"admitted:{self.rank}")
            self.kv.delete(self._journal_key(name))
            self.leases.release(name)
            self._archive(name)
            self._seen.pop(name, None)
            self._meta.pop(name, None)

    # -- backpressure gate + graceful shed ----------------------------

    def _rates_update(self, now: float) -> None:
        if self._last_pump_t is None:
            self._last_pump_t = now
            return
        dt = now - self._last_pump_t
        if dt <= 0:
            return
        self._last_pump_t = now
        self.arrival.update(self._arrived_since, dt)
        self._arrived_since = 0
        done = len(self.sched.report) if self.sched is not None else 0
        self.drain.update(max(0, done - self._done_seen), dt)
        self._done_seen = done

    def _gate_inputs(self, now: float) -> dict:
        arr = self.arrival.value
        drn = self.drain.value
        ratio = (None if arr is None or not drn
                 else round(arr / drn, 6))
        return {
            "ratio": ratio,
            "arrival_per_s": (None if arr is None else round(arr, 6)),
            "drain_per_s": (None if drn is None else round(drn, 6)),
            "queue_age_s": round(self.oldest_age(now), 6),
            "backlog": self.backlog(),
            "hi": self.hi_ratio, "lo": self.lo_ratio,
            "age_bound_s": self.age_bound_s,
        }

    def _gate_tick(self, now: float) -> None:
        """Evaluate the gate once per EWMA window (<= 1 transition
        per window by construction) through the shared pure rule —
        journaled via the autopilot when one is attached."""
        if (self._gate_eval_t is not None
                and now - self._gate_eval_t < self.window_s):
            return
        self._gate_eval_t = now
        inputs = self._gate_inputs(now)
        if self.autopilot is not None:
            new = self.autopilot.record_intake_gate(inputs)
        else:
            d = autopilot_mod.RULES["intake.backpressure"](
                self.gate, inputs)
            new = self.gate if d is None else d
        if new != self.gate:
            self.gate_transitions += 1
            logger.warning("intake: backpressure gate %s (%s)",
                           "CLOSED" if new else "OPEN", inputs)
        self.gate = new
        telemetry.set_gauge("dccrg_intake_gate", self.gate)
        if self.gate:
            self._maybe_shed(now, inputs)

    def _weight(self, tenant: str) -> float:
        w = self.weights or {}
        try:
            return max(1e-6, float(w.get(tenant, w.get("*", 1.0))))
        except (TypeError, ValueError):
            return 1.0

    def _maybe_shed(self, now: float, inputs: dict) -> None:
        """Graceful shed under saturation: when even full drain
        cannot bound the queue age (``backlog / drain > bound``),
        move the NEWEST waiting records of the most-backlogged tenant
        to ``spool/shed/`` — journaled, re-submittable — until the
        projected age is back in bounds."""
        drn = self.drain.value
        if not drn or drn <= 0 or not self._waiting:
            return
        excess = len(self._waiting) - int(drn * self.age_bound_s)
        if excess <= 0:
            return
        by_tenant: dict = {}
        loadable = []
        for name, path in self._waiting:
            try:
                payload = self._load(name, path)
            except Exception:  # noqa: BLE001 - retry path handles it
                continue
            tenant = str(payload.get("tenant", "default"))
            by_tenant.setdefault(tenant, []).append((name, path))
            loadable.append((name, tenant))
        if not by_tenant:
            return
        # the most over-fair-share tenant pays first (backlog scaled
        # by 1/weight), its NEWEST records first (oldest keep their
        # FIFO claim on the reopened gate)
        tenant = max(sorted(by_tenant),
                     key=lambda t: len(by_tenant[t]) / self._weight(t))
        victims = by_tenant[tenant][-excess:]
        sdir = os.path.join(self.spool, SHED_DIR)
        shed_names = []
        for name, path in victims:
            try:
                os.replace(path, os.path.join(sdir, f"{name}.json"))
            except OSError:
                continue
            shed_names.append(name)
            self._seen.pop(name, None)
            self._retry.pop(name, None)
            telemetry.inc("dccrg_intake_shed_total", tenant=tenant)
        if not shed_names:
            return
        self.shed += len(shed_names)
        if self.autopilot is not None:
            self.autopilot.record_intake_shed(
                shed_names, tenant,
                {"backlog": inputs.get("backlog"),
                 "drain_per_s": inputs.get("drain_per_s"),
                 "age_bound_s": self.age_bound_s})
        logger.warning("intake: shed %d record(s) of tenant %s under "
                       "saturation: %s", len(shed_names), tenant,
                       shed_names)

    # -- tenant-fair admission ----------------------------------------

    def _admissible(self, now: float) -> list:
        """The waiting records eligible this pump, ordered by
        weighted virtual-time fairness across tenants (FIFO within a
        tenant), with token buckets enforced at pick time."""
        rows = []
        for name, path in self._waiting:
            st = self._retry.get(name)
            if st is not None and now < st.next_t:
                continue
            rows.append((name, path))
        return rows

    def _bucket(self, tenant: str, now: float):
        if self.rates is None:
            return None
        b = self._buckets.get(tenant)
        if b is None:
            rate = self.rates.get(tenant, self.rates.get("*"))
            if rate is None:
                return None
            b = self._buckets[tenant] = _TokenBucket(
                rate, self.burst, now)
        return b

    def _admit_some(self, now: float) -> int:
        """Admit up to ``max_admit`` records this pump: load each
        eligible record ONCE under the retry envelope, group by
        tenant, then repeatedly pick the tenant with the lowest
        weighted virtual time, spend its token, admit its oldest
        record."""
        rows = self._admissible(now)
        if not rows:
            return 0
        by_tenant: dict = {}
        for name, path in rows:
            if name in self.leases.owned:
                continue  # re-admitted by this pump's reclaim pass
            try:
                payload = self._load(name, path)
            except PERMANENT_FAULTS as e:
                st = self._retry.get(name)
                self._quarantine(name, path, e,
                                 (st.attempts if st else 0) + 1)
                continue
            except (OSError, coord.TornRecordError) as e:
                self._transient_failed(name, path, e, now)
                continue
            tenant = str(payload.get("tenant", "default"))
            by_tenant.setdefault(tenant, []).append(
                (name, path, payload))
        admitted = 0
        throttled = set()
        while admitted < self.max_admit and by_tenant:
            pick = min(sorted(t for t in by_tenant
                              if t not in throttled),
                       key=lambda t: self._vtime.get(t, 0.0),
                       default=None)
            if pick is None:
                break
            b = self._bucket(pick, now)
            if b is not None and not b.take(now):
                throttled.add(pick)
                telemetry.inc("dccrg_intake_throttled_total",
                              tenant=pick)
                continue
            name, path, payload = by_tenant[pick].pop(0)
            if not by_tenant[pick]:
                del by_tenant[pick]
            verdict = self._try_admit(name, path, payload, now)
            if verdict == "admitted":
                admitted += 1
            elif b is not None:
                b.tokens = min(b.burst, b.tokens + 1.0)  # not spent
        return admitted

    # -- the pump ------------------------------------------------------

    def pump(self) -> dict:
        """One intake pass (called from the scheduler's tick loop or
        driven directly by tests/bench): scan the spool, refresh the
        rate EWMAs, recover crashed admissions, finalize completed
        ones, evaluate the backpressure gate, and — gate open —
        admit a fair batch. Returns a stats snapshot."""
        now = float(self.clock())
        with telemetry.span("intake.pump"):
            self._scan(now)
            self._rates_update(now)
            census = coord.prefix_census(self.kv, PREFIX)
            self._watch_owned(census)
            self._reclaim_pass(census, now)
            self._gate_tick(now)
            n = 0
            if not self.gate:
                n = self._admit_some(now)
                if n:
                    self._scan(now)  # admitted names leave _waiting
        telemetry.set_gauge("dccrg_intake_lag", self.backlog())
        return {
            "admitted": n, "backlog": self.backlog(),
            "gate": self.gate,
            "gate_transitions": self.gate_transitions,
            "quarantined": self.quarantined, "shed": self.shed,
            "deduped": self.deduped, "reclaimed": self.reclaimed,
        }
