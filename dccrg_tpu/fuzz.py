"""Seeded stateful fuzzing of structural grid mutations.

Property-based testing of the *stateful* Grid API (the discipline
Hypothesis calls rule-based state machines): a deterministic seeded
driver applies random op sequences — refine/unrefine at random
coordinates, load balances with random curves, checkpoint save/load
round trips, halo exchanges, fused step loops, host writes, structure
queries — and after EVERY op checks

1. every grid invariant (:func:`dccrg_tpu.verify.verify_all`), and
2. a slow pure-numpy **oracle**: an independent ``{cell id: value}``
   mirror of the cell data, advanced with plain numpy (projection on
   refine/unrefine, neighbor-sum steps recomputed through the numpy
   reference engine), plus brute-force cross-checks of the structure
   queries (``get_existing_cell`` resolved by scanning every cell's
   index box; per-cell neighbor lists recomputed from scratch).

With ``fault_rate > 0`` the fuzzer also injects a
:class:`~dccrg_tpu.faults.FaultPlan` mutation fault at a random fault
point before some mutations and asserts the transactional guarantee:
the grid is bitwise either fully rolled back (checkpoint-bytes
identical to the pre-op snapshot) or fully committed, and the retried
mutation succeeds.

Failures raise :class:`FuzzFailure` carrying the seed, op index, the
recent op log and the offending cell ids — everything needed to
replay: two runs with the same seed and config perform the identical
op sequence.

CLI::

    python -m dccrg_tpu.fuzz --seed 0 --ops 200 [--fault-rate 0.3]
    python -m dccrg_tpu.fuzz --seeds 25 --ops 40     # the CI sweep
"""

from __future__ import annotations

import os
import tempfile

import numpy as np

from . import txn
from .faults import MUTATION_FAULT_SITES, FaultPlan
from .grid import DEFAULT_NEIGHBORHOOD_ID, Grid
from .neighbors import _dedup_entries, _find_neighbors_of_numpy
from .txn import MutationAbortedError, MutationError
from .verify import VerificationError, format_cells, verify_all


class FuzzFailure(AssertionError):
    """An invariant or oracle cross-check failed during a fuzz run."""

    def __init__(self, msg, seed=None, op_index=None, cells=(), log=()):
        self.seed = seed
        self.op_index = op_index
        self.cells = tuple(int(c) for c in cells)
        msg = (f"seed {seed} op {op_index}: {msg}"
               + format_cells(self.cells))
        if log:
            msg += f" (recent ops: {'; '.join(list(log)[-6:])})"
        super().__init__(msg)


def _step_kernel(cell, nbr, offs, mask, *extra):
    """Neighbor-averaging diffusion step, mirrored exactly by the
    oracle: 0.5*self + 0.5*mean(neighbor entries)."""
    import jax.numpy as jnp

    cnt = jnp.maximum(jnp.sum(mask, axis=1), 1).astype(jnp.float32)
    s = jnp.sum(jnp.where(mask, nbr["rho"], jnp.float32(0)), axis=1)
    return {"rho": (jnp.float32(0.5) * cell["rho"]
                    + jnp.float32(0.5) * s / cnt)}


# fault points reachable from each mutation kind — the canonical
# table lives next to the fire() sites (faults.py)
_FAULT_SITES = MUTATION_FAULT_SITES

def _default_devices():
    """Device list via the memoized hang-proof subprocess probe
    (resilience.probed_devices — one probe per process, not one per
    fuzzer; a raw jax.devices() into a wedged accelerator tunnel
    blocks forever and survives SIGTERM)."""
    from .resilience import probed_devices

    return probed_devices(timeout=120, retries=1)


class GridFuzzer:
    """One deterministic fuzz run (see module docstring).

    ``GridFuzzer(seed, ops=40).run()`` raises :class:`FuzzFailure` on
    the first violated property; attributes afterwards:
    ``ops_run``, ``faults_injected``, ``log`` (op trail).
    """

    # op weights: mutations dominate (they are what the harness hunts)
    _OPS = ("refine", "unrefine", "balance", "set", "step",
            "exchange", "checkpoint", "query")
    _WEIGHTS = (0.20, 0.15, 0.13, 0.13, 0.13, 0.10, 0.08, 0.08)
    _BALANCE_METHODS = ("morton", "hilbert", "rcb", "block")

    def __init__(self, seed, *, ops=40, length=(4, 4, 2), max_lvl=1,
                 n_dev=2, fault_rate=0.0, devices=None, schema="scalar"):
        from jax.sharding import Mesh

        self.seed = int(seed)
        self.n_ops = int(ops)
        self.rng = np.random.default_rng(self.seed)
        self.fault_rate = float(fault_rate)
        devs = list(devices if devices is not None else _default_devices())
        self.mesh = Mesh(np.array(devs[:min(int(n_dev), len(devs))]),
                         ("dev",))
        # "aux" is a static payload the ops never write: with it in
        # the schema the dirty set {rho} is a proper subset, so the
        # incremental-checkpoint oracle exercises REAL delta saves
        # (a single-field grid would keyframe every time).
        # schema="mhd" swaps in the model zoo's 8-field MHD schema
        # (rho stays the op target), so every mutation/txn/fault site
        # — refine projection, balance moves, delta chains, rollback
        # snapshots — runs over the new models' multi-field state,
        # and the multi-field exchange op gets proper field subsets
        # with genuinely different payloads
        if schema == "mhd":
            from .models.mhd import mhd_cell_data

            cell_data = dict(mhd_cell_data(np.float32))
            cell_data["aux"] = ((2,), np.float32)
        elif schema == "scalar":
            cell_data = {"rho": np.float32, "aux": ((2,), np.float32)}
        else:
            raise ValueError(f"unknown fuzz schema {schema!r}")
        self.schema = schema
        self.grid = (
            Grid(cell_data=cell_data)
            .set_initial_length(length)
            .set_maximum_refinement_level(int(max_lvl))
            .set_periodic(True, True, True)
            .set_neighborhood_length(1)
            .set_geometry("cartesian", start=(0.0, 0.0, 0.0),
                          level_0_cell_length=(1.0, 1.0, 1.0))
            .initialize(self.mesh)
        )
        cells = self.grid.get_cells()
        vals = self.rng.random(len(cells)).astype(np.float32)
        self.grid.set("rho", cells, vals)
        for name in sorted(self.grid.fields):
            if name == "rho":
                continue
            shape, fdt = self.grid.fields[name]
            self.grid.set(name, cells, self.rng.random(
                (len(cells),) + shape).astype(fdt))
        # the oracle: independent host mirror of every cell's value
        self.oracle = {int(c): np.float32(v) for c, v in zip(cells, vals)}
        self.log = []
        self.ops_run = 0
        self.faults_injected = 0
        # incremental-checkpoint oracle state (lazy CheckpointStore)
        self._store = None
        self._store_step = 0

    # -- driver -------------------------------------------------------

    def run(self) -> "GridFuzzer":
        import shutil

        try:
            self._check(0)
            for i in range(1, self.n_ops + 1):
                name = str(self.rng.choice(self._OPS, p=self._WEIGHTS))
                try:
                    detail = getattr(self, "_op_" + name)()
                except FuzzFailure:
                    raise
                except MutationError as e:
                    raise FuzzFailure(
                        f"unexpected mutation failure in {name}: {e}",
                        seed=self.seed, op_index=i,
                        cells=getattr(e, "cells", ()), log=self.log) from e
                self.log.append(f"{i}:{name}"
                                + (f"({detail})" if detail else ""))
                self.ops_run = i
                self._check(i)
        finally:
            if self._store is not None:
                shutil.rmtree(self._store.dir, ignore_errors=True)
        return self

    def _check(self, i):
        """Invariants + oracle sweep after every op."""
        try:
            verify_all(self.grid, check_pins=False)
        except VerificationError as e:
            raise FuzzFailure(
                f"invariant violated: {e}", seed=self.seed, op_index=i,
                cells=getattr(e, "cells", ()), log=self.log) from e
        cells = self.grid.get_cells()
        if set(map(int, cells)) != set(self.oracle):
            odd = set(map(int, cells)) ^ set(self.oracle)
            raise FuzzFailure(
                "grid cell set diverged from the oracle",
                seed=self.seed, op_index=i, cells=sorted(odd)[:16],
                log=self.log)
        got = np.asarray(self.grid.get("rho", cells), dtype=np.float32)
        want = np.array([self.oracle[int(c)] for c in cells],
                        dtype=np.float32)
        close = np.isclose(got, want, rtol=1e-4, atol=1e-5)
        if not close.all():
            raise FuzzFailure(
                f"cell data diverged from the oracle "
                f"(max err {np.abs(got - want).max():.3e})",
                seed=self.seed, op_index=i,
                cells=np.asarray(cells)[~close][:16], log=self.log)
        # re-sync: keep sub-tolerance float drift from accumulating
        for c, v in zip(cells, got):
            self.oracle[int(c)] = np.float32(v)

    # -- mutations (transactional, optionally fault-injected) ---------

    def _guarded(self, kind, commit):
        """Run a mutation to COMMITTED state. With probability
        ``fault_rate`` a mutation fault is injected first; the abort
        must leave the grid bitwise identical to the pre-op snapshot,
        and the retry must succeed."""
        if self.fault_rate and self.rng.random() < self.fault_rate:
            sites = _FAULT_SITES[kind]
            site, phase = sites[int(self.rng.integers(len(sites)))]
            before = txn.grid_state_bytes(self.grid)
            plan = FaultPlan(seed=int(self.rng.integers(1 << 31)))
            plan.mutation_error(site=site, times=1, phase=phase)
            aborted = False
            try:
                with plan:
                    result = commit()
            except MutationAbortedError:
                aborted = True
            if not aborted:
                # the chosen site was not on this op's path (e.g. the
                # hybrid builder on a still-uniform grid): committed
                return result, f"fault:{site}:unreached"
            self.faults_injected += 1
            after = txn.grid_state_bytes(self.grid)
            if after != before:
                raise FuzzFailure(
                    f"rollback after injected {site}/{phase} fault is "
                    f"not bitwise identical", seed=self.seed,
                    op_index=self.ops_run + 1, log=self.log)
            return commit(), f"fault:{site}:rolled-back"
        return commit(), ""

    def _commit_adapt(self):
        """stop_refining + data projection, mirrored in the oracle."""
        g = self.grid
        new, detail = self._guarded("adapt", g.stop_refining)
        g.assign_children_from_parents()
        g.average_parents_from_children()
        removed = g.get_removed_cells()
        if len(new):
            parents = g.mapping.get_parent(new)
            for c, p in zip(new, parents):
                self.oracle[int(c)] = self.oracle[int(p)]
            for p in np.unique(parents):
                self.oracle.pop(int(p), None)
        up = g._unrefined_parents
        if len(up):
            kids = g.mapping.get_all_children(up)  # [n, 8]
            means = {
                int(p): np.float32(np.mean(
                    [self.oracle[int(k)] for k in ks], dtype=np.float32))
                for p, ks in zip(up, kids)
            }
            for k in removed:
                self.oracle.pop(int(k), None)
            self.oracle.update(means)
        g.clear_refined_unrefined_data()
        return len(new), len(removed), detail

    def _op_refine(self):
        cells = self.grid.get_cells()
        cid = int(cells[self.rng.integers(len(cells))])
        if not self.grid.refine_completely(cid):
            return f"{cid}:at-max-level"
        n_new, _n_rm, detail = self._commit_adapt()
        return f"{cid}:+{n_new}" + (f":{detail}" if detail else "")

    def _op_unrefine(self):
        g = self.grid
        cells = g.get_cells()
        lvls = g.mapping.get_refinement_level(cells)
        fine = np.asarray(cells)[lvls > 0]
        if len(fine) == 0:
            return "no-fine-cells"
        cid = int(fine[self.rng.integers(len(fine))])
        if not g.unrefine_completely(cid):
            return f"{cid}:rejected"
        _n_new, n_rm, detail = self._commit_adapt()
        return f"{cid}:-{n_rm}" + (f":{detail}" if detail else "")

    def _op_balance(self):
        method = str(self.rng.choice(self._BALANCE_METHODS))
        self.grid.set_load_balancing_method(method)
        _res, detail = self._guarded("balance", self.grid.balance_load)
        return method + (f":{detail}" if detail else "")

    # -- data ops ------------------------------------------------------

    def _op_set(self):
        cells = np.asarray(self.grid.get_cells())
        k = int(self.rng.integers(1, max(2, len(cells) // 2)))
        pick = self.rng.choice(len(cells), size=k, replace=False)
        vals = self.rng.random(k).astype(np.float32)
        self.grid.set("rho", cells[pick], vals)
        for c, v in zip(cells[pick], vals):
            self.oracle[int(c)] = np.float32(v)
        return f"{k} cells"

    def _op_step(self):
        """One fused exchange+stencil step; the oracle advances through
        the numpy reference engine over the SAME dedup'd entry stream
        the gather tables were built from."""
        g = self.grid
        cells = g.plan.cells
        vals = np.array([self.oracle[int(c)] for c in cells],
                        dtype=np.float32)
        src, nbr, _off, _item = _dedup_entries(
            g.mapping, cells, *_find_neighbors_of_numpy(
                g.mapping, g.topology, cells, cells,
                g.neighborhoods[DEFAULT_NEIGHBORHOOD_ID]))
        acc = np.zeros(len(cells), dtype=np.float32)
        cnt = np.zeros(len(cells), dtype=np.float32)
        np.add.at(acc, src, vals[np.searchsorted(cells, nbr)])
        np.add.at(cnt, src, np.float32(1))
        expected = (np.float32(0.5) * vals
                    + np.float32(0.5) * acc / np.maximum(cnt, 1))
        g.run_steps(_step_kernel, ["rho"], ["rho"], 1)
        for c, v in zip(cells, expected):
            self.oracle[int(c)] = np.float32(v)
        return ""

    def _op_exchange(self):
        """Halo exchange over a RANDOM field subset (the per-field
        ``fields=`` boundary) vs the pure-numpy ghost oracle: every
        exchanged field's ghost rows must hold the owner's bytes
        (bitwise — the exchange is a copy), ``rho`` additionally
        checks against the value oracle, and every field NOT in the
        subset must keep its pre-exchange bytes bitwise (a fused
        multi-field program must never move an unrequested field)."""
        g = self.grid
        names = sorted(g.fields)
        if len(names) > 1 and self.rng.random() < 0.6:
            k = int(self.rng.integers(1, len(names)))
            pick = sorted(str(n) for n in self.rng.choice(
                names, size=k, replace=False))
        else:
            pick = names
        frozen = {n: np.asarray(g.data[n]).tobytes()
                  for n in names if n not in pick}
        g.update_copies_of_remote_neighbors(fields=pick)
        L = g.plan.L
        for n in pick:
            host = np.asarray(g.data[n])
            for d in range(g.n_dev):
                gids = g.plan.ghost_ids[d]
                if not len(gids):
                    continue
                want = np.asarray(g.get(n, gids))  # the owners' bytes
                got = host[d, L:L + len(gids)]
                if got.tobytes() != want.tobytes():
                    bad = (got != want).reshape(len(gids), -1).any(axis=1)
                    raise FuzzFailure(
                        f"ghost rows of field {n!r} on device {d} are "
                        f"not the owner's bytes after exchange "
                        f"(fields={pick})", seed=self.seed,
                        op_index=self.ops_run + 1,
                        cells=np.asarray(gids)[bad][:16], log=self.log)
            if n != "rho":
                continue
            for d in range(g.n_dev):
                gids = g.plan.ghost_ids[d]
                if not len(gids):
                    continue
                want = np.array([self.oracle[int(c)] for c in gids],
                                dtype=np.float32)
                got = host[d, L:L + len(gids)]
                close = np.isclose(got, want, rtol=1e-4, atol=1e-5)
                if not close.all():
                    raise FuzzFailure(
                        f"ghost rows on device {d} diverged after "
                        f"exchange", seed=self.seed,
                        op_index=self.ops_run + 1,
                        cells=gids[~close][:16], log=self.log)
        for n, before in frozen.items():
            if np.asarray(g.data[n]).tobytes() != before:
                raise FuzzFailure(
                    f"field {n!r} changed bytes though the exchange "
                    f"moved only {pick}", seed=self.seed,
                    op_index=self.ops_run + 1, log=self.log)
        return ",".join(pick) if pick != names else "all"

    def _op_checkpoint(self):
        """Save/load round trip into the live grid — bytes must be
        stable across an immediate re-save — plus the incremental-save
        oracle: a dirty-field delta chain materialized back must be
        BITWISE identical to a direct full save, whatever random ops
        (host writes and steps dirty fields; mutations bump the
        structure epoch and force keyframes) came in between."""
        g = self.grid
        delta_detail = self._delta_oracle()
        if self.rng.random() < 0.5:
            # the load half of the round trip conservatively dirties
            # every field (correct production behavior), which forces
            # the NEXT oracle save to a keyframe — run it on half the
            # visits so the other half leaves delta-able windows
            return f"delta-only:{delta_detail}"
        fd, path = tempfile.mkstemp(suffix=".dc", prefix="dccrg_fuzz_")
        os.close(fd)
        try:
            g.save_grid_data(path)
            with open(path, "rb") as f:
                first = f.read()
            g.load_grid_data(path)
            g.save_grid_data(path)
            with open(path, "rb") as f:
                second = f.read()
        finally:
            os.unlink(path)
        if first != second:
            raise FuzzFailure(
                "checkpoint round trip is not byte-stable",
                seed=self.seed, op_index=self.ops_run + 1, log=self.log)
        return f"{len(first)}B:{delta_detail}"

    def _delta_oracle(self) -> str:
        """Two periodic CheckpointStore saves and their oracle: the
        reconstructed chain bytes must equal a direct full save. The
        first save lands as whatever the dirty/epoch state dictates
        (usually a keyframe — most op windows contain a structural
        mutation); a random rho write in between makes the second a
        REAL delta window, so every visit pins the delta machinery."""
        kinds = [self._one_store_save()]
        cells = np.asarray(self.grid.get_cells())
        k = int(self.rng.integers(1, max(2, len(cells) // 3)))
        pick = self.rng.choice(len(cells), size=k, replace=False)
        vals = self.rng.random(k).astype(np.float32)
        self.grid.set("rho", cells[pick], vals)
        for c, v in zip(cells[pick], vals):
            self.oracle[int(c)] = np.float32(v)
        kinds.append(self._one_store_save())
        return "+".join(kinds)

    def _one_store_save(self) -> str:
        from . import resilience, supervise

        g = self.grid
        if self._store is None:
            self._store = supervise.CheckpointStore(
                tempfile.mkdtemp(prefix="dccrg_fuzz_store_"),
                keyframe_every=4)
        self._store_step += 1
        path = self._store.save(g, self._store_step)
        kind = ("delta" if path.endswith(resilience.DELTA_SUFFIX)
                else "key")
        fd, ref = tempfile.mkstemp(suffix=".dc", prefix="dccrg_fuzz_ref_")
        os.close(fd)
        out = path + ".chain.oracle"
        try:
            g.save_grid_data(ref)
            src = path
            if kind == "delta":
                resilience.materialize_chain(path, out, g.fields)
                src = out
            with open(ref, "rb") as f:
                want = f.read()
            with open(src, "rb") as f:
                got = f.read()
        finally:
            os.unlink(ref)
            if os.path.exists(out):
                os.unlink(out)
        if got != want:
            raise FuzzFailure(
                f"incremental checkpoint ({kind}) does not reconstruct "
                "the direct full-save bytes", seed=self.seed,
                op_index=self.ops_run + 1, log=self.log)
        return kind

    # -- structure queries vs brute-force oracle ----------------------

    def _op_query(self):
        g = self.grid
        # 1. get_existing_cell vs scanning every cell's index box
        ilen = g.mapping.get_index_length().astype(np.float64)
        scale = float(1 << g.mapping.max_refinement_level)
        coord = tuple(
            (self.rng.integers(int(ilen[d])) + self.rng.uniform(0.15, 0.85))
            / scale
            for d in range(3)
        )
        got = int(g.get_existing_cell(coord))
        want = self._oracle_existing_cell(coord)
        if got != want:
            raise FuzzFailure(
                f"get_existing_cell({coord}) = {got}, oracle says {want}",
                seed=self.seed, op_index=self.ops_run + 1,
                cells=[c for c in (got, want) if c], log=self.log)
        # 2. per-cell neighbor list vs fresh numpy recomputation
        cells = g.plan.cells
        cid = cells[self.rng.integers(len(cells))]
        got_n = {(int(n), o) for n, o in g.get_neighbors_of(int(cid))}
        src, nbr, off, _item = _dedup_entries(
            g.mapping, np.asarray([cid], dtype=np.uint64),
            *_find_neighbors_of_numpy(
                g.mapping, g.topology, cells,
                np.asarray([cid], dtype=np.uint64),
                g.neighborhoods[DEFAULT_NEIGHBORHOOD_ID]))
        want_n = {(int(n), tuple(int(x) for x in o))
                  for n, o in zip(nbr, off)}
        if got_n != want_n:
            odd = {c for c, _o in got_n ^ want_n}
            raise FuzzFailure(
                f"get_neighbors_of({int(cid)}) diverged from the "
                f"numpy oracle", seed=self.seed,
                op_index=self.ops_run + 1, cells=sorted(odd)[:16],
                log=self.log)
        return ""

    def _oracle_existing_cell(self, coordinate) -> int:
        """Brute force: the unique leaf whose index box contains the
        coordinate, by scanning EVERY cell (unit level-0 cells at the
        origin, so physical coordinate * 2^max_lvl = smallest-cell
        index)."""
        g = self.grid
        cells = g.plan.cells
        idx = g.mapping.get_indices(cells).astype(np.int64)
        lvl = g.mapping.get_refinement_level(cells).astype(np.int64)
        size = (1 << (g.mapping.max_refinement_level - lvl))[:, None]
        p = np.asarray(coordinate, dtype=np.float64) * float(
            1 << g.mapping.max_refinement_level)
        inside = ((idx <= p) & (p < idx + size)).all(axis=1)
        hits = cells[inside]
        return int(hits[0]) if len(hits) else 0


# -- fleet-isolation scenario (the fleet layer's oracle) --------------

def fleet_isolation_case(seed: int, jobs: int = 8, n: int = 8,
                         quantum: int = 4, fault: str = "nan") -> dict:
    """One seeded fleet-isolation scenario: ``jobs`` randomized
    same-shape scenario runs (random kernels, dt, seeds, step counts,
    priorities) are multiplexed through one
    :class:`~dccrg_tpu.scheduler.FleetScheduler` batch while a
    :class:`~dccrg_tpu.faults.FaultPlan` corrupts ONE random victim
    job's field at a random step — ``fault="nan"`` poisons it with
    NaN (the numerics-watchdog class), ``fault="flip"`` lands a
    FINITE silent bit-flip (the SDC class, invisible to the
    finiteness watchdog: only the integrity invariants can convict).
    The oracle is the one-grid-at-a-time path: every job — the victim
    included, whose trip must roll back and replay clean — must
    finish with a final-state digest bitwise equal to its solo
    ``Grid.run_steps`` run, ONLY the victim may trip, and for the SDC
    case the victim's trip must be a CORRUPT verdict. Raises
    :class:`FuzzFailure`; returns ``{victim, trips, report}`` on
    success."""
    import tempfile

    from .fleet import FleetJob, run_solo
    from .scheduler import FleetScheduler

    rng = np.random.default_rng(seed)
    kernels = ("diffuse", "advect_x")

    def mk(i):
        return FleetJob(
            f"f{seed}_{i:02d}", length=(n,) * 3,
            kernel=kernels[int(rng.integers(0, len(kernels)))],
            n_steps=int(rng.integers(6, 24)),
            params=(float(rng.uniform(0.01, 0.08)),),
            priority=int(rng.integers(0, 3)),
            seed=int(rng.integers(0, 2 ** 31)),
            checkpoint_every=int(rng.integers(3, 9)))

    specs = [mk(i) for i in range(jobs)]
    solo = {j.name: run_solo(FleetJob(
        j.name, length=j.length, kernel=j.kernel, n_steps=j.n_steps,
        params=j.params, seed=j.seed)) for j in specs}
    victim = specs[int(rng.integers(0, jobs))]
    poison_step = int(rng.integers(1, victim.n_steps + 1))
    plan = FaultPlan(seed=seed)
    if fault == "flip":
        plan.silent_flip("rho", step=poison_step, job=victim.name)
        site = "step.flip"
    else:
        plan.nan_poison("rho", step=poison_step, job=victim.name)
        site = "step.poison"
    with tempfile.TemporaryDirectory(prefix="dccrg_fleet_fuzz_") as wd:
        with plan:
            report = FleetScheduler(wd, specs, quantum=quantum).run()
    if plan.fired(site) != 1:
        raise FuzzFailure(
            f"fleet {fault} for {victim.name} at step {poison_step} "
            f"never landed", seed=seed)
    for j in specs:
        row = report.get(j.name)
        if row is None or row["status"] != "done":
            raise FuzzFailure(
                f"fleet job {j.name} did not finish: {row}", seed=seed)
        if row["digest"] != solo[j.name]:
            raise FuzzFailure(
                f"fleet job {j.name} final digest differs from its "
                f"solo run (victim was {victim.name}, {fault} after "
                f"step {poison_step})", seed=seed)
        if j.name != victim.name and row["trips"]:
            raise FuzzFailure(
                f"non-victim job {j.name} tripped {row['trips']} "
                f"time(s); only {victim.name} was corrupted",
                seed=seed)
    if report[victim.name]["trips"] < 1:
        raise FuzzFailure(
            f"victim {victim.name} ({fault} after step {poison_step} "
            f"of {victim.n_steps}) never tripped", seed=seed)
    if fault == "flip" and report[victim.name]["sdc_trips"] < 1:
        raise FuzzFailure(
            f"victim {victim.name}'s silent flip tripped, but not as "
            "a CORRUPT verdict", seed=seed)
    return {"victim": victim.name,
            "trips": report[victim.name]["trips"], "report": report}


# -- distributed-AMR commit scenario (the distamr layer's oracle) -----

def _dist_amr_digest(grid):
    """Bitwise fingerprint of one faked rank's world: structure
    (plan digest), owned payload bytes (process-local state digest),
    the pending request sets the rollback must restore, and the epoch
    fence. ``txn.grid_state_bytes`` is the single-controller
    fingerprint; a faked-split rank cannot run a whole two-phase save
    alone, so the distributed scenario composes the same coverage from
    rank-local pieces."""
    from . import distamr
    from .checkpoint import state_digest

    return (distamr.plan_digest(grid.plan), state_digest(grid),
            tuple(sorted(grid._refines)), tuple(sorted(grid._unrefines)),
            tuple(sorted(grid._dont_refines)),
            tuple(sorted(grid._dont_unrefines)),
            grid._amr_group.read_fence())


def dist_amr_case(seed: int, rounds: int = 4, abort_rate: float = 0.6,
                  length=(6, 6, 4), max_lvl: int = 1) -> dict:
    """One seeded distributed-AMR crash-consistency scenario: two
    faked ranks (process-split device masks, one shared
    :class:`~dccrg_tpu.coord.InMemoryKV`, one protocol thread per
    rank) drive ``rounds`` adapt epochs of random rank-local
    refine/unrefine requests through
    :func:`~dccrg_tpu.distamr.distributed_stop_refining`. With
    probability ``abort_rate`` a round first runs with an injected
    fault at a random :data:`~dccrg_tpu.faults.DIST_AMR_FAULT_SITES`
    point on a random victim rank: EVERY rank must abort
    (:class:`~dccrg_tpu.txn.CrossRankAbortedError`), every rank's
    fingerprint (structure, owned bytes, request sets, fence) must be
    bitwise its pre-round value, and the collective fault-free retry
    must commit. After every committed epoch each rank's grid must
    match the single-controller oracle (the merged requests through
    the unchanged local ``stop_refining``) and re-verify
    :func:`~dccrg_tpu.verify.verify_refinement_balance` and
    :func:`~dccrg_tpu.verify.verify_neighbor_symmetry` from scratch.
    Raises :class:`FuzzFailure`; returns summary counts."""
    import threading

    from . import coord, distamr
    from .faults import FaultPlan as _FaultPlan
    from .txn import CrossRankAbortedError
    from .verify import verify_neighbor_symmetry, verify_refinement_balance

    rng = np.random.default_rng(seed)
    devs = _default_devices()
    if len(devs) < 2:
        raise FuzzFailure(
            "dist_amr_case needs >=2 devices (set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=N)",
            seed=seed)

    def mk():
        from jax.sharding import Mesh

        g = (
            Grid(cell_data={"rho": np.float32})
            .set_initial_length(length)
            .set_maximum_refinement_level(int(max_lvl))
            .set_periodic(True, True, True)
            .set_neighborhood_length(1)
            .initialize(Mesh(np.array(devs[:2]), ("dev",)),
                        partition="block")
        )
        cells = g.get_cells()
        g.set("rho", cells,
              (np.asarray(cells) % np.uint64(29)).astype(np.float32))
        return g

    ref = mk()
    kv = coord.InMemoryKV()
    jlock = threading.Lock()  # two threads must never dispatch jax at once
    grids = {}
    for rank in (0, 1):
        g = mk()
        g._proc_local_dev = np.array(
            [(d < 1) == (rank == 0) for d in range(g.n_dev)], dtype=bool)
        g._ckpt_rank = rank
        ig, dg = g._install_plan, g._device_gather

        def _install(plan, same_cells=None, _f=ig):
            with jlock:
                return _f(plan, same_cells=same_cells)

        def _gather(name, dev, rows, cap=None, _f=dg):
            with jlock:
                return _f(name, dev, rows, cap=cap)

        g._install_plan, g._device_gather = _install, _gather
        g.enable_distributed_amr(kv=kv, rank=rank, n_ranks=2, timeout=60)
        grids[rank] = g

    def run_all(plan=None):
        """One collective round on both rank threads; returns
        ``{rank: outcome}`` (the new cells, or the raised error)."""
        out = {}

        def one(rank):
            try:
                out[rank] = grids[rank].stop_refining()
            except BaseException as e:  # noqa: BLE001 - asserted below
                out[rank] = e

        ctx = plan if plan is not None else _NullCtx()
        with ctx:
            ts = [threading.Thread(target=one, args=(r,)) for r in (0, 1)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(120)
        return out

    aborts = commits = 0
    for rnd in range(1, rounds + 1):
        # random rank-local requests, mirrored into the oracle grid
        any_req = False
        for rank in (0, 1):
            g = grids[rank]
            local = g.local_cells().ids
            for cid in rng.choice(local, size=min(2, len(local)),
                                  replace=False):
                if (max_lvl and rng.random() < 0.7
                        and g.refine_completely(int(cid))):
                    ref.refine_completely(int(cid))
                    any_req = True
                elif g.unrefine_completely(int(cid)):
                    ref.unrefine_completely(int(cid))
                    any_req = True
        if not any_req:
            continue

        if rng.random() < abort_rate:
            from .faults import DIST_AMR_FAULT_SITES

            site, phase = DIST_AMR_FAULT_SITES[
                int(rng.integers(len(DIST_AMR_FAULT_SITES)))]
            victim = int(rng.integers(2))
            before = {r: _dist_amr_digest(grids[r]) for r in (0, 1)}
            plan = _FaultPlan(seed=int(rng.integers(1 << 31)))
            plan.amr_error(site=site, phase=phase, rank=victim)
            out = run_all(plan)
            for r in (0, 1):
                if not isinstance(out[r], CrossRankAbortedError):
                    raise FuzzFailure(
                        f"round {rnd}: rank {r} did not abort on "
                        f"injected {site}/{phase}@rank{victim} "
                        f"(got {out[r]!r})", seed=seed)
                if _dist_amr_digest(grids[r]) != before[r]:
                    raise FuzzFailure(
                        f"round {rnd}: rank {r} is not bitwise its "
                        f"pre-round state after the {site} abort",
                        seed=seed)
            aborts += 1

        out = run_all()
        for r in (0, 1):
            if isinstance(out[r], BaseException):
                raise FuzzFailure(
                    f"round {rnd}: fault-free commit failed on rank "
                    f"{r}: {out[r]!r}", seed=seed)
        ref.stop_refining()
        commits += 1
        for r in (0, 1):
            g = grids[r]
            if not (np.array_equal(g.plan.cells, ref.plan.cells)
                    and np.array_equal(g.plan.owner, ref.plan.owner)):
                raise FuzzFailure(
                    f"round {rnd}: rank {r} structure diverged from "
                    "the single-controller oracle", seed=seed)
            try:
                with jlock:
                    verify_refinement_balance(g)
                    verify_neighbor_symmetry(g)
            except VerificationError as e:
                raise FuzzFailure(
                    f"round {rnd}: rank {r} invariants broken after "
                    f"commit: {e}", seed=seed,
                    cells=getattr(e, "cells", ())) from e
            g.clear_refined_unrefined_data()
        ref.clear_refined_unrefined_data()
    return {"rounds": rounds, "aborts": aborts, "commits": commits}


class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


# -- CLI --------------------------------------------------------------

def _main(argv=None) -> int:
    """``python -m dccrg_tpu.fuzz --seed N --ops M`` — run one (or
    ``--seeds K``: seeds 0..K-1) deterministic fuzz run and report;
    ``--fleet K`` runs K seeded fleet-isolation scenarios
    (:func:`fleet_isolation_case`) instead."""
    import argparse
    import time

    ap = argparse.ArgumentParser(description=_main.__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--seeds", type=int, default=None,
                    help="sweep seeds 0..K-1 instead of --seed")
    ap.add_argument("--ops", type=int, default=40)
    ap.add_argument("--fault-rate", type=float, default=0.0)
    ap.add_argument("--length", type=int, nargs=3, default=(4, 4, 2))
    ap.add_argument("--max-level", type=int, default=1)
    ap.add_argument("--devices", type=int, default=2)
    ap.add_argument("--schema", choices=("scalar", "mhd"),
                    default="scalar",
                    help="cell-data schema: the classic scalar rho "
                         "(+aux) or the model zoo's 8-field MHD "
                         "schema (txn/fault sites then exercise the "
                         "multi-field mutation paths)")
    ap.add_argument("--fleet", type=int, default=None, metavar="K",
                    help="run K seeded fleet-isolation scenarios "
                         "(one poisoned batch slot; every job must "
                         "match its solo digest) instead of the "
                         "mutation fuzz")
    ap.add_argument("--dist-amr", type=int, default=None, metavar="K",
                    help="run K seeded distributed-AMR commit "
                         "scenarios (two faked ranks, random aborted "
                         "commits, bitwise rollback + re-verified "
                         "2:1/neighbor invariants) instead of the "
                         "mutation fuzz")
    args = ap.parse_args(argv)

    if args.dist_amr is not None:
        import time as time_mod

        t0 = time_mod.time()
        for s in range(args.dist_amr):
            try:
                out = dist_amr_case(s)
            except FuzzFailure as e:
                print(f"FAIL {e}")
                return 1
            print(f"dist-amr seed {s}: {out['commits']} commit(s), "
                  f"{out['aborts']} injected abort(s) rolled back")
        print(f"OK {args.dist_amr} dist-amr seed(s), "
              f"{time_mod.time() - t0:.1f}s")
        return 0

    if args.fleet is not None:
        import time as time_mod

        t0 = time_mod.time()
        for s in range(args.fleet):
            # even seeds exercise the NaN class, odd seeds the silent
            # (finite bit-flip) SDC class — same isolation oracle
            fault = "flip" if s % 2 else "nan"
            try:
                out = fleet_isolation_case(s, fault=fault)
            except FuzzFailure as e:
                print(f"FAIL {e}")
                return 1
            print(f"fleet seed {s} ({fault}): victim {out['victim']} "
                  f"tripped {out['trips']}x, all digests match solo")
        print(f"OK {args.fleet} fleet seed(s), "
              f"{time_mod.time() - t0:.1f}s")
        return 0

    seeds = range(args.seeds) if args.seeds is not None else [args.seed]
    t0 = time.time()
    total_faults = 0
    for s in seeds:
        try:
            fz = GridFuzzer(
                s, ops=args.ops, length=tuple(args.length),
                max_lvl=args.max_level, n_dev=args.devices,
                fault_rate=args.fault_rate, schema=args.schema,
            ).run()
        except FuzzFailure as e:
            print(f"FAIL {e}")
            return 1
        total_faults += fz.faults_injected
        print(f"seed {s}: {fz.ops_run} ops ok"
              + (f", {fz.faults_injected} fault(s) rolled back"
                 if fz.faults_injected else ""))
    print(f"OK {len(list(seeds))} seed(s) x {args.ops} ops, "
          f"{total_faults} injected fault(s), {time.time() - t0:.1f}s")
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via CLI
    # standalone gotcha (ROUND6_NOTES): the image's site hook may have
    # pre-imported jax pointed at a dead accelerator tunnel; force the
    # CPU backend AFTER import unless the caller opted out
    if os.environ.get("DCCRG_FUZZ_BACKEND", "cpu") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    raise SystemExit(_main())
