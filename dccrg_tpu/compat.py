"""Version-compatibility shims for jax API drift.

``shard_map`` has moved twice (experimental -> top level) and renamed
its replication-check flag (``check_rep`` -> ``check_vma``). The
callers in this package write the newest spelling; this shim adapts it
to whatever the installed jax accepts, so a container pinned to an
older jax runs the same code instead of failing every sharded program
at trace time.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.5 exposes shard_map at top level
    from jax import shard_map as _raw_shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _raw_shard_map

_PARAMS = inspect.signature(_raw_shard_map).parameters

if "check_vma" in _PARAMS:
    shard_map = _raw_shard_map
else:
    def shard_map(f, mesh, in_specs, out_specs, check_vma=None, **kw):
        if check_vma is not None and "check_rep" in _PARAMS:
            kw["check_rep"] = check_vma
        return _raw_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)


def pallas_compiler_params(**kw):
    """TPU Pallas compiler params under either spelling:
    ``pltpu.CompilerParams`` (newer jax) or ``pltpu.TPUCompilerParams``
    (older releases)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)


def pallas_interpret_mode(interpret: bool):
    """The value ``pl.pallas_call(..., interpret=...)`` wants for TPU
    interpret mode: newer jax models it as ``pltpu.InterpretParams()``;
    older releases take the plain boolean. False either way when not
    interpreting."""
    if not interpret:
        return False
    try:
        from jax.experimental.pallas import tpu as pltpu

        return pltpu.InterpretParams()
    except AttributeError:
        return True
