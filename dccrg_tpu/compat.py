"""Version-compatibility shims for jax API drift.

``shard_map`` has moved twice (experimental -> top level) and renamed
its replication-check flag (``check_rep`` -> ``check_vma``). The
callers in this package write the newest spelling; this shim adapts it
to whatever the installed jax accepts, so a container pinned to an
older jax runs the same code instead of failing every sharded program
at trace time.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.5 exposes shard_map at top level
    from jax import shard_map as _raw_shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _raw_shard_map

_PARAMS = inspect.signature(_raw_shard_map).parameters

if "check_vma" in _PARAMS:
    shard_map = _raw_shard_map
else:
    def shard_map(f, mesh, in_specs, out_specs, check_vma=None, **kw):
        if check_vma is not None and "check_rep" in _PARAMS:
            kw["check_rep"] = check_vma
        return _raw_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)


def pallas_compiler_params(**kw):
    """TPU Pallas compiler params under either spelling:
    ``pltpu.CompilerParams`` (newer jax) or ``pltpu.TPUCompilerParams``
    (older releases)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)


def enable_persistent_cache(directory: str) -> bool:
    """Point jax's persistent compilation cache at ``directory``,
    across the API drift between releases: the config keys
    (``jax_compilation_cache_dir`` plus the min-compile-time /
    min-entry-size gates that default CPU programs OUT of the cache)
    on newer jax, ``compilation_cache.set_cache_dir`` on older ones.
    Idempotent; returns False when no spelling is accepted (the
    caller degrades to cold compiles — never an error)."""
    import jax

    ok = False
    try:
        jax.config.update("jax_compilation_cache_dir", str(directory))
        ok = True
    except Exception:  # noqa: BLE001 - drift probe, fallback below
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache)

            compilation_cache.set_cache_dir(str(directory))
            ok = True
        except Exception:  # noqa: BLE001
            return False
    # CPU programs compile in milliseconds and serialize small: both
    # default gates would silently keep them out of the cache
    for knob, val in (("jax_persistent_cache_min_compile_time_secs",
                       0.0),
                      ("jax_persistent_cache_min_entry_size_bytes",
                       -1)):
        try:
            jax.config.update(knob, val)
        except Exception:  # noqa: BLE001 - older jax: gate absent
            pass
    return ok


def pallas_interpret_mode(interpret: bool):
    """The value ``pl.pallas_call(..., interpret=...)`` wants for TPU
    interpret mode: newer jax models it as ``pltpu.InterpretParams()``;
    older releases take the plain boolean. False either way when not
    interpreting."""
    if not interpret:
        return False
    try:
        from jax.experimental.pallas import tpu as pltpu

        return pltpu.InterpretParams()
    except AttributeError:
        return True
